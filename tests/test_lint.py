"""graftlint static analyzer (lightgbm_tpu/analysis/).

Per-pass fixture coverage — one true positive and one true negative for
each of the five passes — plus the suppression/baseline machinery and
the CLI exit-code contract (0 clean / 1 findings / 2 internal error,
the bench_compare convention).  The repo-clean gate itself
(`python -m lightgbm_tpu lint --check` exits 0 on this tree) runs both
here and as the CI lint job.

Regression tests for the true positives the analyzer surfaced when it
first ran live next to the fixtures:

* pallas_hist's row-chunk floor (512) silently oversubscribed the tile
  budget at B>=1024 — now floor 128 + `supports_bins` + onehot fallback
  (vmem-hist-tile).
* the deliberate hot-path readbacks (stop check, tree materialization,
  prediction drain, serve execute) used bare `jax.device_get`,
  invisible to the fence_count() sync audit — now obs/timers.fenced_get
  (sync-device-get).
"""
import ast
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.analysis import core
from lightgbm_tpu.analysis import (config_coherence, events_schema,
                                   hostsync, recompile, vmem)
from lightgbm_tpu.analysis.cli import main as lint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mod_from(src, path="lightgbm_tpu/ops/fixture.py"):
    """Build a SourceModule the way load_modules does, from a string."""
    tree = ast.parse(src, filename=path)
    return core.SourceModule(path, src, tree, src.splitlines())


def run_pass(p, src, path="lightgbm_tpu/ops/fixture.py"):
    return p.run([mod_from(src, path)], REPO_ROOT)


def rules_of(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------- hostsync

def test_hostsync_true_positives():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(g):\n"
        "    x = jnp.sum(g)\n"
        "    a = float(x)\n"             # sync-scalar-cast
        "    b = x.item()\n"             # sync-item
        "    c = np.asarray(x)\n"        # sync-asarray
        "    d = jax.device_get(x)\n"    # sync-device-get
        "    x.block_until_ready()\n"    # sync-block-until-ready
        "    return a, b, c, d\n")
    assert rules_of(run_pass(hostsync, src)) == [
        "sync-asarray", "sync-block-until-ready", "sync-device-get",
        "sync-item", "sync-scalar-cast"]


def test_hostsync_true_negatives():
    src = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from ..obs.timers import fence, fenced_get\n"
        "def f(g, host_rows):\n"
        "    x = jnp.sum(g)\n"
        "    n = int(x.shape[0])\n"      # shape metadata: never a sync
        "    h = fenced_get(x)\n"        # the sanctioned counted readback
        "    fence(x)\n"                 # counted sync, not flagged
        "    y = np.asarray(host_rows)\n"  # unprovable receiver: silent
        "    z = float(n)\n"             # host int, not a device value
        "    return h, y, z\n")
    assert run_pass(hostsync, src) == []


def test_hostsync_flow_sensitive():
    # the host->device rebind pattern from ops/predict.py: np.asarray on
    # a name that only LATER becomes a device value must not fire
    src = (
        "import jax\n"
        "import numpy as np\n"
        "def f(V):\n"
        "    V = np.concatenate([np.asarray(V), np.zeros(4)])\n"
        "    V = jax.device_put(V)\n"
        "    return V\n")
    assert run_pass(hostsync, src) == []


def test_hostsync_out_of_scope_module_silent():
    src = "import jax\nx = jax.device_get(1)\n"
    assert run_pass(hostsync, src, path="lightgbm_tpu/io/fixture.py") == []


# --------------------------------------------------------------- recompile

def test_recompile_true_positives():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('nope',))\n"   # drift
        "def f(x, k):\n"
        "    return x * k\n"
        "@partial(jax.jit, static_argnames=('cfg',))\n"
        "def g(x, cfg):\n"
        "    return x\n"
        "def loop(xs):\n"
        "    out = []\n"
        "    for x in xs:\n"
        "        fn = jax.jit(lambda v: v + 1)\n"          # jit-in-loop
        "        out.append(fn(x))\n"
        "        out.append(g(x, cfg={'a': 1}))\n"         # unhashable
        "    return out\n")
    assert rules_of(run_pass(recompile, src)) == [
        "jit-in-loop", "jit-static-drift", "jit-unhashable-static"]


def test_recompile_true_negatives():
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnames=('k',))\n"
        "def f(x, k):\n"
        "    return x * k\n"
        "def factory():\n"
        "    return jax.jit(lambda v: v + 1)\n"
        "def loop(xs):\n"
        "    return [f(x, k=2) for x in xs]\n")
    assert run_pass(recompile, src) == []


# ------------------------------------------------------------ event schema

def test_events_true_positives():
    src = (
        "def emit_stuff(obs, t):\n"
        "    obs.event('no_such_event_xyz', t=t)\n"        # unknown type
        "    obs.event('iter', t=t, bogus_field=1)\n"      # unknown field
        "    obs.event('straggler', t=t)\n")               # missing req'd
    rules = rules_of(run_pass(
        events_schema, src, path="lightgbm_tpu/obs/fixture.py"))
    assert "event-unknown-type" in rules
    assert "event-unknown-field" in rules
    assert "event-missing-field" in rules


def test_events_true_negatives():
    from lightgbm_tpu.obs import events as ev
    req = sorted(ev._REQUIRED["iter"])
    kw = ", ".join("%s=1" % k for k in req)
    src = (
        "def emit_stuff(obs, t, extra):\n"
        "    obs.event('iter', %s)\n"                      # exact schema
        "    obs.event('iter', **extra)\n"                 # splat: trusted
        "    q = []\n"
        "    q.append(('not_an_event_name', {'free': 1}))\n" % kw)
    assert run_pass(events_schema, src,
                    path="lightgbm_tpu/obs/fixture.py") == []


def test_events_schema_tables_cover_repo():
    # the repo's own emit sites all pass the schema pass (no drift
    # between obs/events.py declarations and real call sites)
    mods = core.load_modules(REPO_ROOT)
    assert events_schema.run(mods, REPO_ROOT) == []


# ----------------------------------------------------------------- config

def test_config_true_positives():
    src = (
        "def f(config):\n"
        "    a = config.definitely_not_a_param_xyz\n"      # unknown read
        "    b = config.raw.get('definitely_not_a_key_xyz')\n"
        "    return a, b\n")
    assert rules_of(run_pass(config_coherence, src)) == [
        "config-unknown-key", "config-unknown-read"]


def test_config_true_negatives():
    src = (
        "import jax\n"
        "def f(config):\n"
        "    jax.config.update('jax_enable_x64', True)\n"  # foreign config
        "    a = config.num_leaves\n"
        "    b = config.raw.get('max_bin', 255)\n"
        "    c = config.raw.get('two_round', 'false')\n"   # alias is fine
        "    return a, b, c\n")
    assert run_pass(config_coherence, src) == []


def test_config_registry_and_doc_fresh():
    # registry internally consistent and docs/Parameters.md regenerates
    # byte-identical (the CI regen-diff gate)
    findings = config_coherence.run([], REPO_ROOT)
    assert findings == []


# ------------------------------------------------------------------- vmem

def test_vmem_clean_on_repo_planners():
    # PR-11 invariants hold: every autotuner-admitted cell plans a live
    # set within physical VMEM, no serialized chunked-RMW plan, and the
    # hist kernel fits its tile budget at every width it claims
    assert vmem.run(core.load_modules(REPO_ROOT), REPO_ROOT) == []


def test_vmem_detects_planner_regression(monkeypatch):
    # resurrect the pathology: a report that claims an over-VMEM live
    # set and a serialized plan must produce both findings
    from lightgbm_tpu.ops import pallas_wave

    def bad_report(n, fc, bp, w, **kw):
        return {"live_new": 300 << 20, "pathological_new": True,
                "resident_bytes": 60 << 20}
    monkeypatch.setattr(pallas_wave, "tile_plan_vmem_report", bad_report)
    rules = set(rules_of(vmem.run([], REPO_ROOT)))
    assert "vmem-budget" in rules
    assert "vmem-serialized-rmw" in rules


def test_vmem_detects_hist_tile_regression(monkeypatch):
    # the original bug: tile_shape hands back a chunk whose one-hot
    # blows the budget for a bin width supports_bins() claims
    from lightgbm_tpu.ops import pallas_hist
    monkeypatch.setattr(pallas_hist, "tile_shape", lambda b: (8, 4096))
    assert "vmem-hist-tile" in rules_of(vmem.run([], REPO_ROOT))


# ------------------------------------------- suppressions and baselines

def test_inline_suppression_honored():
    src = (
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_get(x)  "
        "# lint: ignore[sync-device-get] fixture\n")
    mods = [mod_from(src)]
    raw = hostsync.run(mods, REPO_ROOT)
    assert rules_of(raw) == ["sync-device-get"]
    assert core.apply_suppressions(raw, mods) == []


def test_suppression_star_and_wrong_rule():
    src_star = ("import jax\n"
                "def f(x):\n"
                "    return jax.device_get(x)  # lint: ignore[*]\n")
    mods = [mod_from(src_star)]
    assert core.apply_suppressions(hostsync.run(mods, REPO_ROOT),
                                   mods) == []
    src_wrong = ("import jax\n"
                 "def f(x):\n"
                 "    return jax.device_get(x)  # lint: ignore[sync-item]\n")
    mods = [mod_from(src_wrong)]
    assert rules_of(core.apply_suppressions(
        hostsync.run(mods, REPO_ROOT), mods)) == ["sync-device-get"]


def test_suppression_inside_string_is_inert():
    src = ('MSG = "# lint: ignore[sync-device-get]"\n'
           "import jax\n"
           "def f(x):\n"
           "    return jax.device_get(x)\n")
    mods = [mod_from(src)]
    assert rules_of(core.apply_suppressions(
        hostsync.run(mods, REPO_ROOT), mods)) == ["sync-device-get"]


def test_baseline_round_trip(tmp_path):
    f1 = core.Finding("sync-item", "hostsync",
                      "lightgbm_tpu/ops/x.py", 12, "m")
    f2 = core.Finding("sync-item", "hostsync",
                      "lightgbm_tpu/ops/x.py", 40, "m")
    path = str(tmp_path / "lint_baseline.json")
    core.write_baseline(path, [f1])
    entries = core.load_baseline(path)
    assert core.apply_baseline([f1, f2], entries) == [f2]
    # missing baseline file is an empty grandfather list, not an error
    assert core.load_baseline(str(tmp_path / "nope.json")) == []


def test_corrupt_baseline_fails_closed(tmp_path):
    path = str(tmp_path / "bad.json")
    with open(path, "w") as f:
        f.write("{not json")
    with pytest.raises(core.LintInternalError):
        core.load_baseline(path)
    assert lint_main(["--baseline", path]) == 2     # CLI surfaces exit 2


# ------------------------------------------------------------------- CLI

def test_cli_repo_is_clean():
    # THE acceptance gate: zero unsuppressed findings on this tree
    assert lint_main(["--check"]) == 0


def test_cli_exit_one_on_findings(tmp_path, monkeypatch, capsys):
    fake = tmp_path / "repo" / "lightgbm_tpu" / "ops"
    fake.mkdir(parents=True)
    (fake / "bad.py").write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_get(x)\n")
    (fake.parent / "__init__.py").write_text("")
    (fake / "__init__.py").write_text("")
    from lightgbm_tpu.analysis import cli as lint_cli
    monkeypatch.setattr(lint_cli, "_repo_root",
                        lambda: str(tmp_path / "repo"))
    assert lint_cli.main(["--check"]) == 1
    out = capsys.readouterr().out
    assert "sync-device-get" in out and "FAIL" in out
    # --json emits machine-readable findings with the full shape
    assert lint_cli.main(["--json"]) == 1
    data = json.loads(capsys.readouterr().out)
    assert data["findings"][0]["rule"] == "sync-device-get"
    assert data["findings"][0]["file"] == "lightgbm_tpu/ops/bad.py"
    # a baseline grandfathering the finding turns the gate green
    bl = str(tmp_path / "bl.json")
    assert lint_cli.main(["--write-baseline", bl]) == 0
    assert lint_cli.main(["--check", "--baseline", bl]) == 0


def test_cli_rules_catalog():
    # every pass contributes at least one rule and ids are unique
    cat = core.rule_catalog()
    assert {p for (p, _) in cat.values()} == {
        "hostsync", "recompile", "events", "config", "vmem"}
    assert lint_main(["--rules"]) == 0


def test_cli_module_entry():
    # `python -m lightgbm_tpu lint --check` — the exact CI spelling
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", "lint", "--check"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lint: clean" in r.stdout


# ----------------------- regression tests for the surfaced true positives

def test_pallas_hist_tile_budget_all_supported_widths():
    # TP #1: the 512 row-chunk floor oversubscribed the ~6 MB tile
    # budget at B>=1024; the floor is now the TPU lane minimum (128)
    # and tile_shape must fit the budget at EVERY width it claims
    from lightgbm_tpu.ops import pallas_hist as ph
    for num_bins in (16, 63, 64, 255, 256, 1023):
        if not ph.supports_bins(num_bins):
            continue
        f_blk, row_chunk = ph.tile_shape(num_bins)
        assert row_chunk >= ph._MIN_ROW_CHUNK
        assert row_chunk % 128 == 0
        resident = f_blk * num_bins * 3 * 4
        onehot = f_blk * num_bins * row_chunk * 4
        assert resident + onehot <= ph.TILE_BUDGET, num_bins
    # the widths that CANNOT fit are refused, not silently oversized
    assert not ph.supports_bins(4096)


def test_pallas_hist_unsupported_width_falls_back():
    # beyond capacity the kernel must hand off to the onehot path with
    # identical results instead of planning an over-budget tile
    from lightgbm_tpu.ops import pallas_hist as ph
    from lightgbm_tpu.ops.histogram import leaf_histogram_onehot
    rng = np.random.RandomState(0)
    nb = 4096
    binned = rng.randint(0, nb, size=(64, 3)).astype(np.int32)
    grad = rng.randn(64).astype(np.float32)
    hess = rng.rand(64).astype(np.float32)
    leaf_id = np.zeros(64, np.int32)
    got = np.asarray(ph.leaf_histogram_pallas(
        binned, grad, hess, leaf_id, 0, None, num_bins=nb))
    want = np.asarray(leaf_histogram_onehot(
        binned, grad, hess, leaf_id, 0, None, num_bins=nb))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fenced_get_counts_and_returns():
    # TP #2/#3: hot-path readbacks now go through the counted twin of
    # fence() so the bench.py --dry sync audit sees them
    import jax.numpy as jnp
    from lightgbm_tpu.obs import timers
    x = jnp.arange(4)
    c0 = timers.fence_count()
    out = timers.fenced_get(x)
    assert timers.fence_count() == c0 + 1
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))
    # non-jax values pass through (device_get is identity-ish on host)
    assert timers.fenced_get({"a": 3})["a"] == 3


def test_materialize_readback_is_audited():
    # training then materializing a tree must bump the sync audit —
    # previously these device_get calls were invisible to fence_count()
    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import timers
    rng = np.random.RandomState(7)
    X = rng.rand(200, 4)
    y = (X[:, 0] + rng.rand(200) > 1.0).astype(np.float64)
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "max_bin": 31, "verbose": -1},
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    c0 = timers.fence_count()
    bst.model_to_string()           # forces batched materialization
    assert timers.fence_count() > c0
