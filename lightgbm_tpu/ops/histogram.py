"""Leaf histogram construction — the hottest op, in XLA.

Parity target: the reference's scatter-add kernels (dense_bin.hpp:66-98 on
CPU, src/treelearner/ocl/histogram*.cl on GPU).  TPU-first design instead of
a translation:

* ``scatter`` mode: one `segment_sum` per feature (vmapped), which XLA lowers
  to parallel scatter-adds.  Works on every backend; preferred on CPU.
* ``onehot`` mode: rows are processed in chunks; each chunk builds a
  (C, B) one-hot in bf16/f32 per feature block and contracts it against the
  (C, 3) weight matrix on the MXU — the `max_bin=63` lesson from
  docs/GPU-Performance.md:58-64 maps to "small B lives on the MXU".

Rows outside the target leaf contribute zero via the mask multiplier, which
also carries bagging/GOSS per-row weights (gbdt.cpp:265-324, goss.hpp:79-129
fold into the same mechanism).

Both kernels also come in a *gathered* form operating on a compacted
(capacity,) row-index buffer instead of a full-N mask: the grow loop
compacts the target leaf's rows first (compact_rows) and histograms only
those — restoring the reference's O(rows_in_leaf) cost
(serial_tree_learner.cpp:424-450, dense_bin.hpp:66-98) under XLA's static
shapes via capacity tiers (ops/grow.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _weights(grad, hess, leaf_id, leaf, row_mult):
    """(N, 3) [g, h, 1] masked to the target leaf and row multipliers."""
    mask = (leaf_id == leaf).astype(grad.dtype)
    if row_mult is not None:
        mask = mask * row_mult
    return jnp.stack([grad * mask, hess * mask, mask], axis=-1)


def compact_rows(mask, pos, capacity: int):
    """Indices of rows with mask=True, compacted to a (capacity,) buffer.

    pos = cumsum(mask) - 1 (each masked row's rank, precomputed once by the
    caller so the O(N) cumsum is shared across capacity tiers).  Rows beyond
    `capacity` are dropped — callers select a tier with capacity >= count.
    This is DataPartition's leaf-grouped index array (data_partition.hpp:
    94-147) rebuilt per leaf as one O(N) scatter.
    """
    n = mask.shape[0]
    target = jnp.where(mask, pos, capacity)      # out-of-bounds -> dropped
    return jnp.zeros(capacity, jnp.int32).at[target].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")


def compact_rows_topk(mask, capacity: int):
    """compact_rows via top_k instead of cumsum+scatter.

    On TPU a 1M-row scatter costs ~8ms and the cumsum another ~2.4ms, while
    top_k of the same keys is ~3.4ms total (measured on v5e) — so the
    sort-based compaction wins there.  Keys are n-i for masked rows, so the
    descending top_k yields the leaf's rows in ascending (stable) row
    order; slots past the true count surface arbitrary rows and must be
    masked by the caller's valid vector.
    """
    n = mask.shape[0]
    key = jnp.where(mask, n - jnp.arange(n, dtype=jnp.int32), -1)
    _, idx = lax.top_k(key, capacity)
    return idx.astype(jnp.int32)


def _gathered_weights(grad, hess, row_mult, idx, valid):
    m = valid.astype(grad.dtype)
    if row_mult is not None:
        m = m * jnp.take(row_mult, idx)
    return jnp.stack([jnp.take(grad, idx) * m, jnp.take(hess, idx) * m, m],
                     axis=-1)                     # (C, 3)


def _scatter_accumulate(binned, w, num_bins: int, logical_cols: int = 0):
    """(F, B, 3) from (C, F) bins and (C, 3) weights via segment_sum.

    logical_cols > 0: binned is 4-bit packed (ops/pack.py split-half
    layout); nibbles are extracted per column INSIDE the vmap so the
    full-width matrix never materializes."""
    def per_feature(col):
        return jax.ops.segment_sum(w, col.astype(jnp.int32),
                                   num_segments=num_bins)
    if not logical_cols:
        return jax.vmap(per_feature, in_axes=1)(binned)
    lo = jax.vmap(lambda c: per_feature(c.astype(jnp.int32) & 15),
                  in_axes=1)(binned)
    hi = jax.vmap(lambda c: per_feature(c.astype(jnp.int32) >> 4),
                  in_axes=1)(binned)
    return jnp.concatenate([lo, hi], axis=0)[:logical_cols]


def _onehot_accumulate(binned, w, num_bins: int, chunk: int,
                       logical_cols: int = 0):
    """(F, B, 3) via chunked one-hot contraction on the MXU.

    logical_cols > 0: binned is 4-bit packed (ops/pack.py); chunks unpack
    in-scan so the full-width matrix never materializes in HBM."""
    n, fdev = binned.shape
    f = logical_cols or fdev
    chunk = min(chunk, max(n, 1))
    pad = (-n) % chunk
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        w = jnp.pad(w, ((0, pad), (0, 0)))
    nchunks = (n + pad) // chunk
    xb = binned.reshape(nchunks, chunk, fdev)
    wb = w.reshape(nchunks, chunk, 3)

    def step(acc, args):
        xc, wc = args
        if logical_cols:
            from .pack import unpack4
            xc = unpack4(xc, f)
        onehot = jax.nn.one_hot(xc.astype(jnp.int32), num_bins,
                                dtype=wc.dtype)          # (C, F, B)
        acc = acc + jnp.einsum("cfb,cw->fbw", onehot, wc,
                               preferred_element_type=wc.dtype)
        return acc, None

    init = jnp.zeros((f, num_bins, 3), dtype=w.dtype)
    if nchunks == 1:
        hist, _ = step(init, (xb[0], wb[0]))
        return hist
    hist, _ = lax.scan(step, init, (xb, wb))
    return hist


def gathered_histogram(X, grad, hess, row_mult, idx, valid, num_bins: int,
                       mode: str, chunk: int = 16384,
                       logical_cols: int = 0):
    """(F, B, 3) histogram of the rows in `idx` (valid-masked).

    The gathered analog of leaf_histogram: X/grad/hess/row_mult are full-N;
    idx is a compacted (capacity,) row-index buffer from compact_rows.
    logical_cols > 0: X is 4-bit packed (ops/pack.py); the gathered rows
    stay packed and the accumulators unpack in-scan.
    """
    Xs = jnp.take(X, idx, axis=0)                 # (C, F) or (C, Fh) packed
    w = _gathered_weights(grad, hess, row_mult, idx, valid)
    if mode == "onehot":
        return _onehot_accumulate(Xs, w, num_bins, chunk, logical_cols)
    return _scatter_accumulate(Xs, w, num_bins, logical_cols)


@functools.partial(jax.jit, static_argnames=("num_bins", "logical_cols"))
def leaf_histogram_scatter(binned, grad, hess, leaf_id, leaf, row_mult,
                           num_bins: int, logical_cols: int = 0):
    """(F, B, 3) histogram of the target leaf via per-feature segment_sum.

    binned: (N, F) uint8/uint16 bin ids; grad/hess: (N,) float;
    leaf_id: (N,) int32; leaf: scalar int; row_mult: (N,) float or None.
    """
    w = _weights(grad, hess, leaf_id, leaf, row_mult)  # (N, 3)
    return _scatter_accumulate(binned, w, num_bins, logical_cols)


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "chunk", "logical_cols"))
def leaf_histogram_onehot(binned, grad, hess, leaf_id, leaf, row_mult,
                          num_bins: int, chunk: int = 16384,
                          logical_cols: int = 0):
    """(F, B, 3) histogram via chunked one-hot matmul on the MXU.

    For each row chunk: one_hot(bins) (C, F, B) contracted with weights
    (C, 3) -> (F, B, 3), accumulated over chunks with lax.scan so the
    one-hot tensor never exceeds chunk x F x B.
    """
    w = _weights(grad, hess, leaf_id, leaf, row_mult)  # (N, 3)
    return _onehot_accumulate(binned, w, num_bins, chunk, logical_cols)


def leaf_histogram(binned, grad, hess, leaf_id, leaf, row_mult,
                   num_bins: int, mode: str = "auto"):
    """Dispatch by mode; 'auto' picks onehot on TPU (the fused one-hot
    reduce is at the VPU roofline at every bin count — measured 7.2ms vs
    scatter's 226ms at B=63, 1M x 28 on v5e) and scatter on CPU.  Must stay
    in sync with the same policy in ops/learner.py."""
    if mode == "auto":
        mode = "onehot" if jax.default_backend() == "tpu" else "scatter"
    if mode == "onehot":
        return leaf_histogram_onehot(binned, grad, hess, leaf_id, leaf,
                                     row_mult, num_bins=num_bins)
    if mode == "pallas":
        from .pallas_hist import leaf_histogram_pallas
        return leaf_histogram_pallas(binned, grad, hess, leaf_id, leaf,
                                     row_mult, num_bins=num_bins)
    return leaf_histogram_scatter(binned, grad, hess, leaf_id, leaf,
                                  row_mult, num_bins=num_bins)


@functools.partial(jax.jit, static_argnames=())
def leaf_sums(grad, hess, leaf_id, leaf, row_mult):
    """Leaf total (sum_g, sum_h, count) — LeafSplits::Init (leaf_splits.hpp)."""
    w = _weights(grad, hess, leaf_id, leaf, row_mult)
    return jnp.sum(w, axis=0)
