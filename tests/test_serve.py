"""Serving tier (lightgbm_tpu/serve): AOT bucket executables + the
async microbatch scheduler.

The load-bearing invariant: a row scores bit-identically whatever
bucket it lands in and whoever it shares the bucket with — element-wise
Kahan lanes, no cross-row ops — so concurrent submissions through the
coalescing queue must equal solo submissions exactly, and the steady
state must never compile."""
import threading
import time
from concurrent.futures import CancelledError, Future

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (MicrobatchScheduler, PredictExecutableCache,
                                ServingPredictor, next_pow2)


def _train(params=None, rounds=12, rows=600, features=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, features))
    w = rng.normal(size=features)
    y = (X @ w + 0.2 * rng.normal(size=rows) > 0).astype(np.float64)
    p = dict({"objective": "binary", "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}, **(params or {}))
    return lgb.train(p, lgb.Dataset(X, label=y, params=p),
                     num_boost_round=rounds), X


def test_next_pow2():
    assert [next_pow2(n) for n in (1, 2, 3, 4, 5, 63, 64, 65)] == \
        [1, 2, 4, 4, 8, 64, 64, 128]


# ---------------------------------------------------------------- executable
def test_executable_cache_matches_predict_and_buckets():
    bst, X = _train()
    cache = PredictExecutableCache(bst._gbdt, bucket_min=16, max_batch=256)
    assert cache.bucket_for(1) == 16
    assert cache.bucket_for(17) == 32
    assert cache.bucket_for(5000) == 256        # capped at max_batch
    want = bst.predict(X)
    got = cache.predict_batch(X)[:, 0]          # chunks over max_batch
    assert np.allclose(got, want, rtol=2e-6, atol=1e-7)
    raw = cache.predict_batch(X[:40], convert=False)[:, 0]
    assert np.allclose(raw, bst.predict(X[:40], raw_score=True),
                       rtol=2e-6, atol=1e-7)


def test_executable_bucket_reuse_no_steady_state_compiles():
    bst, X = _train()
    cache = PredictExecutableCache(bst._gbdt, bucket_min=16, max_batch=128)
    cache.warmup(sizes=[1, 20, 50, 128])
    warm = cache.compiles
    cache.mark_warm()
    full = cache.predict_batch(X[:128])
    for n in (1, 2, 9, 16, 17, 40, 100, 128):   # all land on warm rungs
        got = cache.predict_batch(X[:n])
        # bit-identical to the full-bucket run: padding and bucket
        # choice must not leak into any row's arithmetic
        assert np.array_equal(got, full[:n]), n
    assert cache.compiles == warm
    assert cache.steady_state_compiles == 0


def test_executable_normalize_widths():
    bst, X = _train(features=8)
    cache = PredictExecutableCache(bst._gbdt, bucket_min=16)
    want = cache.predict_batch(X[:10])
    # wider input: extra columns sliced off
    wide = np.concatenate([X[:10], np.ones((10, 3))], axis=1)
    assert np.array_equal(cache.predict_batch(wide), want)
    # 1-D input promotes to one row
    one = cache.predict_batch(X[0])
    assert one.shape[0] == 1 and np.array_equal(one[0], want[0])
    # too narrow to cover the model's features: refused
    with pytest.raises(ValueError):
        cache.predict_batch(X[:4, :1])


# ----------------------------------------------------------------- scheduler
def test_scheduler_coalesces_and_splits():
    seen = []

    def runner(route, feats):
        seen.append(feats.shape[0])
        return feats[:, :1] * 2.0

    with MicrobatchScheduler(runner, max_batch=64,
                             max_delay_ms=40.0) as sched:
        blocks = [np.full((n, 3), float(n)) for n in (2, 3, 4)]
        futs = [sched.submit("r", b, len(b)) for b in blocks]
        outs = [f.result(timeout=10) for f in futs]
    for b, o in zip(blocks, outs):
        assert np.array_equal(o, b[:, :1] * 2.0)
    # the three requests landed within one deadline -> fewer batches
    # than requests, and every batch respected the row cap
    assert sum(seen) == 9 and max(seen) <= 64


def test_scheduler_deadline_flushes_lone_request():
    def runner(route, feats):
        return np.zeros((feats.shape[0], 1))

    with MicrobatchScheduler(runner, max_batch=4096,
                             max_delay_ms=30.0) as sched:
        t0 = time.perf_counter()
        sched.submit("r", np.zeros((3, 2)), 3).result(timeout=10)
        dt = time.perf_counter() - t0
    # a lone sub-bucket request must not wait for a full batch: the
    # deadline flushes it — well under a second even on a loaded CI box
    assert dt < 5.0
    assert sched.stats()["batches"] == 1


def test_scheduler_routes_do_not_mix():
    batches = []

    def runner(route, feats):
        batches.append((route, feats.shape[0]))
        return np.zeros((feats.shape[0], 1))

    with MicrobatchScheduler(runner, max_batch=64,
                             max_delay_ms=30.0) as sched:
        futs = [sched.submit(route, np.zeros((2, 2)), 2)
                for route in ("a", "a", "b", "a")]
        for f in futs:
            f.result(timeout=10)
    # same-route neighbors may coalesce; "a" and "b" never share a batch
    assert sum(n for _, n in batches) == 8
    assert all(route in ("a", "b") for route, _ in batches)


def test_scheduler_survives_cancelled_future():
    gate = threading.Event()

    def runner(route, feats):
        gate.wait(5)
        return feats[:, :1]

    with MicrobatchScheduler(runner, max_delay_ms=1.0) as sched:
        first = sched.submit("r", np.zeros((1, 2)), 1)
        time.sleep(0.05)                  # worker is blocked in runner
        doomed = sched.submit("r", np.zeros((2, 2)), 2)
        assert doomed.cancel()            # still queued: cancellable
        gate.set()
        first.result(timeout=10)
        with pytest.raises(CancelledError):
            doomed.result(timeout=10)
        # the worker must survive the cancelled future: resolving it
        # without the set_running_or_notify_cancel() claim raises
        # InvalidStateError and kills the thread, hanging the tier
        out = sched.submit("r", np.ones((3, 2)), 3).result(timeout=10)
    assert out.shape == (3, 1)


def test_scheduler_results_are_copies_not_views():
    gate = threading.Event()
    sizes = []

    def runner(route, feats):
        gate.wait(5)
        sizes.append(feats.shape[0])
        return feats * 2.0

    with MicrobatchScheduler(runner, max_delay_ms=1.0) as sched:
        sched.submit("r", np.zeros((1, 2)), 1)
        time.sleep(0.05)                  # block worker: next two coalesce
        fa = sched.submit("r", np.ones((2, 2)), 2)
        fb = sched.submit("r", np.full((3, 2), 3.0), 3)
        gate.set()
        a, b = fa.result(timeout=10), fb.result(timeout=10)
    assert sizes[-1] == 5                 # they shared one batch
    a[:] = -1.0                           # caller scribbles on its result
    assert np.array_equal(b, np.full((3, 2), 6.0))


def test_scheduler_runner_error_propagates_and_close_rejects():
    def runner(route, feats):
        raise RuntimeError("boom")

    sched = MicrobatchScheduler(runner, max_delay_ms=1.0)
    fut = sched.submit("r", np.zeros((1, 2)), 1)
    with pytest.raises(RuntimeError, match="boom"):
        fut.result(timeout=10)
    sched.close()
    with pytest.raises(RuntimeError, match="closed"):
        sched.submit("r", np.zeros((1, 2)), 1)


# ----------------------------------------------------------- serving predictor
def test_concurrent_submissions_bit_identical_to_solo():
    bst, X = _train()
    with ServingPredictor(bst._gbdt, max_delay_ms=10.0,
                          bucket_min=16) as sp:
        solo = [sp.predict(X[lo:lo + n])
                for lo, n in ((0, 7), (50, 31), (200, 64), (300, 3))]
        barrier = threading.Barrier(4)
        futs = [None] * 4

        def fire(i, lo, n):
            barrier.wait()
            futs[i] = sp.submit(X[lo:lo + n])

        ts = [threading.Thread(target=fire, args=(i, lo, n))
              for i, (lo, n) in enumerate(((0, 7), (50, 31), (200, 64),
                                           (300, 3)))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        together = [f.result(timeout=30) for f in futs]
    for s, t in zip(solo, together):
        assert np.array_equal(s, t)     # bit-identical, not allclose


def test_serve_matches_booster_predict_shapes_and_values():
    bst, X = _train()
    with ServingPredictor(bst._gbdt, max_delay_ms=1.0) as sp:
        conv = sp.predict(X[:50])
        raw = sp.predict(X[:50], raw_score=True)
    assert conv.shape == (50,)                  # 1-D like Booster.predict
    assert np.allclose(conv, bst.predict(X[:50]), rtol=2e-6, atol=1e-7)
    assert np.allclose(raw, bst.predict(X[:50], raw_score=True),
                       rtol=2e-6, atol=1e-7)


def test_serve_multiclass_softmax_fused():
    rng = np.random.default_rng(9)
    X = rng.normal(size=(400, 6))
    y = rng.integers(0, 3, size=400).astype(np.float64)
    p = {"objective": "multiclass", "num_class": 3, "verbose": -1,
         "num_leaves": 7, "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=9)
    with ServingPredictor(bst._gbdt, max_delay_ms=1.0) as sp:
        got = sp.predict(X[:30])
    want = bst.predict(X[:30])
    assert got.shape == want.shape == (30, 3)
    assert np.allclose(got, want, rtol=2e-6, atol=1e-7)


def test_serve_early_stop_and_contrib_round_trip():
    bst, X = _train(rounds=30)
    with ServingPredictor(bst._gbdt, max_delay_ms=5.0) as sp:
        es = sp.predict(X[:80], pred_early_stop=True,
                        pred_early_stop_freq=2, pred_early_stop_margin=1.0)
        contrib = sp.predict(X[:80], pred_contrib=True)
    # both host routes are bit-equal to the Booster entry points
    want_es = bst.predict(X[:80], pred_early_stop=True,
                          pred_early_stop_freq=2,
                          pred_early_stop_margin=1.0)
    assert np.array_equal(es, want_es)
    assert not np.array_equal(es, bst.predict(X[:80]))   # it engaged
    assert np.array_equal(contrib, bst.predict(X[:80], pred_contrib=True))
    assert contrib.shape == (80, X.shape[1] + 1)


def test_serve_mixed_width_requests_coalesce_safely():
    bst, X = _train(features=8)
    wide = np.concatenate([X[:6], np.ones((6, 3))], axis=1)
    with ServingPredictor(bst._gbdt, max_delay_ms=60.0,
                          bucket_min=16) as sp:
        # same dev route, different submitted widths: submit-time
        # normalization gives them one canonical width, so sharing a
        # microbatch cannot blow up np.concatenate
        f1, f2 = sp.submit(X[:4]), sp.submit(wide)
        g1, g2 = f1.result(timeout=30), f2.result(timeout=30)
        assert np.allclose(g1, bst.predict(X[:4]), rtol=2e-6, atol=1e-7)
        assert np.allclose(g2, bst.predict(X[:6]), rtol=2e-6, atol=1e-7)
        # host routes carry the width in the route key instead: the two
        # early-stop requests never share a batch, and both succeed
        e1 = sp.submit(X[:4], pred_early_stop=True)
        e2 = sp.submit(wide, pred_early_stop=True)
        h1, h2 = e1.result(timeout=30), e2.result(timeout=30)
    assert np.array_equal(h1, bst.predict(X[:4], pred_early_stop=True))
    assert np.array_equal(h2, bst.predict(X[:6], pred_early_stop=True))


def test_serve_zero_steady_state_compiles_under_mixed_load():
    bst, X = _train()
    with ServingPredictor(bst._gbdt, max_delay_ms=2.0, bucket_min=16,
                          max_batch=256) as sp:
        sp.cache.warmup([16, 32, 64, 128, 256])
        sp.cache.mark_warm()
        futs = [sp.submit(X[lo:lo + n]) for lo, n in
                ((0, 1), (9, 30), (80, 120), (300, 256), (10, 5))]
        for f in futs:
            f.result(timeout=30)
        assert sp.cache.steady_state_compiles == 0
        assert sp.stats()["batches"] >= 1


def test_booster_serve_reads_config_params():
    bst, X = _train(params={"serve_max_batch": 128,
                            "serve_max_delay_ms": 7.5,
                            "serve_bucket_min": 32})
    with bst.serve() as sp:
        assert sp.scheduler.max_batch == 128
        assert sp.scheduler.max_delay_s == pytest.approx(0.0075)
        assert sp.cache.bucket_min == 32
        assert np.allclose(sp.predict(X[:20]), bst.predict(X[:20]),
                           rtol=2e-6, atol=1e-7)
    with bst.serve(max_batch=64) as sp:      # kwargs override config
        assert sp.scheduler.max_batch == 64


def test_serve_host_fallback_on_unencodable_model(monkeypatch):
    bst, X = _train()
    from lightgbm_tpu.serve import executable as exe_mod

    def boom(*a, **k):
        raise ValueError("mixed categorical/numerical use (test)")

    monkeypatch.setattr(exe_mod.dev_predict, "build_ranked_predictor",
                        boom)
    with ServingPredictor(bst._gbdt, max_delay_ms=1.0) as sp:
        assert sp.cache is None
        got = sp.predict(X[:25])            # host route, still serves
    assert np.array_equal(got, bst.predict(X[:25]))


# ------------------------------------------------------- plain-predict bucket
def test_gbdt_bulk_predict_buckets_reuse_jit_cache():
    from lightgbm_tpu.ops.predict import ranked_predict_device
    bst, X = _train(rows=900)
    bst._gbdt.config.tpu_predict = "true"
    full = bst.predict(X)
    # warm one predict per rung (256, 512, 1024); repeats at novel sizes
    # must hit the same executables — sliced results stay exact
    for n in (200, 400, 800):
        assert np.array_equal(bst.predict(X[:n]), full[:n])
    warm = ranked_predict_device._cache_size()
    for n in (1, 37, 250, 511, 700, 899):
        assert np.array_equal(bst.predict(X[:n]), full[:n])
    assert ranked_predict_device._cache_size() == warm


# ------------------------------------------------------------ observability
def test_observe_predict_counts_input_rows():
    from lightgbm_tpu.obs.metrics import REGISTRY
    bst, X = _train()

    def rows_total():
        snap = REGISTRY.snapshot().get("lgbm_predict_rows_total")
        return snap["value"] if snap else 0

    base = rows_total()
    bst.predict(X[:17])                     # converted output is 1-D
    assert rows_total() == base + 17
    bst.predict(X[0])                       # one 1-D request = one row
    assert rows_total() == base + 18
    from lightgbm_tpu.predictor import Predictor
    Predictor(bst._gbdt).predict(X[:5])
    assert rows_total() == base + 23


def test_serve_batch_counter_labels_route_kind_only():
    from lightgbm_tpu.obs.metrics import REGISTRY, observe_serve_batch
    for margin in (12.5, 99.0):           # client-supplied, unbounded
        observe_serve_batch(("es", False, 10, margin, 8), 4, 0, 4,
                            0.0, 0.0)
    series = [k for k in REGISTRY.snapshot()
              if k.startswith("lgbm_serve_batches_total")]
    assert 'lgbm_serve_batches_total{route="es"}' in series
    # never a rendered route tuple: freq/margin values in the label
    # would make Prometheus cardinality unbounded
    assert all("(" not in s for s in series)


def test_serve_timeline_events(tmp_path):
    from lightgbm_tpu.obs import RunObserver, read_events
    bst, X = _train()
    path = str(tmp_path / "serve.jsonl")
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    with ServingPredictor(bst._gbdt, max_delay_ms=1.0, observer=obs,
                          batch_event_every=1) as sp:
        sp.predict(X[:40])
        sp.predict(X[:10])
    obs.event("serve_bench", qps=123.0, p50_s=0.001, p99_s=0.002)
    obs.close()
    evs = read_events(path)                 # schema-validates everything
    kinds = [e["ev"] for e in evs]
    assert kinds.count("serve_batch") == 2
    assert "serve_bench" in kinds and "compile_attr" in kinds
    batches = [e for e in evs if e["ev"] == "serve_batch"]
    for e in batches:
        assert e["bucket"] >= e["rows"] and e["pad"] >= 0
    attr = [e for e in evs if e["ev"] == "compile_attr"]
    assert all(e["entry"].startswith("serve_predict_b") for e in attr)
    assert all(e["sig_compiles"] == 1 for e in attr)
