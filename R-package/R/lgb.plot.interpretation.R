# Contribution bar plot — parity with
# R-package/R/lgb.plot.interpretation.R, in base graphics.

#' Plot one observation's feature contributions
#'
#' @param tree_interpretation one element of lgb.interprete's output
#' @param top_n show the n largest absolute contributions
#' @param cols panel columns when the model is multiclass (one panel per
#'   class, the reference's layout)
#' @export
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    cols = 1L, left_margin = 10L,
                                    cex = NULL, ...) {
  value_cols <- setdiff(names(tree_interpretation), "Feature")
  par_args <- list(mar = c(3, left_margin, 2, 1))
  if (length(value_cols) > 1L) {
    par_args$mfrow <- c(ceiling(length(value_cols) / cols), cols)
  }
  op <- do.call(graphics::par, par_args)   # captures old mar AND mfrow
  on.exit(graphics::par(op))
  for (vc in value_cols) {
    ti <- tree_interpretation[
      order(-abs(tree_interpretation[[vc]])), , drop = FALSE]
    ti <- utils::head(ti, top_n)
    ti <- ti[rev(seq_len(nrow(ti))), , drop = FALSE]
    graphics::barplot(ti[[vc]], names.arg = ti$Feature, horiz = TRUE,
                      las = 1, cex.names = cex,
                      col = ifelse(ti[[vc]] > 0, "forestgreen",
                                   "firebrick"),
                      main = if (length(value_cols) > 1L) vc
                             else "Feature contribution",
                      xlab = "Contribution", ...)
  }
  invisible(tree_interpretation)
}
