#!/bin/bash
# Stage 5: after the final bench, measure the partition-scan chunk ladder
# + the pallas_ct arms at 1M, then pallas_ct at the flagship shape.
cd /root/repo
while pgrep -f "chain_r03d.sh" > /dev/null; do sleep 60; done
echo "[chain5] stage4 done at $(date -u)" >> /tmp/chain_r03.log
python tools/tpu_ab2.py 999424 --r03e > /tmp/ab2_r03e.out 2>&1
echo "[chain5] ab rc=$? at $(date -u)" >> /tmp/chain_r03.log
python tools/bench_suite.py higgs_ct >> /tmp/chain_r03.log 2>&1
echo "[chain5] higgs_ct rc=$? at $(date -u)" >> /tmp/chain_r03.log
