"""Text-model-format cross-compatibility with the reference.

The golden *.model/*.pred files under tests/data/golden/ were produced by
the UNMODIFIED reference CLI (see gen_golden.py there for provenance).
Loading them with lightgbm_tpu and matching the reference's own
predictions to float precision proves the model text format
(gbdt.cpp:817-971, tree.cpp ToString/Tree(const char*)) is a true
compatibility surface, per SURVEY.md §5 ("the text model format is the
compatibility surface").

The reverse direction (reference loads OUR model files) was verified
manually with the same build — our writer emits the same field set; the
round-trip test below (save→load→predict equality) plus these forward
tests pin both directions.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

HERE = os.path.dirname(__file__)
GOLDEN = os.path.join(HERE, "data", "golden")

TASKS = ["binary", "regression", "multiclass", "lambdarank"]


def _load_tsv(path):
    data = np.loadtxt(path, delimiter="\t", ndmin=2)
    return data[:, 1:], data[:, 0]


@pytest.mark.parametrize("task", TASKS)
def test_load_reference_model_prediction_parity(task):
    model_file = os.path.join(GOLDEN, task + ".model")
    bst = lgb.Booster(model_file=model_file)
    X, _ = _load_tsv(os.path.join(GOLDEN, task + ".test"))
    pred = bst.predict(X)
    ref = np.loadtxt(os.path.join(GOLDEN, task + ".pred"))
    if pred.ndim == 2:  # multiclass: reference writes one row per class-prob row
        ref = ref.reshape(pred.shape)
    np.testing.assert_allclose(pred, ref, rtol=0, atol=1e-12)


@pytest.mark.parametrize("task", TASKS)
def test_reference_model_roundtrip_resave(task):
    """Load golden model, re-save with our writer, re-load, identical preds."""
    model_file = os.path.join(GOLDEN, task + ".model")
    bst = lgb.Booster(model_file=model_file)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    X, _ = _load_tsv(os.path.join(GOLDEN, task + ".test"))
    np.testing.assert_array_equal(bst.predict(X), bst2.predict(X))


def test_continue_training_from_reference_model():
    """init_model continuation from a reference-produced model file."""
    model_file = os.path.join(GOLDEN, "binary.model")
    X, y = _load_tsv(os.path.join(GOLDEN, "binary.train"))
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 20, "max_bin": 63}
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.train(params, ds, num_boost_round=5, init_model=model_file)
    assert bst.current_iteration() == 20  # 15 loaded + 5 new
    Xte, yte = _load_tsv(os.path.join(GOLDEN, "binary.test"))
    pred = bst.predict(Xte)
    # continued model should beat the golden model on train logloss
    base = lgb.Booster(model_file=model_file)
    def logloss(p, yy):
        p = np.clip(p, 1e-15, 1 - 1e-15)
        return -np.mean(yy * np.log(p) + (1 - yy) * np.log(1 - p))
    assert logloss(bst.predict(X), y) < logloss(base.predict(X), y)
