# Internal plumbing for the lightgbm.tpu R package.
#
# The reference R package reaches C++ through 633 lines of SEXP glue
# (src/lightgbm_R.cpp) over the C API.  Here the compute plane is XLA
# driven from Python, so the FFI boundary is the Python package via
# reticulate; every exported function delegates to the same lightgbm_tpu
# calls the Python API uses, keeping one behavior for both languages.

.lgb_env <- new.env(parent = emptyenv())

.lgb_py <- function() {
  if (is.null(.lgb_env$mod)) {
    if (!requireNamespace("reticulate", quietly = TRUE)) {
      stop("lightgbm.tpu requires the 'reticulate' package")
    }
    .lgb_env$mod <- reticulate::import("lightgbm_tpu")
  }
  .lgb_env$mod
}

.as_py_params <- function(params) {
  if (is.null(params)) params <- list()
  # R scalars pass through reticulate; names kept verbatim — parameter
  # names/aliases are the cross-language API (config.h:360-489)
  params
}

# categorical_feature: R is 1-based; as.list keeps length-1 vectors a
# Python list (not a bare scalar) through reticulate
.as_py_categorical <- function(categorical_feature) {
  if (is.null(categorical_feature)) {
    "auto"
  } else if (is.numeric(categorical_feature)) {
    as.list(as.integer(categorical_feature - 1L))
  } else {
    as.list(categorical_feature)   # column names, resolved Python-side
  }
}

.as_int_or_null <- function(x) {
  if (is.null(x)) NULL else as.integer(x)
}

lgb.is.Dataset <- function(x) inherits(x, "lgb.Dataset")

lgb.is.Booster <- function(x) inherits(x, "lgb.Booster")

.lgb_tag_dataset <- function(ds) {
  class(ds) <- unique(c("lgb.Dataset", class(ds)))
  ds
}

.lgb_tag_booster <- function(bst) {
  class(bst) <- unique(c("lgb.Booster", class(bst)))
  bst
}
