"""Worker for the multi-process DCN tests (tests/test_multiprocess.py).

Each process: jax.distributed.initialize over a localhost coordinator
(the TPU-native replacement for machine_list_file + socket handshake,
linkers_socket.cpp), distributed bin finding via JaxProcessComm
(dataset_loader.cpp:733-833 analog), then data-parallel boosting over the
GLOBAL mesh spanning both processes — histograms psum across the process
boundary exactly as they would across DCN on a multi-host pod.

Prints one JSON line with the final model fingerprint + local AUC so the
parent can assert cross-rank agreement and the single-process oracle.

Usage: mp_worker.py <coordinator> <num_procs> <rank>

Observability hooks (tests/test_multiprocess.py distributed-obs tests):

* ``LGBM_MP_OBS_PATH``   — create a RunObserver on that events path; with
  jax.distributed live it auto-shards to ``<path>.r<rank>`` and records
  the host collectives of distributed bin finding plus per-round iter
  events.
* ``LGBM_MP_SLOW_RANK`` / ``LGBM_MP_SLOW_SECS`` — fault injection: that
  rank sleeps before the distributed load and before every boosting
  round, so the merged cross-rank view must attribute nonzero skew to
  it.
"""
import json
import os
import sys
import time

import numpy as np

N_GLOBAL, F, ROUNDS = 4096, 8, 3


def make_data(rank, nproc):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N_GLOBAL, F))
    y = (X[:, 0] + np.sin(X[:, 1] * 2) + 0.4 * rng.normal(size=N_GLOBAL)
         > 0).astype(np.float32)
    per = N_GLOBAL // nproc
    sl = slice(rank * per, (rank + 1) * per)
    return X[sl], y[sl]


def main():
    # env + backend setup ONLY when run as a worker process: importing this
    # module (the test does, for make_data) must not touch global jax state
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
    import jax
    jax.config.update("jax_platforms", "cpu")
    coordinator, nproc, rank = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "dense"
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=nproc, process_id=rank)
    import jax.numpy as jnp
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.models.tree import Tree
    from lightgbm_tpu.ops import predict as dev_predict
    from lightgbm_tpu.parallel.comm import JaxProcessComm
    from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                            make_data_mesh,
                                            make_row_sharded)
    from lightgbm_tpu.utils.config import Config

    assert jax.process_count() == nproc
    X_local, y_local = make_data(rank, nproc)
    cfg_keys = {"num_leaves": 15, "min_data_in_leaf": 5, "max_bin": 63,
                "verbose": -1, "tpu_growth": "exact",
                "enable_bundle": False}
    if mode == "sparse":
        # the sharded coordinate store with per-process nnz agreement
        cfg_keys["tpu_sparse"] = True
    cfg = Config(cfg_keys)
    comm = JaxProcessComm()

    # distributed-obs hooks: observer AFTER the comm (rank context) and
    # BEFORE from_matrix, so the loading collectives land in the shard
    slow_rank = int(os.environ.get("LGBM_MP_SLOW_RANK", "-1"))
    slow_secs = float(os.environ.get("LGBM_MP_SLOW_SECS", "0.2"))
    obs = None
    obs_path = os.environ.get("LGBM_MP_OBS_PATH", "")
    if obs_path:
        from lightgbm_tpu.obs import RunObserver
        obs = RunObserver(events_path=obs_path, timing="iter")
        obs.run_header(backend=jax.default_backend(), devices=[],
                       params=dict(cfg_keys), context={"mode": mode})

    if rank == slow_rank:
        time.sleep(slow_secs)        # skew the loading collectives
    # distributed bin finding across REAL processes (this also min-syncs
    # the RNG-bearing params automatically, application.cpp:118-199)
    td = TrainingData.from_matrix(X_local, label=y_local, config=cfg,
                                  comm=comm)
    mesh = make_data_mesh()              # global mesh over both processes
    learner = DataParallelTreeLearner(cfg, td, mesh)

    y_dev = make_row_sharded(mesh, y_local.astype(np.float32))
    score = make_row_sharded(mesh, np.zeros(len(y_local), np.float32))
    lr = jnp.asarray(0.2, jnp.float32)

    @jax.jit
    def grads(score, y):
        p = 1.0 / (1.0 + jnp.exp(-score))
        return p - y, p * (1.0 - p)

    trees = []
    for it in range(ROUNDS):
        if obs is not None:
            obs.iter_begin(it)
        if rank == slow_rank:
            time.sleep(slow_secs)
        g, h = grads(score, y_dev)
        tree_dev, leaf_id = learner.train_device(g, h)
        score = dev_predict.update_score_from_partition(
            score, leaf_id, tree_dev.leaf_value, lr)
        trees.append(tree_dev)
        if obs is not None:
            obs.iter_end(it, value=score)
    if obs is not None:
        obs.close()

    # fingerprint: structure of every tree (replicated outputs, addressable
    # on all processes) + this rank's local AUC
    fp = []
    for t in trees:
        fp.append({
            "num_leaves": int(jax.device_get(t.num_leaves)),
            "split_feature": np.asarray(
                jax.device_get(t.split_feature)).tolist(),
            "threshold_bin": np.asarray(
                jax.device_get(t.threshold_bin)).tolist(),
            "leaf_value_sum": float(np.asarray(
                jax.device_get(t.leaf_value)).sum()),
        })
    local_score = np.concatenate(
        [np.asarray(s.data) for s in score.addressable_shards])
    order = np.argsort(local_score)
    ranks = np.empty(len(order)); ranks[order] = np.arange(1, len(order) + 1)
    npos = y_local.sum(); nneg = len(y_local) - npos
    auc = float((ranks[y_local > 0].sum() - npos * (npos + 1) / 2)
                / (npos * nneg))
    print("MPRESULT " + json.dumps({"rank": rank, "trees": fp,
                                    "auc": round(auc, 6)}), flush=True)


if __name__ == "__main__":
    main()
