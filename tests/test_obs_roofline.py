"""Roofline attribution (obs/roofline.py, schema 13).

Covers the device-peak registry (table lookup, alias/prefix resolution,
the unknown-kind CPU fallback, JSON overrides), the per-entry roofline
join and its bound classification edges (compute / memory / collective /
host-orchestration, the ORCH_FLOOR regime), the per-iteration
``utilization`` rollup math and its end-to-end emission from a real
training run, the ``obs roofline`` CLI and its ``--check`` exit codes,
the autotune-cell roofline stamp (analytic traffic model + probe-event
stamping + ``obs explain`` rendering), the serving-tier executable
join, the humanized ``obs recompiles`` cost tags, the shared
``parse_compiled`` helper both the JIT tracker and the serve tier read
XLA analyses through (list-form ``cost_analysis`` regression), and the
ledger / bench_compare lockstep extraction of ``flop_util`` /
``hbm_util``.
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import SCHEMA_VERSION, read_events, validate_event
from lightgbm_tpu.obs.compile import analyze_compiled, parse_compiled
from lightgbm_tpu.obs.ledger import metrics_from_events
from lightgbm_tpu.obs.query import main as obs_main
from lightgbm_tpu.obs.roofline import (BOUNDS, DEFAULT_PEAKS, ORCH_FLOOR,
                                       cell_roofline, cell_traffic,
                                       describe_roofline_position,
                                       entry_roofline, fmt_bytes,
                                       fmt_quantity, load_peak_overrides,
                                       normalize_kind, peaks_for,
                                       timeline_roofline,
                                       utilization_rollup)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

# a known-profile peak set for exact-math assertions: 100 GFLOP/s,
# 25 GB/s HBM, 10 GB/s ICI (the built-in CPU fallback figures)
CPU_PEAKS = dict(DEFAULT_PEAKS["cpu"], kind="cpu", source="table")

PROV = {"git_rev": "feedc0ffee12", "git_dirty": False,
        "hostname": "testhost", "argv": ["bench.py", "--dry"]}


def _header(run="r0", t=1e9, kind="cpu", **kw):
    return dict({"ev": "run_header", "run": run, "t": t,
                 "schema": SCHEMA_VERSION, "backend": "cpu",
                 "devices": [{"id": 0, "kind": kind}], "params": {},
                 "context": {}, "timing": "iter", "provenance": PROV},
                **kw)


def _attr(entry, cost, run="r0", t=1e9):
    return {"ev": "compile_attr", "run": run, "t": t + 1, "entry": entry,
            "n_compiles": 1, "sig": {}, "cost": cost}


def _end(entries, run="r0", t=1e9):
    return {"ev": "run_end", "run": run, "t": t + 9, "iters": 2,
            "phase_totals": {}, "entries": entries, "status": "ok"}


def _write(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    read_events(path)                    # must be schema-valid
    return str(path)


# -------------------------------------------------- peak registry

def test_normalize_kind_and_aliases():
    assert normalize_kind("TPU v4") == "tpu_v4"
    assert normalize_kind("TPU-v5p") == "tpu_v5p"
    assert normalize_kind("tpu_v5e") == "tpu_v5_lite"
    assert normalize_kind("TPU v6e") == "tpu_v6_lite"
    assert normalize_kind("") == ""


def test_peaks_exact_prefix_and_fallback():
    p = peaks_for("TPU v4")
    assert p["kind"] == "tpu_v4" and p["source"] == "table"
    assert p["flops_bf16"] == DEFAULT_PEAKS["tpu_v4"]["flops_bf16"]
    # prefix resolution: a pod-suffixed kind still finds its generation
    assert peaks_for("tpu_v5p_pod")["kind"] == "tpu_v5p"
    # unknown chip degrades to the labelled CPU fallback, never a crash
    q = peaks_for("warp_drive_9000")
    assert q["source"] == "fallback"
    assert q["flops_f32"] == DEFAULT_PEAKS["cpu"]["flops_f32"]
    assert peaks_for("")["source"] == "fallback"
    # every profile carries the full field set
    for prof in DEFAULT_PEAKS.values():
        assert set(prof) == {"flops_f32", "flops_bf16", "hbm_bytes_per_s",
                             "ici_bytes_per_s", "vmem_bytes"}


def test_peak_overrides_merge_over_defaults(tmp_path):
    path = tmp_path / "peaks.json"
    path.write_text(json.dumps({
        "TPU v4": {"hbm_bytes_per_s": 999e9},
        "mychip": {"flops_f32": 1e12},
    }))
    ov = load_peak_overrides(str(path))
    p = peaks_for("tpu_v4", ov)
    assert p["source"] == "override"
    assert p["hbm_bytes_per_s"] == 999e9
    # un-overridden fields keep the table figure (merge, not replace)
    assert p["flops_f32"] == DEFAULT_PEAKS["tpu_v4"]["flops_f32"]
    q = peaks_for("mychip", ov)
    assert q["source"] == "override" and q["flops_f32"] == 1e12
    # unknown chip's remaining fields come from the CPU base profile
    assert q["hbm_bytes_per_s"] == DEFAULT_PEAKS["cpu"]["hbm_bytes_per_s"]


def test_unreadable_overrides_warn_and_disable(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert load_peak_overrides(str(bad)) == {}
    assert load_peak_overrides("") == {}
    assert load_peak_overrides(str(tmp_path / "absent.json")) == {}


# -------------------------------------------------- per-entry join

def test_memory_bound_entry():
    # 25e6 B at 25 GB/s -> 1 ms memory roof; 2 ms measured -> 50% HBM
    r = entry_roofline({"flops": 1e6, "bytes_accessed": 25e6},
                       2e-3, 10, CPU_PEAKS)
    assert r["bound"] == "memory"
    assert r["hbm_util"] == pytest.approx(0.5)
    assert r["flop_util"] == pytest.approx(0.005)
    assert r["achieved_bytes_per_s"] == pytest.approx(12.5e9)
    assert r["ai"] == pytest.approx(1e6 / 25e6)
    # headroom: (2 ms - 1 ms) x 10 calls = 10 ms recoverable
    assert r["headroom_s"] == pytest.approx(1e-2)


def test_compute_bound_entry_and_util_cap():
    # 100e6 FLOP at 100 GFLOP/s -> 1 ms compute roof
    r = entry_roofline({"flops": 100e6, "bytes_accessed": 1e3},
                       2e-3, 1, CPU_PEAKS)
    assert r["bound"] == "compute"
    assert r["flop_util"] == pytest.approx(0.5)
    # measured faster than the model's roof: utilization clips at 1.0
    fast = entry_roofline({"flops": 100e6, "bytes_accessed": 1e3},
                          1e-4, 1, CPU_PEAKS)
    assert fast["flop_util"] == 1.0 and fast["headroom_s"] == 0.0


def test_collective_bound_needs_world_size():
    cost = {"flops": 1e3, "bytes_accessed": 1e3}
    # 10e6 ICI bytes at 10 GB/s -> 1 ms; dominates at world_size > 1
    r = entry_roofline(cost, 2e-3, 1, CPU_PEAKS, ici_bytes=10e6,
                      world_size=2)
    assert r["bound"] == "collective"
    assert r["ici_util"] == pytest.approx(0.5)
    # single-process runs ignore ICI byte estimates entirely
    r1 = entry_roofline(cost, 2e-3, 1, CPU_PEAKS, ici_bytes=10e6,
                        world_size=1)
    assert "ici_util" not in r1 and r1["bound"] != "collective"


def test_host_orchestration_floor():
    # just above the floor on the memory roof -> still memory-bound
    near = entry_roofline(
        {"flops": 0.0, "bytes_accessed": (ORCH_FLOOR + 0.001) * 25e9},
        1.0, 1, CPU_PEAKS)
    assert near["bound"] == "memory"
    # under the floor on EVERY roof -> the time bought dispatch glue
    r = entry_roofline(
        {"flops": 0.0, "bytes_accessed": (ORCH_FLOOR - 0.001) * 25e9},
        1.0, 1, CPU_PEAKS)
    assert r["bound"] == "host-orchestration"
    # no cost estimate at all: zero utilization, host-orchestration
    none = entry_roofline(None, 1e-3, 5, CPU_PEAKS)
    assert none["bound"] == "host-orchestration"
    assert none["flop_util"] == 0.0 and none["ai"] is None
    assert {r["bound"], near["bound"], none["bound"]} <= set(BOUNDS)


def test_zero_exec_time_is_safe():
    r = entry_roofline({"flops": 1e6, "bytes_accessed": 1e6}, 0.0, 0,
                       CPU_PEAKS)
    assert r["flop_util"] == 0.0 and r["headroom_s"] == 0.0
    assert r["bound"] == "host-orchestration"


# -------------------------------------------------- timeline join

def _timeline(kind="cpu"):
    return [
        _header(kind=kind),
        _attr("tree_grow", {"flops": 1e6, "bytes_accessed": 25e6}),
        _end({
            # memory-bound with 1 ms headroom per call, 10 calls
            "tree_grow": {"exec_mean_s": 2e-3, "exec_n": 10,
                          "exec_total_s": 2e-2, "first_s": 0.5},
            # timed entry XLA never modelled: host-orchestration
            "boost": {"exec_mean_s": 1e-4, "exec_n": 10,
                      "exec_total_s": 1e-3, "first_s": 0.1},
        }),
    ]


def test_timeline_roofline_ranks_by_headroom():
    res = timeline_roofline(_timeline())
    assert res["problems"] == []
    assert res["device_kind"] == "cpu"
    assert res["peaks"]["source"] == "fallback" or \
        res["peaks"]["kind"] == "cpu"
    rows = res["rows"]
    assert [r["entry"] for r in rows] == ["tree_grow", "boost"]
    grow, boost = rows
    assert grow["has_cost"] and grow["bound"] == "memory"
    assert grow["headroom_s"] == pytest.approx(1e-2)
    assert not boost["has_cost"]
    assert boost["bound"] == "host-orchestration"
    assert boost["exec_total_s"] == pytest.approx(1e-3)


def test_last_compile_attr_cost_wins():
    evs = _timeline()
    # a later recompile supersedes the warmup program's estimate
    evs.insert(2, _attr("tree_grow", {"flops": 5e7,
                                      "bytes_accessed": 1e3}, t=2e9))
    row = timeline_roofline(evs)["rows"][0]
    assert row["entry"] == "tree_grow"
    assert row["flops"] == 5e7 and row["bound"] == "compute"


def test_timeline_problems():
    # no run_end at all: nothing to attribute
    res = timeline_roofline([_header()])
    assert any("run_end" in p for p in res["problems"])
    # timed entries but zero cost estimates: tell them to turn on
    # obs_compile rather than rendering an all-orchestration table
    evs = [_header(), _end({"tree_grow": {"exec_mean_s": 1e-3,
                                          "exec_n": 2,
                                          "exec_total_s": 2e-3}})]
    res = timeline_roofline(evs)
    assert any("obs_compile" in p for p in res["problems"])


# -------------------------------------------------- utilization rollup

def test_utilization_rollup_weighted_mean():
    summary = {
        # hbm_util 0.5, weight 1.0 s, headroom 0.5 s
        "a": {"exec_mean_s": 1.0, "exec_n": 1, "exec_total_s": 1.0},
        # hbm_util 0.1, weight 3.0 s, headroom 2.7 s (the worst)
        "b": {"exec_mean_s": 3.0, "exec_n": 1, "exec_total_s": 3.0},
    }
    costs = {"a": {"flops": 1.0, "bytes_accessed": 12.5e9},
             "b": {"flops": 1.0, "bytes_accessed": 7.5e9}}
    roll = utilization_rollup(summary, costs, CPU_PEAKS)
    assert roll["hbm_util"] == pytest.approx((0.5 * 1 + 0.1 * 3) / 4.0)
    assert roll["headroom_s"] == pytest.approx(0.5 + 2.7)
    assert roll["bound"] == "memory"          # the worst entry's bound
    assert roll["device_kind"] == "cpu"
    assert roll["roof_source"] == "table"
    assert set(roll["entries"]) == {"a", "b"}
    assert roll["entries"]["a"]["hbm_util"] == pytest.approx(0.5)
    assert all(v["bound"] in BOUNDS for v in roll["entries"].values())


def test_rollup_none_without_costs():
    summary = {"a": {"exec_mean_s": 1.0, "exec_n": 1,
                     "exec_total_s": 1.0}}
    assert utilization_rollup(summary, {}, CPU_PEAKS) is None
    assert utilization_rollup({}, {"a": {"flops": 1.0}}, CPU_PEAKS) is None
    # entries without a cost estimate are skipped, not zero-averaged
    roll = utilization_rollup(
        dict(summary, b={"exec_mean_s": 9.0, "exec_n": 1,
                         "exec_total_s": 9.0}),
        {"a": {"flops": 1.0, "bytes_accessed": 12.5e9}}, CPU_PEAKS)
    assert set(roll["entries"]) == {"a"}


def test_utilization_event_emitted_from_training(tmp_path):
    """End to end: obs_utilization_every rides the iter path, implies
    the compile tracker, and every rollup validates under schema 13."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    path = str(tmp_path / "tl.jsonl")
    lgb.train({"objective": "binary", "num_leaves": 7, "max_bin": 15,
               "verbose": -1, "obs_events_path": path,
               "obs_timing": "iter", "obs_utilization_every": 2},
              lgb.Dataset(X, label=y), num_boost_round=4)
    evs = read_events(path)              # validates every record
    header = next(e for e in evs if e["ev"] == "run_header")
    assert header["schema"] == SCHEMA_VERSION >= 13
    utils = [e for e in evs if e["ev"] == "utilization"]
    assert utils, "obs_utilization_every=2 emitted no rollups"
    assert [u["it"] for u in utils] == [0, 2]
    for u in utils:
        assert 0.0 <= u["flop_util"] <= 1.0
        assert 0.0 <= u["hbm_util"] <= 1.0
        assert u["bound"] in BOUNDS
        assert u["entries"]
        assert all(v["bound"] in BOUNDS for v in u["entries"].values())
        assert u["roof_source"] in ("table", "override", "fallback")
        assert u["device_kind"]
    # the timeline must also satisfy the CLI gate it feeds in CI
    assert obs_main(["roofline", path, "--check"]) == 0


# -------------------------------------------------- obs roofline CLI

def test_cli_renders_table_and_passes_check(tmp_path, capsys):
    p = _write(tmp_path / "tl.jsonl", _timeline())
    assert obs_main(["roofline", p]) == 0
    out = capsys.readouterr().out
    assert "== roofline: cpu" in out
    assert "tree_grow" in out and "boost" in out
    assert "(no cost estimate)" in out      # the boost entry's suffix
    assert "memory" in out and "host-orchestration" in out
    assert "total headroom" in out and "bound mix" in out
    assert obs_main(["roofline", p, "--check"]) == 0


def test_cli_check_exit_codes(tmp_path, capsys):
    # structurally unusable timelines fail the gate with exit 1 ...
    no_cost = _write(tmp_path / "nc.jsonl", [
        _header(), _end({"tree_grow": {"exec_mean_s": 1e-3, "exec_n": 2,
                                       "exec_total_s": 2e-3}})])
    assert obs_main(["roofline", no_cost, "--check"]) == 1
    assert "PROBLEM" in capsys.readouterr().out
    no_end = _write(tmp_path / "ne.jsonl", [_header()])
    assert obs_main(["roofline", no_end, "--check"]) == 1
    # ... but render informationally without --check
    assert obs_main(["roofline", no_cost]) == 0
    # and a missing file is a usage error, matching the other subcommands
    assert obs_main(["roofline", str(tmp_path / "nope.jsonl")]) == 2


def test_cli_peaks_override(tmp_path, capsys):
    peaks = tmp_path / "peaks.json"
    peaks.write_text(json.dumps({"cpu": {"hbm_bytes_per_s": 50e9}}))
    p = _write(tmp_path / "tl.jsonl", _timeline())
    assert obs_main(["roofline", p, "--peaks", str(peaks)]) == 0
    out = capsys.readouterr().out
    assert "override peaks" in out
    assert "50.00 GB" in out.replace("GiB", "GB") or "46.57 GiB" in out


# -------------------------------------------------- autotune stamping

def test_cell_traffic_model():
    from lightgbm_tpu.ops.autotune import Cell, ShapeBucket
    bucket = ShapeBucket(ncols=28, bin_pad=64, num_leaves=255,
                         n_bucket=1 << 20)
    hilo = Cell("pallas_ct", 8, True, False)
    flops, nbytes = cell_traffic(bucket, hilo)
    n = float(1 << 20)
    assert flops == pytest.approx(2.0 * n * 28 * 8)
    assert nbytes == pytest.approx(n * 28 + n * 8.0 * 8
                                   + 8 * 64 * 28 * 8.0)
    # the bf16 trade halves the gradient/hessian read traffic
    _, nb_bf16 = cell_traffic(bucket, Cell("pallas_ct", 8, False, False))
    assert nb_bf16 == pytest.approx(nbytes - n * 4.0 * 8)


def test_cell_roofline_stamp_shape():
    from lightgbm_tpu.ops.autotune import Cell, ShapeBucket
    bucket = ShapeBucket(28, 64, 255, 1 << 16)
    stamp = cell_roofline(bucket, Cell("pallas_t", 8, True, False),
                          s_per_wave=1e-3, kind="tpu_v4")
    assert set(stamp) == {"flop_util", "hbm_util", "ai", "bound",
                          "device_kind", "roof_source"}
    assert stamp["device_kind"] == "tpu_v4"
    assert stamp["roof_source"] == "table"
    assert stamp["bound"] in BOUNDS
    assert 0.0 <= stamp["flop_util"] <= 1.0
    # the stamp validates as an autotune_probe optional field
    validate_event({"ev": "autotune_probe", "t": 1.0, "run": "r0",
                    "cell": {}, "s_per_wave": 1e-3, "roofline": stamp},
                   strict=True)


def test_measure_cells_stamps_every_probe():
    from lightgbm_tpu.ops.autotune import (Cell, ShapeBucket,
                                           clear_probe_hooks,
                                           install_probe_hooks,
                                           measure_cells)
    bucket = ShapeBucket(8, 64, 15, 2048)
    cells = [Cell("pallas_t", 8, True, False),
             Cell("pallas_ct", 4, False, False)]
    events = []
    install_probe_hooks(bench=lambda cell, b: 1e-3)
    try:
        out = measure_cells(cells, bucket, None, 2, events)
    finally:
        clear_probe_hooks()
    assert len(out) == 2 and len(events) == 2
    for name, fields in events:
        assert name == "autotune_probe"
        stamp = fields["roofline"]
        assert stamp is not None and stamp["bound"] in BOUNDS


def test_explain_prints_roofline_position(tmp_path, capsys):
    assert describe_roofline_position(
        {"bound": "memory", "hbm_util": 0.71}) == "71% HBM"
    assert describe_roofline_position(
        {"bound": "compute", "flop_util": 0.12}) == "12% MXU"
    assert describe_roofline_position(
        {"bound": "collective", "ici_util": 0.4}) == "40% ICI"
    assert "host-orchestration" in describe_roofline_position(
        {"bound": "host-orchestration", "hbm_util": 0.01})
    assert describe_roofline_position(None) == ""
    assert describe_roofline_position({}) == ""
    cell = {"hist_mode": "pallas_ct", "wave_width": 8,
            "hist_hilo": True, "compact": False}
    p = _write(tmp_path / "tl.jsonl", [
        _header(),
        {"ev": "autotune_decision", "run": "r0", "t": 1e9 + 1,
         "mode": "measure", "source": "measured", "cell": cell,
         "cells": [
             {"cell": cell, "s_per_wave": 1e-3,
              "roofline": {"bound": "memory", "hbm_util": 0.71}},
             {"cell": dict(cell, hist_mode="pallas_t"),
              "s_per_wave": 2e-3,
              "roofline": {"bound": "memory", "hbm_util": 0.34}}]},
        _end({}),
    ])
    assert obs_main(["explain", p]) == 0
    out = capsys.readouterr().out
    assert "[at 71% HBM]" in out and "[at 34% HBM]" in out
    assert "<- winner" in out


# -------------------------------------------------- serve tier

def test_serve_roofline_joins_bucket_executables():
    from lightgbm_tpu.obs.serve import serve_roofline
    evs = [
        _header(),
        _attr("serve_predict_b256", {"flops": 1e6,
                                     "bytes_accessed": 25e6}),
        _attr("serve_predict_b512_conv", {"flops": 1e6,
                                          "bytes_accessed": 1e6}),
        {"ev": "serve_batch", "run": "r0", "t": 1e9 + 2,
         "route": "predict", "rows": 200, "bucket": 256, "pad": 56,
         "requests": 1, "queue_s": 1e-4, "exec_s": 2e-3},
    ]
    rows = serve_roofline(evs)
    by_entry = {r["entry"]: r for r in rows}
    timed = by_entry["serve_predict_b256"]
    assert timed["timed"] and timed["bucket"] == 256
    assert timed["hbm_util"] == pytest.approx(0.5)
    assert timed["bound"] == "memory"
    untimed = by_entry["serve_predict_b512_conv"]
    assert not untimed["timed"] and untimed["bucket"] == 512
    assert untimed["exec_n"] == 0
    # non-serve timelines produce no rows (the report section is absent)
    assert serve_roofline([_header()]) == []


# -------------------------------------------------- recompiles units

def test_recompiles_humanized_cost_tags(tmp_path, capsys):
    p = _write(tmp_path / "tl.jsonl", [
        _header(),
        _attr("tree_grow", {"flops": 2.5e9,
                            "bytes_accessed": 3 * 2**20}),
        _end({}),
    ])
    assert obs_main(["recompiles", p]) == 0
    out = capsys.readouterr().out
    assert "2.50 GFLOP" in out and "3.00 MiB" in out
    assert "2500000000" not in out          # no raw-unit spelunking


def test_fmt_helpers():
    assert fmt_quantity(2.5e9, "FLOP") == "2.50 GFLOP"
    assert fmt_quantity(1e3) == "1.00 K"
    assert fmt_quantity(12) == "12"
    assert fmt_quantity(3.2e13, "FLOP") == "32.00 TFLOP"
    assert fmt_bytes(3 * 2**20) == "3.00 MiB"
    assert fmt_bytes(512) == "512 B"
    assert fmt_bytes(1.5 * 2**30) == "1.50 GiB"


# -------------------------------------------------- shared cost parser

class _FakeCompiled:
    """cost_analysis in the LIST form recent jax CPU backends return."""

    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        return self._ca

    def memory_analysis(self):
        raise NotImplementedError


class _FakeJitted:
    def __init__(self, compiled):
        self._compiled = compiled

    def lower(self, *args, **kw):
        return self

    def compile(self):
        return self._compiled


def test_parse_compiled_handles_list_and_dict_forms():
    want = {"cost": {"flops": 5.0, "bytes_accessed": 7.0}}
    listed = _FakeCompiled([{"flops": 5.0, "bytes accessed": 7.0}])
    assert parse_compiled(listed) == want
    bare = _FakeCompiled({"flops": 5.0, "bytes accessed": 7.0})
    assert parse_compiled(bare) == want
    assert parse_compiled(_FakeCompiled([])) == {}
    # the JIT call site reads through the same parser
    assert analyze_compiled(_FakeJitted(listed), (1,)) == want


def test_serve_executable_uses_shared_parser():
    """Regression guard for the dedup: serve/executable.py must read
    XLA analyses through obs/compile.parse_compiled rather than a
    private copy (the list-form quirk is handled exactly once)."""
    from lightgbm_tpu.serve import executable
    assert executable.parse_compiled is parse_compiled
    assert not hasattr(executable, "_compiled_analysis")


# -------------------------------------------------- ledger lockstep

def _util_timeline():
    t = 1e9
    return [
        _header(t=t),
        {"ev": "iter", "run": "r0", "t": t + 1, "it": 0, "time_s": 0.5,
         "phases": {}, "fenced": True},
        {"ev": "iter", "run": "r0", "t": t + 2, "it": 1, "time_s": 0.5,
         "phases": {}, "fenced": True},
        {"ev": "utilization", "run": "r0", "t": t + 3, "it": 0,
         "flop_util": 0.9, "hbm_util": 0.9, "bound": "memory",
         "entries": {"tree_grow": {"bound": "memory"}}},
        # the LAST rollup is the steady-state figure readers keep
        {"ev": "utilization", "run": "r0", "t": t + 4, "it": 1,
         "flop_util": 0.25, "hbm_util": 0.5, "bound": "memory",
         "entries": {"tree_grow": {"bound": "memory"}}},
        _end({}, t=t),
    ]


def test_ledger_reads_last_utilization_rollup():
    m = metrics_from_events(_util_timeline())
    assert m["flop_util"] == pytest.approx(0.25)
    assert m["hbm_util"] == pytest.approx(0.5)
    # and both are gated metric directions (higher is better)
    from lightgbm_tpu.obs.ledger import METRIC_DIRECTIONS
    assert METRIC_DIRECTIONS["flop_util"] == +1
    assert METRIC_DIRECTIONS["hbm_util"] == +1


def test_bench_compare_extracts_utilization_in_lockstep(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_compare
    finally:
        sys.path.pop(0)
    p = _write(tmp_path / "tl.jsonl", _util_timeline())
    m = bench_compare._from_timeline(read_events(p))
    assert m["flop_util"] == pytest.approx(0.25)
    assert m["hbm_util"] == pytest.approx(0.5)
    assert bench_compare.METRICS["flop_util"][0] == +1
    assert bench_compare.METRICS["hbm_util"][0] == +1
    # self-compare must pass with the new gated metrics present
    assert bench_compare.main([p, p]) == 0
