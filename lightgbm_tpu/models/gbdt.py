"""GBDT booster: the training loop, bagging, scores, eval, model I/O.

Parity target: src/boosting/gbdt.cpp / gbdt.h.  Mirrored behaviors:

* boost_from_average stub tree on the first iteration for single-class
  regression-style objectives (gbdt.cpp:339-362);
* degenerate-class skip with constant default output (gbdt.cpp:166-205);
* bagging re-drawn every ``bagging_freq`` iterations with exact
  ``bagging_fraction`` count (gbdt.cpp:242-324) — realized as a per-row
  0/1 multiplier folded into the histogram weights instead of index
  re-partitioning (TPU-friendly; same leaf statistics);
* early stopping bookkeeping per (valid set, metric) with
  factor_to_bigger_better and model pop-back (gbdt.cpp:527-585,479-500);
* rollback (gbdt.cpp:460-477);
* model text format round-trip (gbdt.cpp:817-971) — the compatibility
  surface shared with the reference line;
* split-count feature importance (gbdt.cpp:973-997).

TPU-first design: train/valid scores are DEVICE arrays; a fast-path
iteration (gradients -> grow tree -> partition score update -> valid
traversal updates) is a handful of async XLA dispatches with **zero host
round-trips** — essential because the accelerator may sit behind a
high-latency link.  Host numpy mirrors are pulled lazily (metric eval,
custom fobj) and trees are materialized lazily in one stacked transfer.
Scores layout is the reference's column-major flat array, shaped
(num_tree_per_iteration, num_data).
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import TrainingData
from ..metrics import Metric
from ..obs import NULL_OBSERVER, observer_from_config
from ..obs.timers import OrchestrationClock, fenced_get
from ..objectives import ObjectiveFunction, load_objective_from_string
from ..ops.learner import SerialTreeLearner, materialize_tree
from ..ops import predict as dev_predict
from ..utils.config import Config
from ..utils.common import parse_kv_lines
from ..utils.log import Log
from .tree import Tree

kEpsilon = 1e-15


class _NullOrchestration:
    """No-op stand-in for OrchestrationClock when telemetry is off — the
    disabled hot path must not construct obs objects (the allocation
    guard in tests/test_obs.py)."""
    __slots__ = ()

    def enter(self):
        pass

    def exit(self):
        pass

    def host_seconds(self):
        return 0.0


_NULL_ORCH = _NullOrchestration()


class GBDT:
    """Gradient Boosting Decision Tree (boosting.h:21-261 interface)."""

    def __init__(self, config: Config,
                 train_data: Optional[TrainingData] = None,
                 objective: Optional[ObjectiveFunction] = None,
                 training_metrics: Sequence[Metric] = ()):
        self.config = config
        # models: host Trees; None entries are pending materialization from
        # the aligned _models_dev/_models_shrink slots
        self.models: List[Optional[Tree]] = []
        self._models_dev: List[Optional[object]] = []
        self._models_shrink: List[float] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.boost_from_average_used = False
        self.num_class = config.num_class if config else 1
        self.max_feature_idx = 0
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.objective = objective
        self.shrinkage_rate = config.learning_rate
        self.early_stopping_round = config.early_stopping_round
        self.train_data: Optional[TrainingData] = None
        self.learner: Optional[SerialTreeLearner] = None
        self.training_metrics: List[Metric] = list(training_metrics)
        self.valid_data: List[TrainingData] = []
        self.valid_metrics: List[List[Metric]] = []
        self._valid_X_dev: List[jnp.ndarray] = []
        self._valid_score_dev: List[jnp.ndarray] = []
        self._valid_score_host: List[Optional[np.ndarray]] = []
        self.best_score: List[List[float]] = []
        self.best_iter: List[List[int]] = []
        self.best_msg: List[List[str]] = []
        self._score_dev: Optional[jnp.ndarray] = None
        self._score_host: Optional[np.ndarray] = None
        self._obs = NULL_OBSERVER
        self._metrics = None
        # serving-time drift reference (obs/drift.py): lazily completed
        # from the dataset fingerprint + train scores + last eval, or
        # restored verbatim from the model text header
        self._drift_fingerprint: Optional[dict] = None
        self._last_eval_results: List[dict] = []
        # lazily-resolved fused iteration (ops/fused_iter.py): None =
        # unresolved; (obj_or_None,) = resolved.  Invalidated whenever
        # the learner / objective / observer it binds is rebuilt.
        self._fused_state = None
        self.num_tree_per_iteration = 1
        if objective is not None:
            self.num_tree_per_iteration = objective.num_tree_per_iteration()
            self.is_constant_hessian = objective.is_constant_hessian()
        else:
            self.num_tree_per_iteration = max(1, self.num_class)
            self.is_constant_hessian = False
        if train_data is not None:
            self.reset_training_data(config, train_data, objective,
                                     training_metrics)

    # ----------------------------------------------------------------- setup
    def _resolve_score_engine(self, config: Config) -> None:
        se = str(config.tpu_score_update).strip().lower()
        if se not in ("auto", "gather", "pallas"):
            Log.fatal("Unknown tpu_score_update %s (expected auto/"
                      "gather/pallas)", config.tpu_score_update)
        # Round-5 promotion (pre-registered rule, BENCH_NOTES.md "Armed
        # decks"; measured tools/BENCH_SUITE.md 15:50 block): auto ->
        # the pallas compare-select kernel — 1.45 vs 1.30 it/s at the
        # 10.5M flagship with EXACTLY equal AUC (0.89295, the bit-equal
        # claim held on chip).  The dispatch itself (ops/predict.py)
        # still gates on TPU + num_leaves<=512 + f32 score and falls
        # back to the XLA gather otherwise, so 'auto' is safe to
        # resolve unconditionally here.
        self._score_engine = "pallas" if se == "auto" else se

    def _reset_observer(self, config: Config) -> None:
        """Build the run observer (lightgbm_tpu/obs) for this training
        dataset and emit the run header.  All-default obs params leave the
        shared NULL observer in place — the hot path then pays one
        attribute load and an empty call per hook, no fencing, no event
        objects."""
        prev = getattr(self, "_obs", NULL_OBSERVER)
        if prev.enabled:
            prev.close()
        self._obs = observer_from_config(
            config, comm=getattr(self.train_data, "_comm", None))
        self._metrics = None
        # model-observability cadence (obs/model.py): split audit + top-k
        # importance snapshots, both host-side on materialized trees
        self._obs_split_audit = bool(getattr(config, "obs_split_audit",
                                             False))
        self._obs_importance_every = int(
            getattr(config, "obs_importance_every", 0) or 0)
        self._obs_importance_topk = int(
            getattr(config, "obs_importance_topk", 20) or 20)
        if self._obs.enabled:
            devices = [{"id": int(d.id), "platform": str(d.platform),
                        "kind": str(getattr(d, "device_kind", ""))}
                       for d in jax.devices()]
            self._obs.run_header(
                backend=str(jax.default_backend()), devices=devices,
                params={k: str(v) for k, v in self.config.raw.items()},
                context=self.learner.obs_info())
            collective_info = getattr(self.learner, "collective_info", None)
            if collective_info is not None:
                self._obs.event("collectives", **collective_info())
            # arm the continuous host sampling profiler (obs/prof.py,
            # obs_prof_hz) for the run; finalize_telemetry -> obs.close()
            # disarms and flushes the final prof_profile window
            self._obs.prof_arm()
            # registry instruments are only touched when the observer is
            # on — the disabled hot path stays allocation-free (pinned by
            # the overhead guard in tests/test_obs.py)
            from ..obs import REGISTRY
            self._metrics = {
                "trees": REGISTRY.counter(
                    "lgbm_trees_built_total",
                    "trees grown on device by the training loop"),
                "leaves": REGISTRY.counter(
                    "lgbm_tree_leaves_built_total",
                    "leaves across materialized trained trees"),
            }
            nbins = getattr(self.train_data, "num_bin_arr", None)
            if nbins is not None:
                REGISTRY.counter(
                    "lgbm_dataset_bins_built_total",
                    "feature-discretization bins constructed for "
                    "training datasets").inc(int(np.sum(nbins)))
            # construction-phase accounting captured by io/dataset.py and
            # io/streaming.py: rows/chunks, sketch/bin/write phase
            # seconds, peak RSS, workers — the schema-v9 event
            # bench_compare gates (`construct_s`, --tol-construct)
            cstats = getattr(self.train_data, "_construct_stats", None)
            if cstats is not None:
                self._obs.event("dataset_construct", **cstats)
            # data-quality profile captured at Dataset construction
            # (io/dataset.py _profile_quality); may Log.fatal under
            # obs_health=fatal on a degenerate dataset — before any
            # iteration burns device time
            profile = getattr(self.train_data, "_data_profile", None)
            if (profile is not None
                    and bool(getattr(config, "obs_data_profile", True))):
                from ..obs import dataquality
                label_prof = dataquality.label_profile(
                    self.train_data.metadata.label)
                findings = dataquality.build_findings(
                    profile, label_prof,
                    getattr(self.train_data, "feature_names", None))
                dataquality.emit_data_profile(
                    self._obs, profile, label_prof, findings,
                    health_mode=str(getattr(config, "obs_health", "off")
                                    or "off").strip().lower())
        self.learner.set_observer(self._obs)

    def reset_config(self, config: Config) -> None:
        """GBDT::ResetConfig (gbdt.cpp:64-74): re-read training
        hyperparameters IN PLACE — training scores and the device-resident
        dataset are untouched, so a per-iteration reset_parameter callback
        costs one learner rebuild, not an O(num_trees) score replay plus a
        dataset re-upload (that full path is reset_training_data)."""
        # flush pending device trees first: _materialize stacks them, and
        # trees grown under the old num_leaves must not mix shapes with
        # trees grown under the new one
        self._materialize()
        self.config = config
        self.early_stopping_round = config.early_stopping_round
        self.shrinkage_rate = config.learning_rate
        self._resolve_score_engine(config)
        from ..ops.learner import SerialTreeLearner
        from ..parallel.mesh import create_tree_learner
        old = self.learner
        from ..ops.sparse_mxu import ChunkedSparseStore
        from ..ops.sparse_store import SparseDeviceStore
        old_sparse = isinstance(getattr(old, "X", None),
                                (SparseDeviceStore, ChunkedSparseStore))
        if (type(old) is SerialTreeLearner and old_sparse
                and bool(config.tpu_sparse)):
            # reuse the device sparse store — train_data is unchanged on a
            # hyperparameter reset, so the store is too
            self.learner = SerialTreeLearner(
                config, self.train_data, device_data=old.X,
                device_sparse_col_cap=old.sparse_col_cap)
        elif (type(old) is SerialTreeLearner and not old_sparse
                and not bool(config.tpu_sparse)   # sparse request rebuilds
                and old.X.shape[0]
                == self.train_data.num_data + old._row_pad):
            # reuse the uploaded (padded) bin matrix — no host->device
            # transfer on a hyperparameter reset
            self.learner = SerialTreeLearner(
                config, self.train_data, device_data=old.X,
                device_row_pad=old._row_pad,
                device_packed_cols=getattr(old, "packed_cols", 0))
        else:
            self.learner = create_tree_learner(config, self.train_data)
        # re-attach the run observer to the rebuilt learner so entry-point
        # timing survives a reset_parameter callback
        self.learner.set_observer(self._obs)
        # the fused iteration binds the OLD learner's grow closure
        self._fused_state = None
        # bagging state (gbdt.cpp ResetBaggingConfig, :134-160)
        self.bag_data_cnt = self.num_data
        self.row_mult = None
        if config.bagging_fraction < 1.0 and config.bagging_freq > 0:
            self.bag_data_cnt = int(config.bagging_fraction * self.num_data)

    def reset_training_data(self, config: Config, train_data: TrainingData,
                            objective: Optional[ObjectiveFunction],
                            training_metrics: Sequence[Metric]) -> None:
        """GBDT::ResetTrainingData (gbdt.cpp:76-208)."""
        self.config = config
        self.objective = objective
        self.early_stopping_round = config.early_stopping_round
        self.shrinkage_rate = config.learning_rate
        if objective is not None:
            self.num_tree_per_iteration = objective.num_tree_per_iteration()
            self.is_constant_hessian = objective.is_constant_hessian()
        self.train_data = train_data
        self.num_data = train_data.num_data
        from ..parallel.mesh import create_tree_learner
        self.learner = create_tree_learner(config, train_data)
        self.score_dtype = self.learner.dtype
        self._resolve_score_engine(config)
        self._reset_observer(config)
        # new learner + objective + observer: re-resolve the fused program
        self._fused_state = None
        self.training_metrics = list(training_metrics)
        self.max_feature_idx = train_data.num_total_features - 1
        self.feature_names = list(train_data.feature_names)
        self.feature_infos = train_data.feature_infos()

        k = self.num_tree_per_iteration
        init = train_data.metadata.init_score
        self.has_init_score = init is not None
        score0 = np.zeros((k, self.num_data), dtype=np.float64)
        if self.has_init_score:
            if len(init) % self.num_data != 0 or len(init) // self.num_data != k:
                Log.fatal("number of class for initial score error")
            score0[:] = np.asarray(init).reshape(k, self.num_data)
        self._score_dev = jnp.asarray(score0, self.score_dtype)
        self._score_host = None
        # re-apply every existing model (incl. loaded/continued ones) on the
        # (possibly new) training data
        self._materialize()
        for t, tree in enumerate(self.models):
            self._apply_tree_to_train(tree, t % k)

        # degenerate class handling (gbdt.cpp:166-205)
        self.class_need_train = [True] * k
        self.class_default_output = [0.0] * k
        if objective is not None and objective.skip_empty_class():
            label = np.asarray(train_data.metadata.label)
            if k > 1:
                for i in range(k):
                    cnt = int((label.astype(np.int32) == i).sum())
                    if cnt == self.num_data:
                        self.class_need_train[i] = False
                        self.class_default_output[i] = -np.log(kEpsilon)
                    elif cnt == 0:
                        self.class_need_train[i] = False
                        self.class_default_output[i] = -np.log(1.0 / kEpsilon - 1.0)
            else:
                cnt_pos = int((label > 0).sum())
                if cnt_pos == 0:
                    self.class_need_train[0] = False
                    self.class_default_output[0] = -np.log(1.0 / kEpsilon - 1.0)
                elif cnt_pos == self.num_data:
                    self.class_need_train[0] = False
                    self.class_default_output[0] = -np.log(kEpsilon)

        # bagging state (gbdt.cpp ResetBaggingConfig, :134-160)
        self.bag_data_cnt = self.num_data
        self.row_mult: Optional[jnp.ndarray] = None
        if config.bagging_fraction < 1.0 and config.bagging_freq > 0:
            self.bag_data_cnt = int(config.bagging_fraction * self.num_data)

    def add_valid_dataset(self, valid_data: TrainingData,
                          valid_metrics: Sequence[Metric]) -> None:
        """GBDT::AddValidDataset (gbdt.cpp:210-240)."""
        k = self.num_tree_per_iteration
        score = np.zeros((k, valid_data.num_data), dtype=np.float64)
        init = valid_data.metadata.init_score
        if init is not None:
            score[:] = np.asarray(init).reshape(k, valid_data.num_data)
        from ..ops.learner import paged_device_matrix
        # out-of-core valid sets upload shard-by-shard (no host matrix)
        Xv = paged_device_matrix(valid_data)
        if Xv is None:
            Xv = jnp.asarray(valid_data.binned)
        score_dev = jnp.asarray(score, self.score_dtype)
        self.valid_data.append(valid_data)
        self._valid_X_dev.append(Xv)
        self._valid_score_dev.append(score_dev)
        self._valid_score_host.append(None)
        vi = len(self.valid_data) - 1
        # apply existing models
        self._materialize()
        for t, tree in enumerate(self.models):
            self._apply_tree_to_valid(tree, vi, t % k)
        self.valid_metrics.append(list(valid_metrics))
        self.best_score.append([-np.inf] * len(valid_metrics))
        self.best_iter.append([0] * len(valid_metrics))
        self.best_msg.append([""] * len(valid_metrics))

    # ------------------------------------------------------ score management
    @property
    def train_score(self) -> np.ndarray:
        """Host mirror of the training scores (pull-on-demand)."""
        if self._score_host is None:
            self._score_host = np.asarray(self._score_dev, dtype=np.float64)
        return self._score_host

    def valid_score_host(self, i: int) -> np.ndarray:
        if self._valid_score_host[i] is None:
            self._valid_score_host[i] = np.asarray(self._valid_score_dev[i],
                                                   dtype=np.float64)
        return self._valid_score_host[i]

    def _invalidate_train(self):
        self._score_host = None

    def _invalidate_valid(self, i: int):
        self._valid_score_host[i] = None

    def _apply_tree_to_train(self, tree: Tree, tid: int, scale: float = 1.0):
        """Add a host tree's prediction to the train score (device traversal
        when bin thresholds exist, raw-data fallback for loaded models)."""
        if tree.num_leaves <= 1:
            return
        from ..ops.sparse_mxu import ChunkedSparseStore
        from ..ops.sparse_store import SparseDeviceStore
        sparse_store = isinstance(self.learner.X,
                                  (SparseDeviceStore, ChunkedSparseStore))
        if tree.has_bin_thresholds and not sparse_store:
            ta = dev_predict.traversal_from_host_tree(tree, self.score_dtype)
            self._score_dev = self._score_dev.at[tid].set(
                dev_predict.add_tree_to_score(
                    self._score_dev[tid], self.learner.X[:self.num_data],
                    ta, jnp.asarray(scale, self.score_dtype),
                    self.learner.bundle_arrays,
                    packed=bool(getattr(self.learner, "packed_cols", 0))))
        elif self.train_data.raw_data is not None:
            s = self.train_score
            s[tid] += scale * tree.predict(self.train_data.raw_data)
            self._score_dev = self._score_dev.at[tid].set(
                jnp.asarray(s[tid], self.score_dtype))
        elif sparse_store:
            Log.fatal("tpu_sparse=true keeps no dense device matrix to "
                      "traverse; DART/rollback/continued training need the "
                      "raw data (keep_raw) under the sparse store")
        else:
            Log.fatal("Cannot apply a loaded model to binned-only data; "
                      "keep raw data when continuing training")
        self._invalidate_train()

    def _apply_tree_to_valid(self, tree: Tree, vi: int, tid: int,
                             scale: float = 1.0):
        if tree.num_leaves <= 1:
            return
        if tree.has_bin_thresholds:
            ta = dev_predict.traversal_from_host_tree(tree, self.score_dtype)
            self._valid_score_dev[vi] = self._valid_score_dev[vi].at[tid].set(
                dev_predict.add_tree_to_score(self._valid_score_dev[vi][tid],
                                              self._valid_X_dev[vi], ta,
                                              jnp.asarray(scale, self.score_dtype),
                                              self.learner.bundle_arrays))
        elif self.valid_data[vi].raw_data is not None:
            s = self.valid_score_host(vi)
            s[tid] += scale * tree.predict(self.valid_data[vi].raw_data)
            self._valid_score_dev[vi] = self._valid_score_dev[vi].at[tid].set(
                jnp.asarray(s[tid], self.score_dtype))
        else:
            Log.fatal("Validation data lacks both bin thresholds and raw data")
        self._invalidate_valid(vi)

    # ---------------------------------------------------- model realization
    def _materialize(self) -> None:
        """Materialize all pending device trees into host Trees (one stacked
        device->host transfer for the whole batch)."""
        pending = [i for i, m in enumerate(self.models) if m is None]
        if not pending:
            return
        devs = [self._models_dev[i] for i in pending]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *devs) \
            if len(devs) > 1 else devs[0]
        host = fenced_get(stacked)      # counted: one sync per batch
        for j, i in enumerate(pending):
            ht = jax.tree_util.tree_map(lambda x: x[j], host) \
                if len(devs) > 1 else host
            tree = materialize_tree(ht, self.train_data,
                                    self.config.num_leaves)
            tree.shrink(self._models_shrink[i])
            self.models[i] = tree
            self._models_dev[i] = None
        if self._metrics is not None:
            # host num_leaves is free here — trees just landed on host
            self._metrics["leaves"].inc(
                sum(self.models[i].num_leaves for i in pending))
        # release device buffers
        self._models_shrink = [0.0 if m is not None else s
                               for m, s in zip(self.models, self._models_shrink)]

    def _append_host_tree(self, tree: Tree) -> None:
        self.models.append(tree)
        self._models_dev.append(None)
        self._models_shrink.append(1.0)

    # --------------------------------------------------------------- bagging
    def _bagging(self, it: int, gradients=None, hessians=None) -> None:
        """Re-draw the bag on schedule (gbdt.cpp:265-324).  The exact-count
        sample is drawn by ranking per-row random keys (same distribution as
        the reference's reservoir chunks; deterministic per seed+iter)."""
        cfg = self.config
        if self.bag_data_cnt < self.num_data and it % cfg.bagging_freq == 0:
            rng = np.random.default_rng(cfg.bagging_seed + it)
            keys = rng.random(self.num_data)
            idx = np.argpartition(keys, self.bag_data_cnt)[:self.bag_data_cnt]
            mult = np.zeros(self.num_data, dtype=np.float32)
            mult[idx] = 1.0
            self.row_mult = jnp.asarray(mult)
            Log.debug("Re-bagging, using %d data to train", self.bag_data_cnt)

    # ------------------------------------------------------------- iteration
    def _resolve_fused_iter(self):
        """Resolve ``tpu_fused_iter`` (auto/on/off) to a built
        FusedIteration, or None for the staged chain.  Resolved once and
        cached — the verdict depends only on booster/learner/objective
        shape, all of which invalidate ``_fused_state`` when rebuilt.

        auto: fuse when eligible AND the win is expected — the TPU
        Pallas wave path is live (dispatch latency is what the fused
        program removes) or the autotuner measured the fused cell as
        this shape bucket's winner.  on: force when eligible; an
        explicit opt-in is never dropped silently, so ineligibility
        warns.  off: never."""
        if self._fused_state is not None:
            return self._fused_state[0]
        mode = str(getattr(self.config, "tpu_fused_iter", "auto")
                   or "auto").strip().lower()
        if mode not in ("auto", "on", "off"):
            Log.fatal("Unknown tpu_fused_iter %s (expected auto/on/off)",
                      self.config.tpu_fused_iter)
        fused = None
        if mode != "off":
            from ..ops import fused_iter as _fi
            ok, why = _fi.fused_supported(self)
            if not ok:
                if mode == "on":
                    Log.warning("tpu_fused_iter=on but the fused iteration "
                                "is unavailable (%s); using the staged "
                                "chain", why)
            else:
                want = mode == "on"
                if mode == "auto":
                    from ..ops.wave import pallas_wave_active
                    lrn = self.learner
                    want = (pallas_wave_active(
                        getattr(lrn, "hist_mode", ""), lrn.dtype)
                        or bool(getattr(lrn, "fused_autotune", False)))
                if want:
                    fused = _fi.FusedIteration.build(
                        self.learner, self.objective.get_gradients,
                        self.num_data, self.score_dtype)
        self._fused_state = (fused,)
        return fused

    def train_one_iter(self, gradients=None, hessians=None,
                       is_eval: bool = True) -> bool:
        """GBDT::TrainOneIter (gbdt.cpp:339-458); returns True to stop."""
        cfg = self.config
        k = self.num_tree_per_iteration
        obs = self._obs
        it0 = self.iter
        obs.iter_begin(it0)
        # iteration-context stamp: what the loop is doing right now, for
        # /statusz and incident evidence bundles (obs/incident.py) —
        # a host dict update, nothing on the device path
        obs.stamp_context(stage="boost", it=it0, trees=len(self.models))
        # host-orchestration accounting (obs/timers.py): everything this
        # method does OUTSIDE the enter()/exit()-bracketed device
        # dispatches is per-iteration host glue — emitted as the
        # schema-11 ``host_orchestration_s`` iter field, the quantity
        # the fused iteration exists to drive to ~0
        oc = OrchestrationClock() if obs.enabled else _NULL_ORCH
        # split-audit needs to know which models this iteration appends
        # (includes the iteration-0 boost_from_average stub, which the
        # audit emitter skips — a stub has no realized split to record)
        start_models = len(self.models)
        # boost from average (gbdt.cpp:341-362)
        if (not self.models and cfg.boost_from_average
                and not self.has_init_score and self.num_class <= 1
                and self.objective is not None
                and self.objective.boost_from_average()):
            label = np.asarray(self.train_data.metadata.label, dtype=np.float64)
            init_score = float(label.sum() / self.num_data)
            stub = Tree(2)
            stub.split(0, 0, False, 0, 0, 0.0, init_score, init_score,
                       0, self.num_data, -1.0, 0, 0, 0.0)
            self._score_dev = self._score_dev + jnp.asarray(init_score,
                                                            self.score_dtype)
            self._invalidate_train()
            for vi in range(len(self.valid_data)):
                self._valid_score_dev[vi] = self._valid_score_dev[vi] + \
                    jnp.asarray(init_score, self.score_dtype)
                self._invalidate_valid(vi)
            self._append_host_tree(stub)
            self.boost_from_average_used = True

        custom = gradients is not None and hessians is not None
        # fused iteration (ops/fused_iter.py): gradients + grow + score
        # update submitted as ONE device entry per tree.  Per-call custom
        # gradients force the staged chain — they are host arrays the
        # fused program cannot see.
        fused = None if custom else self._resolve_fused_iter()
        g_dev = h_dev = None
        if fused is not None:
            # no host gradient section at all: the bag multiplier is the
            # only host-side training input the fused program takes
            # (eligibility excludes the GOSS rescale, so plain _bagging
            # is exactly what _bagging_with_grad would have done)
            self._bagging(self.iter)
            obs.lap("boost")
        elif not custom:
            if self.objective is None:
                Log.fatal("No object function provided")
            oc.enter()
            g_dev, h_dev = self.objective.get_gradients(
                self._score_for_objective())
            oc.exit()
            g_dev = jnp.reshape(g_dev, (k, self.num_data))
            h_dev = jnp.reshape(h_dev, (k, self.num_data))
            gradients = hessians = None
        else:
            gradients = np.array(gradients, dtype=np.float32).reshape(k, self.num_data)
            hessians = np.array(hessians, dtype=np.float32).reshape(k, self.num_data)
            g_dev = jnp.asarray(gradients)
            h_dev = jnp.asarray(hessians)

        if fused is None:
            # bagging / GOSS may need host gradients and may rescale them
            g_dev, h_dev = self._bagging_with_grad(self.iter, g_dev, h_dev)
            # "boost" = objective gradients + bagging (+ first-iter stub
            # tree)
            obs.lap("boost", (g_dev, h_dev))

        # health monitors (obs/health.py): dispatch the finiteness /
        # magnitude reductions async now, verdicts in one sync below
        health = obs.health
        health_leaves = None
        if health is not None and health.due(it0):
            health.stage_gradients(g_dev, h_dev)
            health_leaves = []

        num_leaves_this_iter = []
        last_leaf_id = None
        for tid in range(k):
            if self.class_need_train[tid]:
                if fused is not None:
                    # one dispatch: gradients, the grow while_loop and
                    # the partition score update never return to host
                    # (bit-identical to the staged chain below —
                    # tests/test_fused_iter.py)
                    oc.enter()
                    dev_tree, leaf_id, new_score = fused.run(
                        self._score_dev[tid], self.row_mult, None,
                        jnp.asarray(self.shrinkage_rate, self.score_dtype))
                    obs.lap("grow", leaf_id)
                    self._score_dev = self._score_dev.at[tid].set(new_score)
                    self._invalidate_train()
                    obs.lap("partition", self._score_dev)
                    oc.exit()
                    last_leaf_id = leaf_id
                else:
                    oc.enter()
                    dev_tree, leaf_id = self.learner.train_device(
                        g_dev[tid], h_dev[tid], self.row_mult)
                    if getattr(self.learner, "_nproc", 1) > 1:
                        # multi-host pod: the grow program psums
                        # histograms over the global mesh and hands back
                        # a GLOBAL row->leaf map; scores here stay
                        # rank-LOCAL, so take this process's rows (an
                        # addressable-shard read, no collective)
                        leaf_id = self.learner.local_rows(leaf_id)
                    # "grow" = the histogram+split+partition XLA program
                    # (one jitted entry; finer decomposition needs a
                    # profiler window — see docs/Observability.md)
                    obs.lap("grow", leaf_id)
                    oc.exit()
                    last_leaf_id = leaf_id
                    # device score updates (train via partition, valids
                    # via traversal) — all async
                    oc.enter()
                    self._score_dev = self._score_dev.at[tid].set(
                        dev_predict.update_score_from_partition(
                            self._score_dev[tid], leaf_id,
                            dev_tree.leaf_value,
                            jnp.asarray(self.shrinkage_rate,
                                        self.score_dtype),
                            engine=self._score_engine))
                    self._invalidate_train()
                    obs.lap("partition", self._score_dev)
                    oc.exit()
                oc.enter()
                ta = dev_predict.traversal_from_grow(dev_tree)
                scaled = ta._replace(leaf_value=ta.leaf_value)
                for vi in range(len(self.valid_data)):
                    self._valid_score_dev[vi] = self._valid_score_dev[vi].at[tid].set(
                        dev_predict.add_tree_to_score(
                            self._valid_score_dev[vi][tid],
                            self._valid_X_dev[vi], scaled,
                            jnp.asarray(self.shrinkage_rate,
                                        self.score_dtype),
                            self.learner.bundle_arrays))
                    self._invalidate_valid(vi)
                if self.valid_data:
                    obs.lap("update", self._valid_score_dev[-1])
                oc.exit()
                self.models.append(None)
                self._models_dev.append(dev_tree)
                self._models_shrink.append(self.shrinkage_rate)
                num_leaves_this_iter.append(dev_tree.num_leaves)
                if health_leaves is not None:
                    health_leaves.append(dev_tree.leaf_value)
                if self._metrics is not None:
                    self._metrics["trees"].inc()
            else:
                tree = Tree(2)
                if len(self.models) < k:
                    out = self.class_default_output[tid]
                    tree.split(0, 0, False, 0, 0, 0.0, out, out,
                               0, self.num_data, -1.0, 0, 0, 0.0)
                    self._score_dev = self._score_dev.at[tid].add(
                        jnp.asarray(out, self.score_dtype))
                    self._invalidate_train()
                    for vi in range(len(self.valid_data)):
                        self._valid_score_dev[vi] = \
                            self._valid_score_dev[vi].at[tid].add(
                                jnp.asarray(out, self.score_dtype))
                        self._invalidate_valid(vi)
                self._append_host_tree(tree)

        # snapshot BEFORE the opt-in sync work below (health verdicts,
        # eval, model obs): host_orchestration_s is the per-tree
        # submission glue, not the explicitly-priced sync features
        host_orch = oc.host_seconds()

        if health_leaves is not None:
            # one batched device_get over the staged scalars; may raise
            # LightGBMError under obs_health=fatal
            health.stage_leaf_values(health_leaves)
            health.run_checks(obs, it0)

        if last_leaf_id is not None:
            # straggler sampling (obs/straggler.py, obs_straggler_every):
            # the row->leaf map is the iteration's most row-sharded
            # artifact, so its per-shard arrival order exposes which
            # device the collectives waited on
            obs.straggler_sample(it0, last_leaf_id)

        # stop check: any trained tree must have >1 leaves.  Evaluating the
        # device scalars here costs one sync; skip it when nothing forces a
        # sync anyway (pure fast path) and rely on the periodic check.
        should_continue = True
        if num_leaves_this_iter:
            if is_eval or (self.iter % 16 == 0):
                should_continue = any(int(nl) > 1
                                      for nl in fenced_get(num_leaves_this_iter))
                comm = self._dist_comm()
                if comm is not None:
                    # pod-wide stop vote.  Trees are bit-identical across
                    # ranks (split search runs on psum'd histograms), so
                    # ranks normally agree — the vote pins the invariant:
                    # no rank may stop alone and leave the others hanging
                    # in the next wave's psum.  Cadence (is_eval or
                    # iter%16) is config-derived, hence collective-aligned.
                    from ..parallel.comm import vote_stop
                    should_continue = not vote_stop(comm,
                                                    not should_continue)
        else:
            should_continue = False
        if not should_continue:
            self._pop_degenerate_iterations()
            obs.iter_end(it0, value=self._score_dev, stopped=True,
                         host_orchestration_s=host_orch)
            return True
        self.iter += 1
        self._emit_model_obs(it0, start_models)
        if is_eval:
            stop = self.eval_and_check_early_stopping()
            obs.lap("eval")
            obs.iter_end(it0, value=self._score_dev,
                         host_orchestration_s=host_orch)
            return stop
        obs.iter_end(it0, value=self._score_dev,
                     host_orchestration_s=host_orch)
        return False

    def _emit_model_obs(self, it0: int, start_models: int) -> None:
        """Split-audit + importance events for this iteration (obs/model.py).

        Costs a _materialize (device sync) when due, so both are opt-in:
        ``obs_split_audit`` audits every iteration's new trees;
        ``obs_importance_every=N`` snapshots top-k importance every N
        iterations."""
        if not self._obs.enabled:
            return
        every = self._obs_importance_every
        imp_due = every > 0 and (it0 % every) == 0
        if not self._obs_split_audit and not imp_due:
            return
        from ..obs import model as obs_model
        self._materialize()
        if self._obs_split_audit:
            for t in range(start_models, len(self.models)):
                obs_model.emit_split_audit(self._obs, it0, t,
                                           self.models[t])
        if imp_due:
            obs_model.emit_importance(
                self._obs, it0, self.feature_importance("split"),
                self.feature_importance("gain"),
                self._obs_importance_topk)

    def _bagging_with_grad(self, it, g_dev, h_dev):
        """Hook: base bagging ignores gradients; GOSS overrides."""
        self._bagging(it)
        return g_dev, h_dev

    def _pop_degenerate_iterations(self) -> None:
        """No leaf met the split requirements: drop this iteration's trees
        and any identical degenerate tail (gbdt.cpp:440-448)."""
        Log.warning("Stopped training because there are no more leaves "
                    "that meet the split requirements.")
        k = self.num_tree_per_iteration
        for _ in range(k):
            self.models.pop()
            self._models_dev.pop()
            self._models_shrink.pop()

    def _score_for_objective(self):
        k = self.num_tree_per_iteration
        if k == 1:
            return self._score_dev[0]
        return jnp.reshape(self._score_dev, (-1,))

    def merge_from(self, other: "GBDT") -> None:
        """GBDT::MergeFrom (gbdt.h:47-62): the other model's trees come
        FIRST (as if this booster had been continued-trained from the other
        model), and the merged prefix becomes the init-iteration count.
        Scores are NOT replayed (matches the reference, which only merges
        the model arrays).  Trees are deep-copied so later in-place
        mutation (rollback's shrink, SetLeafValue) of one booster cannot
        corrupt the other."""
        import copy
        self._materialize()
        other._materialize()
        merged = [copy.deepcopy(t) for t in other.models]
        self.models = merged + self.models
        self._models_dev = [None] * len(merged) + self._models_dev
        self._models_shrink = [1.0] * len(merged) + self._models_shrink
        k = max(self.num_tree_per_iteration, 1)
        self.num_init_iteration = len(merged) // k
        self.num_iteration_for_pred = len(self.models) // k

    def rollback_one_iter(self) -> None:
        """GBDT::RollbackOneIter (gbdt.cpp:460-477)."""
        if self.iter <= 0:
            return
        self._materialize()
        k = self.num_tree_per_iteration
        cur_iter = self.iter + self.num_init_iteration - 1
        for tid in range(k):
            t = cur_iter * k + tid
            self.models[t].shrink(-1.0)
            self._apply_tree_to_train(self.models[t], tid)
            for vi in range(len(self.valid_data)):
                self._apply_tree_to_valid(self.models[t], vi, tid)
        for _ in range(k):
            self.models.pop()
            self._models_dev.pop()
            self._models_shrink.pop()
        self.iter -= 1

    # ------------------------------------------------------------------ eval
    def _dist_comm(self):
        """The training dataset's multi-process comm, or None.  Present
        only for rank-sharded datasets (io/dataset.py from_binned /
        from_matrix with a comm) — the signal that metric values are
        partial sums over local rows and stop decisions need a vote."""
        comm = (getattr(self.train_data, "_comm", None)
                if self.train_data is not None else None)
        if comm is not None and getattr(comm, "size", 1) > 1 \
                and not getattr(comm, "closed", False):
            return comm
        return None

    def _reduce_scores(self, scores, num_local_rows):
        """Row-weighted cross-rank mean of per-metric scores.  Metrics
        evaluate over the rank's LOCAL score shard; the weighted mean by
        local row count recovers the global row-average every rank then
        agrees on — which keeps the early-stopping bookkeeping (and its
        model pop-back) bit-identical across the pod.  Routes through
        the host comm (parallel/comm.py), so it lands in the
        host_collective observability stream with a seq number."""
        comm = self._dist_comm()
        if comm is None:
            return scores
        from ..parallel.comm import reduce_metrics
        red = reduce_metrics(
            comm, {str(i): float(s) for i, s in enumerate(scores)},
            weight=float(num_local_rows))
        return [red[str(i)] for i in range(len(scores))]

    def eval_and_check_early_stopping(self) -> bool:
        best_msg = self.output_metric(self.iter)
        met = bool(best_msg)
        comm = self._dist_comm()
        if comm is not None:
            # unanimous vote: with reduced metrics every rank already
            # computed the same answer, so this is a divergence guard —
            # a rank that disagrees (e.g. a stale shard) cannot keep
            # training against ranks that popped models back
            from ..parallel.comm import vote_stop
            met = vote_stop(comm, met)
        if met:
            Log.info("Early stopping at iteration %d, the best iteration round is %d",
                     self.iter, self.iter - self.early_stopping_round)
            Log.info("Output of best iteration round:\n%s", best_msg)
            for _ in range(self.early_stopping_round * self.num_tree_per_iteration):
                self.models.pop()
                self._models_dev.pop()
                self._models_shrink.pop()
        return met

    def output_metric(self, it: int) -> str:
        """GBDT::OutputMetric (gbdt.cpp:527-585)."""
        need_output = (it % self.config.output_freq) == 0
        ret = ""
        msg_lines: List[str] = []
        meet_pairs: List[Tuple[int, int]] = []
        # metric values double as timeline `eval` events (convergence /
        # overfit-gap surface for `obs explain` and bench_compare's
        # final_eval_metric gate) and as the drift fingerprint's eval
        # snapshot — always collected; only the event is observer-gated
        eval_results: List[dict] = []
        if need_output:
            for m in self.training_metrics:
                scores = self._reduce_scores(
                    m.eval(self.train_score, self.objective),
                    self.num_data)
                for name, s in zip(m.get_names(), scores):
                    line = "Iteration:%d, training %s : %g" % (it, name, s)
                    Log.info(line)
                    if self.early_stopping_round > 0:
                        msg_lines.append(line)
                    if eval_results is not None:
                        eval_results.append({"dataset": "training",
                                             "metric": name,
                                             "value": float(s)})
        if need_output or self.early_stopping_round > 0:
            for i in range(len(self.valid_metrics)):
                for j, m in enumerate(self.valid_metrics[i]):
                    test_scores = self._reduce_scores(
                        m.eval(self.valid_score_host(i), self.objective),
                        self.valid_data[i].num_data)
                    for name, s in zip(m.get_names(), test_scores):
                        line = "Iteration:%d, valid_%d %s : %g" % (it, i + 1, name, s)
                        if need_output:
                            Log.info(line)
                        if self.early_stopping_round > 0:
                            msg_lines.append(line)
                        if eval_results is not None:
                            eval_results.append(
                                {"dataset": "valid_%d" % (i + 1),
                                 "metric": name, "value": float(s)})
                    if not ret and self.early_stopping_round > 0:
                        cur = m.factor_to_bigger_better * test_scores[-1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = it
                            meet_pairs.append((i, j))
                        elif it - self.best_iter[i][j] >= self.early_stopping_round:
                            ret = self.best_msg[i][j]
        if eval_results:
            self._last_eval_results = eval_results
            self._drift_fingerprint = None   # eval snapshot went stale
            if self._obs.enabled:
                self._obs.event("eval", it=it, results=eval_results)
        msg = "\n".join(msg_lines)
        for i, j in meet_pairs:
            self.best_msg[i][j] = msg
        return ret

    def get_eval_at(self, data_idx: int) -> List[float]:
        """GBDT::GetEvalAt (gbdt.cpp:588-609)."""
        out: List[float] = []
        if data_idx == 0:
            for m in self.training_metrics:
                out.extend(m.eval(self.train_score, self.objective))
        else:
            i = data_idx - 1
            for m in self.valid_metrics[i]:
                out.extend(m.eval(self.valid_score_host(i), self.objective))
        return out

    def eval_names(self, data_idx: int) -> List[str]:
        ms = self.training_metrics if data_idx == 0 else self.valid_metrics[data_idx - 1]
        out: List[str] = []
        for m in ms:
            out.extend(m.get_names())
        return out

    # --------------------------------------------------------------- predict
    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def total_iterations(self) -> int:
        return len(self.models) // self.num_tree_per_iteration

    def _used_trees(self, num_iteration: int) -> int:
        num_used = len(self.models)
        if num_iteration > 0:
            ni = num_iteration + (1 if self.boost_from_average_used else 0)
            num_used = min(ni * self.num_tree_per_iteration, len(self.models))
        return num_used

    def predict_raw(self, features: np.ndarray,
                    num_iteration: int = -1,
                    allow_device: bool = True) -> np.ndarray:
        """Raw scores (N, num_tree_per_iteration) on real-valued features
        (gbdt_prediction.cpp PredictRaw).  allow_device=False pins the
        exact f64 host path — continued-training init scores need it
        (the device path's Kahan f32 accumulation is ~1e-7 relative)."""
        self._materialize()
        features = np.ascontiguousarray(np.asarray(features, dtype=np.float64))
        n = features.shape[0]
        k = self.num_tree_per_iteration
        num_used = self._used_trees(num_iteration)
        dev = (self._device_bulk_predict(features, num_used, k)
               if allow_device else None)
        if dev is not None:
            return dev
        from .. import native
        nat = native.predict_raw(
            [(self.models[t], t % k) for t in range(num_used)], k, features)
        if nat is not None:
            return nat
        out = np.zeros((n, k), dtype=np.float64)
        for t in range(num_used):
            out[:, t % k] += self.models[t].predict(features)
        return out

    # ------------------------------------------------- device bulk predict
    _DEVICE_PREDICT_MIN_ROWS = 100_000

    @staticmethod
    def _predict_chunk_rows(n_features: int, n_devices: int) -> int:
        """Rows per device-predict chunk.  Host V (i32) + D (bool) cost
        F*5 bytes/row; the one-deep pipeline keeps TWO chunks resident,
        so the per-chunk budget is 1.5 GB for a ~3 GB device peak
        (ADVICE r3: the old 3 GB/chunk budget meant a ~6 GB peak)."""
        bytes_per_row = max(n_features, 1) * 5
        return min(4_000_000 * max(n_devices, 1),
                   max(65_536, 1_500_000_000 // bytes_per_row))

    def _device_bulk_predict(self, features, num_used, k):
        """Rank-encoded TPU bulk prediction (ops/predict.py): f64-exact
        routing as int compares, Kahan f32 accumulation.  Returns None
        when the host paths should run instead (small batches, non-TPU
        backends under tpu_predict=auto, tpu_predict=false, or a model
        whose features mix categorical and numerical decisions)."""
        from ..utils.config import _FALSE_SET, _TRUE_SET
        cfg = str(getattr(self.config, "tpu_predict", "auto")).strip().lower()
        if cfg in _FALSE_SET:
            return None
        if cfg not in _TRUE_SET:       # auto
            if (jax.default_backend() != "tpu"
                    or features.shape[0] < self._DEVICE_PREDICT_MIN_ROWS):
                return None
        key = (num_used, k, len(self.models), self.iter,
               features.shape[1])
        if getattr(self, "_ranked_pred_key", None) != key:
            try:
                self._ranked_pred = dev_predict.build_ranked_predictor(
                    self.models[:num_used], k, features.shape[1])
            except ValueError as e:    # mixed cat/num feature use
                Log.warning("device bulk predict unavailable (%s); "
                            "using the host predictor", e)
                self._ranked_pred = None
            self._ranked_pred_key = key
        rp = self._ranked_pred
        if rp is None:
            return None
        if features.shape[1] < rp.max_feature + 1:
            return None                # fewer columns than the model uses
        devices = jax.local_devices()   # per-process rows -> local mesh
        out = np.empty((features.shape[0], k), np.float64)
        chunk = self._predict_chunk_rows(features.shape[1], len(devices))
        def dispatch(part):
            """Async: device call issued, nothing blocked on."""
            V, D = dev_predict.rank_encode(rp, part)
            n = len(part)
            # power-of-two row bucketing (floor 256, capped at the chunk
            # size): the jit cache keys on shape, so varying batch sizes
            # would otherwise each compile a fresh executable — padded
            # rows are sliced off in drain()
            bucket = min(1 << max(int(n - 1).bit_length(), 8), chunk)
            if bucket > n:
                V = np.concatenate(
                    [V, np.zeros((bucket - n, V.shape[1]), V.dtype)])
                D = np.concatenate(
                    [D, np.zeros((bucket - n, D.shape[1]), D.dtype)])
            if len(devices) > 1:
                # rows shard over the device mesh; trees replicate —
                # bit-identical to single-device (pure data parallel)
                score, _ = dev_predict.ranked_predict_sharded(
                    rp, V, D, k, devices=devices)
                return score, n
            return dev_predict.ranked_predict_device(
                rp.dev, jnp.asarray(V), jnp.asarray(D), k), n

        def drain(pending):
            plo, pscore, pnrows = pending
            out[plo:plo + pnrows] = np.asarray(
                fenced_get(pscore)[:pnrows], np.float64)

        # one-deep pipeline: encode chunk i+1 on the host while the
        # device computes chunk i (jax dispatch is async; device_get is
        # the only sync point)
        pending = None
        for lo in range(0, features.shape[0], chunk):
            score, nrows = dispatch(features[lo:lo + chunk])
            if pending is not None:
                drain(pending)
            pending = (lo, score, nrows)
        if pending is not None:
            drain(pending)
        return out

    def predict(self, features: np.ndarray,
                num_iteration: int = -1, raw_score: bool = False,
                pred_leaf: bool = False) -> np.ndarray:
        if pred_leaf:
            return self.predict_leaf_index(features, num_iteration)
        raw = self.predict_raw(features, num_iteration)
        if raw_score or self.objective is None:
            return raw[:, 0] if raw.shape[1] == 1 else raw
        conv = np.asarray(self.objective.convert_output(
            raw if raw.shape[1] > 1 else raw[:, 0]))
        return conv

    def predict_leaf_index(self, features: np.ndarray,
                           num_iteration: int = -1) -> np.ndarray:
        self._materialize()
        features = np.ascontiguousarray(np.asarray(features, dtype=np.float64))
        num_used = self._used_trees(num_iteration)
        cols = [self.models[t].predict_leaf_index(features)
                for t in range(num_used)]
        return np.stack(cols, axis=1) if cols else np.zeros((features.shape[0], 0), np.int32)

    def pred_contrib(self, features: np.ndarray, num_iteration: int = -1,
                     per: str = "feature") -> np.ndarray:
        """Prediction attribution (debug path, host-only, f64 exact).

        per='tree': (N, num_used) matrix of each tree's contribution —
        column t sums into raw-score class t % num_tree_per_iteration, so
        summing the columns of a class reproduces predict_raw exactly.

        per='feature': gain-weighted path attribution per tree
        (Tree.predict_contrib), summed over trees.  Returns
        (N, num_features + 1) for single-output models — the last column
        is the bias (stub trees and zero-gain paths) — and
        (N, k, num_features + 1) for multi-class.  Rows sum to the raw
        score by construction.
        """
        if per not in ("feature", "tree"):
            raise KeyError("pred_contrib per must be 'feature' or 'tree'")
        self._materialize()
        features = np.ascontiguousarray(np.asarray(features,
                                                   dtype=np.float64))
        n = features.shape[0]
        k = self.num_tree_per_iteration
        num_used = self._used_trees(num_iteration)
        if per == "tree":
            out = np.zeros((n, num_used), dtype=np.float64)
            for t in range(num_used):
                out[:, t] = self.models[t].predict(features)
            return out
        nf = self.max_feature_idx + 1
        out = np.zeros((n, k, nf + 1), dtype=np.float64)
        for t in range(num_used):
            out[:, t % k, :] += self.models[t].predict_contrib(features, nf)
        return out[:, 0, :] if k == 1 else out

    # ------------------------------------------------------------- model I/O
    def sub_model_name(self) -> str:
        return "tree"

    def drift_fingerprint(self) -> Optional[dict]:
        """Serving-time drift reference (obs/drift.py): the dataset's
        per-feature binned histograms completed with the training-score
        distribution(s) and the final eval snapshot.  Cached — each
        eval pass invalidates it — and restored verbatim when the model
        was loaded from text, so a serving process never needs the
        training dataset."""
        if self._drift_fingerprint is not None:
            return self._drift_fingerprint
        td = getattr(self, "train_data", None)
        base = getattr(td, "_drift_fingerprint", None)
        if base is None:
            return None
        from ..obs import drift
        try:
            score = self.train_score
        except Exception:            # score engine not stood up yet
            score = None
        self._drift_fingerprint = drift.attach_scores(
            base, train_score=score, objective=self.objective,
            eval_results=self._last_eval_results)
        return self._drift_fingerprint

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        """GBDT::SaveModelToString (gbdt.cpp:817-861)."""
        self._materialize()
        lines = [self.sub_model_name()]
        lines.append("num_class=%d" % self.num_class)
        lines.append("num_tree_per_iteration=%d" % self.num_tree_per_iteration)
        lines.append("label_index=%d" % self.label_idx)
        lines.append("max_feature_idx=%d" % self.max_feature_idx)
        if self.objective is not None:
            lines.append("objective=%s" % self.objective.to_string())
        if self.boost_from_average_used:
            lines.append("boost_from_average")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self.feature_infos))
        fp = self.drift_fingerprint()
        if fp is not None:
            # one compact-JSON header line (no newlines, so it survives
            # parse_kv_lines round trips); any process loading the model
            # text gets the serving-time drift reference for free
            lines.append("drift_fingerprint=%s"
                         % json.dumps(fp, sort_keys=True,
                                      separators=(",", ":")))
        lines.append("")
        num_used = self._used_trees(num_iteration)
        for i in range(num_used):
            lines.append("Tree=%d" % i)
            lines.append(self.models[i].to_string())
        lines.append("")
        lines.append("feature importances:")
        for cnt, name in self.feature_importance_pairs():
            lines.append("%s=%d" % (name, cnt))
        return "\n".join(lines) + "\n"

    def save_model_to_file(self, filename: str, num_iteration: int = -1) -> None:
        with open(filename, "w") as f:
            f.write(self.save_model_to_string(num_iteration))

    def load_model_from_string(self, model_str: str) -> bool:
        """GBDT::LoadModelFromString (gbdt.cpp:875-971)."""
        self.models = []
        self._models_dev = []
        self._models_shrink = []
        lines = model_str.splitlines()
        header_lines = []
        for line in lines:
            if line.startswith("Tree="):
                break
            header_lines.append(line)
        kv = parse_kv_lines(header_lines)
        if "num_class" not in kv:
            Log.fatal("Model file doesn't specify the number of classes")
        self.num_class = int(kv["num_class"])
        self.num_tree_per_iteration = int(kv.get("num_tree_per_iteration",
                                                 self.num_class))
        if "label_index" not in kv:
            Log.fatal("Model file doesn't specify the label index")
        self.label_idx = int(kv["label_index"])
        if "max_feature_idx" not in kv:
            Log.fatal("Model file doesn't specify max_feature_idx")
        self.max_feature_idx = int(kv["max_feature_idx"])
        self.boost_from_average_used = any(
            l.strip() == "boost_from_average" for l in header_lines)
        if "feature_names" in kv:
            self.feature_names = kv["feature_names"].split(" ")
            if len(self.feature_names) != self.max_feature_idx + 1:
                Log.fatal("Wrong size of feature_names")
        if "feature_infos" in kv:
            self.feature_infos = kv["feature_infos"].split(" ")
        if "objective" in kv:
            self.objective = load_objective_from_string(kv["objective"])
        if "drift_fingerprint" in kv:
            try:
                self._drift_fingerprint = json.loads(kv["drift_fingerprint"])
            except ValueError as e:
                Log.warning("ignoring malformed drift_fingerprint in "
                            "model text: %s", e)
        # tree blocks
        text = "\n".join(lines)
        parts = text.split("Tree=")
        for part in parts[1:]:
            block_lines = part.splitlines()
            body = []
            for bl in block_lines[1:]:
                if bl.startswith("feature importances"):
                    break
                body.append(bl)
            block = "\n".join(body).strip()
            if block:
                self._append_host_tree(Tree.from_string(block))
        self.num_iteration_for_pred = len(self.models) // max(self.num_tree_per_iteration, 1)
        self.num_init_iteration = self.num_iteration_for_pred
        self.iter = 0
        return True

    def dump_model(self, num_iteration: int = -1) -> str:
        """GBDT::DumpModel JSON (gbdt.cpp:665-699)."""
        self._materialize()
        out = ['{"name":"%s",' % self.sub_model_name(),
               '"num_class":%d,' % self.num_class,
               '"num_tree_per_iteration":%d,' % self.num_tree_per_iteration,
               '"label_index":%d,' % self.label_idx,
               '"max_feature_idx":%d,' % self.max_feature_idx]
        if self.objective is not None:
            out.append('"objective":"%s",' % self.objective.to_string())
        out.append('"feature_names":[%s],' % ",".join(
            '"%s"' % n for n in self.feature_names))
        out.append('"tree_info":[')
        num_used = self._used_trees(num_iteration)
        tree_strs = []
        for i in range(num_used):
            tree_strs.append('{"tree_index":%d,%s}' % (i, self.models[i].to_json()))
        out.append(",".join(tree_strs))
        out.append("]}")
        return "\n".join(out)

    # ------------------------------------------------------------ importance
    def feature_importance_pairs(self) -> List[Tuple[int, str]]:
        """Split-count importance, descending, stable (gbdt.cpp:973-997)."""
        counts = self.feature_importance()
        pairs = [(int(counts[i]), self.feature_names[i] if i < len(self.feature_names)
                  else "Column_%d" % i)
                 for i in range(len(counts)) if counts[i] > 0]
        pairs.sort(key=lambda p: -p[0])
        return pairs

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """'split' = times a feature is used; 'gain' = total gain of the
        splits using it (python-package basic.py:1646-1680 semantics)."""
        if importance_type not in ("split", "gain"):
            raise KeyError("importance_type must be split or gain")
        self._materialize()
        if importance_type == "gain":
            gains = np.zeros(self.max_feature_idx + 1, dtype=np.float64)
            for tree in self.models:
                for i in range(tree.num_leaves - 1):
                    if tree.split_gain[i] > 0:
                        gains[tree.split_feature[i]] += tree.split_gain[i]
            return gains
        counts = np.zeros(self.max_feature_idx + 1, dtype=np.int64)
        for tree in self.models:
            for i in range(tree.num_leaves - 1):
                if tree.split_gain[i] > 0:
                    counts[tree.split_feature[i]] += 1
        return counts
