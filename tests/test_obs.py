"""Run telemetry (lightgbm_tpu/obs): schema, timers, wiring, overhead.

Covers the observability subsystem end-to-end on the CPU backend:
JSONL schema validation of an emitted timeline, the compile-vs-execute
split, fencing semantics, callback/timeline integration, config/CLI
round-trips, profiler-window logic (monkeypatched tracer), Log
redirection, the trace_summary JSONL reader, bench --dry, and the
disabled-path overhead guard.
"""
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import (NULL_OBSERVER, SCHEMA_VERSION, RunObserver,
                              observer_from_config, read_events,
                              validate_event)
from lightgbm_tpu.utils.config import Config
from lightgbm_tpu.utils.log import Log

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _data(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    y = (X @ w + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _train(params, path, n_rounds=5, valid=False, callbacks=None):
    X, y = _data()
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "binary", "num_leaves": 7, "verbose": -1,
            "obs_events_path": str(path)}
    base.update(params)
    kw = {}
    if valid:
        Xv, yv = _data(seed=1)
        kw["valid_sets"] = [lgb.Dataset(Xv, label=yv, reference=ds)]
    return lgb.train(base, ds, num_boost_round=n_rounds,
                     callbacks=callbacks, **kw)


# ---------------------------------------------------------------- schema

def test_emitted_timeline_is_schema_valid(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_memory_every": 2}, path)
    events = read_events(path)            # validates every record
    kinds = [e["ev"] for e in events]
    assert kinds[0] == "run_header"
    assert kinds[-1] == "run_end"
    for need in ("iter", "compile", "memory"):
        assert need in kinds
    header = events[0]
    assert header["schema"] == SCHEMA_VERSION
    assert header["backend"] == "cpu"
    assert len(header["devices"]) == 8    # conftest's virtual mesh
    assert header["context"]["learner"]
    # every record of one run shares the run id
    assert len({e["run"] for e in events}) == 1


def test_validate_event_rejects_bad_records():
    # unknown event types pass by default (forward compatibility: older
    # readers must accept newer-schema timelines) but fail under strict
    unknown = {"ev": "nope", "t": 0, "run": "x"}
    assert validate_event(unknown) is unknown
    with pytest.raises(ValueError):
        validate_event(unknown, strict=True)
    with pytest.raises(ValueError):
        validate_event({"ev": "iter", "t": 0, "run": "x"})   # missing keys
    with pytest.raises(ValueError):
        validate_event({"ev": "run_header", "t": 0, "run": "x",
                        "schema": 99, "backend": "cpu", "devices": [],
                        "params": {}, "context": {}, "timing": "phase"})
    validate_event({"ev": "iter", "t": 0, "run": "x", "it": 0,
                    "time_s": 0.1, "phases": {}, "fenced": True})


def test_iter_records_carry_phases_and_fencing(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_timing": "phase"}, path, valid=True)
    iters = [e for e in read_events(path) if e["ev"] == "iter"]
    assert len(iters) == 5
    assert [e["it"] for e in iters] == list(range(5))
    for e in iters:
        assert e["fenced"] is True
        assert e["time_s"] > 0
        for phase in ("boost", "grow", "partition", "update"):
            assert phase in e["phases"], e["phases"]
        # phase laps can never exceed the fenced iteration total
        assert sum(e["phases"].values()) <= e["time_s"] + 1e-6


def test_timing_off_never_fences(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_timing": "off"}, path)
    events = read_events(path)
    for e in events:
        if e["ev"] in ("iter", "compile"):
            assert e["fenced"] is False


# --------------------------------------------- compile vs execute split

def test_compile_execute_split(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_timing": "phase"}, path)
    events = read_events(path)
    compiles = [e for e in events if e["ev"] == "compile"]
    assert [e["entry"] for e in compiles] == ["tree_grow"]
    run_end = events[-1]
    st = run_end["entries"]["tree_grow"]
    assert run_end["iters"] == 5
    # first call compiled; the 4 later calls are steady-state executes
    # (jit caches may be warm from earlier tests in this module, so only
    # the split's bookkeeping — not first_s >> exec — can be asserted)
    assert st["exec_n"] == 4
    assert st["first_s"] > 0
    assert st["exec_max_s"] >= st["exec_min_s"] > 0
    assert st["compile_est_s"] >= 0
    assert run_end["phase_totals"]["grow"] > 0


def test_entry_timers_unit():
    from lightgbm_tpu.obs.timers import EntryTimers
    t = EntryTimers()
    assert t.record("e", 2.0) is True          # first call -> compile
    assert t.record("e", 0.5) is False
    assert t.record("e", 0.25) is False
    s = t.summary()["e"]
    assert s["exec_n"] == 2
    assert s["exec_min_s"] == 0.25 and s["exec_max_s"] == 0.5
    assert s["exec_mean_s"] == pytest.approx(0.375)
    assert s["compile_est_s"] == pytest.approx(2.0 - 0.375)


def test_fence_is_type_forgiving():
    import jax.numpy as jnp
    from lightgbm_tpu.obs.timers import fence
    fence(None)
    fence(3.5)
    fence(np.zeros(3))
    fence((jnp.ones(2), [jnp.zeros(1), None]))


# ------------------------------------------- callback / timeline access

def test_record_telemetry_and_booster_timeline(tmp_path):
    records = []
    bst = _train({}, tmp_path / "ev.jsonl",
                 callbacks=[lgb.record_telemetry(records)])
    tl = bst.telemetry()
    assert tl[-1]["ev"] == "run_end"
    # the callback saw everything up to finalization; finalize itself
    # appends only the profiler's final window flush (obs_prof_hz is
    # on by default) and run_end
    assert len(records) < len(tl)
    tail = {e["ev"] for e in tl[len(records):]}
    assert tail <= {"prof_profile", "metrics", "run_end"}, tail
    assert sum(1 for e in records if e["ev"] == "iter") == 5
    with pytest.raises(TypeError):
        lgb.record_telemetry({})


def test_telemetry_disabled_by_default():
    X, y = _data()
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbose": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=2)
    assert bst._gbdt._obs is NULL_OBSERVER
    assert bst.telemetry() == []


def test_cv_folds_share_file_distinct_runs(tmp_path):
    path = tmp_path / "cv.jsonl"
    X, y = _data()
    lgb.cv({"objective": "binary", "num_leaves": 7, "verbose": -1,
            "obs_events_path": str(path)}, lgb.Dataset(X, label=y),
           num_boost_round=3, nfold=2, stratified=False)
    events = read_events(path)
    runs = {e["run"] for e in events}
    assert len(runs) == 2                  # one run id per fold
    for run in runs:
        kinds = [e["ev"] for e in events if e["run"] == run]
        assert kinds.count("run_header") == 1
        assert kinds.count("run_end") == 1
        assert kinds.count("iter") == 3


# --------------------------------------------------- config round-trip

def test_config_aliases_round_trip():
    cfg = Config({"obs_events_file": "/tmp/x.jsonl",
                  "obs_profile_iters": "3:5",
                  "obs_profile_dir": "/tmp/tr",
                  "obs_memory_freq": 4})
    assert cfg.obs_events_path == "/tmp/x.jsonl"
    assert cfg.obs_trace_iters == "3:5"
    assert cfg.obs_trace_dir == "/tmp/tr"
    assert cfg.obs_memory_every == 4


def test_observer_from_config_policies():
    assert observer_from_config(Config({})) is NULL_OBSERVER
    obs = observer_from_config(Config({"obs_events_path": "/tmp/x.jsonl"}))
    assert isinstance(obs, RunObserver) and obs.timing == "phase"
    obs = observer_from_config(Config({"obs_events_path": "/tmp/x.jsonl",
                                       "obs_timing": "iter"}))
    assert obs.timing == "iter"
    with pytest.raises(lgb.LightGBMError):
        observer_from_config(Config({"obs_events_path": "/tmp/x.jsonl",
                                     "obs_timing": "sideways"}))
    with pytest.raises(lgb.LightGBMError):
        # trace window without a destination
        observer_from_config(Config({"obs_trace_iters": "1:2"}))


def test_cli_smoke_on_shipped_example(tmp_path, monkeypatch):
    """The shipped examples/binary_classification data + confs run as-is,
    and the CLI grows the obs flags (events path relative to cwd)."""
    import shutil
    from lightgbm_tpu import cli
    src = os.path.join(REPO, "examples", "binary_classification")
    work = tmp_path / "ex"
    shutil.copytree(src, work)
    monkeypatch.chdir(work)
    rc = cli.main(["config=train.conf", "num_trees=3", "metric_freq=1",
                   "obs_events_path=events.jsonl", "obs_timing=iter",
                   "obs_memory_every=2"])
    assert rc == 0
    assert (work / "LightGBM_model.txt").exists()
    events = read_events(work / "events.jsonl")
    kinds = [e["ev"] for e in events]
    assert kinds[0] == "run_header" and kinds[-1] == "run_end"
    assert kinds.count("iter") == 3
    rc = cli.main(["config=predict.conf"])
    assert rc == 0
    preds = (work / "LightGBM_predict_result.txt").read_text().split()
    assert len(preds) == 400               # binary.test rows


# ----------------------------------------------------- profiler window

def test_trace_window_opens_and_closes(monkeypatch, tmp_path):
    from lightgbm_tpu.obs import profile
    calls = []
    monkeypatch.setattr(profile, "_start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profile, "_stop_trace",
                        lambda: calls.append(("stop",)))
    path = tmp_path / "ev.jsonl"
    _train({"obs_trace_iters": "1:3", "obs_trace_dir": str(tmp_path)},
           path)
    assert calls == [("start", str(tmp_path)), ("stop",)]
    windows = [e for e in read_events(path) if e["ev"] == "trace_window"]
    assert [(w["action"], w["it"]) for w in windows] == [("start", 1),
                                                         ("stop", 2)]


def test_trace_window_force_stop_on_short_run(monkeypatch, tmp_path):
    from lightgbm_tpu.obs import profile
    calls = []
    monkeypatch.setattr(profile, "_start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(profile, "_stop_trace",
                        lambda: calls.append("stop"))
    # window [1, 100) stays open at run end -> finalize must close it
    _train({"obs_trace_iters": "1:100", "obs_trace_dir": str(tmp_path)},
           tmp_path / "ev.jsonl", n_rounds=3)
    assert calls == ["start", "stop"]


def test_parse_trace_iters():
    from lightgbm_tpu.obs.profile import parse_trace_iters
    assert parse_trace_iters("") is None
    assert parse_trace_iters("3:8") == (3, 8)
    assert parse_trace_iters(" 0:1 ") == (0, 1)
    for bad in ("5", "5:5", "8:3", "-1:4", "a:b", "1:2:3"):
        with pytest.raises(lgb.LightGBMError):
            parse_trace_iters(bad)


# ------------------------------------------------------- log redirection

def test_log_set_stream_captures_output():
    # earlier trainings ran verbose=-1; pin the level for this test
    level = Log._level
    Log.reset_level(1)
    buf = io.StringIO()
    prev = Log.set_stream(buf)
    try:
        Log.warning("obs test %d", 7)
    finally:
        Log.set_stream(prev)
        Log.reset_level(level)
    assert "[Warning] obs test 7" in buf.getvalue()
    buf2 = io.StringIO()
    Log.set_stream(buf2)
    Log.set_stream(None)                   # None restores stderr
    Log.warning("not captured")
    assert buf2.getvalue() == ""


# -------------------------------------------------------- trace_summary

def test_trace_summary_reads_jsonl(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_memory_every": 2, "obs_timing": "phase"}, path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "trace_summary.py"),
                        str(path)], capture_output=True, text=True,
                       env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "per-phase time over 5 iterations (fenced)" in r.stdout
    assert "grow" in r.stdout and "tree_grow" in r.stdout
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "trace_summary.py"),
                        str(path), "--csv"], capture_output=True,
                       text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    rows = [ln.split(",") for ln in r.stdout.strip().splitlines()]
    assert rows[0] == ["kind", "name", "total_s", "mean_s", "count",
                      "extra"]
    kinds = {row[0] for row in rows[1:]}
    assert {"phase", "entry_compile", "entry_execute"} <= kinds


# ------------------------------------------------------------ bench --dry

def test_bench_dry_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                        "--dry"], capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["status"] == "dry_ok"
    assert rec["iters"] == 5


# -------------------------------------------------------- overhead guard

def test_disabled_path_allocates_no_event_objects():
    """With telemetry off, training must not touch the obs subsystem:
    no observer construction, no fencing, no per-iteration allocations
    attributable to lightgbm_tpu/obs."""
    import tracemalloc
    X, y = _data()
    bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7,
                              "verbose": -1},
                      train_set=lgb.Dataset(X, label=y))
    gbdt = bst._gbdt
    assert gbdt._obs is NULL_OBSERVER
    assert gbdt.learner._obs is NULL_OBSERVER
    gbdt.train_one_iter(None, None, False)      # compile outside the probe
    obs_dir = os.path.join(REPO, "lightgbm_tpu", "obs")
    tracemalloc.start()
    try:
        for _ in range(3):
            gbdt.train_one_iter(None, None, False)
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    obs_allocs = snap.filter_traces(
        [tracemalloc.Filter(True, os.path.join(obs_dir, "*"))])
    assert sum(st.size for st in obs_allocs.statistics("filename")) == 0
    assert NULL_OBSERVER.timeline == ()
    assert NULL_OBSERVER.entry_start() == 0.0
