"""Optional-dependency shims (python-package/compat.py analog)."""
from __future__ import annotations

try:
    import pandas as pd  # noqa: F401 — re-exported shim
    from pandas import DataFrame, Series  # noqa: F401
    PANDAS_INSTALLED = True
except ImportError:
    PANDAS_INSTALLED = False

    class DataFrame:  # type: ignore
        pass

    class Series:  # type: ignore
        pass

try:
    import sklearn  # noqa: F401
    SKLEARN_INSTALLED = True
except ImportError:
    SKLEARN_INSTALLED = False

try:
    import matplotlib  # noqa: F401
    MATPLOTLIB_INSTALLED = True
except ImportError:
    MATPLOTLIB_INSTALLED = False

try:
    import graphviz  # noqa: F401
    GRAPHVIZ_INSTALLED = True
except ImportError:
    GRAPHVIZ_INSTALLED = False
