"""Training entry points: ``train`` and ``cv`` — parity with
python-package/engine.py:17-315 (callback-driven loop, early stopping via
exception, init_model continuation, stratified/group folds)."""
from __future__ import annotations

import collections
from typing import Any, Dict

import numpy as np

from . import callback as callback_mod
from .basic import Booster, Dataset, _InnerPredictor
from .utils.config import key_alias_transform
from .utils.log import LightGBMError, Log

__all__ = ["train", "cv"]


def train(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
          valid_sets=None, valid_names=None, fobj=None, feval=None,
          init_model=None, feature_name="auto", categorical_feature="auto",
          early_stopping_rounds=None, evals_result=None, verbose_eval=True,
          learning_rates=None, keep_training_booster=False, callbacks=None):
    """Mirror of engine.py:17-203."""
    params = key_alias_transform(dict(params or {}))
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if "early_stopping_round" in params and params["early_stopping_round"] is not None:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    if fobj is not None:
        params["objective"] = "none"

    # elastic checkpoint/resume (models/checkpoint.py): a compatible
    # checkpoint in checkpoint_dir resumes the run — the model text seeds
    # scores through the init_model machinery and only the REMAINING
    # rounds run.  An explicit init_model wins over any checkpoint.
    ck_dir = str(params.get("checkpoint_dir", "") or "")
    ck_every = int(params.get("checkpoint_every", 0) or 0)
    resumed_ck = None
    if ck_dir and init_model is None:
        from .models import checkpoint as ckpt_mod
        resumed_ck = ckpt_mod.load_checkpoint(ck_dir)
        if resumed_ck is not None:
            ckpt_mod.check_resumable(resumed_ck, params)

    predictor = None
    if init_model is not None:
        if isinstance(init_model, str):
            predictor = _InnerPredictor(model_file=init_model)
        elif isinstance(init_model, Booster):
            predictor = _InnerPredictor(booster=init_model)
    elif resumed_ck is not None:
        predictor = _InnerPredictor(model_str=resumed_ck["model"])
        done = int(resumed_ck["iteration"])
        num_boost_round = max(0, num_boost_round - done)
        Log.info("Resuming from checkpoint %s: %d round(s) done, "
                 "%d remain", ck_dir, done, num_boost_round)
    init_iteration = (len(predictor.gbdt.models) // max(predictor.gbdt.num_tree_per_iteration, 1)
                      if predictor is not None else 0)
    # total completed rounds from the ORIGINAL zero — a twice-resumed
    # run keeps counting where the first run started (the model-count
    # derived init_iteration can be off by the boost_from_average stub)
    rounds_done_base = (int(resumed_ck["iteration"]) if resumed_ck
                        is not None else init_iteration)

    if isinstance(train_set, str):
        # pre-binned dataset directory (io/binned_format.py): open it
        # transparently — construction cost was paid at save_binned time
        from .io.dataset import TrainingData
        if TrainingData.can_load_binned(train_set):
            train_set = Dataset.from_binned(train_set)
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")
    train_set._update_params(params)
    if predictor is not None:
        train_set._set_predictor(predictor)
    if feature_name != "auto":
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto":
        train_set.set_categorical_feature(categorical_feature)

    # objective 'none' with fobj: booster builds without internal objective
    if fobj is not None:
        params["objective"] = "none"
    booster = Booster(params=params, train_set=train_set)
    if resumed_ck is not None:
        # elastic shrink: record the mesh transition when the resumed
        # world differs from the one that wrote the checkpoint (schema 12
        # mesh_shrink — the flight-record anchor for `obs explain`)
        _comm = getattr(booster._gbdt.train_data, "_comm", None)
        _world = int(getattr(_comm, "size", 1) or 1)
        _ck_world = int(resumed_ck.get("world_size", 1) or 1)
        _obs = booster._gbdt._obs
        if _ck_world != _world and _obs.enabled:
            from .models import checkpoint as ckpt_mod
            _obs.event("mesh_shrink", world_size_from=_ck_world,
                       world_size_to=_world,
                       it=int(resumed_ck["iteration"]), reason="resume",
                       checkpoint=ckpt_mod.checkpoint_path(ck_dir))
    is_valid_contain_train = False
    train_data_name = "training"
    reduced_valid_sets = []
    name_valid_sets = []
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if isinstance(valid_names, str):
            valid_names = [valid_names]
        for i, valid_data in enumerate(valid_sets):
            if valid_data is train_set:
                is_valid_contain_train = True
                if valid_names is not None:
                    train_data_name = valid_names[i]
                continue
            if not isinstance(valid_data, Dataset):
                raise TypeError("Validation data should be Dataset instance, "
                                "met %s" % type(valid_data).__name__)
            valid_data.set_reference(train_set)
            reduced_valid_sets.append(valid_data)
            if valid_names is not None and len(valid_names) > i:
                name_valid_sets.append(valid_names[i])
            else:
                name_valid_sets.append("valid_%d" % i)
    for valid_data, name in zip(reduced_valid_sets, name_valid_sets):
        booster.add_valid(valid_data, name)

    # callbacks
    cbs = set(callbacks or [])
    if learning_rates is not None:
        cbs.add(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        cbs.add(callback_mod.record_evaluation(evals_result))
    cbs_before, cbs_after = _assemble_callbacks(cbs, verbose_eval,
                                                early_stopping_rounds)

    booster.best_iteration = -1
    finished_iteration = num_boost_round
    evaluation_result_list = []  # stays empty when num_boost_round == 0
    try:
        for i in range(init_iteration, init_iteration + num_boost_round):
            for cb in cbs_before:
                cb(callback_mod.CallbackEnv(model=booster, params=params,
                                            iteration=i,
                                            begin_iteration=init_iteration,
                                            end_iteration=init_iteration + num_boost_round,
                                            evaluation_result_list=None))
            booster.update(fobj=fobj)

            if ck_every > 0 and ck_dir:
                total_rounds = rounds_done_base + (i - init_iteration) + 1
                comm = getattr(booster._gbdt.train_data, "_comm", None)
                world = int(getattr(comm, "size", 1) or 1)
                if total_rounds % ck_every == 0 and \
                        int(getattr(comm, "rank", 0) or 0) == 0:
                    from .models import checkpoint as ckpt_mod
                    obs = booster._gbdt._obs
                    obs.stamp_context(stage="checkpoint", it=total_rounds)
                    path = ckpt_mod.save_checkpoint(
                        ck_dir, booster._gbdt, total_rounds, params,
                        world_size=world)
                    if obs.enabled:
                        import os as _os
                        obs.event("checkpoint", it=total_rounds,
                                  path=path,
                                  bytes=int(_os.path.getsize(path)),
                                  world_size=world)

            evaluation_result_list = []
            if valid_sets is not None or feval is not None:
                # context stamp for incident bundles: an anomaly firing
                # here happened during eval, not mid-boost
                booster._gbdt._obs.stamp_context(stage="eval", it=i)
                if is_valid_contain_train:
                    evaluation_result_list.extend(booster.eval_train(feval))
                evaluation_result_list.extend(booster.eval_valid(feval))
                # metric values double as timeline `eval` events — the
                # convergence/overfit-gap surface for `obs explain` and
                # bench_compare's final_eval_metric gate (the CLI path
                # gets the same events from GBDT.output_metric) — and as
                # the drift fingerprint's eval snapshot (obs/drift.py)
                if evaluation_result_list:
                    results = [{"dataset": str(n), "metric": str(m),
                                "value": float(v)}
                               for n, m, v, _ in evaluation_result_list]
                    booster._gbdt._last_eval_results = results
                    booster._gbdt._drift_fingerprint = None
                    obs = booster._gbdt._obs
                    if obs.enabled:
                        obs.event("eval", it=i, results=results)
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(model=booster, params=params,
                                                iteration=i,
                                                begin_iteration=init_iteration,
                                                end_iteration=init_iteration + num_boost_round,
                                                evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as earlyStopException:
                booster.best_iteration = earlyStopException.best_iteration + 1
                evaluation_result_list = earlyStopException.best_score
                finished_iteration = booster.best_iteration
                break
    except BaseException:
        # stop the sampling profiler before the interpreter unwinds the
        # raising stack (close() disarms too, but only after the trace
        # teardown — the sampler must not walk dying frames first)
        try:
            booster._gbdt._obs.prof_disarm()
        except Exception:
            pass
        # a crashed run still finalizes its timeline: run_end
        # lands with status='aborted' and the writer flushes
        booster.finalize_telemetry(status="aborted")
        raise
    booster.best_score = collections.defaultdict(dict)
    for dataset_name, eval_name, score, _ in evaluation_result_list or []:
        booster.best_score[dataset_name][eval_name] = score
    booster.finalize_telemetry()
    obs = getattr(booster, "_obs", None)
    ep = (str(getattr(obs, "events_path", "") or "")
          if obs is not None and obs.enabled
          else str(params.get("obs_events_path", "") or ""))
    if ep:
        if obs is not None and getattr(obs, "world_size", 1) > 1:
            # per-rank shard — the cross-rank view needs the merge step
            Log.debug("obs: rank %d/%d timeline shard %s (cross-rank "
                      "view: python -m lightgbm_tpu obs merge %s)",
                      obs.rank, obs.world_size, ep, ep)
        else:
            Log.debug("obs: timeline %s (query: python -m lightgbm_tpu "
                      "obs summary %s)", ep, ep)
    return booster


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: dict,
                  seed: int, fpreproc=None, stratified: bool = False,
                  shuffle: bool = True):
    """engine.py:227-286 fold construction."""
    full_data = full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and hasattr(folds, "split"):
            folds = folds.split(X=np.zeros(num_data),
                                y=full_data.get_label())
    else:
        if stratified:
            try:
                from sklearn.model_selection import StratifiedKFold
                skf = StratifiedKFold(n_splits=nfold, shuffle=shuffle,
                                      random_state=seed if shuffle else None)
                folds = skf.split(np.zeros(num_data), full_data.get_label())
            except ImportError:
                raise LightGBMError("Scikit-learn is required for stratified cv")
        else:
            rng = np.random.default_rng(seed)
            randidx = rng.permutation(num_data) if shuffle else np.arange(num_data)
            kstep = int(num_data / nfold)
            folds = []
            for k in range(nfold):
                test_id = randidx[k * kstep: (k + 1) * kstep if k + 1 < nfold else num_data]
                train_id = np.setdiff1d(randidx, test_id, assume_unique=True)
                folds.append((train_id, test_id))
    ret = []
    for train_idx, test_idx in folds:
        train_sub = full_data.subset(np.sort(np.asarray(train_idx)))
        valid_sub = full_data.subset(np.sort(np.asarray(test_idx)))
        if fpreproc is not None:
            train_sub, valid_sub, tparam = fpreproc(train_sub, valid_sub,
                                                    params.copy())
        else:
            tparam = params
        ret.append((train_sub, valid_sub, tparam))
    return ret


class CVBooster:
    """Container for the per-fold boosters of a cv run (engine.py:206-224).

    Attribute access that isn't a field broadcasts the method call to every
    fold's booster and returns the list of results, as the reference does:
    ``cvb.predict(X)`` -> ``[b.predict(X) for b in cvb.boosters]``.
    """

    def __init__(self):
        self.boosters = []
        self.best_iteration = -1

    def append(self, booster):
        self.boosters.append(booster)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def handler(*args, **kwargs):
            return [getattr(bst, name)(*args, **kwargs)
                    for bst in self.boosters]
        return handler


def _assemble_callbacks(cbs, verbose_eval, early_stopping_rounds,
                        show_stdv: bool = True):
    """One callback-engine assembly for train() AND cv(): implicit
    print/early-stop injection from the legacy kwargs, then the
    before/after-iteration split in `order` order."""
    cbs = set(cbs)
    if verbose_eval is True:
        cbs.add(callback_mod.print_evaluation(show_stdv=show_stdv))
    elif isinstance(verbose_eval, int) and not isinstance(verbose_eval,
                                                          bool) \
            and verbose_eval:
        cbs.add(callback_mod.print_evaluation(verbose_eval, show_stdv))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        cbs.add(callback_mod.early_stopping(early_stopping_rounds,
                                            verbose=bool(verbose_eval)))
    cbs_before = {cb for cb in cbs if getattr(cb, "before_iteration", False)}
    cbs_after = cbs - cbs_before
    return (sorted(cbs_before, key=lambda cb: getattr(cb, "order", 0)),
            sorted(cbs_after, key=lambda cb: getattr(cb, "order", 0)))


def cv(params, train_set, num_boost_round: int = 10, folds=None, nfold: int = 5,
       stratified: bool = False, shuffle: bool = True, metrics=None, fobj=None,
       feval=None, init_model=None, feature_name="auto",
       categorical_feature="auto", early_stopping_rounds=None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks=None):
    """Mirror of engine.py:288-425; returns dict of per-iteration mean/stdv."""
    params = key_alias_transform(dict(params or {}))
    if "num_iterations" in params:
        num_boost_round = int(params.pop("num_iterations"))
    if metrics:
        params["metric"] = metrics
    if not isinstance(train_set, Dataset):
        raise TypeError("Training only accepts Dataset object")

    results = collections.defaultdict(list)
    cvfolds = _make_n_folds(train_set, folds, nfold, params, seed,
                            fpreproc=fpreproc, stratified=stratified,
                            shuffle=shuffle)
    cvbooster = CVBooster()
    for train_sub, valid_sub, tparam in cvfolds:
        bst = Booster(params=tparam, train_set=train_sub)
        bst.add_valid(valid_sub, "valid")
        cvbooster.append(bst)

    # callbacks drive the fold loop exactly as they drive train()'s:
    # env.model is the CVBooster, whose __getattr__ broadcasts
    # update/reset_parameter to every fold (reference engine.py:398-425);
    # cv aggregates cross as 5-tuples ("cv_agg", name, mean, hb, stdv)
    cbs_before, cbs_after = _assemble_callbacks(callbacks or [],
                                                verbose_eval,
                                                early_stopping_rounds,
                                                show_stdv)

    try:
        for i in range(num_boost_round):
            for cb in cbs_before:
                cb(callback_mod.CallbackEnv(model=cvbooster, params=params,
                                            iteration=i, begin_iteration=0,
                                            end_iteration=num_boost_round,
                                            evaluation_result_list=None))
            agg = collections.defaultdict(list)
            # broadcast through CVBooster.__getattr__, as the reference's cv
            # drives its folds (engine.py:398-401)
            cvbooster.update(fobj=fobj)
            for fold_evals in cvbooster.eval_valid(feval):
                for (_, name, score, hb) in fold_evals:
                    agg[(name, hb)].append(score)
            res = []
            for (name, hb), scores in agg.items():
                mean, stdv = float(np.mean(scores)), float(np.std(scores))
                results[name + "-mean"].append(mean)
                results[name + "-stdv"].append(stdv)
                res.append(("cv_agg", name, mean, hb, stdv))
            try:
                for cb in cbs_after:
                    cb(callback_mod.CallbackEnv(model=cvbooster, params=params,
                                                iteration=i, begin_iteration=0,
                                                end_iteration=num_boost_round,
                                                evaluation_result_list=res))
            except callback_mod.EarlyStopException as e:
                for k in list(results.keys()):
                    results[k] = results[k][:e.best_iteration + 1]
                break
    except BaseException:
        cvbooster.finalize_telemetry(status="aborted")
        raise
    cvbooster.finalize_telemetry()     # broadcasts across folds
    return dict(results)
