"""Compact elastic booster checkpoints (pod shrink-and-resume).

A checkpoint is ONE JSON file, small by construction: the model text
(the same format save_model_to_string ships — trees only, no scores, no
dataset), the completed boosting-round count, the trajectory seeds, and
a fingerprint of the training-relevant parameters.  Scores are NOT
saved: continued training re-seeds them from the model's raw predictions
(the established init_model path, basic.py _InnerPredictor), and bagging
is re-drawn per iteration from ``default_rng(bagging_seed + it)`` — so
rounds + seeds fully determine the resumed trajectory.

Write is atomic (tmp file + ``os.replace`` in the same directory): a
rank killed mid-save can never leave a half-written checkpoint for the
surviving ranks to resume from.  Rank 0 writes; every rank may read.

The fingerprint covers the parameters that shape the trajectory and
deliberately EXCLUDES the ones a shrink-and-resume legitimately changes:
``dist_*`` topology (the resumed world is smaller — that is the point),
``checkpoint_*`` knobs, observability paths, and verbosity.  A mismatch
on anything else means the resume would silently train a different model
than the run that saved — engine.train refuses it loudly.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, Optional

from ..utils.log import LightGBMError

CHECKPOINT_SCHEMA = 1
_FILE = "checkpoint.json"

# trajectory seeds snapshotted into the checkpoint (informational — the
# fingerprint already pins them; surfacing them makes flight-record
# forensics self-contained)
_SEED_KEYS = ("seed", "bagging_seed", "data_random_seed",
              "feature_fraction_seed", "drop_seed")


def _excluded(key: str) -> bool:
    key = key.lower()
    return (key.startswith("obs_")
            or key.startswith("dist_")
            or key.startswith("checkpoint_")
            or key.startswith("verbos")
            or key in ("num_iterations", "num_boost_round", "num_threads",
                       "output_model", "snapshot_freq", "machine_list_file"))


def config_fingerprint(params: Dict[str, Any]) -> str:
    """Order-independent sha256 over the training-relevant raw params.
    ``params`` should already be alias-transformed (engine.train's are)
    so spellings of the same knob fingerprint identically."""
    h = hashlib.sha256()
    for k, v in sorted((str(k), str(v)) for k, v in dict(params).items()
                       if not _excluded(str(k))):
        h.update(k.encode())
        h.update(b"=")
        h.update(v.encode())
        h.update(b"\n")
    return h.hexdigest()[:16]


def checkpoint_path(ckdir: str) -> str:
    return os.path.join(str(ckdir), _FILE)


def save_checkpoint(ckdir: str, gbdt, iteration: int,
                    params: Dict[str, Any],
                    world_size: int = 1) -> str:
    """Atomically write the checkpoint; returns its path.  ``iteration``
    is the TOTAL completed boosting-round count (including rounds done
    before any earlier resume), so a twice-resumed run still counts
    rounds from the original zero."""
    ckdir = str(ckdir)
    os.makedirs(ckdir, exist_ok=True)
    cfg = getattr(gbdt, "config", None)
    payload = {
        "schema": CHECKPOINT_SCHEMA,
        "t": time.time(),
        "iteration": int(iteration),
        "world_size": int(world_size),
        "config_fingerprint": config_fingerprint(params),
        "seeds": {k: int(getattr(cfg, k)) for k in _SEED_KEYS
                  if cfg is not None and hasattr(cfg, k)},
        "model": gbdt.save_model_to_string(),
    }
    path = checkpoint_path(ckdir)
    fd, tmp = tempfile.mkstemp(dir=ckdir, prefix=".ckpt.", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(ckdir: str) -> Optional[Dict[str, Any]]:
    """The checkpoint dict, or None when the directory holds none."""
    path = checkpoint_path(ckdir)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        ck = json.load(f)
    if int(ck.get("schema", -1)) != CHECKPOINT_SCHEMA:
        raise LightGBMError(
            "checkpoint %s has schema %s; this build reads schema %d"
            % (path, ck.get("schema"), CHECKPOINT_SCHEMA))
    return ck


def check_resumable(ck: Dict[str, Any], params: Dict[str, Any]) -> None:
    """Refuse a resume that would train a different model than the run
    that saved (fingerprint over training-relevant params)."""
    want = config_fingerprint(params)
    have = str(ck.get("config_fingerprint", ""))
    if have != want:
        raise LightGBMError(
            "checkpoint config fingerprint %s does not match this run's "
            "%s — the training-relevant parameters changed since the "
            "checkpoint was written; refusing to resume (delete the "
            "checkpoint or restore the original parameters)"
            % (have, want))
