"""Exclusive Feature Bundling (EFB) — host-side grouping + group binning.

Parity target: FindGroups / FastFeatureBundling (src/io/dataset.cpp:64-208)
and the FeatureGroup bin-offset scheme (feature_group.h:30-117):

* greedy packing of (almost-)mutually-exclusive features into one column,
  conflict budget = total_sample * max_conflict_rate; two insertion orders
  tried (natural, by nonzero-count desc), fewer groups wins;
* group bin layout: bin 0 reserved for "every feature at its default";
  feature i occupies [offset_i, offset_i + nb_i) with nb_i = num_bin
  (minus 1 when its default bin is 0, whose slot is never stored);
  pushed value = orig_bin + offset_i - (1 if default_i == 0 else 0),
  default-bin rows stay 0 (feature_group.h PushData semantics).

TPU-first difference: singleton groups keep RAW per-feature bins (no
reserved slot, offset 0) so the unbundled fast path is byte-identical to
the non-EFB layout; the learner reconstructs every feature's default-bin
count by subtraction (the FixHistogram trick, dataset.cpp:764-783)
uniformly for both cases.  Groups are capped at 256 bins so the binned
matrix stays uint8 — the GPU learner's gpu_max_bin_per_group constraint
(dataset.cpp:74) carried over because it is an HBM-width win here too.
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

MAX_GROUP_BINS = 256
MAX_SEARCH_GROUP = 100


class BundleLayout(NamedTuple):
    """Per-inner-feature group layout (all numpy, host side).

    local_bin(f, v) = v - off[f] + adj[f]  if off[f] <= v < off[f]+span[f]
                      default[f]           otherwise
    """
    groups: List[List[int]]          # group -> inner feature indices
    group_of: np.ndarray             # (F,) int32
    bin_off: np.ndarray              # (F,) int32
    bin_adj: np.ndarray              # (F,) int32 (1 iff bundled & default==0)
    bin_span: np.ndarray             # (F,) int32
    num_group_bins: np.ndarray       # (G,) int32

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def has_bundles(self) -> bool:
        return any(len(g) > 1 for g in self.groups)


def _stored_bins(num_bin: int, default_bin: int) -> int:
    """Slots a feature occupies inside a bundle (feature_group.h:40-44)."""
    return num_bin - (1 if default_bin == 0 else 0)


def _find_groups(order, nonzero_masks, num_bin_arr, default_bin_arr,
                 max_error_cnt, filter_cnt, num_data, total_sample,
                 rng) -> List[List[int]]:
    """One greedy pass (FindGroups, dataset.cpp:64-134)."""
    groups: List[List[int]] = []
    marks: List[np.ndarray] = []     # per-group used-row bitmap over sample
    conflict_cnt: List[int] = []
    group_bins: List[int] = []       # incl. the reserved 0 slot

    for f in order:
        nz = nonzero_masks[f]
        cnt_f = int(nz.sum())
        nb_f = _stored_bins(int(num_bin_arr[f]), int(default_bin_arr[f]))
        available = [g for g in range(len(groups))
                     if group_bins[g] + nb_f <= MAX_GROUP_BINS]
        if len(available) > MAX_SEARCH_GROUP:
            # bounded search like the reference's rand.Sample cap
            pick = rng.choice(len(available) - 1, MAX_SEARCH_GROUP - 1,
                              replace=False)
            available = [available[-1]] + [available[i] for i in pick]
        placed = False
        for g in available:
            rest = max_error_cnt - conflict_cnt[g]
            if rest < 0:
                continue
            cnt = int((marks[g] & nz).sum())
            if cnt > rest:
                continue
            rest_nonzero = (cnt_f - cnt) * num_data / max(total_sample, 1)
            if rest_nonzero < filter_cnt:
                continue
            groups[g].append(f)
            conflict_cnt[g] += cnt
            marks[g] |= nz
            group_bins[g] += nb_f
            placed = True
            break
        if not placed:
            groups.append([f])
            conflict_cnt.append(0)
            marks.append(nz.copy())
            group_bins.append(1 + nb_f)
    return groups


def find_feature_groups(binned_sample: np.ndarray, num_bin_arr: np.ndarray,
                        default_bin_arr: np.ndarray,
                        max_conflict_rate: float, min_data_in_leaf: int,
                        num_data: int) -> Optional[BundleLayout]:
    """FastFeatureBundling (dataset.cpp:139-208) on the binning sample.

    binned_sample: (S, F) per-feature bins of the sampled rows.
    Returns None when no bundle forms (caller keeps the raw layout).
    """
    total_sample, F = binned_sample.shape
    if F < 2 or total_sample == 0:
        return None
    nonzero_masks = [binned_sample[:, f] != default_bin_arr[f]
                     for f in range(F)]
    max_error_cnt = int(total_sample * max_conflict_rate)
    filter_cnt = int(0.95 * min_data_in_leaf / max(num_data, 1) * total_sample)
    rng = np.random.default_rng(num_data)

    natural = list(range(F))
    by_cnt = sorted(natural,
                    key=lambda f: -int(nonzero_masks[f].sum()))
    g1 = _find_groups(natural, nonzero_masks, num_bin_arr, default_bin_arr,
                      max_error_cnt, filter_cnt, num_data, total_sample, rng)
    g2 = _find_groups(by_cnt, nonzero_masks, num_bin_arr, default_bin_arr,
                      max_error_cnt, filter_cnt, num_data, total_sample, rng)
    groups = g2 if len(g2) < len(g1) else g1
    for g in groups:
        g.sort()
    if not any(len(g) > 1 for g in groups):
        return None
    return build_layout(groups, num_bin_arr, default_bin_arr)


def build_layout(groups: List[List[int]], num_bin_arr: np.ndarray,
                 default_bin_arr: np.ndarray) -> BundleLayout:
    F = len(num_bin_arr)
    group_of = np.zeros(F, np.int32)
    bin_off = np.zeros(F, np.int32)
    bin_adj = np.zeros(F, np.int32)
    bin_span = np.zeros(F, np.int32)
    num_group_bins = np.zeros(len(groups), np.int32)
    for gid, feats in enumerate(groups):
        if len(feats) == 1:
            f = feats[0]
            group_of[f] = gid
            bin_off[f] = 0
            bin_adj[f] = 0
            bin_span[f] = num_bin_arr[f]
            num_group_bins[gid] = num_bin_arr[f]
        else:
            off = 1                   # bin 0 reserved for all-default
            for f in feats:
                group_of[f] = gid
                default0 = int(default_bin_arr[f]) == 0
                bin_off[f] = off
                bin_adj[f] = 1 if default0 else 0
                bin_span[f] = _stored_bins(int(num_bin_arr[f]),
                                           int(default_bin_arr[f]))
                off += bin_span[f]
            num_group_bins[gid] = off
    return BundleLayout(groups=groups, group_of=group_of, bin_off=bin_off,
                        bin_adj=bin_adj, bin_span=bin_span,
                        num_group_bins=num_group_bins)


def bin_rows_grouped(per_feature_bins, layout: BundleLayout,
                     default_bin_arr: np.ndarray) -> np.ndarray:
    """(N, G) group-binned matrix from per-feature bins.

    per_feature_bins: callable f -> (N,) int bins, or (N, F) array.
    Within a bundle, later features overwrite on (rare, budgeted) conflict
    rows — the reference's push-order semantics.
    """
    if isinstance(per_feature_bins, np.ndarray):
        getcol = lambda f: per_feature_bins[:, f]
    else:
        getcol = per_feature_bins
    G = layout.num_groups
    n = getcol(0).shape[0] if layout.groups else 0
    dtype = np.uint8 if int(layout.num_group_bins.max(initial=2)) <= 256 \
        else np.uint16
    out = np.zeros((n, G), dtype=dtype)
    for gid, feats in enumerate(layout.groups):
        if len(feats) == 1:
            out[:, gid] = getcol(feats[0]).astype(dtype)
            continue
        col = np.zeros(n, dtype=np.int64)
        for f in feats:
            b = np.asarray(getcol(f), np.int64)
            nondef = b != default_bin_arr[f]
            col[nondef] = (b[nondef] + layout.bin_off[f]
                           - layout.bin_adj[f])
        out[:, gid] = col.astype(dtype)
    return out


def local_bins_np(group_col: np.ndarray, f: int,
                  layout: BundleLayout, default_bin: int) -> np.ndarray:
    """Host-side local-bin reconstruction (SubFeatureIterator semantics)."""
    v = np.asarray(group_col, np.int64)
    off = int(layout.bin_off[f])
    span = int(layout.bin_span[f])
    adj = int(layout.bin_adj[f])
    in_range = (v >= off) & (v < off + span)
    return np.where(in_range, v - off + adj, default_bin).astype(np.int64)
