"""Training health monitors: catch sick runs while they are cheap.

A diverging or NaN-poisoned boosting run on a TPU pod burns its whole
reservation before anyone reads the metrics; the monitors here watch the
quantities the training loop already has in flight and emit ``health``
events into the run timeline (events.py) the moment something is off:

* **non-finite guard** — gradients, hessians and grown-tree leaf values.
  One ``mean(abs(x))`` reduction per array: the mean is NaN/Inf iff any
  element is non-finite, so a single device scalar answers both "is it
  finite" and "how big is it".  All reductions are dispatched async and
  pulled in one batched ``device_get`` — one extra sync per checked
  iteration, nothing per-element on the host.
* **EMA divergence / plateau** — an exponential moving average over the
  per-iteration gradient magnitude (the training signal that exists every
  iteration, eval or not).  Divergence fires after two consecutive checks
  above ``divergence x EMA``; plateau fires after ``plateau`` consecutive
  checks with relative EMA movement under 1e-4 (plateau is informational
  and never escalates to fatal).
* **memory watermark** — per-device ``bytes_in_use`` against
  ``bytes_limit`` where the backend reports them (TPU/GPU; the CPU
  backend has no byte counters and the check is a no-op), with the peak
  fraction tracked per device.

``obs_health`` picks the consequence: ``off`` (no monitors), ``warn``
(log + ``health`` event), ``fatal`` (log + event + flush the timeline +
raise LightGBMError, aborting the run).  Cadence via ``obs_health_every``.

Warn-channel events are edge-triggered: a check that keeps failing
emits ONE ``health`` event at first occurrence and stays silent until
it recovers (a clean evaluation re-arms it) or escalates to fatal — so
the incident engine (obs/incident.py) groups a flapping guard into one
incident instead of being flooded by identical recurrences.
"""
from __future__ import annotations

import math

from ..utils.log import Log

MODES = ("off", "warn", "fatal")

# checks that never abort the run even under obs_health=fatal: a flat
# loss is a tuning smell, not a poisoned run, and an SLO burn-rate alert
# (obs/serve.py) is a paging signal for operators — killing the server
# that is already missing latency targets only makes the outage total.
# Drift and input anomalies (obs/drift.py) are retrain signals for the
# continuous-training loop for the same reason: the model still serves,
# it just serves traffic it was not trained on.
_WARN_ONLY = frozenset(("plateau", "slo_burn_rate", "drift",
                        "serve_input", "online_quality"))

_PLATEAU_REL = 1e-4


def _finite(x):
    return math.isfinite(x)


class HealthMonitors:
    """Stateful per-run monitor set.  The training loop stages device
    reductions while the iteration is in flight (``stage_gradients``,
    ``stage_leaf_values``) and the observer triggers the single host
    sync + verdicts at iteration end (``run_checks``)."""

    def __init__(self, mode="warn", every=1, divergence=3.0, plateau=0,
                 mem_frac=0.9, ema_alpha=0.3):
        if mode not in MODES:
            raise ValueError("obs_health mode %r (expected off/warn/fatal)"
                             % (mode,))
        self.mode = mode
        self.every = max(1, int(every))
        self.divergence = float(divergence)
        self.plateau = int(plateau)
        self.mem_frac = float(mem_frac)
        self.ema_alpha = float(ema_alpha)
        self._staged = None            # (mean|g|, mean|h|) device scalars
        self._staged_leaves = None     # max|leaf_value| device scalar
        self._ema = None
        self._diverging = 0
        self._flat = 0
        self.mem_peak_frac = {}        # device id -> peak in_use/limit
        self.counts = {"ok": 0, "warn": 0, "fatal": 0}
        self._firing = {}              # check -> status last emitted

    # ----------------------------------------------------------- staging
    def due(self, it):
        return it % self.every == 0

    def stage_gradients(self, g_dev, h_dev):
        """Dispatch the finiteness/magnitude reductions without syncing;
        the results are pulled in run_checks."""
        import jax.numpy as jnp
        self._staged = (jnp.mean(jnp.abs(g_dev)), jnp.mean(jnp.abs(h_dev)))

    def stage_leaf_values(self, leaf_values):
        """``leaf_values``: device arrays of the leaf outputs grown this
        iteration (one per tree)."""
        import jax.numpy as jnp
        if leaf_values:
            self._staged_leaves = jnp.max(jnp.stack(
                [jnp.max(jnp.abs(lv)) for lv in leaf_values]))

    # ----------------------------------------------------------- verdicts
    def run_checks(self, obs, it):
        """One batched host sync over the staged scalars, then verdicts.
        Emits a ``health`` stats event plus one event per firing check;
        raises LightGBMError under mode='fatal'."""
        import jax
        staged = list(self._staged or ())
        has_leaves = self._staged_leaves is not None
        if has_leaves:
            staged.append(self._staged_leaves)
        self._staged = None
        self._staged_leaves = None
        if not staged:
            return
        host = [float(x) for x in jax.device_get(staged)]
        stats = {}
        problems = []
        g_mean = h_mean = None
        if len(host) >= 2 + (1 if has_leaves else 0):
            g_mean, h_mean = host[0], host[1]
            stats["grad_abs_mean"] = g_mean
            stats["hess_abs_mean"] = h_mean
            if not _finite(g_mean) or not _finite(h_mean):
                problems.append(("nonfinite_gradients",
                                 {"grad_abs_mean": repr(g_mean),
                                  "hess_abs_mean": repr(h_mean)}))
        if has_leaves:
            leaf_max = host[-1]
            stats["leaf_abs_max"] = leaf_max
            if not _finite(leaf_max):
                problems.append(("nonfinite_leaf_values",
                                 {"leaf_abs_max": repr(leaf_max)}))
        problems.extend(self._trend(g_mean))
        status = "ok" if not problems else self.mode
        obs.event("health", check="stats", status=status, it=it,
                  detail=stats)
        self.counts["ok" if not problems else self.mode] += 1
        evaluated = set()
        if g_mean is not None:
            evaluated.update(("nonfinite_gradients", "loss_divergence",
                              "plateau"))
        if has_leaves:
            evaluated.add("nonfinite_leaf_values")
        self._resolve(obs, it, problems, evaluated=evaluated)

    def _trend(self, g_mean):
        """EMA divergence / plateau over the gradient-magnitude series."""
        out = []
        if g_mean is None or not _finite(g_mean):
            return out
        if self._ema is None:
            self._ema = g_mean
            return out
        prev = self._ema
        if self.divergence > 0 and g_mean > self.divergence * prev + 1e-300:
            self._diverging += 1
            if self._diverging >= 2:
                out.append(("loss_divergence",
                            {"grad_abs_mean": g_mean, "ema": prev,
                             "factor": self.divergence,
                             "consecutive": self._diverging}))
        else:
            self._diverging = 0
        self._ema = (1.0 - self.ema_alpha) * prev + self.ema_alpha * g_mean
        rel = abs(self._ema - prev) / max(abs(prev), 1e-300)
        if self.plateau > 0:
            if rel < _PLATEAU_REL:
                self._flat += 1
                if self._flat >= self.plateau:
                    out.append(("plateau",
                                {"ema": self._ema, "rel_change": rel,
                                 "checks": self._flat}))
                    self._flat = 0
            else:
                self._flat = 0
        return out

    def check_memory(self, obs, it, devices=None):
        """Per-device in-use/limit watermark; ``devices`` reuses an
        already-captured memory snapshot when the cadences line up."""
        if self.mem_frac <= 0:
            return
        if devices is None:
            from .memory import device_memory_stats
            devices = device_memory_stats()
        problems = []
        for d in devices:
            limit = d.get("bytes_limit", 0)
            in_use = d.get("bytes_in_use")
            if not limit or in_use is None:
                continue          # CPU backend: identity rows only
            frac = in_use / limit
            did = d["id"]
            if frac > self.mem_peak_frac.get(did, 0.0):
                self.mem_peak_frac[did] = frac
            if frac > self.mem_frac:
                problems.append(("memory_watermark",
                                 {"device": did, "bytes_in_use": in_use,
                                  "bytes_limit": limit,
                                  "frac": round(frac, 4),
                                  "threshold": self.mem_frac}))
        if problems:
            self.counts[self.mode] += 1
        self._resolve(obs, it, problems, evaluated=("memory_watermark",))

    # ------------------------------------------------------------ actions
    def _resolve(self, obs, it, problems, evaluated=()):
        """Emit one ``health`` event per firing check — edge-triggered
        on the warn channel: a check already firing at ``warn`` stays
        silent until a clean evaluation (``evaluated`` names the checks
        this call assessed) re-arms it or it escalates to fatal.  Fatal
        verdicts are never deduplicated: they abort the run."""
        fatal = []
        firing = set()
        for check, detail in problems:
            status = ("warn" if (self.mode == "warn"
                                 or check in _WARN_ONLY) else "fatal")
            firing.add(check)
            if status == "warn" and self._firing.get(check) == "warn":
                continue
            self._firing[check] = status
            obs.event("health", check=check, status=status, it=it,
                      detail=detail)
            Log.warning("health[%s] %s at iteration %d: %s",
                        status, check, it, detail)
            if status == "fatal":
                fatal.append(check)
        for check in evaluated:
            if check not in firing:
                self._firing.pop(check, None)
        if fatal:
            obs.flush()           # the timeline must survive the raise
            try:                  # black box for the abort (obs/watchdog.py)
                obs.flight("obs_health=fatal: %s" % "/".join(fatal),
                           extra={"it": it, "checks": fatal})
            except Exception:
                pass
            Log.fatal("obs_health=fatal: %s tripped at iteration %d "
                      "(timeline has the health event)"
                      % ("/".join(fatal), it))

    def verdict(self):
        """Worst verdict recorded so far — the live /healthz signal
        (obs/live.py): any fatal count makes the probe serve 503."""
        if self.counts.get("fatal"):
            return "fatal"
        if self.counts.get("warn"):
            return "warn"
        return "ok"

    def summary(self):
        """Folded into run_end: verdict counts + per-device memory peaks."""
        out = {"mode": self.mode, "counts": dict(self.counts)}
        if self.mem_peak_frac:
            out["mem_peak_frac"] = {str(k): round(v, 4)
                                    for k, v in self.mem_peak_frac.items()}
        return out
