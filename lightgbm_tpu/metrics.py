"""Evaluation metrics.

Parity targets: src/metric/*.hpp + src/metric/dcg_calculator.cpp, factory in
src/metric/metric.cpp:10-40.  Each metric declares
``factor_to_bigger_better`` exactly as the reference does (early stopping
multiplies by it).  All computed host-side in numpy (eval is off the
training hot path).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from .io.metadata import Metadata
from .objectives import (ObjectiveFunction, default_label_gain, get_discounts,
                         _max_dcg_at_k)
from .utils.config import Config
from .utils.log import Log

kEpsilon = 1e-15


class Metric:
    name = "base"
    # early stopping maximizes factor*score: loss-style metrics use -1
    # (regression_metric.hpp:29, binary_metric.hpp:54), AUC/NDCG/MAP use +1
    # (binary_metric.hpp:170, rank_metric.hpp:81, map_metric.hpp:65)
    factor_to_bigger_better = -1.0

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = None if metadata.label is None else np.asarray(metadata.label)
        self.weights = None if metadata.weights is None else np.asarray(metadata.weights)
        if self.weights is None:
            self.sum_weights = float(num_data)
        else:
            self.sum_weights = float(self.weights.sum())

    def get_names(self) -> List[str]:
        return [self.name]

    def eval(self, score: np.ndarray,
             objective: Optional[ObjectiveFunction]) -> List[float]:
        raise NotImplementedError


class _PointwiseRegressionMetric(Metric):
    def loss_on_point(self, label, score):
        raise NotImplementedError

    def average_loss(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64)
        # regression metrics apply objective->ConvertOutput when present
        # (regression_metric.hpp:70-84); identity for plain regression
        if objective is not None:
            score = np.asarray(objective.convert_output(score)).reshape(-1)
        loss = self.loss_on_point(self.label, score)
        if self.weights is not None:
            loss = loss * self.weights
        return [float(self.average_loss(loss.sum(), self.sum_weights))]


class L2Metric(_PointwiseRegressionMetric):
    name = "l2"

    def loss_on_point(self, label, score):
        return (score - label) ** 2


class RMSEMetric(_PointwiseRegressionMetric):
    name = "rmse"

    def loss_on_point(self, label, score):
        return (score - label) ** 2

    def average_loss(self, sum_loss, sum_weights):
        return np.sqrt(sum_loss / sum_weights)


class L1Metric(_PointwiseRegressionMetric):
    name = "l1"

    def loss_on_point(self, label, score):
        return np.abs(score - label)


class HuberLossMetric(_PointwiseRegressionMetric):
    name = "huber"

    def __init__(self, config: Config):
        self.delta = float(config.huber_delta)

    def loss_on_point(self, label, score):
        diff = score - label
        return np.where(np.abs(diff) <= self.delta,
                        0.5 * diff * diff,
                        self.delta * (np.abs(diff) - 0.5 * self.delta))


class FairLossMetric(_PointwiseRegressionMetric):
    name = "fair"

    def __init__(self, config: Config):
        self.c = float(config.fair_c)

    def loss_on_point(self, label, score):
        x = np.abs(score - label)
        return self.c * x - self.c * self.c * np.log(1.0 + x / self.c)


class PoissonMetric(_PointwiseRegressionMetric):
    name = "poisson"

    def loss_on_point(self, label, score):
        score = np.maximum(score, 1e-10)
        return score - label * np.log(score)


class _PointwiseBinaryMetric(Metric):
    """binary_metric.hpp:20-108: score converted to probability via the
    objective's sigmoid when available."""

    def loss_on_point(self, label, prob):
        raise NotImplementedError

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        if objective is not None:
            prob = np.asarray(objective.convert_output(score)).reshape(-1)
        else:
            prob = score
        loss = self.loss_on_point(self.label, prob)
        if self.weights is not None:
            loss = loss * self.weights
        return [float(loss.sum() / self.sum_weights)]


class BinaryLoglossMetric(_PointwiseBinaryMetric):
    name = "binary_logloss"

    def loss_on_point(self, label, prob):
        pos = label > 0
        p = np.where(pos, prob, 1.0 - prob)
        return -np.log(np.maximum(p, kEpsilon))


class BinaryErrorMetric(_PointwiseBinaryMetric):
    name = "binary_error"

    def loss_on_point(self, label, prob):
        return np.where(prob <= 0.5, label > 0, label <= 0).astype(np.float64)


class AUCMetric(Metric):
    """Weighted AUC via sorted rank-sum with tie blocks
    (binary_metric.hpp:157-259)."""
    name = "auc"
    factor_to_bigger_better = 1.0

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        label = self.label
        w = self.weights if self.weights is not None else np.ones_like(score)
        pos = (label > 0).astype(np.float64)
        order = np.argsort(-score, kind="stable")
        s, p, ww = score[order], pos[order], w[order]
        wpos = ww * p
        wneg = ww * (1.0 - p)
        # tie groups share credit: accum += neg_before * pos_in + 0.5*neg_in*pos_in
        boundaries = np.nonzero(np.diff(s))[0] + 1
        starts = np.concatenate([[0], boundaries])
        ends = np.concatenate([boundaries, [len(s)]])
        cpos = np.concatenate([[0.0], np.cumsum(wpos)])
        cneg = np.concatenate([[0.0], np.cumsum(wneg)])
        pos_in = cpos[ends] - cpos[starts]
        neg_in = cneg[ends] - cneg[starts]
        neg_before = cneg[starts]
        accum = (neg_before * pos_in + 0.5 * neg_in * pos_in).sum()
        total_pos = wpos.sum()
        total_neg = wneg.sum()
        if total_pos <= 0 or total_neg <= 0:
            return [1.0]
        # reference accumulates "correctly ordered" mass from the top; the
        # closed form equals 1 - wrong/total
        return [float(1.0 - accum / (total_pos * total_neg))]


class _MulticlassMetric(Metric):
    def __init__(self, config: Config):
        self.num_class = int(config.num_class)

    def loss_on_point(self, label, probs):
        raise NotImplementedError

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(self.num_class, self.num_data).T
        if objective is not None:
            probs = np.asarray(objective.convert_output(score))
        else:
            probs = score
        loss = self.loss_on_point(self.label.astype(np.int32), probs)
        if self.weights is not None:
            loss = loss * self.weights
        return [float(loss.sum() / self.sum_weights)]


class MultiSoftmaxLoglossMetric(_MulticlassMetric):
    name = "multi_logloss"

    def loss_on_point(self, label, probs):
        p = probs[np.arange(len(label)), label]
        return -np.log(np.maximum(p, kEpsilon))


class MultiErrorMetric(_MulticlassMetric):
    name = "multi_error"

    def loss_on_point(self, label, probs):
        return (np.argmax(probs, axis=1) != label).astype(np.float64)


class NDCGMetric(Metric):
    """rank_metric.hpp + dcg_calculator.cpp; all-negative queries count as
    NDCG=1."""
    name = "ndcg"
    factor_to_bigger_better = 1.0

    def __init__(self, config: Config):
        self.eval_at = list(config.ndcg_eval_at or [1, 2, 3, 4, 5])
        self.label_gain = np.asarray(config.label_gain or default_label_gain())

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("For NDCG metric, there should be query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.query_weights = metadata.query_weights
        nq = len(self.qb) - 1
        if self.query_weights is None:
            self.sum_query_weights = float(nq)
        else:
            self.sum_query_weights = float(np.asarray(self.query_weights).sum())
        self.inv_max_dcgs = np.zeros((nq, len(self.eval_at)))
        for q in range(nq):
            lab = self.label[self.qb[q]:self.qb[q + 1]]
            for j, k in enumerate(self.eval_at):
                m = _max_dcg_at_k(k, lab, self.label_gain)
                self.inv_max_dcgs[q, j] = 1.0 / m if m > 0.0 else -1.0

    def get_names(self) -> List[str]:
        return ["ndcg@%d" % k for k in self.eval_at]

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        nq = len(self.qb) - 1
        result = np.zeros(len(self.eval_at))
        for q in range(nq):
            s, e = self.qb[q], self.qb[q + 1]
            qw = 1.0 if self.query_weights is None else float(self.query_weights[q])
            if self.inv_max_dcgs[q, 0] <= 0.0:
                result += qw
                continue
            lab = self.label[s:e].astype(np.int32)
            order = np.argsort(-score[s:e], kind="stable")
            ranked_gain = self.label_gain[lab[order]]
            disc = get_discounts(len(lab))
            dcg_all = ranked_gain * disc
            for j, k in enumerate(self.eval_at):
                kk = min(k, len(lab))
                result[j] += dcg_all[:kk].sum() * self.inv_max_dcgs[q, j] * qw
        return [float(r / self.sum_query_weights) for r in result]


class MapMetric(Metric):
    """map_metric.hpp:16-140.  Note: the precision denominator uses the
    eval_at slot index (i + 1), reproducing the reference's behavior
    (map_metric.hpp:88-90) rather than the textbook position denominator."""
    name = "map"
    factor_to_bigger_better = 1.0

    def __init__(self, config: Config):
        self.eval_at = list(config.ndcg_eval_at or [1, 2, 3, 4, 5])

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("For MAP metric, there should be query information")
        self.qb = np.asarray(metadata.query_boundaries)
        self.query_weights = metadata.query_weights
        nq = len(self.qb) - 1
        if self.query_weights is None:
            self.sum_query_weights = float(nq)
        else:
            self.sum_query_weights = float(np.asarray(self.query_weights).sum())

    def get_names(self) -> List[str]:
        return ["map@%d" % k for k in self.eval_at]

    def eval(self, score, objective) -> List[float]:
        score = np.asarray(score, dtype=np.float64).reshape(-1)
        nq = len(self.qb) - 1
        result = np.zeros(len(self.eval_at))
        for q in range(nq):
            s, e = self.qb[q], self.qb[q + 1]
            qw = 1.0 if self.query_weights is None else float(self.query_weights[q])
            lab = self.label[s:e]
            order = np.argsort(-score[s:e], kind="stable")
            hits = lab[order] > 0.5
            num_hit = 0
            sum_ap = 0.0
            cur_left = 0
            for i, k in enumerate(self.eval_at):
                cur_k = min(k, len(lab))
                for j in range(cur_left, cur_k):
                    if hits[j]:
                        num_hit += 1
                        sum_ap += num_hit / (i + 1.0)
                result[i] += (sum_ap / cur_k) * qw if cur_k > 0 else 0.0
                cur_left = cur_k
        return [float(r / self.sum_query_weights) for r in result]


_METRIC_FACTORY = {
    "l2": L2Metric, "mean_squared_error": L2Metric, "mse": L2Metric,
    "l2_root": RMSEMetric, "root_mean_squared_error": RMSEMetric, "rmse": RMSEMetric,
    "l1": L1Metric, "mean_absolute_error": L1Metric, "mae": L1Metric,
    "huber": HuberLossMetric,
    "fair": FairLossMetric,
    "poisson": PoissonMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "ndcg": NDCGMetric,
    "map": MapMetric, "mean_average_precision": MapMetric,
    "multi_logloss": MultiSoftmaxLoglossMetric,
    "multi_error": MultiErrorMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Metric::CreateMetric (metric.cpp:10-40); None for unknown names."""
    cls = _METRIC_FACTORY.get(name)
    if cls is None:
        return None
    try:
        return cls(config)
    except TypeError:
        return cls()
