"""Boosting factory (src/boosting/boosting.cpp:30-62)."""
from __future__ import annotations

from ..utils.log import Log
from .gbdt import GBDT


def create_boosting(boosting_type: str, config, train_data=None,
                    objective=None, training_metrics=()):
    from .dart import DART
    from .goss import GOSS
    from .infiniteboost import InfiniteBoost
    types = {"gbdt": GBDT, "gbrt": GBDT, "dart": DART, "goss": GOSS,
             "infinite": InfiniteBoost, "infiniteboost": InfiniteBoost}
    cls = types.get(boosting_type)
    if cls is None:
        Log.fatal("Unknown boosting type %s", boosting_type)
    return cls(config, train_data, objective, training_metrics)
