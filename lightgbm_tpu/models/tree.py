"""Tree model: flat-array binary tree with text/JSON serialization.

Parity target: include/LightGBM/tree.h + src/io/tree.cpp.  Layout is the
reference's SoA scheme (tree.h:195-229): internal nodes indexed 0..n-2, leaves
addressed as bitwise-complement (~leaf) in child arrays.  The text format
written by ``to_string`` matches Tree::ToString (tree.cpp:312-343) so model
files interchange with the reference line.

Decision semantics (tree.h:229-276):
* numerical: fval <= threshold -> left;  categorical: int(fval) == threshold;
* a feature value in the zero range (-1e-20, 1e-20] is replaced by the node's
  ``default_value`` before the comparison (DefaultValueForZero).
"""
from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from ..utils.common import (array_to_string, avoid_inf, kMaxTreeOutput,
                            kMissingValueRange, parse_kv_lines, string_to_array)
from ..utils.log import Log


class NodeArrays(NamedTuple):
    """Per-internal-node SoA views of one tree, trimmed to the realized
    node count (Tree.node_arrays) — the unit the stacked device predictor
    packs without per-node Python loops."""
    split_feature: np.ndarray   # (ni,) i32 real (outer) feature index
    threshold: np.ndarray       # (ni,) f64
    decision_type: np.ndarray   # (ni,) i8 (1 = categorical)
    default_value: np.ndarray   # (ni,) f64 zero-range replacement value
    left_child: np.ndarray      # (ni,) i32 (~leaf for leaves)
    right_child: np.ndarray     # (ni,) i32


class Tree:
    def __init__(self, max_leaves: int = 2):
        self.max_leaves = max(int(max_leaves), 1)
        n = self.max_leaves
        self.num_leaves = 1
        # per internal node (n-1)
        self.left_child = np.zeros(n - 1, dtype=np.int32)
        self.right_child = np.zeros(n - 1, dtype=np.int32)
        self.split_feature_inner = np.zeros(n - 1, dtype=np.int32)
        self.split_feature = np.zeros(n - 1, dtype=np.int32)
        self.threshold_in_bin = np.zeros(n - 1, dtype=np.int32)
        self.threshold = np.zeros(n - 1, dtype=np.float64)
        self.decision_type = np.zeros(n - 1, dtype=np.int8)
        self.default_value = np.zeros(n - 1, dtype=np.float64)
        self.zero_bin = np.zeros(n - 1, dtype=np.int32)
        self.default_bin_for_zero = np.zeros(n - 1, dtype=np.int32)
        self.split_gain = np.zeros(n - 1, dtype=np.float64)
        self.internal_value = np.zeros(n - 1, dtype=np.float64)
        self.internal_count = np.zeros(n - 1, dtype=np.int64)
        # split-audit runner-up (runtime-only; NOT part of the text format):
        # real feature index of the second-best candidate at each split and
        # its gain — -1 / 0 when the winner had no competitor (including
        # trees loaded from the text format, which never carry these)
        self.second_feature = np.full(n - 1, -1, dtype=np.int32)
        self.second_gain = np.zeros(n - 1, dtype=np.float64)
        # per leaf (n)
        self.leaf_parent = np.zeros(n, dtype=np.int32)
        self.leaf_value = np.zeros(n, dtype=np.float64)
        self.leaf_count = np.zeros(n, dtype=np.int64)
        self.leaf_depth = np.zeros(n, dtype=np.int32)
        self.leaf_parent[0] = -1
        self.shrinkage = 1.0
        self.has_categorical = False
        # trees loaded from the text format carry only real-valued
        # thresholds (tree.cpp:312-343), so binned traversal is unavailable
        self.has_bin_thresholds = True

    # ---------------------------------------------------------------- build
    def split(self, leaf: int, inner_feature: int, bin_type_categorical: bool,
              threshold_bin: int, real_feature: int, threshold_double: float,
              left_value: float, right_value: float, left_cnt: int,
              right_cnt: int, gain: float, zero_bin: int,
              default_bin_for_zero: int, default_value: float) -> int:
        """Tree::Split (tree.cpp:55-110); returns the new (right) leaf id."""
        new_node = self.num_leaves - 1
        parent = self.leaf_parent[leaf]
        if parent >= 0:
            if self.left_child[parent] == ~leaf:
                self.left_child[parent] = new_node
            else:
                self.right_child[parent] = new_node
        self.split_feature_inner[new_node] = inner_feature
        self.split_feature[new_node] = real_feature
        self.zero_bin[new_node] = zero_bin
        self.default_bin_for_zero[new_node] = default_bin_for_zero
        self.default_value[new_node] = avoid_inf(default_value)
        if bin_type_categorical:
            self.decision_type[new_node] = 1
            self.has_categorical = True
        else:
            self.decision_type[new_node] = 0
        self.threshold_in_bin[new_node] = threshold_bin
        self.threshold[new_node] = avoid_inf(threshold_double)
        self.split_gain[new_node] = avoid_inf(gain)
        self.left_child[new_node] = ~leaf
        self.right_child[new_node] = ~self.num_leaves
        self.leaf_parent[leaf] = new_node
        self.leaf_parent[self.num_leaves] = new_node
        self.internal_value[new_node] = self.leaf_value[leaf]
        self.internal_count[new_node] = left_cnt + right_cnt
        self.leaf_value[leaf] = 0.0 if np.isnan(left_value) else left_value
        self.leaf_count[leaf] = left_cnt
        self.leaf_value[self.num_leaves] = 0.0 if np.isnan(right_value) else right_value
        self.leaf_count[self.num_leaves] = right_cnt
        depth = self.leaf_depth[leaf] + 1
        self.leaf_depth[leaf] = depth
        self.leaf_depth[self.num_leaves] = depth
        self.num_leaves += 1
        return self.num_leaves - 1

    def shrink(self, rate: float) -> None:
        """Tree::Shrinkage with the ±100 output clamp (tree.h:110-118)."""
        lv = self.leaf_value[:self.num_leaves] * rate
        self.leaf_value[:self.num_leaves] = np.clip(lv, -kMaxTreeOutput, kMaxTreeOutput)
        self.shrinkage *= rate

    def set_leaf_value(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = value

    def node_arrays(self) -> "NodeArrays":
        """Trimmed per-internal-node views (num_leaves - 1 entries) for
        bulk packing into stacked device tree arrays (ops/predict.py
        build_ranked_predictor).  Views, not copies — callers must not
        mutate."""
        ni = max(self.num_leaves - 1, 0)
        return NodeArrays(
            split_feature=self.split_feature[:ni],
            threshold=self.threshold[:ni],
            decision_type=self.decision_type[:ni],
            default_value=self.default_value[:ni],
            left_child=self.left_child[:ni],
            right_child=self.right_child[:ni])

    # -------------------------------------------------------------- predict
    def predict(self, features: np.ndarray) -> np.ndarray:
        """Batch predict on raw feature values, vectorized over rows.

        Mirrors Tree::GetLeaf (tree.h:250-276): iterative descent with the
        zero-range default redirect.
        """
        leaves = self.predict_leaf_index(features)
        if self.num_leaves <= 1:
            return np.zeros(features.shape[0], dtype=np.float64)
        return self.leaf_value[leaves]

    def predict_leaf_index(self, features: np.ndarray) -> np.ndarray:
        n = features.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            feat = self.split_feature[nd]
            fval = features[idx, feat]
            dv = self.default_value[nd]
            use_default = (fval > -kMissingValueRange) & (fval <= kMissingValueRange)
            fval = np.where(use_default, dv, fval)
            is_cat = self.decision_type[nd] == 1
            th = self.threshold[nd]
            with np.errstate(invalid="ignore"):
                go_left = np.where(
                    is_cat,
                    fval.astype(np.int64, copy=False) == th.astype(np.int64),
                    fval <= th)
            # NaN comparisons are False -> right, matching C++ operator<=
            node[idx] = np.where(go_left, self.left_child[nd], self.right_child[nd])
            active = node >= 0
        return (~node).astype(np.int32)

    def predict_contrib(self, features: np.ndarray,
                        num_features: int) -> np.ndarray:
        """Gain-weighted per-feature attribution of this tree's output.

        One descent (same semantics as predict_leaf_index) recording the
        visited nodes per depth level; each row's leaf value is then
        distributed over its path's split features proportionally to
        split gain.  Returns (N, num_features + 1): the last column is
        the bias — rows whose path carries no positive gain (stub trees,
        loaded models without gains) put the whole leaf value there.
        Rows sum to predict(features) up to one rounding per path node.
        """
        n = features.shape[0]
        out = np.zeros((n, num_features + 1), dtype=np.float64)
        if self.num_leaves <= 1:
            return out
        values = self.leaf_value[self.predict_leaf_index(features)]
        steps = []          # (rows, nodes) per depth level of the descent
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            steps.append((idx, nd))
            feat = self.split_feature[nd]
            fval = features[idx, feat]
            dv = self.default_value[nd]
            use_default = (fval > -kMissingValueRange) & \
                (fval <= kMissingValueRange)
            fval = np.where(use_default, dv, fval)
            is_cat = self.decision_type[nd] == 1
            th = self.threshold[nd]
            with np.errstate(invalid="ignore"):
                go_left = np.where(
                    is_cat,
                    fval.astype(np.int64, copy=False) == th.astype(np.int64),
                    fval <= th)
            node[idx] = np.where(go_left, self.left_child[nd],
                                 self.right_child[nd])
            active = node >= 0
        total = np.zeros(n, dtype=np.float64)
        for idx, nd in steps:
            g = self.split_gain[nd]
            total[idx] += np.where(g > 0, g, 0.0)
        no_gain = total <= 0
        out[no_gain, num_features] = values[no_gain]
        scale = np.where(no_gain, 0.0,
                         values / np.where(no_gain, 1.0, total))
        for idx, nd in steps:
            g = self.split_gain[nd]
            np.add.at(out, (idx, self.split_feature[nd]),
                      np.where(g > 0, g, 0.0) * scale[idx])
        return out

    def add_prediction_to_score(self, binned: np.ndarray, score: np.ndarray,
                                used_feature_idx: List[int]) -> None:
        """Valid-set score update on binned data (Tree::AddPredictionToScore).

        Decision in bin space: default-bin rows follow default_bin_for_zero;
        otherwise numerical bin <= threshold_bin, categorical bin == threshold.
        """
        n = binned.shape[0]
        if self.num_leaves <= 1:
            return
        inner_of_real = {r: i for i, r in enumerate(used_feature_idx)}
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        while active.any():
            idx = np.nonzero(active)[0]
            nd = node[idx]
            feat = self.split_feature_inner[nd]
            b = binned[idx, feat].astype(np.int64)
            th = self.threshold_in_bin[nd]
            is_cat = self.decision_type[nd] == 1
            go_left = np.where(is_cat, b == th, b <= th)
            is_def = b == self.zero_bin[nd]
            dbz = self.default_bin_for_zero[nd]
            def_left = np.where(is_cat, dbz == th, dbz <= th)
            go_left = np.where(is_def, def_left, go_left)
            node[idx] = np.where(go_left, self.left_child[nd], self.right_child[nd])
            active = node >= 0
        score += self.leaf_value[(~node).astype(np.int32)]

    # ------------------------------------------------------------ serialize
    def to_string(self) -> str:
        """Tree::ToString field order (tree.cpp:312-343)."""
        nl = self.num_leaves
        ni = nl - 1
        buf = ["num_leaves=%d" % nl]
        buf.append("split_feature=" + array_to_string(self.split_feature[:ni]))
        buf.append("split_gain=" + array_to_string(self.split_gain[:ni]))
        buf.append("threshold=" + array_to_string(self.threshold[:ni]))
        buf.append("decision_type=" + array_to_string(self.decision_type[:ni]))
        buf.append("default_value=" + array_to_string(self.default_value[:ni]))
        buf.append("left_child=" + array_to_string(self.left_child[:ni]))
        buf.append("right_child=" + array_to_string(self.right_child[:ni]))
        buf.append("leaf_parent=" + array_to_string(self.leaf_parent[:nl]))
        buf.append("leaf_value=" + array_to_string(self.leaf_value[:nl]))
        buf.append("leaf_count=" + array_to_string(self.leaf_count[:nl]))
        buf.append("internal_value=" + array_to_string(self.internal_value[:ni]))
        buf.append("internal_count=" + array_to_string(self.internal_count[:ni]))
        buf.append("shrinkage=%s" % repr(self.shrinkage))
        buf.append("has_categorical=%d" % (1 if self.has_categorical else 0))
        buf.append("")
        return "\n".join(buf) + "\n"

    @classmethod
    def from_string(cls, s: str) -> "Tree":
        """Tree(const std::string&) loader (tree.cpp:443-552)."""
        kv = parse_kv_lines(s.splitlines())
        if "num_leaves" not in kv:
            Log.fatal("Tree model should contain num_leaves field.")
        num_leaves = int(kv["num_leaves"])
        self = cls(max(num_leaves, 2))
        self.num_leaves = num_leaves
        if num_leaves <= 1:
            return self
        ni, nl = num_leaves - 1, num_leaves

        def req(key, dtype, count):
            if key not in kv:
                Log.fatal("Tree model string format error, should contain %s field", key)
            return string_to_array(kv[key], dtype)[:count]

        self.left_child[:ni] = req("left_child", np.int32, ni)
        self.right_child[:ni] = req("right_child", np.int32, ni)
        self.split_feature[:ni] = req("split_feature", np.int32, ni)
        self.threshold[:ni] = req("threshold", np.float64, ni)
        self.default_value[:ni] = req("default_value", np.float64, ni)
        self.leaf_value[:nl] = req("leaf_value", np.float64, nl)
        if "decision_type" in kv:
            self.decision_type[:ni] = string_to_array(kv["decision_type"], np.float64)[:ni].astype(np.int8)
        if "split_gain" in kv:
            self.split_gain[:ni] = string_to_array(kv["split_gain"], np.float64)[:ni]
        if "leaf_parent" in kv:
            self.leaf_parent[:nl] = string_to_array(kv["leaf_parent"], np.int32)[:nl]
        if "leaf_count" in kv:
            self.leaf_count[:nl] = string_to_array(kv["leaf_count"], np.float64)[:nl].astype(np.int64)
        if "internal_value" in kv:
            self.internal_value[:ni] = string_to_array(kv["internal_value"], np.float64)[:ni]
        if "internal_count" in kv:
            self.internal_count[:ni] = string_to_array(kv["internal_count"], np.float64)[:ni].astype(np.int64)
        if "shrinkage" in kv:
            self.shrinkage = float(kv["shrinkage"])
        if "has_categorical" in kv:
            self.has_categorical = int(kv["has_categorical"]) != 0
        self.has_bin_thresholds = False
        return self

    def to_json(self) -> str:
        """Tree::ToJSON (tree.cpp:345-358)."""
        out = ['"num_leaves":%d,' % self.num_leaves,
               '"shrinkage":%s,' % repr(float(self.shrinkage)),
               '"has_categorical":%d,' % (1 if self.has_categorical else 0)]
        root = -1 if self.num_leaves == 1 else 0
        out.append('"tree_structure":' + self._node_to_json(root))
        return "\n".join(out) + "\n"

    def _node_to_json(self, index: int) -> str:
        if index >= 0:
            return ("{\n"
                    '"split_index":%d,\n'
                    '"split_feature":%d,\n'
                    '"split_gain":%s,\n'
                    '"threshold":%s,\n'
                    '"decision_type":"%s",\n'
                    '"default_value":%s,\n'
                    '"internal_value":%s,\n'
                    '"internal_count":%d,\n'
                    '"left_child":%s,\n'
                    '"right_child":%s\n'
                    "}") % (
                index, self.split_feature[index], repr(float(self.split_gain[index])),
                repr(float(self.threshold[index])),
                "no_greater" if self.decision_type[index] == 0 else "is",
                repr(float(self.default_value[index])), repr(float(self.internal_value[index])),
                self.internal_count[index],
                self._node_to_json(self.left_child[index]),
                self._node_to_json(self.right_child[index]))
        leaf = ~index
        return ("{\n"
                '"leaf_index":%d,\n'
                '"leaf_parent":%d,\n'
                '"leaf_value":%s,\n'
                '"leaf_count":%d\n'
                "}") % (leaf, self.leaf_parent[leaf],
                        repr(float(self.leaf_value[leaf])), self.leaf_count[leaf])

    # ------------------------------------------------------------- analysis
    def depth_of_leaf(self, leaf: int) -> int:
        return int(self.leaf_depth[leaf])
