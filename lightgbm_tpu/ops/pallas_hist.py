"""Pallas TPU kernel for leaf histogram construction — the hottest op.

Parity target: the reference's OpenCL histogram kernels
(src/treelearner/ocl/histogram256.cl etc.), which scatter-add into
workgroup-local memory with atomics.  TPUs have no fast scatter, so the
kernel re-expresses the histogram as a one-hot contraction on the MXU —
but unlike the XLA `onehot` path (ops/histogram.py), the one-hot tile is
built **inside VMEM** per (row-chunk, feature-block) grid cell and never
round-trips through HBM:

  grid = (F/F_BLK, N/ROW_CHUNK)          (row chunks iterate fastest)
  per cell: for f in feature block:
      oh  = (bins_iota == x[f, :])        (B, C) one-hot in VMEM
      acc = oh (B, C) @ w (3, C)^T        MXU contraction (A @ B^T)
      out[f] += acc                        revisiting accumulation over chunks

Layouts are chosen for the TPU tiling rules (last dim % 128, second-to-last
% 8): bins arrive transposed (F, N), weights as a (3, N) row-vector
[g*m, h*m, m] (an (N, 3) column operand would pay the 128-lane tile
padding — 42.7x its logical bytes; see pallas_wave.py), the
histogram leaves as (F, B, 3) — exactly the layout the split scanner wants,
no transposes anywhere.  The leaf mask and bagging/GOSS row multipliers are
folded into `w` by the caller, so rows outside the target leaf contribute
zero, as in the other histogram modes.

HBM traffic per leaf: read the bins + 12N bytes of weights, write F*B*12
bytes of histogram — the one-hot (N*F*B*4 bytes) stays on-chip.

Measured on v5e (1M x 28 rows, dedup-proof varying inputs): 25ms at B=63 /
45ms at B=255 versus XLA's fused one-hot reduce at 7.2ms / 25.6ms — the
XLA path is already at the VPU roofline, and the MXU contraction here
wastes 125/128 output lanes because a histogram has only 3 weight columns.
The kernel therefore is an optional mode (tpu_histogram_mode=pallas), kept
as the foundation for the regime where the MXU *does* win: batching many
weight columns (multiclass trees, multi-leaf level-wise growth) to fill
the N dimension.  Default TPU mode is `onehot` (ops/learner.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    HAS_PALLAS = True
except ImportError:                                    # pragma: no cover
    HAS_PALLAS = False

# the one-hot tile + resident accumulator must fit here; the lanes
# (row-chunk) axis of the block can shrink no further than the TPU's
# 128-lane tile, so bin widths the floor cannot absorb are OUT of the
# kernel's capacity (supports_bins) rather than silently over budget
TILE_BUDGET = 6 * 2**20
_MIN_ROW_CHUNK = 128


def tile_shape(num_bins: int):
    """(F_BLK, ROW_CHUNK) sized so the (F_BLK*B, C) one-hot tile stays well
    under the ~16MB VMEM budget.  F_BLK stays at 8 (the TPU sublane
    minimum for f32 blocks); large-B kernels shrink the row chunk.

    The budget is accounted against the kernel's LIVE SET, not the
    one-hot tile alone — the wave-kernel band post-mortem
    (ops/pallas_wave.py::_tile_plan, docs/FusedIteration.md) showed that
    ignoring resident blocks is exactly how mid-size shapes silently
    oversubscribe VMEM.  Here the resident (F_BLK, B, 3) f32 accumulator
    is bounded (F_BLK is fixed at 8), so it is subtracted from the tile
    budget rather than driving a separate regime.

    The chunk floor is the 128-lane tile minimum, NOT a round perf
    number: the old 512 floor quietly handed B=1024 a 16MB one-hot
    (2.7x the budget) and B=4096 a 64MB one — the exact
    floor-masks-the-budget bug class of the wave band post-mortem,
    surfaced by the vmem lint pass (analysis/vmem.py) when it first ran.
    Widths even the 128 floor cannot absorb fail ``supports_bins`` and
    never reach the kernel (leaf_histogram_pallas falls back to onehot).

    Public: the kernel's VMEM geometry is part of the selection surface
    the autotuner (ops/autotune.py) and its probe harness reason about
    when instantiating kernel cells standalone."""
    f_blk = 8
    row_chunk = 2048
    resident = f_blk * num_bins * 3 * 4          # the out block, VMEM-held
    budget = TILE_BUDGET - resident
    while f_blk * num_bins * row_chunk * 4 > budget \
            and row_chunk > _MIN_ROW_CHUNK:
        row_chunk //= 2
    return f_blk, row_chunk


def supports_bins(num_bins: int) -> bool:
    """True when some %128 row chunk keeps the kernel's live set
    (one-hot tile + resident accumulator) within TILE_BUDGET.  At f32
    with F_BLK=8 this tops out just under B=2048; beyond it the kernel
    would need bin-axis blocking it does not have."""
    f_blk = 8
    resident = f_blk * num_bins * 3 * 4
    return (f_blk * num_bins * _MIN_ROW_CHUNK * 4
            <= TILE_BUDGET - resident)


_tile_shape = tile_shape        # pre-v8 private name, kept importable


def _hist_kernel(x_ref, w_ref, out_ref, *, num_bins: int, f_blk: int):
    """One (feature-block, row-chunk) cell.

    x_ref: (F_BLK, C) f32 bin ids; w_ref: (3, C) f32 row-vector weights;
    out_ref: (F_BLK, B, 3) f32 accumulated over the row-chunk grid axis.

    The whole block's one-hot is built as ONE (F_BLK*B, C) tile: row r
    compares feature r//B against bin r%B.  The row replication x[r//B] is
    an MXU matmul with a constant 0/1 selection matrix, so the cell is two
    MXU contractions + one VPU compare — no per-feature loop.
    """
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    C = x_ref.shape[1]
    FB = f_blk * num_bins
    x = x_ref[:]                                       # (F_BLK, C) f32
    w = w_ref[:]                                       # (3, C) row-vector
    # S[r, j] = 1 iff j == r // B  (compile-time constant tile)
    r_over_b = lax.broadcasted_iota(jnp.int32, (FB, f_blk), 0) // num_bins
    feat = lax.broadcasted_iota(jnp.int32, (FB, f_blk), 1)
    sel = (r_over_b == feat).astype(jnp.float32)       # (FB, F_BLK)
    x_rep = jnp.dot(sel, x, preferred_element_type=jnp.float32)  # (FB, C)
    b_of_r = (lax.broadcasted_iota(jnp.int32, (FB, C), 0)
              % num_bins).astype(jnp.float32)
    oh = (x_rep == b_of_r).astype(jnp.float32)         # (FB, C)
    acc = lax.dot_general(                             # A @ B^T: both C
        oh, w, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (FB, 3)
    out_ref[:] = out_ref[:] + acc.reshape(f_blk, num_bins, 3)


@functools.partial(jax.jit, static_argnames=("num_bins", "interpret"))
def _hist_pallas(xt, w, num_bins: int, interpret: bool):
    f, n = xt.shape
    f_blk, row_chunk = tile_shape(num_bins)
    grid = (f // f_blk, n // row_chunk)
    kernel = functools.partial(_hist_kernel, num_bins=num_bins, f_blk=f_blk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((f_blk, row_chunk), lambda i, c: (i, c)),
            pl.BlockSpec((3, row_chunk), lambda i, c: (0, c)),
        ],
        out_specs=pl.BlockSpec((f_blk, num_bins, 3), lambda i, c: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, num_bins, 3), jnp.float32),
        interpret=interpret,
    )(xt, w)


def leaf_histogram_pallas(binned, grad, hess, leaf_id, leaf, row_mult,
                          num_bins: int, interpret: bool = None):
    """(F, B, 3) histogram of the target leaf via the fused Pallas kernel.

    Same contract as leaf_histogram_scatter/onehot (ops/histogram.py).
    interpret defaults to True off-TPU so tests exercise the kernel on the
    CPU mesh (the reference's OpenCL-on-CPU trick, SURVEY.md §4).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if not supports_bins(num_bins):
        # beyond the kernel's bin capacity even the minimum row chunk
        # oversubscribes VMEM — serve the request from the XLA one-hot
        # path instead of shipping an over-budget tile to the compiler
        from ..utils.log import Log
        from .histogram import leaf_histogram_onehot
        Log.warning("pallas histogram: num_bins=%d exceeds the kernel's "
                    "VMEM capacity (analysis/vmem.py vmem-hist-tile); "
                    "falling back to onehot", num_bins)
        return leaf_histogram_onehot(binned, grad, hess, leaf_id, leaf,
                                     row_mult, num_bins=num_bins)
    n, f = binned.shape
    from .histogram import _weights
    w = _weights(jnp.asarray(grad, jnp.float32),
                 jnp.asarray(hess, jnp.float32), leaf_id, leaf,
                 None if row_mult is None
                 else jnp.asarray(row_mult, jnp.float32))   # (N, 3)

    f_blk, row_chunk = tile_shape(num_bins)
    npad = (-n) % row_chunk
    fpad = (-f) % f_blk
    xt = binned.astype(jnp.float32).T                   # (F, N); bins < 2^24
                                                        # so f32 compare exact
    # weights as a (3, N) row-vector operand: an (N, 3) column layout
    # would pay TPU's 128-lane tile padding (42.7x its logical bytes —
    # the same class of HBM blowup fixed in pallas_wave.py)
    wt = jnp.transpose(w)                               # (3, N)
    if npad:
        xt = jnp.pad(xt, ((0, 0), (0, npad)))
        wt = jnp.pad(wt, ((0, 0), (0, npad)))           # zero weight rows
    if fpad:
        xt = jnp.pad(xt, ((0, fpad), (0, 0)))

    out = _hist_pallas(xt, wt, num_bins, interpret)
    return out[:f]                                      # (F, B, 3)
