#!/bin/bash
# Stage 4: after stage 3 (A/B + suite re-run), final flagship bench with
# the full round-3 configuration (compact-layout kernels + compact lookup).
cd /root/repo
while pgrep -f "chain_r03c.sh" > /dev/null; do sleep 60; done
echo "[chain4] stage3 done at $(date -u)" >> /tmp/chain_r03.log
BENCH_DEADLINE_S=14400 python bench.py > /tmp/bench_r03d.out 2> /tmp/bench_r03d.err
echo "[chain4] bench rc=$? at $(date -u)" >> /tmp/chain_r03.log
cat /tmp/bench_r03d.out >> /tmp/chain_r03.log
