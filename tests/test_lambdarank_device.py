"""Device lambdarank vs the numpy oracle (VERDICT r1 weak #4).

The jitted padded-vmap gradient program must reproduce the reference-shaped
per-query numpy implementation (rank_objective.hpp:100-190 semantics)
bit-closely; and ranking training must stay on device end-to-end.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.metadata import Metadata
from lightgbm_tpu.objectives import LambdarankNDCG
from lightgbm_tpu.utils.config import Config


def _make_ranking(nq=50, seed=3, max_docs=40):
    rng = np.random.default_rng(seed)
    counts = rng.integers(1, max_docs, size=nq)
    n = int(counts.sum())
    qb = np.concatenate([[0], np.cumsum(counts)])
    labels = rng.integers(0, 5, size=n).astype(np.float64)
    X = rng.normal(size=(n, 8))
    X[:, 0] += labels  # informative feature
    return X, labels, qb, counts


def _objective(labels, qb, weights=None, **params):
    cfg = Config(dict({"objective": "lambdarank", "verbose": -1}, **params))
    md = Metadata(len(labels))
    md.set_label(labels)
    md.set_query_counts(np.diff(qb))
    if weights is not None:
        md.set_weights(weights)
    obj = LambdarankNDCG(cfg)
    obj.init(md, len(labels))
    return obj


@pytest.mark.parametrize("with_weights", [False, True])
def test_device_matches_host_oracle(with_weights):
    X, labels, qb, counts = _make_ranking()
    n = len(labels)
    rng = np.random.default_rng(7)
    w = rng.random(n) + 0.5 if with_weights else None
    obj = _objective(labels, qb, weights=w)
    for it in range(3):
        score = rng.normal(size=n) * (it + 1)
        g_d, h_d = obj.get_gradients(score)
        g_h, h_h = obj.get_gradients_host(score)
        np.testing.assert_allclose(np.asarray(g_d), np.asarray(g_h),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(h_d), np.asarray(h_h),
                                   rtol=2e-4, atol=2e-5)


def test_device_handles_degenerate_queries():
    # single-doc queries and all-equal labels must produce zero lambdas
    labels = np.array([1.0, 2.0, 2.0, 2.0, 0.0])
    qb = np.array([0, 1, 4, 5])
    obj = _objective(labels, qb)
    g, h = obj.get_gradients(np.array([0.3, 0.1, 0.2, -0.5, 0.9]))
    assert np.allclose(np.asarray(g), 0.0)
    assert np.allclose(np.asarray(h), 0.0)


def test_ranking_trains_end_to_end():
    X, labels, qb, counts = _make_ranking(nq=80)
    ds = lgb.Dataset(X, label=labels, group=np.diff(qb))
    bst = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                     "ndcg_eval_at": [5], "num_leaves": 15,
                     "learning_rate": 0.1, "verbose": -1},
                    ds, num_boost_round=20,
                    valid_sets=[ds], valid_names=["train"])
    res = bst.eval_train()
    ndcg = [v for (_, name, v, _) in res if "ndcg" in name][0]
    assert ndcg > 0.75, ndcg
