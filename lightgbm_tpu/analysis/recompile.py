"""Pass 2 — recompile-hazard: statically enforce what ``obs recompiles
--check`` only observes.

The runtime tracker (obs/compile.py) attributes a steady-state recompile
to the argument signature that changed — after the device time is
already burned.  Three hazards are decidable from the AST alone:

* ``jit-in-loop`` — ``jax.jit`` (or ``functools.partial(jax.jit, ...)``)
  called inside a ``for``/``while`` body builds a NEW jitted callable
  (and a new jit cache) every trip; nothing ever hits warm.  The
  trackers would report it as an entry rebuild — this rejects it before
  it runs.
* ``jit-static-drift`` — a ``static_argnames`` entry that names no
  parameter of the decorated function, or a ``static_argnums`` index out
  of range.  jax errors on some of these only at call time, and a
  misspelled static name silently demotes the argument to traced — the
  exact drift class the rule name comes from.
* ``jit-unhashable-static`` — a dict/set/list literal passed in a static
  position of a module-local jitted function: ``TypeError: unhashable
  type`` at call time, found at lint time instead.
"""
from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional

from .core import Finding, SourceModule, dotted_name, str_const
from .hostsync import walk_scope

PASS_NAME = "recompile"

RULES = {
    "jit-in-loop":
        "jax.jit called inside a loop body re-creates the jitted "
        "callable (and its cache) every iteration",
    "jit-static-drift":
        "static_argnames/static_argnums names a parameter the function "
        "does not have",
    "jit-unhashable-static":
        "unhashable literal (dict/set/list) passed as a static argument "
        "of a jitted entry",
}


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / functools.partial(jax.jit, ...) / partial(jax.jit, ...)"""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(node.func)
    return False


def _jit_call_node(node: ast.AST) -> Optional[ast.Call]:
    """The Call whose keywords carry static_argnames/nums, if this
    expression is a jit application with arguments."""
    if isinstance(node, ast.Call) and _is_jit_expr(node):
        return node
    return None


class JitEntry(NamedTuple):
    fn: ast.FunctionDef
    static_names: List[str]      # resolved static parameter NAMES
    decorator_line: int


def _str_items(node: ast.AST) -> Optional[List[str]]:
    """["a", "b"] for a str constant or tuple/list of str constants."""
    s = str_const(node)
    if s is not None:
        return [s]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            s = str_const(el)
            if s is None:
                return None
            out.append(s)
        return out
    return None


def _int_items(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return out
    return None


def _param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs])


def _check_decorator(mod: SourceModule, fn: ast.FunctionDef,
                     jit_call: ast.Call,
                     findings: List[Finding]) -> List[str]:
    """Validate static_argnames/nums against the signature; return the
    resolved static parameter names for call-site checking."""
    params = _param_names(fn)
    positional = ([p.arg for p in fn.args.posonlyargs]
                  + [p.arg for p in fn.args.args])
    static: List[str] = []
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            names = _str_items(kw.value)
            if names is None:
                continue            # dynamic expression: not decidable
            for n in names:
                if n not in params:
                    findings.append(Finding(
                        "jit-static-drift", PASS_NAME, mod.path,
                        jit_call.lineno,
                        "static_argnames %r is not a parameter of %s()"
                        % (n, fn.name),
                        "rename the entry in static_argnames or the "
                        "parameter — a misspelled name silently traces "
                        "the argument"))
                else:
                    static.append(n)
        elif kw.arg == "static_argnums":
            nums = _int_items(kw.value)
            if nums is None:
                continue
            for i in nums:
                j = i + len(positional) if i < 0 else i
                if not 0 <= j < len(positional):
                    findings.append(Finding(
                        "jit-static-drift", PASS_NAME, mod.path,
                        jit_call.lineno,
                        "static_argnums %d is out of range for %s() "
                        "(%d positional parameters)"
                        % (i, fn.name, len(positional)),
                        "re-point static_argnums at the intended "
                        "parameter"))
                else:
                    static.append(positional[j])
    return static


_UNHASHABLE = (ast.Dict, ast.Set, ast.List, ast.DictComp, ast.SetComp,
               ast.ListComp)


def _check_call_sites(mod: SourceModule, entries: Dict[str, JitEntry],
                      findings: List[Finding]) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        entry = entries.get(fname) or entries.get(
            fname.rsplit(".", 1)[-1] if "." in fname else "")
        if entry is None:
            continue
        positional = ([p.arg for p in entry.fn.args.posonlyargs]
                      + [p.arg for p in entry.fn.args.args])
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred):
                break               # positions unknowable past a splat
            if i < len(positional) and positional[i] in entry.static_names \
                    and isinstance(arg, _UNHASHABLE):
                findings.append(Finding(
                    "jit-unhashable-static", PASS_NAME, mod.path,
                    arg.lineno,
                    "unhashable literal passed for static parameter %r "
                    "of %s()" % (positional[i], entry.fn.name),
                    "pass a hashable (tuple / frozenset / scalar) — "
                    "static args key the jit cache"))
        for kw in node.keywords:
            if kw.arg in entry.static_names \
                    and isinstance(kw.value, _UNHASHABLE):
                findings.append(Finding(
                    "jit-unhashable-static", PASS_NAME, mod.path,
                    kw.value.lineno,
                    "unhashable literal passed for static parameter %r "
                    "of %s()" % (kw.arg, entry.fn.name),
                    "pass a hashable (tuple / frozenset / scalar) — "
                    "static args key the jit cache"))


def _check_jit_in_loop(mod: SourceModule,
                       findings: List[Finding]) -> None:
    """Flag jax.jit applications syntactically inside a loop body.

    Scoped per function (walk_scope) so a jit in a factory function that
    is itself CALLED from a loop is the caller's problem, not a textual
    false positive here."""
    scopes: List[List[ast.stmt]] = [mod.tree.body]
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    for body in scopes:
        loops = [n for n in walk_scope(body)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        for loop in loops:
            for node in walk_scope([loop]):
                if isinstance(node, ast.Call) and _is_jit_expr(node) \
                        and dotted_name(node.func) in ("jax.jit", "jit"):
                    findings.append(Finding(
                        "jit-in-loop", PASS_NAME, mod.path, node.lineno,
                        "jax.jit inside a loop builds a fresh jitted "
                        "callable every iteration — its cache never "
                        "hits warm",
                        "hoist the jit out of the loop (build once, "
                        "call many)"))


def run(modules: List[SourceModule], repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for mod in modules:
        entries: Dict[str, JitEntry] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                jc = _jit_call_node(dec)
                if jc is None:
                    if _is_jit_expr(dec):
                        entries[node.name] = JitEntry(node, [],
                                                      node.lineno)
                    continue
                static = _check_decorator(mod, node, jc, findings)
                entries[node.name] = JitEntry(node, static, node.lineno)
        if entries:
            _check_call_sites(mod, entries, findings)
        _check_jit_in_loop(mod, findings)
    return findings
