# RDS persistence — parity with R-package/R/saveRDS.lgb.Booster.R and
# readRDS.lgb.Booster.R: the booster handle is a live runtime object, so
# the RDS carries the reference-compatible model TEXT (the durable
# serialization surface, gbdt.cpp:817-861) plus the training attributes.

#' Save an lgb.Booster to an RDS file
#'
#' @param object lgb.Booster
#' @param file path to write
#' @export
saveRDS.lgb.Booster <- function(object, file = "", ascii = FALSE,
                                version = NULL, compress = TRUE,
                                refhook = NULL) {
  if (!lgb.is.Booster(object)) stop("saveRDS.lgb.Booster: need an lgb.Booster")
  payload <- list(model_str = lgb.model.to.string(object),
                  best_iter = attr(object, "best_iter"),
                  record_evals = attr(object, "record_evals"))
  class(payload) <- "lgb.Booster.rds"
  saveRDS(payload, file = file, ascii = ascii, version = version,
          compress = compress, refhook = refhook)
  invisible(object)
}

#' Restore an lgb.Booster from an RDS file
#'
#' @param file path written by saveRDS.lgb.Booster
#' @export
readRDS.lgb.Booster <- function(file = "", refhook = NULL) {
  payload <- readRDS(file = file, refhook = refhook)
  if (!inherits(payload, "lgb.Booster.rds")) {
    stop("readRDS.lgb.Booster: file was not written by saveRDS.lgb.Booster")
  }
  bst <- lgb.load(model_str = payload$model_str)
  attr(bst, "best_iter") <- payload$best_iter
  attr(bst, "record_evals") <- payload$record_evals
  bst
}
