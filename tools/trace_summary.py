"""Summarize a jax.profiler trace directory OR an obs JSONL timeline.

There is no TensorBoard/Perfetto UI in this image, so the flagship
residue analysis (ROADMAP.md: ~130 ms/wave outside the histogram
kernel) needs a programmatic reader.  Two input kinds:

* a profiler trace directory — jax.profiler.trace() writes a
  Perfetto-format ``*.trace.json.gz`` under
  ``<outdir>/plugins/profile/<run>/``; aggregates complete ('ph' == 'X')
  events per track, ranks device-side op time, prints top offenders;
* a ``.jsonl`` event timeline written by the run observer
  (``obs_events_path``, lightgbm_tpu/obs) — prints the run header, the
  per-phase table, the compile-vs-execute split per jitted entry point,
  and the peak device memory.  ``--csv`` emits the per-phase and
  per-entry rows as CSV instead (for the bench artifacts directory).

Usage:  python tools/trace_summary.py /tmp/tpu_trace_1m [top_n]
        python tools/trace_summary.py /tmp/run_events.jsonl [--csv]
"""
import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir):
    pats = [os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json.gz")]
    paths = []
    for p in pats:
        paths = sorted(glob.glob(p, recursive=True))
        if paths:
            break
    if not paths:
        raise SystemExit("no *.trace.json.gz under %s" % trace_dir)
    path = paths[-1]                      # newest run
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    return path, data.get("traceEvents", [])


def summarize_jsonl(path, csv=False, out=None):
    """Summarize the LAST run recorded in an obs event timeline.

    Ingest rides lightgbm_tpu/obs/query.py — the same loader the
    ``python -m lightgbm_tpu obs`` CLI uses, so the two consumers can
    never disagree about run grouping or validation."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from lightgbm_tpu.obs import query
    out = out if out is not None else sys.stdout
    events = query.last_run(query.load_timeline(path))
    if not events:
        raise SystemExit("no events in %s" % path)
    run = events[-1]["run"]
    header = next((e for e in events if e["ev"] == "run_header"), None)
    iters = [e for e in events if e["ev"] == "iter"]
    compiles = [e for e in events if e["ev"] == "compile"]
    recompiles = query.recompile_rows(events)
    run_end = next((e for e in events if e["ev"] == "run_end"), None)

    phase_totals = collections.Counter()
    for e in iters:
        for k, v in e["phases"].items():
            phase_totals[k] += v
    total_s = sum(e["time_s"] for e in iters)
    entries = (run_end or {}).get("entries", {})
    health = [e for e in events if e["ev"] == "health"]
    metric_evs = [e for e in events if e["ev"] == "metrics"]
    scrape = metric_evs[-1]["scrape"] if metric_evs else {}
    serve = None
    if any(e["ev"].startswith("serve_") for e in events):
        from lightgbm_tpu.obs import serve as obs_serve
        serve = obs_serve.serve_metrics(events)

    if csv:
        w = out.write
        w("kind,name,total_s,mean_s,count,extra\n")
        for k, v in phase_totals.most_common():
            w("phase,%s,%.6f,%.6f,%d,\n" % (k, v, v / max(len(iters), 1),
                                            len(iters)))
        for name, st in sorted(entries.items()):
            w("entry_compile,%s,%.6f,%.6f,1,first_call\n"
              % (name, st["first_s"], st["first_s"]))
            w("entry_execute,%s,%.6f,%.6f,%d,steady_state\n"
              % (name, st["exec_total_s"], st["exec_mean_s"],
                 st["exec_n"]))
        for r in recompiles:
            w("compile_attr,%s,,,%d,sig_compiles=%d\n"
              % (r["entry"], r["n_compiles"], r["sig_compiles"]))
        hc = collections.Counter((e["check"], e["status"]) for e in health)
        for (check, status), n in sorted(hc.items()):
            w("health,%s,,,%d,%s\n" % (check, n, status))
        for name, m in sorted(scrape.items()):
            if m.get("type") == "histogram":
                w("metric,%s,%.6f,,%d,histogram\n"
                  % (name, m["sum"], m["count"]))
            else:
                w("metric,%s,%.6f,,1,%s\n"
                  % (name, float(m["value"]), m.get("type", "")))
        if serve and serve.get("present"):
            t = serve["totals"]
            w("serve_total,all,,,%d,rows=%d pad=%d shed=%d sampled=%d\n"
              % (t["batches"], t["rows"], t["pad_rows"],
                 t["shed_total"], int(t["sampled"])))
            for k, r in sorted(serve.get("routes", {}).items()):
                w("serve_route,%s,%.6f,%.6f,%d,p99_s=%.6f\n"
                  % (k, r.get("mean_s", 0.0) * r["n"],
                     r.get("mean_s", 0.0), r["n"], r.get("p99_s", 0.0)))
        return

    w = lambda s="": out.write(s + "\n")
    w("timeline: %s  (run %s)" % (path, run))
    if header is not None:
        ctx = header.get("context", {})
        w("backend: %s  devices: %d  timing: %s" % (
            header.get("backend"), len(header.get("devices", [])),
            header.get("timing")))
        ws = int(header.get("world_size", 1) or 1)
        if header.get("merged"):
            w("merged view: %d-rank run (ranks %s)" % (
                ws, header.get("merged_ranks", [])))
        elif ws > 1:
            w("rank: %d of %d — ONE shard; merge for the cross-rank "
              "view (python -m lightgbm_tpu obs merge %s)" % (
                  int(header.get("rank", 0)), ws, path))
        w("learner: %s" % (", ".join(
            "%s=%s" % (k, ctx[k]) for k in sorted(ctx))))
    fenced = all(e.get("fenced") for e in iters) if iters else False
    if iters or not (serve and serve.get("present")):
        # serve-only timelines have no training iterations — skip the
        # empty phase table instead of printing a 0-iteration header
        w("\n== per-phase time over %d iterations (%s) ==" % (
            len(iters), "fenced" if fenced else "dispatch-only — NOT "
            "device-accurate (obs_timing=off)"))
        w("  %10s %10s %7s  %s" % ("total_s", "mean_ms", "share",
                                   "phase"))
        for k, v in phase_totals.most_common():
            w("  %10.3f %10.2f %6.1f%%  %s"
              % (v, 1e3 * v / max(len(iters), 1),
                 100.0 * v / total_s if total_s else 0.0, k))
        w("  %10.3f %10.2f %7s  total" % (
            total_s, 1e3 * total_s / max(len(iters), 1), ""))

    if entries or compiles:
        w("\n== compile vs execute per jitted entry point ==")
        w("  %-12s %12s %12s %12s %8s" % ("entry", "first_call_s",
                                          "compile_est_s", "exec_mean_s",
                                          "exec_n"))
        for name, st in sorted(entries.items()):
            w("  %-12s %12.3f %12.3f %12.4f %8d"
              % (name, st["first_s"], st.get("compile_est_s", 0.0),
                 st["exec_mean_s"], st["exec_n"]))

    if recompiles:
        from lightgbm_tpu.obs.compile import format_diff
        w("\n== recompiles (compile_attr, obs_compile=true) ==")
        w("  %-12s %4s %5s  %s" % ("entry", "n", "sig#", "what changed"))
        for r in recompiles:
            why = ("; ".join(format_diff(d) for d in r["diff"])
                   or "first compile")
            w("  %-12s %4d %5d  %s" % (r["entry"], r["n_compiles"],
                                       r["sig_compiles"], why))

    rank_report = (run_end or {}).get("rank_report")
    if rank_report:
        # merged cross-rank view: per-rank totals + barrier skew
        w("\n== per-rank comparison (merged view) ==")
        w("  %-6s %12s  %s" % ("rank", "iter_total_s", "slowest in"))
        slowest = rank_report.get("slowest_rank_collectives", {})
        for r, t in sorted(rank_report.get("per_rank_iter_total_s",
                                           {}).items(),
                           key=lambda kv: int(kv[0])):
            w("  r%-5s %12.4f  %s collective(s)"
              % (r, t, slowest.get(str(r), 0)))
        w("max barrier skew: %.6f s (seq %s)" % (
            rank_report.get("collective_skew_max_s", 0.0),
            rank_report.get("collective_skew_max_seq")))

    stragglers = query.straggler_rows(events)
    if stragglers:
        w("\n== straggler samples ==")
        for e in stragglers[:10]:
            w("  it %-5d skew %5.1f%%  slowest device %s"
              % (e["it"], 100.0 * e.get("skew", 0.0),
                 e.get("slowest", "?")))
        if len(stragglers) > 10:
            w("  ... %d more samples" % (len(stragglers) - 10))

    peaks = {}
    for e in events:
        if e["ev"] != "memory":
            continue
        for d in e["devices"]:
            if "peak_bytes_in_use" in d or "bytes_in_use" in d:
                cur = d.get("peak_bytes_in_use", d.get("bytes_in_use", 0))
                peaks[d["id"]] = max(peaks.get(d["id"], 0), cur)
    if peaks:
        w("\n== peak device memory ==")
        for did, b in sorted(peaks.items()):
            w("  device %d: %.1f MiB" % (did, b / 2**20))

    if serve and serve.get("present"):
        t = serve["totals"]
        eff = ("%.1f%%" % (100.0 * t["batch_efficiency"])
               if t["batch_efficiency"] is not None else "-")
        w("\n== serving (%s totals) =="
          % ("sampled, lower bound" if t["sampled"] else "exact"))
        w("  batches %s  rows %s  batch efficiency %s  shed %s"
          % (t["batches"], t["rows"], eff, t["shed_total"]))
        for k in sorted(serve.get("routes", {})):
            r = serve["routes"][k]
            fmt = lambda v: "-" if v is None else "%.2f" % (1e3 * v)
            w("  route %-10s n=%-6d p50 %s ms  p95 %s ms  p99 %s ms"
              % (k, r["n"], fmt(r.get("p50_s")), fmt(r.get("p95_s")),
                 fmt(r.get("p99_s"))))
        slo = serve.get("slo")
        if slo:
            ov = slo.get("overall") or {}
            w("  last SLO window: qps %.1f  p99 %s ms  err %.3f%%"
              % (float(ov.get("qps", 0.0) or 0.0),
                 "-" if ov.get("p99_s") is None
                 else "%.2f" % (1e3 * float(ov["p99_s"])),
                 100.0 * float(ov.get("error_rate", 0.0) or 0.0)))
        al = serve["alerts"]
        w("  slo burn-rate alerts: %d fired / %d cleared%s"
          % (al["fired"], al["cleared"],
             "  [ACTIVE]" if al["active"] else ""))
        w("  (full report: python -m lightgbm_tpu obs serve <timeline>)")

    if health:
        hc = collections.Counter((e["check"], e["status"]) for e in health)
        w("\n== health (%d events, run ended %s) ==" % (
            len(health), (run_end or {}).get("status", "?")))
        w("  %6s %8s  %s" % ("count", "status", "check"))
        for (check, status), n in sorted(hc.items()):
            w("  %6d %8s  %s" % (n, status, check))
        fired = [e for e in health if e["status"] != "ok"]
        for e in fired[:20]:
            w("  it %-5d %s/%s: %s" % (e["it"], e["check"], e["status"],
                                       e.get("detail", {})))
        if len(fired) > 20:
            w("  ... %d more non-ok health events" % (len(fired) - 20))

    if scrape:
        w("\n== final metrics snapshot (it %s) ==" % metric_evs[-1]["it"])
        for name, m in sorted(scrape.items()):
            if m.get("type") == "histogram":
                mean = m["sum"] / m["count"] if m["count"] else 0.0
                w("  %-34s count=%d sum=%.4f mean=%.5f"
                  % (name, m["count"], m["sum"], mean))
            else:
                w("  %-34s %s" % (name, m["value"]))


def main():
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_trace"
    if trace_dir.endswith(".jsonl") or (os.path.isfile(trace_dir)
                                        and not trace_dir.endswith(".gz")):
        summarize_jsonl(trace_dir, csv="--csv" in sys.argv[2:])
        return
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30

    path, events = load_events(trace_dir)
    # pid/tid -> human-readable track names from metadata events
    proc = {}
    thread = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc[e.get("pid")] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread[(e.get("pid"), e.get("tid"))] = e["args"].get("name", "")

    per_track = collections.Counter()          # track -> total us
    per_op = collections.defaultdict(lambda: [0.0, 0])   # (track, op) -> [us, n]
    for e in events:
        if e.get("ph") != "X":
            continue
        pid, tid = e.get("pid"), e.get("tid")
        track = proc.get(pid, str(pid))
        tname = thread.get((pid, tid), "")
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        key = "%s/%s" % (track, tname) if tname else track
        per_track[key] += dur
        per_op[(key, name)][0] += dur
        per_op[(key, name)][1] += 1

    print("trace: %s" % path)
    print("\n== total busy time per track (ms) ==")
    for track, us in per_track.most_common(12):
        print("  %10.2f  %s" % (us / 1e3, track))

    # rank ops on device-ish tracks (XLA Ops / TensorFlow Op / stream
    # tracks); fall back to all tracks if nothing matches
    def devicey(track):
        t = track.lower()
        return ("xla op" in t or "tensorflow op" in t or "/device" in t
                or "tpu" in t.split("/")[0] or "stream" in t)

    rows = [(v[0], v[1], tr, op) for (tr, op), v in per_op.items()
            if devicey(tr)]
    if not rows:
        rows = [(v[0], v[1], tr, op) for (tr, op), v in per_op.items()]
    rows.sort(reverse=True)
    print("\n== top %d ops by total time ==" % top_n)
    print("  %10s %8s  %s" % ("total_ms", "count", "op [track]"))
    for us, n, tr, op in rows[:top_n]:
        print("  %10.2f %8d  %s  [%s]" % (us / 1e3, n, op[:100], tr[:60]))


if __name__ == "__main__":
    main()
