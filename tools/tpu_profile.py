"""Capture a jax.profiler trace of the wave engine on the current backend.

The flagship residue analysis (ROADMAP.md) needs a ranked breakdown of
where the ~130 ms/wave that is not the histogram kernel goes; no trace
has ever been captured on chip.  This tool trains the bench recipe and
wraps the steady-state iterations in a profiler trace viewable in
Perfetto / TensorBoard.

Usage:  python tools/tpu_profile.py [n_rows] [outdir] [k=v ...]
        # defaults: 1_000_000 /tmp/tpu_trace; k=v pairs override params
        # e.g. python tools/tpu_profile.py 999424 /tmp/tr tpu_wave_chunk=131072
        python tools/tpu_profile.py --shape expo_cat [outdir] [k=v ...]
        # profile a bench_suite shape instead (binned-dataset cache
        # shared with the suite) — e.g. the 3.9x categorical headline
        # (VERDICT r4 weak #7) or a pathological width cell:
        # tools/tpu_profile.py --shape yahoo /tmp/tr tpu_wave_width=32
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _obs_params(outdir):
    """Timeline params for the profiled run: per-iteration fencing plus
    compile-cost capture so the roofline table can be printed next to
    the trace (the trace says WHERE the time goes, the roofline says how
    far each entry sits from the hardware)."""
    os.makedirs(outdir, exist_ok=True)
    return {"obs_events_path": os.path.join(outdir, "obs_timeline.jsonl"),
            "obs_timing": "iter", "obs_compile": True,
            "obs_utilization_every": 1}


def _print_roofline(gbdt, outdir):
    gbdt._obs.close()
    obs_path = os.path.join(outdir, "obs_timeline.jsonl")
    try:
        from lightgbm_tpu.obs import read_events
        from lightgbm_tpu.obs.roofline import render_roofline
        print()
        events = read_events(obs_path)
        render_roofline(events)
        print("timeline written to", obs_path,
              "- rerun the table with: python -m lightgbm_tpu obs "
              "roofline", obs_path)
    except Exception as e:           # the trace must survive a table bug
        print("tpu_profile: roofline table unavailable (%s)" % e,
              file=sys.stderr)
        return
    # the host half of the same window (obs/prof.py): the device trace
    # above shows what the chips ran, this shows what the host was doing
    # between submissions — one command, both halves of the pipeline
    try:
        from lightgbm_tpu.obs.prof import render_top
        print()
        render_top(events, top=10)
        print("full host profile: python -m lightgbm_tpu obs prof %s "
              "--flame %s" % (obs_path,
                              os.path.join(outdir, "flamegraph.html")))
    except Exception as e:
        print("tpu_profile: host top-table unavailable (%s)" % e,
              file=sys.stderr)


def main():
    argv = list(sys.argv[1:])
    shape = None
    if "--shape" in argv:
        i = argv.index("--shape")
        shape = argv[i + 1]
        del argv[i:i + 2]
    args = [a for a in argv if "=" not in a]
    overrides = dict(a.split("=", 1) for a in argv if "=" in a)
    if shape is None:
        n = int(args[0]) if args else 999_424
        outdir = args[1] if len(args) > 1 else "/tmp/tpu_trace"
    else:
        n = 999_424                      # unused; the shape sizes itself
        outdir = args[-1] if args else "/tmp/tpu_trace"

    from lightgbm_tpu.utils.common import honor_jax_platforms
    honor_jax_platforms()
    import jax
    import lightgbm_tpu as lgb

    if shape is not None:
        from tools.bench_suite import SHAPES, cached_dataset
        spec = SHAPES[shape]
        train_set = cached_dataset(shape)
        params = dict(spec["params"], verbose=-1, **_obs_params(outdir))
        params.update(overrides)
        train_set.params = dict(train_set.params or {}, **params)
        bst = lgb.Booster(params=params, train_set=train_set)
        gbdt = bst._gbdt
        for _ in range(2):
            gbdt.train_one_iter(None, None, False)
        jax.block_until_ready(gbdt._score_dev)
        with jax.profiler.trace(outdir):
            for _ in range(3):
                gbdt.train_one_iter(None, None, False)
            jax.block_until_ready(gbdt._score_dev)
        print("trace written to", outdir)
        _print_roofline(gbdt, outdir)
        return

    from tools.bench_modes import make_data
    X, y = make_data(n)
    params = {"objective": "binary", "num_leaves": 255, "max_bin": 63,
              "learning_rate": 0.1, "min_data_in_leaf": 1, "verbose": -1,
              "metric": "auc", "tpu_growth": "wave", "tpu_wave_width": 32}
    params.update(_obs_params(outdir))
    params.update(overrides)
    bst = lgb.Booster(params=params,
                      train_set=lgb.Dataset(X, label=y, params=params))
    gbdt = bst._gbdt
    for _ in range(3):                      # compile + warm
        gbdt.train_one_iter(None, None, False)
    jax.block_until_ready(gbdt._score_dev)

    with jax.profiler.trace(outdir):
        for _ in range(3):
            gbdt.train_one_iter(None, None, False)
        jax.block_until_ready(gbdt._score_dev)
    print("trace written to", outdir,
          "- open the .trace.json.gz in Perfetto (ui.perfetto.dev) or "
          "point TensorBoard's profile plugin at the directory")
    _print_roofline(gbdt, outdir)


if __name__ == "__main__":
    main()
