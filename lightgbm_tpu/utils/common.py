"""Small helpers shared across layers (mirrors utils/common.h roles)."""
from __future__ import annotations

from typing import Dict, List

import numpy as np


def array_to_string(arr, high_precision: bool = False) -> str:
    """Space-joined array serialization as Common::ArrayToString renders it."""
    out = []
    for v in arr:
        if isinstance(v, (np.floating, float)):
            if high_precision:
                out.append(repr(float(v)))
            else:
                out.append(_format_double(float(v)))
        else:
            out.append(str(int(v)))
    return " ".join(out)


def _format_double(v: float) -> str:
    # C++ default stream precision is 6 significant digits; the model files
    # round-trip through this.  We keep full precision instead (loaders on
    # both sides parse it fine and it preserves exact re-load equality).
    return repr(v)


def string_to_array(s: str, dtype) -> np.ndarray:
    if not s:
        return np.asarray([], dtype=dtype)
    return np.asarray(s.split(" "), dtype=dtype)


def parse_kv_lines(lines: List[str]) -> Dict[str, str]:
    """key=value lines -> dict (Common::Split on first '=')."""
    out: Dict[str, str] = {}
    for line in lines:
        if "=" in line:
            key, _, val = line.partition("=")
            key = key.strip()
            val = val.strip()
            if key and val:
                out[key] = val
    return out


def avoid_inf(v: float) -> float:
    """Common::AvoidInf — clamp ±inf to ±1e300 for serialization."""
    if np.isnan(v):
        return 0.0
    if v == np.inf:
        return 1e300
    if v == -np.inf:
        return -1e300
    return float(v)


kEpsilon = 1e-15
kMissingValueRange = 1e-20
kMaxTreeOutput = 100.0
kMinScore = -np.inf


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a durable directory.

    The flagship wave program costs ~200 s to compile cold; on a flaky
    device tunnel that compile can eat most of a healthy window.  With
    the persistent cache a retry (or the driver's round-end bench) reuses
    the serialized executable and reaches its first timed iteration in
    seconds.  Resolution order: explicit arg > LGBM_TPU_COMPILE_CACHE env
    (set to "0" to disable) > /tmp/lgbm_tpu_xla_cache.  Without an
    explicit ``cache_dir`` the cache engages on the TPU backend ONLY:
    CPU executables embed host-specific AOT machine features and their
    serialization has been observed to segfault (and CPU compiles are
    cheap anyway).  Must run before the first compilation; safe no-op
    if the config knobs are missing.  Returns the directory in use, or
    None when disabled/unavailable.
    """
    import os

    import jax

    d = cache_dir or os.environ.get("LGBM_TPU_COMPILE_CACHE",
                                    "/tmp/lgbm_tpu_xla_cache")
    if not d or d == "0":
        return None
    if cache_dir is None and "LGBM_TPU_COMPILE_CACHE" not in os.environ:
        # default-on only for TPU: CPU executables carry host-specific
        # AOT machine features, and serializing them has been observed
        # to SEGFAULT sporadically (jax compilation_cache
        # put_executable_and_time) — CPU compiles are seconds anyway.
        # An explicit cache_dir argument or the env var overrides
        # (tests use tmpdirs; operators opting in know their host).
        try:
            if jax.default_backend() != "tpu":
                return None
        except Exception:
            return None
    try:
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        # cache every program, however small/fast — bench retries reuse
        # dozens of sub-programs (binning, predict, metrics), not just
        # the big grow loop
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # bound the dir: every bench/test child writes here, so without
        # LRU eviction /tmp would grow until it squeezed out the dataset
        # caches the retry path depends on
        jax.config.update("jax_compilation_cache_max_size", 4 << 30)
    except Exception as e:  # unknown config name on an older jax, RO fs...
        from .log import Log
        Log.warning("persistent compilation cache unavailable (%s)", e)
        return None
    return d


def honor_jax_platforms() -> None:
    """Apply $JAX_PLATFORMS via the config update, before backend init.

    The env var alone does NOT override the axon TPU platform — the
    explicit ``jax.config.update("jax_platforms", ...)`` before the
    first backend touch does (the tests/conftest.py trick).  Every
    tool that wants to be CPU-pinnable must call this first, or a
    "CPU-only" invocation silently dispatches to the tunneled TPU.
    """
    import os

    import jax

    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)


def probe_device(timeout: float = 90.0) -> str:
    """One tiny matmul in a SUBPROCESS; returns the backend name.

    A wedged device tunnel (e.g. axon) blocks inside C calls where
    in-process alarms never fire, so the probe must be a separate
    process.  Raises subprocess.TimeoutExpired on a hang and
    RuntimeError (with the child's stderr) on a non-hang failure —
    callers can distinguish "maybe recovering, retry" from "permanently
    broken, abort".
    """
    import subprocess
    import sys
    # honor JAX_PLATFORMS explicitly: the env var alone does not override
    # the axon TPU platform, the config update before backend init does —
    # this lets tests point the probe at the CPU platform
    code = ("import os, jax, jax.numpy as jnp; "
            "p = os.environ.get('JAX_PLATFORMS'); "
            "p and jax.config.update('jax_platforms', p); "
            "x = jnp.ones((128, 128)); "
            "print(jax.default_backend(), float(jnp.sum(x @ x)))")
    r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                       capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError("device probe failed:\n" + r.stderr[-500:])
    return r.stdout.split()[-2]
