"""R bridge smoke test (VERDICT r1 weak #6).

The reference ships a full R test dir (/root/reference/R-package/tests/).
Our R package delegates to the Python runtime via reticulate, so the
heavyweight behavior tests live in the Python suite; this file (a) keeps
the R sources structurally sane and (b) actually executes the R smoke
script when an R interpreter with reticulate is present (it is not in the
build image, so that path is skip-gated, like the reference gating GPU
tests on an OpenCL driver).
"""
import shutil
import subprocess
from pathlib import Path

import pytest

R_DIR = Path(__file__).resolve().parent.parent / "R-package"


def test_r_sources_exist_and_balanced():
    src = R_DIR / "R" / "lightgbm_tpu.R"
    smoke = R_DIR / "tests" / "smoke.R"
    assert src.is_file() and smoke.is_file()
    for f in (src, smoke):
        text = f.read_text()
        # cheap structural sanity that survives without an R interpreter
        for op, cl in (("(", ")"), ("{", "}"), ("[", "]")):
            assert text.count(op) == text.count(cl), (
                "unbalanced %r in %s" % (op, f.name))
        assert "lgb" in text


def test_r_exports_cover_reference_surface():
    """The functions the reference R API exposes must exist here by name."""
    text = (R_DIR / "R" / "lightgbm_tpu.R").read_text()
    for fn in ("lgb.Dataset", "lgb.Dataset.create.valid", "lgb.train",
               "lgb.cv", "lgb.save", "lgb.load", "lgb.dump",
               "lgb.importance", "lgb.model.to.string",
               "lgb.get.eval.result", "predict.lgb.Booster"):
        assert ("%s <- function" % fn) in text, fn


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="no R interpreter in this image")
def test_r_smoke_script_runs():
    proc = subprocess.run(
        ["Rscript", str(R_DIR / "tests" / "smoke.R")],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert "R smoke test OK" in proc.stdout
