"""Generic multi-process training worker (launch.py spec target).

One function, ``train_worker(comm, payload)``, drives the FULL product
path on one rank: rank-sharded dataset open (or deterministic synthetic
data), the engine.train loop with its checkpoint/resume wiring, and the
GBDT comm integration (metric reduce, stop votes, global-mesh grow).
Subprocess mode runs it under ``run_ranks_subprocess`` (spec
"lightgbm_tpu.parallel.worker:train_worker"); thread mode calls it
directly from ``run_ranks`` ranks — same function, host-comm collectives
only (threads share one backend, so each rank trains its shard on the
local mesh; cross-process psum parity belongs to subprocess mode).

Fault hooks (PR-4 ``LGBM_MP_*`` convention; payload keys override when
the env is unset):

* ``LGBM_MP_SLOW_RANK`` / ``LGBM_MP_SLOW_SECS`` — that rank sleeps
  before every round (skew injection for the merged-timeline tests);
* ``LGBM_MP_KILL_RANK`` / ``LGBM_MP_KILL_ITER`` — that rank dies after
  completing ITER rounds of this run: ``os._exit(1)`` in subprocess mode
  (payload ``kill_hard``, default), a raised RuntimeError in thread mode
  — after the engine's checkpoint save for the round, so the elastic
  drill resumes from it.

Returns a JSON-able summary: model digest + tree count for bit-identity
asserts, the reader's mapped-shard accounting for the no-foreign-mmap
assert, and timing for the weak-scaling ledger.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Dict


def make_data(rows: int, cols: int, seed: int):
    """Deterministic synthetic binary-classification data.  Every rank
    generates the FULL matrix from the seed and slices its shard — the
    cheap stand-in for a shared filesystem."""
    import numpy as np
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, cols))
    y = (X[:, 0] + np.sin(X[:, 1] * 2.0)
         + 0.4 * rng.normal(size=rows) > 0).astype(np.float32)
    return X, y


def default_params() -> Dict[str, Any]:
    return {"objective": "binary", "num_leaves": 15, "max_bin": 63,
            "min_data_in_leaf": 5, "verbose": -1, "learning_rate": 0.2,
            "tree_learner": "data", "enable_bundle": False,
            "bagging_seed": 3, "data_random_seed": 1,
            "feature_fraction_seed": 2}


def _env_or_payload_int(env_key: str, payload, key: str, default: int):
    v = os.environ.get(env_key, "")
    if v != "":
        return int(v)
    return int(payload.get(key, default))


def train_worker(comm, payload):
    p = dict(payload or {})
    rows = int(p.get("rows", 2048))
    cols = int(p.get("cols", 8))
    rounds = int(p.get("num_rounds", 5))
    seed = int(p.get("seed", 0))
    size = max(int(getattr(comm, "size", 1) or 1), 1)
    rank = int(getattr(comm, "rank", 0) or 0)

    params = default_params()
    params.update(p.get("params") or {})
    if p.get("obs_path"):
        # multi-rank observers auto-shard to <path>.r<rank>
        params["obs_events_path"] = str(p["obs_path"])
    if p.get("checkpoint_dir"):
        params["checkpoint_dir"] = str(p["checkpoint_dir"])
        params["checkpoint_every"] = int(p.get("checkpoint_every", 1))

    from .. import engine as engine_mod
    from ..basic import Dataset

    mcomm = comm if size > 1 else None
    binned_dir = str(p.get("binned_dir") or "")
    if binned_dir:
        # tentpole (b): rank-aware open of the pre-binned directory —
        # this rank mmaps ONLY its row range of the shard table
        ds = Dataset.from_binned(binned_dir, params=dict(params),
                                 comm=mcomm)
    else:
        X, y = make_data(rows, cols, seed)
        lo, hi = rank * rows // size, (rank + 1) * rows // size
        ds = Dataset(X[lo:hi], label=y[lo:hi], params=dict(params))
        if mcomm is not None:
            # distributed bin finding: mappers agree across ranks via
            # the host comm (io/dataset.py _construct_mappers_distributed)
            from ..io.dataset import TrainingData
            from ..utils.config import Config
            ds._handle = TrainingData.from_matrix(
                X[lo:hi], label=y[lo:hi], config=Config(dict(params)),
                comm=mcomm)

    slow_rank = _env_or_payload_int("LGBM_MP_SLOW_RANK", p,
                                    "slow_rank", -1)
    slow_secs = float(os.environ.get("LGBM_MP_SLOW_SECS",
                                     p.get("slow_secs", 0.2)))
    kill_rank = _env_or_payload_int("LGBM_MP_KILL_RANK", p,
                                    "kill_rank", -1)
    kill_iter = _env_or_payload_int("LGBM_MP_KILL_ITER", p,
                                    "kill_iter", -1)
    kill_hard = bool(p.get("kill_hard", True))

    cbs = []
    if rank == slow_rank and slow_secs > 0:
        def _slow(env):
            time.sleep(slow_secs)
        _slow.before_iteration = True
        cbs.append(_slow)
    if rank == kill_rank and kill_iter >= 0:
        state = {"n": 0}

        def _kill(env):
            # after-iteration: engine already wrote this round's
            # checkpoint (when the cadence hit), so the survivors can
            # resume from it
            state["n"] += 1
            if state["n"] >= kill_iter:
                if kill_hard:
                    os._exit(1)
                raise RuntimeError(
                    "injected rank kill (LGBM_MP_KILL_RANK=%d after %d "
                    "round(s))" % (kill_rank, kill_iter))
        cbs.append(_kill)

    t0 = time.perf_counter()
    booster = engine_mod.train(params, ds, num_boost_round=rounds,
                               verbose_eval=False, callbacks=cbs)
    train_s = time.perf_counter() - t0

    gbdt = booster._gbdt
    model_str = booster.model_to_string()
    td = gbdt.train_data
    reader = getattr(td, "_binned_reader", None)
    out = {
        "rank": rank,
        "size": size,
        "digest": hashlib.sha256(model_str.encode()).hexdigest()[:16],
        "num_trees": len(gbdt.models),
        "iter": int(gbdt.iter),
        "num_data": int(td.num_data),
        "train_s": train_s,
    }
    if reader is not None:
        out["row_range"] = [int(reader.row_range[0]),
                            int(reader.row_range[1])]
        out["mapped_shards"] = sorted(int(i) for i in reader.mapped_shards)
        out["active_shards"] = sorted(int(i) for i in reader.active_shards)
    if p.get("return_model"):
        out["model"] = model_str
    return out
