"""Vectorized best-split search over (feature, bin) histograms — XLA native.

Parity target: src/treelearner/feature_histogram.hpp:78-387.  The reference
scans each feature's histogram sequentially (up to 3 passes to place the
zero/default bin left, right, or in natural position).  Here every pass is a
masked cumulative-sum over the whole (F, B) histogram tensor, so the entire
split search for a leaf is one fused XLA program — no per-feature loop, no
host round-trips.  Tie-breaking reproduces the reference's iteration order:

* dir=-1 passes iterate bins high->low with strict ``>`` updates, so equal
  gains keep the LARGER threshold; dir=+1 keeps the smaller.
* across passes, earlier passes win ties (strict ``>`` replacement,
  feature_histogram.hpp:88-97);
* across features, the smaller feature index wins ties
  (SplitInfo comparison, split_info.hpp:102-107 — argmax picks first max).

Gain / leaf-output formulas with L1/L2 and the kEpsilon seeding match
GetLeafSplitGain / CalculateSplittedLeafOutput (feature_histogram.hpp:230-249)
bit-for-bit in the chosen dtype.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

kEpsilon = 1e-15

# packed SplitInfo layout (one float vector per leaf; device-resident)
GAIN = 0
FEATURE = 1
THRESHOLD = 2
DEFAULT_BIN_FOR_ZERO = 3
LEFT_OUTPUT = 4
RIGHT_OUTPUT = 5
LEFT_SUM_G = 6
LEFT_SUM_H = 7
LEFT_COUNT = 8
RIGHT_SUM_G = 9
RIGHT_SUM_H = 10
RIGHT_COUNT = 11
IS_CAT = 12
# runner-up feature and its gain (split-audit margin: how close the
# second-best feature came); SECOND_FEATURE is -1 and SECOND_GAIN 0 when
# no other feature had a valid split
SECOND_FEATURE = 13
SECOND_GAIN = 14
SPLIT_VEC_SIZE = 15


class FeatureMeta(NamedTuple):
    """Static per-inner-feature arrays living on device."""
    num_bin: jnp.ndarray        # (F,) int32
    default_bin: jnp.ndarray    # (F,) int32
    is_categorical: jnp.ndarray  # (F,) bool


class SplitParams(NamedTuple):
    """Python-scalar hyperparameters (static under jit closure)."""
    lambda_l1: float
    lambda_l2: float
    min_gain_to_split: float
    min_data_in_leaf: float
    min_sum_hessian_in_leaf: float
    use_missing: bool


def _leaf_split_gain(sum_g, sum_h, l1, l2):
    """GetLeafSplitGain (feature_histogram.hpp:230-236)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def _leaf_output(sum_g, sum_h, l1, l2):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:244-249)."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return -jnp.sign(sum_g) * reg / (sum_h + l2)


def _suffix_sum(x):
    """sr[t] = sum_{b >= t} x[b] along the last axis."""
    return jnp.flip(jnp.cumsum(jnp.flip(x, axis=-1), axis=-1), axis=-1)


def _argmax_prefer_last(x):
    """argmax that returns the LAST index among ties (descending scan order)."""
    n = x.shape[-1]
    return n - 1 - jnp.argmax(jnp.flip(x, axis=-1), axis=-1)


class _Cand(NamedTuple):
    gain: jnp.ndarray       # (F,) candidate gain, -inf when invalid
    threshold: jnp.ndarray  # (F,) int32
    dbz: jnp.ndarray        # (F,) int32 default_bin_for_zero
    left_g: jnp.ndarray     # (F,)
    left_h: jnp.ndarray     # (F,) includes +kEpsilon seed
    left_c: jnp.ndarray     # (F,)


def _numerical_pass(g, h, c, meta: FeatureMeta, params: SplitParams,
                    total_g, total_h_eps, total_cnt,
                    min_gain_shift, mode: str) -> _Cand:
    """One FindBestThresholdSequence pass, vectorized over all features.

    mode: 'zero_left' (dbz=0), 'natural' (dbz=default_bin),
          'zero_right' (dbz=num_bin-1).
    """
    F, B = g.shape
    bins = jnp.arange(B, dtype=jnp.int32)
    valid = bins[None, :] < meta.num_bin[:, None]
    skip_default = mode in ("zero_left", "zero_right")
    if skip_default:
        keep = valid & (bins[None, :] != meta.default_bin[:, None])
    else:
        keep = valid
    gk = jnp.where(keep, g, 0.0)
    hk = jnp.where(keep, h, 0.0)
    ck = jnp.where(keep, c, 0.0)

    eps = jnp.asarray(kEpsilon, g.dtype)
    if mode != "zero_right":
        # dir = -1: accumulate right side from the top bin down; split point t
        # puts bins >= t on the right, threshold = t-1
        right_g = _suffix_sum(gk)
        right_h = _suffix_sum(hk) + eps
        right_c = _suffix_sum(ck)
        left_g = total_g - right_g
        left_h = total_h_eps - right_h
        left_c = total_cnt - right_c
        t_ok = (bins[None, :] >= 1) & valid
        threshold = bins[None, :] - 1
        prefer_last = True
    else:
        # dir = +1: accumulate left side from bin 0 up; threshold = t
        left_g = jnp.cumsum(gk, axis=-1)
        left_h = jnp.cumsum(hk, axis=-1) + eps
        left_c = jnp.cumsum(ck, axis=-1)
        right_g = total_g - left_g
        right_h = total_h_eps - left_h
        right_c = total_cnt - left_c
        t_ok = (bins[None, :] <= meta.num_bin[:, None] - 2) & valid
        threshold = jnp.broadcast_to(bins[None, :], (F, B))
        prefer_last = False

    ok = (t_ok
          & (right_c >= params.min_data_in_leaf)
          & (right_h >= params.min_sum_hessian_in_leaf)
          & (left_c >= params.min_data_in_leaf)
          & (left_h >= params.min_sum_hessian_in_leaf))
    gain = (_leaf_split_gain(left_g, left_h, params.lambda_l1, params.lambda_l2)
            + _leaf_split_gain(right_g, right_h, params.lambda_l1, params.lambda_l2))
    ok = ok & (gain > min_gain_shift)
    gain = jnp.where(ok, gain, -jnp.inf)

    pick = _argmax_prefer_last(gain) if prefer_last else jnp.argmax(gain, axis=-1)
    fidx = jnp.arange(F)
    best_gain = gain[fidx, pick]
    if mode == "zero_left":
        dbz = jnp.zeros(F, jnp.int32)
    elif mode == "natural":
        dbz = meta.default_bin
    else:
        dbz = meta.num_bin - 1
    return _Cand(
        gain=best_gain,
        threshold=threshold[fidx, pick].astype(jnp.int32),
        dbz=dbz,
        left_g=left_g[fidx, pick],
        left_h=left_h[fidx, pick],
        left_c=left_c[fidx, pick],
    )


def _categorical_pass(g, h, c, meta: FeatureMeta, params: SplitParams,
                      total_g, total_h_eps, total_cnt,
                      min_gain_shift) -> _Cand:
    """One-vs-rest categorical scan (feature_histogram.hpp:100-198); left side
    is the single category bin t; ties keep the larger t (descending loop)."""
    F, B = g.shape
    bins = jnp.arange(B, dtype=jnp.int32)
    valid = bins[None, :] < meta.num_bin[:, None]
    eps = jnp.asarray(kEpsilon, g.dtype)

    other_c = total_cnt - c
    other_h = total_h_eps - h - eps
    other_g = total_g - g
    ok = (valid
          & (c >= params.min_data_in_leaf)
          & (h >= params.min_sum_hessian_in_leaf)
          & (other_c >= params.min_data_in_leaf)
          & (other_h >= params.min_sum_hessian_in_leaf))
    gain = (_leaf_split_gain(other_g, other_h, params.lambda_l1, params.lambda_l2)
            + _leaf_split_gain(g, h + eps, params.lambda_l1, params.lambda_l2))
    ok = ok & (gain > min_gain_shift)
    gain = jnp.where(ok, gain, -jnp.inf)

    pick = _argmax_prefer_last(gain)
    fidx = jnp.arange(F)
    return _Cand(
        gain=gain[fidx, pick],
        threshold=pick.astype(jnp.int32),
        dbz=meta.default_bin,
        left_g=g[fidx, pick],
        left_h=h[fidx, pick] + eps,
        left_c=c[fidx, pick],
    )


def _merge(best: _Cand, cand: _Cand) -> _Cand:
    """Later candidate replaces only on strictly greater gain."""
    take = cand.gain > best.gain
    return _Cand(*[jnp.where(take, cn, bn) for cn, bn in zip(cand, best)])


def per_feature_candidates(hist, total_g, total_h, total_cnt,
                           meta: FeatureMeta, params: SplitParams):
    """Per-feature best split candidates for one leaf.

    Returns (best: _Cand with (F,) arrays, total_g, total_h_eps, total_cnt,
    min_gain_shift).  `best.gain` is the raw gain (shift NOT yet subtracted);
    -inf marks unsplittable features.  The voting-parallel learner uses this
    to propose local top-k features (FindBestThresholds local pass,
    voting_parallel_tree_learner.cpp:255-300).
    """
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    dtype = g.dtype
    eps = jnp.asarray(kEpsilon, dtype)
    total_g = jnp.asarray(total_g, dtype)
    total_h_eps = jnp.asarray(total_h, dtype) + 2 * eps
    total_cnt = jnp.asarray(total_cnt, dtype)

    gain_shift = _leaf_split_gain(total_g, total_h_eps,
                                  params.lambda_l1, params.lambda_l2)
    min_gain_shift = gain_shift + params.min_gain_to_split

    args = (g, h, c, meta, params, total_g, total_h_eps, total_cnt, min_gain_shift)
    if params.use_missing:
        best = _numerical_pass(*args, mode="zero_left")
        best = _merge(best, _numerical_pass(*args, mode="natural"))
        best = _merge(best, _numerical_pass(*args, mode="zero_right"))
    else:
        best = _numerical_pass(*args, mode="natural")
    # the 'natural' pass with an edge default_bin duplicates a skip pass; the
    # reference guards those duplicates, we simply let _merge's strict >
    # keep the earlier pass.  Edge default bins are handled identically.
    cat = _categorical_pass(g, h, c, meta, params, total_g, total_h_eps,
                            total_cnt, min_gain_shift)
    best = _Cand(*[jnp.where(meta.is_categorical, cn, bn)
                   for cn, bn in zip(cat, best)])
    return best, total_g, total_h_eps, total_cnt, min_gain_shift


def find_best_split_impl(hist, total_g, total_h, total_cnt,
                         meta: FeatureMeta, feature_mask, params: SplitParams):
    """Best split for one leaf.

    Args:
      hist: (F, B, 3) float histogram [sum_grad, sum_hess, count].
      total_g / total_h / total_cnt: leaf totals (scalars).
      meta: FeatureMeta arrays.
      feature_mask: (F,) bool — feature_fraction sampling for this tree.
      params: SplitParams (static).

    Returns: packed (SPLIT_VEC_SIZE,) vector; gain=-inf when unsplittable.
    """
    best, total_g, total_h_eps, total_cnt, min_gain_shift = \
        per_feature_candidates(hist, total_g, total_h, total_cnt, meta, params)
    dtype = best.gain.dtype
    eps = jnp.asarray(kEpsilon, dtype)

    masked_gain = jnp.where(feature_mask, best.gain, -jnp.inf)
    f = jnp.argmax(masked_gain)          # ties -> smaller feature index
    bgain = masked_gain[f]
    # runner-up: best gain over the OTHER features (split-audit margin)
    masked2 = masked_gain.at[f].set(-jnp.inf)
    f2 = jnp.argmax(masked2)
    g2 = masked2[f2]
    lg, lh, lc = best.left_g[f], best.left_h[f], best.left_c[f]
    rg = total_g - lg
    rh = total_h_eps - lh
    rc = total_cnt - lc
    out = jnp.stack([
        bgain - min_gain_shift,
        f.astype(dtype),
        best.threshold[f].astype(dtype),
        best.dbz[f].astype(dtype),
        _leaf_output(lg, lh, params.lambda_l1, params.lambda_l2),
        _leaf_output(rg, rh, params.lambda_l1, params.lambda_l2),
        lg,
        lh - eps,
        lc,
        rg,
        rh - eps,
        rc,
        meta.is_categorical[f].astype(dtype),
        jnp.where(jnp.isfinite(g2), f2, -1).astype(dtype),
        jnp.where(jnp.isfinite(g2), g2 - min_gain_shift,
                  jnp.asarray(0.0, dtype)),
    ])
    # keep -inf gain truly -inf (the subtraction above turns it into nan)
    out = out.at[GAIN].set(jnp.where(jnp.isfinite(bgain),
                                     bgain - min_gain_shift, -jnp.inf))
    return out


@functools.partial(jax.jit, static_argnames=("params",))
def find_best_split(hist, total_g, total_h, total_cnt,
                    meta: FeatureMeta, feature_mask, params: SplitParams):
    """Jitted standalone wrapper around find_best_split_impl."""
    return find_best_split_impl(hist, total_g, total_h, total_cnt, meta,
                                feature_mask, params)


def depth_gated_best(hist, sums, meta, feature_mask, params: SplitParams,
                     max_depth: int, depth):
    """Best split of one leaf with the max_depth gate applied.

    `sums` is the (3,) [sum_grad, sum_hess, count] leaf total; a leaf at
    depth >= max_depth keeps its packed vector but has its gain forced to
    -inf so the frontier argmax can never pick it (tree.cpp max-depth
    check hoisted into the device program).
    """
    b = find_best_split_impl(hist, sums[0], sums[1], sums[2], meta,
                             feature_mask, params)
    if max_depth > 0:
        b = b.at[GAIN].set(jnp.where(depth < max_depth, b[GAIN], -jnp.inf))
    return b


def best_splits_vmapped(hists_k, sums_k, depths_k, meta, feature_mask,
                        params: SplitParams, max_depth: int, hist_view=None):
    """Packed best-split search vmapped over K leaves at once.

    The wave engine's frontier produces K = 2*W child histograms per
    pass; searching them as one vmapped program keeps the whole level's
    FindBestThreshold on-device in a single fused XLA op.  `hist_view`,
    when given, maps each leaf's raw group histogram (+ its sums) to the
    per-feature view (EFB gather / default-bin fix) inside the vmap so
    the view tensors never materialize for all K leaves at once outside
    the fusion.  Shared by ops/wave.py and ops/fused_iter.py.
    """
    def one(h, s, d):
        hv = hist_view(h, s) if hist_view is not None else h
        return depth_gated_best(hv, s, meta, feature_mask, params,
                                max_depth, d)
    return jax.vmap(one)(hists_k, sums_k, depths_k)
