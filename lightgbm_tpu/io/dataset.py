"""Training dataset: binned column store + metadata (host side).

Parity target: src/io/dataset.cpp + src/io/dataset_loader.cpp.  Differences
by design (TPU-first): the binned matrix is a dense row-major
``(num_data, num_used_features)`` uint8/uint16 array destined for device HBM
(row-sharded under data-parallel training) instead of per-group Bin objects —
the moral equivalent of the GPU learner's Feature4 packing
(gpu_tree_learner.cpp:234-353) without the dword gymnastics.  EFB bundling is
not needed for correctness (a bundle is a perf optimization) and is tracked as
a later optimization.

Reference flow mirrored here (dataset_loader.cpp:159-216,661-840):
sample rows -> per-feature BinMapper.find_bin -> drop trivial features ->
bin all rows -> metadata check.
"""
from __future__ import annotations

import json
import os
import time as _time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.config import Config
from ..utils.log import Log
from ..utils.random import Random
from .binning import BinMapper, CATEGORICAL, NUMERICAL
from .bundle import (BundleLayout, bin_rows_grouped, build_layout,
                     find_feature_groups)
from .metadata import Metadata
from . import parser as _parser


class TrainingData:
    """The constructed dataset the tree learner consumes.

    Naming note: the Python-facing ``Dataset`` wrapper lives in basic.py; this
    class corresponds to the C++ ``Dataset`` (include/LightGBM/dataset.h:280).
    """

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        # per total-feature BinMapper (None for ignored)
        self.bin_mappers: List[Optional[BinMapper]] = []
        # inner (used) feature -> real feature index
        self.used_feature_idx: List[int] = []
        # real -> inner (-1 if unused), used_feature_map_ in the reference
        self.real_to_inner: Dict[int, int] = {}
        # mmap-backed shard reader (io/binned_format.py) when the dataset
        # came from / was streamed to the pre-binned on-disk format; the
        # `binned` property materializes from it only on demand so paged
        # device uploads never build the full host matrix
        self._binned_reader = None
        self._binned: Optional[np.ndarray] = None     # (N, F_used)
        self.metadata: Metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_bin: int = 255
        # learner-facing per-inner-feature arrays
        self.num_bin_arr: Optional[np.ndarray] = None
        self.default_bin_arr: Optional[np.ndarray] = None
        self.is_categorical_arr: Optional[np.ndarray] = None
        self.raw_data: Optional[np.ndarray] = None    # kept for valid alignment
        # EFB layout (io/bundle.py); None = binned is per-feature raw bins
        self.bundle: Optional[BundleLayout] = None
        # data-quality profile of the binning sample (obs/dataquality.py);
        # None when binning was copied/loaded rather than fitted here
        self._data_profile: Optional[dict] = None
        # per-feature drift fingerprint of the binning sample
        # (obs/drift.py feature_fingerprint) — the serving-time
        # reference; completed with score/eval snapshots by the GBDT
        self._drift_fingerprint: Optional[dict] = None
        # construction-phase accounting for the `dataset_construct` obs
        # event (rows, chunks, phase seconds, peak RSS, workers)
        self._construct_stats: Optional[dict] = None
        self._comm = None

    @property
    def binned(self) -> Optional[np.ndarray]:
        if self._binned is None and self._binned_reader is not None:
            r = self._binned_reader
            lo, hi = r.row_range
            if (lo, hi) == (0, r.num_data):
                self._binned = r.matrix()
            else:
                # rank-sharded open: materialize ONLY this rank's rows,
                # mapping only the shards that overlap them
                self._binned = np.ascontiguousarray(r.rows(lo, hi))
        return self._binned

    @binned.setter
    def binned(self, value) -> None:
        self._binned = value

    def _note_construct_stats(self, source: str, rows: int, chunks: int,
                              sketch_s: float, bin_s: float, write_s: float,
                              workers: int, rss_before: int,
                              **extra) -> None:
        from .streaming import _peak_rss_bytes
        peak = _peak_rss_bytes()
        self._construct_stats = {
            "source": source,
            "rows": int(rows),
            "chunks": int(chunks),
            "sketch_s": round(float(sketch_s), 6),
            "bin_s": round(float(bin_s), 6),
            "write_s": round(float(write_s), 6),
            "construct_s": round(float(sketch_s + bin_s + write_s), 6),
            "peak_rss_bytes": int(peak),
            "rss_growth_bytes": max(int(peak) - int(rss_before), 0),
            "workers": int(workers),
        }
        self._construct_stats.update(extra)

    # ------------------------------------------------------------- construct
    @classmethod
    def from_matrix(cls, data: np.ndarray, label=None, config: Optional[Config] = None,
                    weights=None, group=None, init_score=None,
                    categorical_feature: Sequence[int] = (),
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["TrainingData"] = None,
                    keep_raw: bool = False, comm=None) -> "TrainingData":
        """comm: optional parallel.comm.HostComm for multi-host loading —
        `data` is then this rank's pre-partitioned row shard and bin
        mappers are constructed distributed (feature-sharded + allgather,
        dataset_loader.cpp:733-833)."""
        config = config or Config()
        data = np.ascontiguousarray(np.asarray(data, dtype=np.float64))
        if data.ndim != 2:
            Log.fatal("Data must be 2-dimensional")
        self = cls()
        self.num_data, self.num_total_features = data.shape
        self.max_bin = config.max_bin
        self.feature_names = list(feature_names) if feature_names else [
            "Column_%d" % i for i in range(self.num_total_features)]

        cats = set(int(c) for c in categorical_feature)
        # remember the comm: the Booster shards its observer's timeline
        # per rank (obs/events.py) off the training data's comm
        self._comm = comm if (comm is not None and comm.size > 1) else None
        from .streaming import _peak_rss_bytes
        rss0 = _peak_rss_bytes()
        t0 = _time.time()
        sketch_s = 0.0
        if reference is not None:
            self._align_with(reference, data)
        elif comm is not None and comm.size > 1:
            # ranks must agree on RNG-bearing params BEFORE any sampling
            # (GlobalSyncUpByMin, application.cpp:118-199) — automatic
            # here, like the reference's Application init
            from ..parallel.comm import sync_config_across_ranks
            sync_config_across_ranks(comm, config)
            self._construct_mappers_distributed(data, config, cats, comm)
            sketch_s = _time.time() - t0
            self._bin_data(data)
        else:
            self._construct_mappers(data, config, cats)
            sketch_s = _time.time() - t0
            self._bin_data(data)
        self._note_construct_stats("matrix", rows=self.num_data, chunks=1,
                                   sketch_s=sketch_s,
                                   bin_s=_time.time() - t0 - sketch_s,
                                   write_s=0.0, workers=1, rss_before=rss0)
        if keep_raw:
            self.raw_data = data
        if label is not None:
            self.metadata.set_label(label)
        else:
            self.metadata.num_data = self.num_data
        if weights is not None:
            self.metadata.set_weights(weights)
        if group is not None:
            self.metadata.set_query_counts(group)
        if init_score is not None:
            self.metadata.set_init_score(init_score)
        return self

    @classmethod
    def from_csc(cls, sp, label=None, config: Optional[Config] = None,
                 weights=None, group=None, init_score=None,
                 categorical_feature: Sequence[int] = (),
                 feature_names: Optional[List[str]] = None,
                 reference: Optional["TrainingData"] = None) -> "TrainingData":
        """Sparse ingestion without densification (SparseBin analog,
        sparse_bin.hpp:68 + dataset_loader.cpp:840-930).

        sp: io.sparse.SparseColumns.  Bin mappers are constructed from
        per-column NONZERO samples (zeros are implicit in find_bin's total
        count, exactly as the dense path drops them), and binned columns
        are written as a default-bin fill plus a nonzero scatter.  Peak
        host memory is O(nnz + N*F_used bin bytes) — the N x F float64
        matrix never exists.
        """
        config = config or Config()
        self = cls()
        n = sp.num_row
        self.num_data = n
        self.num_total_features = sp.num_col
        self.max_bin = config.max_bin
        self.feature_names = list(feature_names) if feature_names else [
            "Column_%d" % i for i in range(sp.num_col)]
        cats = set(int(c) for c in categorical_feature)
        from .streaming import _peak_rss_bytes
        rss0 = _peak_rss_bytes()
        t0 = _time.time()

        if reference is not None:
            if sp.num_col != reference.num_total_features:
                Log.fatal("Validation data has %d features, train data "
                          "has %d", sp.num_col,
                          reference.num_total_features)
            self._copy_binning_from(reference)
        else:
            sample_cnt = min(config.bin_construct_sample_cnt, n)
            rng = Random(config.data_random_seed)
            sample_idx = rng.sample(n, sample_cnt)
            if len(sample_idx) == 0:
                sample_idx = np.arange(n, dtype=np.int32)
            total_sample = len(sample_idx)
            # row -> sample position (or -1), so each column's sampled
            # nonzeros come from one O(col_nnz) lookup
            sample_pos = np.full(n, -1, dtype=np.int64)
            sample_pos[np.asarray(sample_idx, dtype=np.int64)] = \
                np.arange(total_sample)
            filter_cnt = int(config.min_data_in_leaf * total_sample
                             / max(n, 1))

            self.bin_mappers = []
            col_sample_cache = []
            for f in range(sp.num_col):
                rows, vals = sp.column(f)
                pos = sample_pos[rows]
                sel = pos >= 0
                sv, spos = vals[sel], pos[sel]
                # the cache keeps NaN entries: the dense EFB sample bins
                # them to the last bin via value_to_bin, and the sparse
                # sample must agree; only find_bin drops them (the dense
                # mapper-construction path does the same)
                col_sample_cache.append((spos, sv))
                fb = sv[~np.isnan(sv)]
                m = BinMapper()
                bin_type = CATEGORICAL if f in cats else NUMERICAL
                m.find_bin(fb[fb != 0.0], total_sample, config.max_bin,
                           config.min_data_in_bin, filter_cnt, bin_type)
                self.bin_mappers.append(m)
            # the row->sample map is O(N) int64 — drop it before the
            # (N, G) binned product allocates (RSS watermark audit)
            del sample_pos

            self.used_feature_idx = [
                i for i, m in enumerate(self.bin_mappers)
                if m is not None and not m.is_trivial]
            if not self.used_feature_idx:
                Log.warning("There are no meaningful features, as all "
                            "feature values are constant.")
            self.real_to_inner = {r: i for i, r in
                                  enumerate(self.used_feature_idx)}
            self._build_feature_arrays()

            def col_from_cache(f):
                # sampled column densified: implicit zeros + nonzero
                # scatter (NaN entries preserved by the cache)
                spos, sv = col_sample_cache[f]
                col = np.zeros(total_sample, dtype=np.float64)
                if len(spos):
                    col[spos] = sv
                return col
            self._profile_quality(col_from_cache, total_sample, cats,
                                  config)

            # EFB on the binning sample, rebuilt sparsely (dense path:
            # Dataset::Construct, dataset.cpp:229-235)
            if (config.enable_bundle and len(self.used_feature_idx) > 1
                    and config.tree_learner not in ("feature",
                                                    "feature_parallel")):
                # uint16 is enough for bin ids (max_bin caps below 65536)
                # and keeps the (S, F) sample ~8x smaller than int64 —
                # at Bosch shape (200k x 968) that is 0.39 GB vs 1.55 GB
                binned_sample = np.empty(
                    (total_sample, len(self.used_feature_idx)), np.uint16)
                for i, r in enumerate(self.used_feature_idx):
                    mapper = self.bin_mappers[r]
                    col = np.full(total_sample,
                                  self.default_bin_arr[i], np.uint16)
                    spos, sv = col_sample_cache[r]
                    if len(spos):
                        col[spos] = mapper.value_to_bin(sv)
                    binned_sample[:, i] = col
                self.bundle = find_feature_groups(
                    binned_sample, self.num_bin_arr, self.default_bin_arr,
                    config.max_conflict_rate, config.min_data_in_leaf,
                    self.num_data)
                del binned_sample   # before the (N, G) product allocates
                if self.bundle is not None:
                    Log.info("EFB bundled %d features into %d groups",
                             len(self.used_feature_idx),
                             self.bundle.num_groups)
            del col_sample_cache

        sketch_s = _time.time() - t0
        self._bin_sparse(sp)
        self._note_construct_stats("csc", rows=n, chunks=1,
                                   sketch_s=sketch_s,
                                   bin_s=_time.time() - t0 - sketch_s,
                                   write_s=0.0, workers=1, rss_before=rss0)
        if label is not None:
            self.metadata.set_label(label)
        else:
            self.metadata.num_data = n
        if weights is not None:
            self.metadata.set_weights(weights)
        if group is not None:
            self.metadata.set_query_counts(group)
        if init_score is not None:
            self.metadata.set_init_score(init_score)
        return self

    def _bin_sparse(self, sp) -> None:
        """Binned matrix from CSC columns: default-bin fill + nonzero
        scatter per column (never a dense float64 intermediate)."""
        n = sp.num_row
        f_used = len(self.used_feature_idx)

        def dense_binned_col(i):
            r = self.used_feature_idx[i]
            mapper = self.bin_mappers[r]
            rows, vals = sp.column(r)
            col = np.full(n, mapper.value_to_bin(0.0), dtype=np.int64)
            if len(rows):
                col[rows] = mapper.value_to_bin(vals)
            return col

        if self.bundle is not None:
            self.binned = bin_rows_grouped(dense_binned_col, self.bundle,
                                           self.default_bin_arr)
            return
        max_num_bin = int(self.num_bin_arr.max()) if f_used else 2
        dtype = np.uint8 if max_num_bin <= 256 else np.uint16
        out = np.empty((n, f_used), dtype=dtype)
        for i in range(f_used):
            out[:, i] = dense_binned_col(i).astype(dtype)
        self.binned = out

    @classmethod
    def from_file(cls, filename: str, config: Optional[Config] = None,
                  reference: Optional["TrainingData"] = None,
                  keep_raw: bool = False) -> "TrainingData":
        """CLI/file path (dataset_loader.cpp:159-216): parse, side files,
        label column handling."""
        config = config or Config()
        if cls.can_load_binned(filename):
            # pre-binned directory: construction cost was already paid
            return cls.from_binned(filename)
        label_idx = 0
        header_names: Optional[List[str]] = None
        if config.has_header:
            header_names = _parser.read_header(filename)
        if config.label_column:
            lc = config.label_column
            if lc.startswith("name:"):
                name = lc[5:]
                if not header_names or name not in header_names:
                    Log.fatal("Could not find label column %s in data file", name)
                label_idx = header_names.index(name)
            else:
                label_idx = int(lc)
        feature_names = None
        if header_names:
            feature_names = [n for i, n in enumerate(header_names) if i != label_idx]
        categorical = _resolve_columns(config.categorical_column, feature_names)
        ignore = _resolve_columns(config.ignore_column, feature_names)

        # streaming two-round loading (dataset_loader.cpp:554-660): pick it
        # when asked for, or automatically for big dense files — the
        # in-memory parser would otherwise materialize the whole text plus
        # an N x F float64 matrix
        from . import streaming as _streaming
        file_bytes = 0
        try:
            file_bytes = os.path.getsize(filename)
        except OSError:
            pass
        out_dir = (str(config.ooc_binned_dir)
                   if getattr(config, "ooc_binned_dir", "")
                   and reference is None else None)
        want_stream = (config.use_two_round_loading or bool(out_dir)
                       or file_bytes > (256 << 20)) and not keep_raw
        if want_stream and _streaming.stream_supported(filename,
                                                       config.has_header):
            self = cls()
            self.feature_names = feature_names or []
            keep = None
            if ignore:
                # column count from the first data lines only (O(1) memory
                # — the whole point of the streaming path)
                with open(filename, "r") as fh:
                    if config.has_header:
                        fh.readline()
                    head = [fh.readline() for _ in range(2)]
                probe = _parser.parse_text(
                    "".join(head), has_header=False, label_idx=label_idx)
                keep = [i for i in range(probe.features.shape[1])
                        if i not in ignore]
                if feature_names:
                    self.feature_names = [feature_names[i] for i in keep]
                categorical = {keep.index(c) for c in categorical
                               if c in keep}
            _streaming.stream_load(self, filename, config, label_idx,
                                   categorical, keep, reference=reference,
                                   out_dir=out_dir)
            if not self.feature_names:
                self.feature_names = ["Column_%d" % i
                                      for i in range(self.num_total_features)]
            self.metadata.init_from_file(filename)
            if out_dir:
                # side files (.weight/.query/.init) load after streaming,
                # so refresh the persisted metadata sidecars
                from . import binned_format as _bf
                _bf.update_metadata(out_dir, self.metadata)
            return self

        parsed = _parser.parse_file(filename, has_header=config.has_header,
                                    label_idx=label_idx)
        data = parsed.features
        if ignore:
            keep = [i for i in range(data.shape[1]) if i not in ignore]
            data = data[:, keep]
            if feature_names:
                feature_names = [feature_names[i] for i in keep]
            categorical = {keep.index(c) for c in categorical if c in keep}
        self = cls.from_matrix(data, label=parsed.label, config=config,
                               categorical_feature=sorted(categorical),
                               feature_names=feature_names,
                               reference=reference, keep_raw=keep_raw)
        self.metadata.init_from_file(filename)
        return self

    def _construct_mappers(self, data: np.ndarray, config: Config,
                           categorical: set) -> None:
        n = self.num_data
        sample_cnt = min(config.bin_construct_sample_cnt, n)
        rng = Random(config.data_random_seed)
        sample_idx = rng.sample(n, sample_cnt)
        if len(sample_idx) == 0:
            sample_idx = np.arange(n, dtype=np.int32)
        sample = data[sample_idx]
        self._fit_mappers_from_sample(sample, config, categorical)

    def _fit_mappers_from_sample(self, sample: np.ndarray, config: Config,
                                 categorical: set) -> None:
        """BinMapper construction from an already-drawn row sample (the
        shared tail of one-round and streaming two-round loading)."""
        n = self.num_data
        total_sample = len(sample)
        # filter_cnt formula from dataset_loader.cpp:491-492
        filter_cnt = int(config.min_data_in_leaf * total_sample / max(n, 1))

        self.bin_mappers = []
        for f in range(self.num_total_features):
            col = sample[:, f]
            col = col[~np.isnan(col)]
            nonzero = col[col != 0.0]
            m = BinMapper()
            bin_type = CATEGORICAL if f in categorical else NUMERICAL
            m.find_bin(nonzero, total_sample, config.max_bin,
                       config.min_data_in_bin, filter_cnt, bin_type)
            self.bin_mappers.append(m)

        self.used_feature_idx = [i for i, m in enumerate(self.bin_mappers)
                                 if m is not None and not m.is_trivial]
        if not self.used_feature_idx:
            Log.warning("There are no meaningful features, as all feature values are constant.")
        self.real_to_inner = {r: i for i, r in enumerate(self.used_feature_idx)}
        self._build_feature_arrays()
        self._profile_quality(lambda f: sample[:, f], total_sample,
                              categorical, config)

        # EFB on the binning sample (Dataset::Construct, dataset.cpp:229-235)
        if (config.enable_bundle and len(self.used_feature_idx) > 1
                and config.tree_learner not in ("feature",
                                                "feature_parallel")):
            binned_sample = np.stack(
                [self.bin_mappers[r].value_to_bin(sample[:, r])
                 .astype(np.uint16) for r in self.used_feature_idx], axis=1)
            self.bundle = find_feature_groups(
                binned_sample, self.num_bin_arr, self.default_bin_arr,
                config.max_conflict_rate, config.min_data_in_leaf,
                self.num_data)
            # drop the (S, F) sample bins before the (N, G) product
            # allocates (retained-intermediate RSS audit, BENCH_NOTES.md)
            del binned_sample
            if self.bundle is not None:
                Log.info("EFB bundled %d features into %d groups",
                         len(self.used_feature_idx), self.bundle.num_groups)

    def _construct_mappers_distributed(self, data: np.ndarray, config: Config,
                                       categorical: set, comm) -> None:
        """Distributed bin finding (dataset_loader.cpp:733-833): features
        partitioned evenly across ranks; each rank finds bins for its
        feature block from its LOCAL row shard's sample; serialized mappers
        are allgathered so every rank holds the identical full set.
        """
        F = self.num_total_features
        n_local = data.shape[0]
        local_counts = comm.allgather_obj(int(n_local))
        total_n = int(sum(local_counts))

        sample_cnt = min(config.bin_construct_sample_cnt, n_local)
        rng = Random(config.data_random_seed)
        sample_idx = rng.sample(n_local, sample_cnt)
        if len(sample_idx) == 0:
            sample_idx = np.arange(n_local, dtype=np.int32)
        sample = data[sample_idx]
        total_sample = len(sample_idx)
        # filter_cnt against the GLOBAL row count (dataset_loader.cpp:491)
        filter_cnt = int(config.min_data_in_leaf * total_sample
                         / max(total_n, 1))

        # even feature partition, same formula on every rank
        # (dataset_loader.cpp:741-767)
        bounds = np.linspace(0, F, comm.size + 1).astype(int)
        start, end = int(bounds[comm.rank]), int(bounds[comm.rank + 1])
        my_mappers = []
        for f in range(start, end):
            col = sample[:, f]
            col = col[~np.isnan(col)]
            nonzero = col[col != 0.0]
            m = BinMapper()
            bin_type = CATEGORICAL if f in categorical else NUMERICAL
            m.find_bin(nonzero, total_sample, config.max_bin,
                       config.min_data_in_bin, filter_cnt, bin_type)
            my_mappers.append(m.to_dict())

        gathered = comm.allgather_obj(my_mappers)
        self.bin_mappers = [BinMapper.from_dict(d)
                            for rank_list in gathered for d in rank_list]
        assert len(self.bin_mappers) == F
        self.used_feature_idx = [i for i, m in enumerate(self.bin_mappers)
                                 if m is not None and not m.is_trivial]
        if not self.used_feature_idx:
            Log.warning("There are no meaningful features, as all feature "
                        "values are constant.")
        self.real_to_inner = {r: i for i, r in enumerate(self.used_feature_idx)}
        self._build_feature_arrays()
        # rank-local sample: the profile reflects this rank's row shard
        self._profile_quality(lambda f: sample[:, f], total_sample,
                              categorical, config)

        # EFB under distribution: every rank MUST end with the identical
        # group structure (histogram psums assume one layout), so rank 0
        # decides from its sample and the groups are broadcast — the
        # allgather doubles as the broadcast.
        if (config.enable_bundle and len(self.used_feature_idx) > 1
                and config.tree_learner not in ("feature",
                                                "feature_parallel")):
            groups = None
            if comm.rank == 0:
                binned_sample = np.stack(
                    [self.bin_mappers[r].value_to_bin(sample[:, r])
                     .astype(np.uint16) for r in self.used_feature_idx],
                    axis=1)
                layout = find_feature_groups(
                    binned_sample, self.num_bin_arr, self.default_bin_arr,
                    config.max_conflict_rate, config.min_data_in_leaf,
                    total_n)
                del binned_sample
                if layout is not None:
                    groups = [list(map(int, g)) for g in layout.groups]
            groups = comm.allgather_obj(groups)[0]
            if groups is not None:
                self.bundle = build_layout(groups, self.num_bin_arr,
                                           self.default_bin_arr)
                if comm.rank == 0:
                    Log.info("EFB bundled %d features into %d groups",
                             len(self.used_feature_idx),
                             self.bundle.num_groups)

    def _copy_binning_from(self, reference: "TrainingData") -> None:
        """Share the train set's binning state (mappers, used features,
        per-feature arrays, EFB layout) — dataset_loader.cpp:220-261."""
        self.bin_mappers = reference.bin_mappers
        self.used_feature_idx = list(reference.used_feature_idx)
        self.real_to_inner = dict(reference.real_to_inner)
        self.num_bin_arr = reference.num_bin_arr
        self.default_bin_arr = reference.default_bin_arr
        self.is_categorical_arr = reference.is_categorical_arr
        self.max_bin = reference.max_bin
        self.bundle = reference.bundle

    def _align_with(self, reference: "TrainingData", data: np.ndarray) -> None:
        """Valid set shares the train set's mappers
        (dataset_loader.cpp:220-261 CreateValid path)."""
        if data.shape[1] != reference.num_total_features:
            Log.fatal("Validation data has %d features, train data has %d",
                      data.shape[1], reference.num_total_features)
        self._copy_binning_from(reference)
        self._bin_data(data)

    def _profile_quality(self, get_col, sample_size: int, categorical: set,
                         config: Config) -> None:
        """Post-binning quality pass: the single-bucket warning (always on
        — it costs one scan of the mappers) plus the data-quality profile
        the Booster emits as a ``data_profile`` obs event
        (``obs_data_profile``, default on)."""
        single = [i for i, m in enumerate(self.bin_mappers)
                  if m is not None and m.num_bin <= 1]
        if single:
            head = ",".join(str(i) for i in single[:20])
            Log.warning(
                "%d feature(s) binned into a single bucket (constant, "
                "never splittable): %s%s", len(single), head,
                ",..." if len(single) > 20 else "")
        if bool(getattr(config, "obs_drift_fingerprint", True)):
            from ..obs import drift
            self._drift_fingerprint = drift.feature_fingerprint(
                self.bin_mappers, get_col, self.num_total_features,
                sample_size, self.feature_names)
        if not bool(getattr(config, "obs_data_profile", True)):
            return
        from ..obs import dataquality
        self._data_profile = dataquality.profile_columns(
            self.bin_mappers, get_col, self.num_total_features,
            sample_size, categorical)

    def _build_feature_arrays(self) -> None:
        used = self.used_feature_idx
        self.num_bin_arr = np.asarray(
            [self.bin_mappers[r].num_bin for r in used], dtype=np.int32)
        self.default_bin_arr = np.asarray(
            [self.bin_mappers[r].default_bin for r in used], dtype=np.int32)
        self.is_categorical_arr = np.asarray(
            [self.bin_mappers[r].bin_type == CATEGORICAL for r in used], dtype=bool)

    def _bin_data(self, data: np.ndarray) -> None:
        n = data.shape[0]
        self.num_data = n
        f_used = len(self.used_feature_idx)
        if self.bundle is not None:
            getcol = lambda i: self.bin_mappers[
                self.used_feature_idx[i]].value_to_bin(
                    data[:, self.used_feature_idx[i]])
            self.binned = bin_rows_grouped(getcol, self.bundle,
                                           self.default_bin_arr)
            return
        max_num_bin = int(self.num_bin_arr.max()) if f_used else 2
        dtype = np.uint8 if max_num_bin <= 256 else np.uint16
        out = np.zeros((n, f_used), dtype=dtype)
        for i, r in enumerate(self.used_feature_idx):
            out[:, i] = self.bin_mappers[r].value_to_bin(data[:, r]).astype(dtype)
        self.binned = out

    # ------------------------------------------------------------- accessors
    @property
    def num_features(self) -> int:
        return len(self.used_feature_idx)

    def inner_feature_index(self, real_idx: int) -> int:
        return self.real_to_inner.get(real_idx, -1)

    def real_feature_index(self, inner_idx: int) -> int:
        return self.used_feature_idx[inner_idx]

    def real_threshold(self, inner_idx: int, threshold_bin: int) -> float:
        """bin threshold -> real-valued threshold (dataset.h:457-462)."""
        return self.bin_mappers[self.used_feature_idx[inner_idx]].bin_to_value(threshold_bin)

    def feature_bin_mapper(self, inner_idx: int) -> BinMapper:
        return self.bin_mappers[self.used_feature_idx[inner_idx]]

    def feature_infos(self) -> List[str]:
        """Per total-feature info string for the model file
        (dataset.h:514-526)."""
        out = []
        for i in range(self.num_total_features):
            if self.real_to_inner.get(i, -1) == -1:
                out.append("none")
            else:
                out.append(self.bin_mappers[i].bin_info())
        return out

    def subset(self, indices: np.ndarray) -> "TrainingData":
        """Bagging subset copy (dataset.cpp:399 CopySubset)."""
        out = TrainingData()
        out.num_data = len(indices)
        out.num_total_features = self.num_total_features
        out.bin_mappers = self.bin_mappers
        out.used_feature_idx = self.used_feature_idx
        out.real_to_inner = self.real_to_inner
        out.num_bin_arr = self.num_bin_arr
        out.default_bin_arr = self.default_bin_arr
        out.is_categorical_arr = self.is_categorical_arr
        out.max_bin = self.max_bin
        out.feature_names = self.feature_names
        out.bundle = self.bundle
        out.binned = self.binned[indices]
        out.metadata = self.metadata.subset(indices)
        return out

    # ------------------------------------------------------- binary file I/O
    _BINARY_MAGIC = "lightgbm_tpu.dataset.v1"

    def save_binary(self, filename: str) -> None:
        """Binary dataset file (dataset.cpp:489 SaveBinaryFile analog)."""
        meta = {
            "magic": self._BINARY_MAGIC,
            "num_data": self.num_data,
            "num_total_features": self.num_total_features,
            "used_feature_idx": self.used_feature_idx,
            "feature_names": self.feature_names,
            "max_bin": self.max_bin,
            "bin_mappers": [None if m is None else m.to_dict()
                            for m in self.bin_mappers],
            "bundle_groups": (None if self.bundle is None
                              else [list(map(int, g))
                                    for g in self.bundle.groups]),
        }
        arrays = {"binned": self.binned}
        if self.metadata.label is not None:
            arrays["label"] = self.metadata.label
        if self.metadata.weights is not None:
            arrays["weights"] = self.metadata.weights
        if self.metadata.query_boundaries is not None:
            arrays["query_boundaries"] = self.metadata.query_boundaries
        if self.metadata.init_score is not None:
            arrays["init_score"] = self.metadata.init_score
        # write through a handle: np.savez_compressed(<str>) appends
        # ".npz" to alien extensions, breaking the reference's
        # save-to-any-name contract (e.g. "train.bin")
        with open(filename, "wb") as f:
            np.savez_compressed(f, meta=json.dumps(meta), **arrays)

    @classmethod
    def can_load_binary(cls, filename: str) -> bool:
        try:
            with np.load(filename, allow_pickle=False) as z:
                meta = json.loads(str(z["meta"]))
            return meta.get("magic") == cls._BINARY_MAGIC
        except Exception:
            return False

    @classmethod
    def load_binary(cls, filename: str) -> "TrainingData":
        with np.load(filename, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            if meta.get("magic") != cls._BINARY_MAGIC:
                Log.fatal("Not a lightgbm_tpu binary dataset file: %s", filename)
            self = cls()
            self.num_data = meta["num_data"]
            self.num_total_features = meta["num_total_features"]
            self.used_feature_idx = list(meta["used_feature_idx"])
            self.real_to_inner = {r: i for i, r in enumerate(self.used_feature_idx)}
            self.feature_names = meta["feature_names"]
            self.max_bin = meta["max_bin"]
            self.bin_mappers = [None if d is None else BinMapper.from_dict(d)
                                for d in meta["bin_mappers"]]
            self._build_feature_arrays()
            groups = meta.get("bundle_groups")
            if groups is not None:
                self.bundle = build_layout(groups, self.num_bin_arr,
                                           self.default_bin_arr)
            self.binned = z["binned"]
            self.metadata = Metadata(self.num_data)
            if "label" in z:
                self.metadata.label = z["label"]
            if "weights" in z:
                self.metadata.weights = z["weights"]
            if "query_boundaries" in z:
                self.metadata.query_boundaries = z["query_boundaries"]
            if "init_score" in z:
                self.metadata.init_score = z["init_score"]
        return self

    # --------------------------------------------- pre-binned mmap format
    @classmethod
    def from_streamed(cls, data, label=None, config: Optional[Config] = None,
                      weights=None, group=None, init_score=None,
                      categorical_feature: Sequence[int] = (),
                      feature_names: Optional[List[str]] = None,
                      reference: Optional["TrainingData"] = None,
                      out_dir: Optional[str] = None,
                      chunk_rows: Optional[int] = None) -> "TrainingData":
        """Out-of-core construction from an in-memory matrix, a ``.npy``
        path, or SparseColumns — the two-pass parallel pipeline of
        io/streaming.py (text files go through from_file, which streams
        automatically).  out_dir persists the result as a binned dataset
        directory and keeps td mmap-backed."""
        from . import streaming as _streaming
        config = config or Config()
        chunk = int(chunk_rows or config.ooc_chunk_rows
                    or _streaming.DEFAULT_CHUNK_ROWS)
        if hasattr(data, "colptr"):          # SparseColumns
            source = _streaming.SparseSource(data, label=label,
                                             chunk_rows=chunk)
        else:
            source = _streaming.MatrixSource(data, label=label,
                                             chunk_rows=chunk)
        self = cls()
        self.feature_names = list(feature_names) if feature_names else []
        cats = set(int(c) for c in categorical_feature)
        _streaming.stream_construct(self, source, config, categorical=cats,
                                    reference=reference, out_dir=out_dir)
        if not self.feature_names:
            self.feature_names = ["Column_%d" % i
                                  for i in range(self.num_total_features)]
        if weights is not None:
            self.metadata.set_weights(weights)
        if group is not None:
            self.metadata.set_query_counts(group)
        if init_score is not None:
            self.metadata.set_init_score(init_score)
        if out_dir and (weights is not None or group is not None
                        or init_score is not None):
            from . import binned_format as _bf
            _bf.update_metadata(out_dir, self.metadata)
        return self

    def save_binned(self, path: str) -> None:
        """Persist as the mmap-able pre-binned directory format
        (io/binned_format.py) so later runs skip construction entirely."""
        from . import binned_format as _bf
        _bf.save_training_data(self, path)

    @classmethod
    def can_load_binned(cls, path) -> bool:
        from . import binned_format as _bf
        return _bf.is_binned_dir(path)

    @classmethod
    def from_binned(cls, path: str, verify=None, comm=None,
                    row_range=None) -> "TrainingData":
        """Open a pre-binned dataset directory: shards stay mmap-backed
        (no bin matrix materialized until something asks for it; the
        learner pages shards straight to the device).

        ``comm``: optional parallel.comm.HostComm for multi-host sharded
        ingest — each rank opens only its balanced row-range of the
        shard table (``row_range`` overrides the balance), so peak
        per-host RSS stays O(rank rows).  Bin mappers come verbatim from
        the shared header, so every rank freezes bit-identical binning
        with zero collective rounds.

        ``verify``: ``None`` picks the right default — a full CRC scan
        for whole-dataset opens (the original ``verify=True`` contract),
        lazy per-mapped-shard CRCs for rank-sharded opens (a rank
        reading 1/64th of the rows must not stream the other 63/64ths).
        Pass ``True``/``"lazy"``/``False`` to force a mode."""
        from . import binned_format as _bf
        from .streaming import _peak_rss_bytes
        rss0 = _peak_rss_bytes()
        t0 = _time.time()
        sharded = (comm is not None and comm.size > 1) \
            or row_range is not None
        if verify is None:
            verify = "lazy" if sharded else True
        if comm is not None and comm.size > 1 and row_range is None:
            total = int(_bf._read_header(str(path))["num_data"])
            row_range = (comm.rank * total // comm.size,
                         (comm.rank + 1) * total // comm.size)
        reader = _bf.BinnedReader(path, verify=verify, row_range=row_range)
        h = reader.header
        self = cls()
        lo, hi = reader.row_range
        self.num_data = hi - lo
        self.num_total_features = int(h["num_total_features"])
        self.used_feature_idx = list(h["used_feature_idx"])
        self.real_to_inner = {r: i for i, r in
                              enumerate(self.used_feature_idx)}
        self.feature_names = list(h["feature_names"])
        self.max_bin = int(h["max_bin"])
        self.bin_mappers = [None if d is None else BinMapper.from_dict(d)
                            for d in h["bin_mappers"]]
        self._drift_fingerprint = h.get("drift_fingerprint")
        self._build_feature_arrays()
        groups = h.get("bundle_groups")
        if groups is not None:
            self.bundle = build_layout(groups, self.num_bin_arr,
                                       self.default_bin_arr)
        self._binned_reader = reader
        self._comm = comm if (comm is not None and comm.size > 1) else None
        self.metadata = Metadata(self.num_data)

        def _local(arr):
            """This rank's row slice of a per-row sidecar, copied out of
            the memmap so resident bytes stay O(rank rows)."""
            if arr is None or not sharded:
                return arr
            if arr.shape[0] == hi - lo:     # already rank-local
                return np.asarray(arr)
            return np.array(arr[lo:hi])

        label = reader.load_metadata_array("label", mmap=sharded)
        if label is not None:
            self.metadata.label = _local(label)
        self.metadata.weights = _local(
            reader.load_metadata_array("weights", mmap=sharded))
        qb = reader.load_metadata_array("query_boundaries")
        if qb is not None and sharded:
            # query groups straddle row-range cuts; pre-partition ranking
            # data per rank instead (the reference's pre_partition path)
            Log.fatal("rank-sharded from_binned does not support ranking "
                      "(query_boundaries) datasets — pre-partition them "
                      "per rank")
        self.metadata.query_boundaries = qb
        self.metadata.init_score = _local(
            reader.load_metadata_array("init_score", mmap=sharded))
        # sketch_s and bin_s stay 0: opening the format does ZERO
        # re-binning work (the CI ooc-smoke gate asserts exactly this)
        extra = {"load_s": round(_time.time() - t0, 6)}
        if sharded:
            extra["row_range"] = [int(lo), int(hi)]
            extra["world_size"] = int(comm.size) if comm is not None else 1
        self._note_construct_stats("binned", rows=self.num_data,
                                   chunks=reader.num_shards, sketch_s=0.0,
                                   bin_s=0.0, write_s=0.0, workers=1,
                                   rss_before=rss0, **extra)
        return self


def _resolve_columns(spec: str, feature_names: Optional[List[str]]) -> set:
    """Parse 'name:a,b,c' or '0,1,2' column specs (dataset_loader.cpp:22-120
    SetHeader column-role resolution)."""
    out: set = set()
    if not spec:
        return out
    if spec.startswith("name:"):
        names = spec[5:].split(",")
        if feature_names:
            for nm in names:
                if nm in feature_names:
                    out.add(feature_names.index(nm))
                else:
                    Log.warning("Could not find column %s in data file", nm)
    else:
        for tok in spec.split(","):
            tok = tok.strip()
            if tok:
                out.add(int(tok))
    return out
