# End-to-end test of the lightgbm.tpu R package (run: Rscript tests/smoke.R).
# Mirrors the reference R-package test style: Dataset -> train with valids
# -> predict -> save/load -> RDS round-trip -> importance / tree table /
# interpretation -> cv -> Dataset accessors.

this_file <- sub("--file=", "", grep("--file=", commandArgs(FALSE),
                                     value = TRUE))
r_dir <- file.path(dirname(this_file), "..", "R")
for (f in c("utils.R", "lgb.Dataset.R", "lgb.Booster.R", "lgb.train.R",
            "lgb.cv.R", "lightgbm.R", "lgb.importance.R",
            "lgb.model.dt.tree.R", "lgb.interprete.R",
            "lgb.plot.importance.R", "lgb.plot.interpretation.R",
            "lgb.prepare.R", "saveRDS.lgb.Booster.R", "callback.R")) {
  source(file.path(r_dir, f))
}

set.seed(42)
n <- 500
x <- matrix(rnorm(n * 4), ncol = 4)
colnames(x) <- paste0("f", 1:4)
y <- as.numeric(x[, 1] + 0.5 * x[, 2] > 0)
xv <- matrix(rnorm(200 * 4), ncol = 4)
yv <- as.numeric(xv[, 1] + 0.5 * xv[, 2] > 0)

# ---- Dataset accessors
dtrain <- lgb.Dataset(x, label = y)
stopifnot(identical(dim(dtrain), c(500L, 4L)))
stopifnot(identical(dimnames(dtrain)[[2]], paste0("f", 1:4)))
setinfo(dtrain, "weight", rep(1.0, n))
stopifnot(length(getinfo(dtrain, "label")) == n)
dsub <- slice(dtrain, 1:100)
stopifnot(dim(dsub)[1] == 100L)

# ---- training with a valid set + eval record
dvalid <- lgb.Dataset.create.valid(dtrain, xv, label = yv)
bst <- lgb.train(params = list(objective = "binary", num_leaves = 7,
                               learning_rate = 0.2, metric = "binary_logloss",
                               verbose = -1),
                 data = dtrain, nrounds = 25L,
                 valids = list(valid_0 = dvalid), verbose = 0L)
ev <- lgb.get.eval.result(bst, "valid_0", "binary_logloss")
stopifnot(length(ev) == 25L, ev[25] < ev[1])

pred <- predict(bst, x)
stopifnot(length(pred) == n, mean((pred > 0.5) == (y > 0.5)) > 0.9)

# ---- save / load (text model)
f <- tempfile(fileext = ".txt")
lgb.save(bst, f)
bst2 <- lgb.load(filename = f)
stopifnot(max(abs(pred - predict(bst2, x))) < 1e-9)
stopifnot(nchar(lgb.model.to.string(bst)) > 100)

# ---- RDS round-trip
rds <- tempfile(fileext = ".rds")
saveRDS.lgb.Booster(bst, rds)
bst3 <- readRDS.lgb.Booster(rds)
stopifnot(max(abs(pred - predict(bst3, x))) < 1e-9)
stopifnot(length(lgb.get.eval.result(bst3, "valid_0", "binary_logloss")) == 25L)

# ---- importance / tree table / interpretation
imp <- lgb.importance(bst)
stopifnot(is.data.frame(imp), nrow(imp) >= 2, imp$Feature[1] %in% c("f1", "f2"))
dt <- lgb.model.dt.tree(bst)
stopifnot(is.data.frame(dt), sum(!is.na(dt$leaf_value)) > 0,
          max(dt$tree_index) == 24)
ii <- lgb.interprete(bst, x, idxset = 1:2)
stopifnot(length(ii) == 2, is.data.frame(ii[[1]]))
pdf(NULL)  # plots render headlessly
lgb.plot.importance(imp, top_n = 3)
lgb.plot.interpretation(ii[[1]])
dev.off()

# ---- cv
cv <- lgb.cv(params = list(objective = "binary", num_leaves = 7,
                           metric = "binary_logloss", verbose = -1),
             data = lgb.Dataset(x, label = y), nrounds = 8L, nfold = 3L,
             stratified = FALSE, verbose = 0L)
stopifnot(inherits(cv, "lgb.CVBooster"),
          length(cv$record_evals[["binary_logloss-mean"]]) == 8L)

# ---- callbacks: LR schedule + explicit record + early stop
rec_cb <- cb.record.evaluation()
bst5 <- lgb.train(params = list(objective = "binary", num_leaves = 7,
                                metric = "binary_logloss", verbose = -1),
                  data = dtrain, nrounds = 12L,
                  valids = list(valid_0 = dvalid), verbose = 0L,
                  callbacks = list(
                    cb.reset.parameters(list(
                      learning_rate = function(iter, n) 0.3 * 0.95^iter)),
                    rec_cb))
rec <- reticulate::py_to_r(attr(rec_cb, "eval_result"))
stopifnot(length(rec$valid_0$binary_logloss) == 12L)
bst6 <- lgb.train(params = list(objective = "binary", num_leaves = 7,
                                metric = "binary_logloss", verbose = -1),
                  data = dtrain, nrounds = 200L,
                  valids = list(valid_0 = dvalid), verbose = 0L,
                  callbacks = list(cb.early.stop(5L, verbose = FALSE)))
stopifnot(attr(bst6, "best_iter") < 200L)

# ---- lightgbm() convenience + prepare
df <- data.frame(a = rnorm(50), b = factor(sample(c("x", "y", "z"), 50,
                                                  replace = TRUE)))
pr <- lgb.prepare_rules(df)
stopifnot(is.numeric(pr$data$b), length(pr$rules$b) == 3)
pr2 <- lgb.prepare_rules(df[1:10, ], rules = pr$rules)
stopifnot(identical(pr2$data$b[1:10], pr$data$b[1:10]))
bst4 <- lightgbm(x, label = y,
                 params = list(objective = "binary", verbose = -1),
                 nrounds = 5L, verbose = 0L, save_name = "")
stopifnot(length(predict(bst4, x)) == n)

cat("R smoke test OK\n")
