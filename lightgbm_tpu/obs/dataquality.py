"""Data-quality profiling at Dataset construction time.

Profiles the binning sample against the fitted BinMappers (io/binning.py)
— the exact data the split search will see — and emits one
``data_profile`` event per training dataset on the obs timeline:

* per-feature missing rate (NaN fraction in the sample) and normalized
  bin-occupancy entropy (H / log(num_bin): 1.0 = uniform over bins,
  -> 0 = mass piled in one bin);
* degeneracy flags: ``constant`` (binned into a single bucket, the
  learner will never split it), ``near_constant`` (top bin holds almost
  every row), ``high_cardinality`` (categorical with almost as many
  categories as sampled rows — an ID-like column that invites
  overfitting);
* label balance (distinct values / class fractions for few-class labels).

Findings route through the health channel (health.py semantics): under
``obs_health=warn`` every finding is a ``health`` event + log warning;
under ``obs_health=fatal`` the *error*-severity findings (constant
feature, all-missing feature, single-class label) abort the run before
any iteration burns device time on a dataset that cannot train.
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.log import Log
from .metrics import REGISTRY

# near-constant: top bin occupancy at or above this fraction of the sample
NEAR_CONSTANT_TOP_FRAC = 0.999
# high-cardinality categorical: distinct categories >= this fraction of
# the (non-missing) sampled rows
HIGH_CARDINALITY_FRAC = 0.5
# label imbalance warning: minority class below this fraction
LABEL_IMBALANCE_FRAC = 0.01
# per-feature arrays are included in the event only up to this width
# (beyond it the flags + aggregates still tell the story at 1/100 the bytes)
MAX_PROFILE_ARRAYS = 512


def profile_columns(bin_mappers, get_col: Callable[[int], np.ndarray],
                    n_features: int, sample_size: int,
                    categorical: Optional[set] = None) -> dict:
    """Per-feature quality profile from the binning sample.

    ``get_col(f)`` returns feature f's sampled values (NaN = missing,
    zeros materialized) — a closure over the dense sample matrix or the
    sparse per-column sample cache.  ``bin_mappers[f]`` may be None or
    trivial; those features are profiled from raw values only.
    """
    from ..io.binning import CATEGORICAL

    categorical = categorical or set()
    missing_rate: List[float] = []
    entropy: List[Optional[float]] = []
    constant: List[int] = []
    filtered: List[int] = []
    near_constant: List[int] = []
    high_cardinality: List[int] = []
    s = max(int(sample_size), 1)
    for f in range(n_features):
        col = np.asarray(get_col(f), dtype=np.float64)
        nan_mask = np.isnan(col)
        miss = float(nan_mask.sum()) / s
        missing_rate.append(round(miss, 6))
        m = bin_mappers[f] if f < len(bin_mappers) else None
        is_cat = (f in categorical or
                  (m is not None and m.bin_type == CATEGORICAL))
        if miss >= 1.0 or m is None or m.num_bin <= 1:
            # single bucket (or nothing to bin): the learner cannot split it
            constant.append(f)
            entropy.append(None)
            continue
        finite = col[~nan_mask]
        if is_cat:
            # categorical value_to_bin is a scalar dict loop — count raw
            # category occupancy directly instead
            _, counts = np.unique(finite, return_counts=True)
        else:
            bins = m.value_to_bin(finite)
            counts = np.bincount(bins.astype(np.int64),
                                 minlength=m.num_bin)
            counts = counts[counts > 0]
        if len(counts) <= 1:
            # one occupied bucket in the sample: constant in the data —
            # even when the mapper allotted two bins (a constant nonzero
            # value gets a value bin plus the zero bin)
            constant.append(f)
            entropy.append(None)
            continue
        if m.is_trivial:
            # multiple occupied buckets but dropped by the min-split-data
            # filter (need_filter, io/binning.py) — unusable, not constant
            filtered.append(f)
            entropy.append(None)
            continue
        p = counts / counts.sum()
        h = float(-(p * np.log(p)).sum()) / math.log(max(m.num_bin, 2))
        entropy.append(round(h, 4))
        if float(counts.max()) / max(len(finite), 1) >= \
                NEAR_CONSTANT_TOP_FRAC:
            near_constant.append(f)
        if is_cat and len(counts) >= HIGH_CARDINALITY_FRAC * \
                max(len(finite), 1) and len(counts) > 8:
            high_cardinality.append(f)

    profile = {
        "n_features": int(n_features),
        "sample_size": int(sample_size),
        "constant": constant,
        "filtered": filtered,
        "near_constant": near_constant,
        "high_cardinality": high_cardinality,
        "mean_missing_rate": round(float(np.mean(missing_rate)), 6)
        if missing_rate else 0.0,
    }
    ent = [e for e in entropy if e is not None]
    if ent:
        profile["mean_entropy"] = round(float(np.mean(ent)), 4)
    if n_features <= MAX_PROFILE_ARRAYS:
        profile["missing_rate"] = missing_rate
        profile["entropy"] = entropy
    return profile


def profile_dense_sample(bin_mappers, sample: np.ndarray,
                         categorical: Optional[set] = None) -> dict:
    """Convenience wrapper over the (S, F) dense binning sample."""
    return profile_columns(bin_mappers, lambda f: sample[:, f],
                           sample.shape[1], sample.shape[0], categorical)


def label_profile(label: Optional[np.ndarray], max_classes: int = 32) -> dict:
    """Label balance: class fractions when the label has few distinct
    values (classification-shaped), distinct count otherwise."""
    if label is None or len(label) == 0:
        return {"n": 0}
    label = np.asarray(label, dtype=np.float64)
    out: Dict = {"n": int(len(label))}
    values, counts = np.unique(label[~np.isnan(label)], return_counts=True)
    out["n_distinct"] = int(len(values))
    if 0 < len(values) <= max_classes:
        total = counts.sum()
        out["classes"] = {repr(float(v)): int(c)
                          for v, c in zip(values, counts)}
        out["min_class_frac"] = round(float(counts.min()) / max(total, 1), 6)
    return out


def build_findings(profile: dict, label: dict,
                   feature_names: Optional[List[str]] = None) -> List[dict]:
    """Profile -> findings list.  severity 'error' = training cannot work
    (fatal-eligible under obs_health=fatal); 'warning' = suspicious."""
    def name(f):
        if feature_names and 0 <= f < len(feature_names):
            return feature_names[f]
        return "Column_%d" % f

    findings: List[dict] = []
    rates = profile.get("missing_rate") or []
    for f in profile.get("constant", []):
        all_missing = f < len(rates) and rates[f] >= 1.0
        findings.append({
            "severity": "error", "feature": int(f),
            "flag": "all_missing" if all_missing else "constant",
            "message": "feature %d (%s) is %s — it bins into a single "
                       "bucket and can never be split" %
                       (f, name(f),
                        "entirely missing" if all_missing else "constant")})
    for f in profile.get("filtered", []):
        findings.append({
            "severity": "warning", "feature": int(f),
            "flag": "filtered",
            "message": "feature %d (%s) was dropped by the min-split-data "
                       "filter (no bin boundary can satisfy "
                       "min_data_in_leaf)" % (f, name(f))})
    for f in profile.get("near_constant", []):
        findings.append({
            "severity": "warning", "feature": int(f),
            "flag": "near_constant",
            "message": "feature %d (%s) is near-constant (top bin holds "
                       ">=%.1f%% of sampled rows)" %
                       (f, name(f), NEAR_CONSTANT_TOP_FRAC * 100)})
    for f in profile.get("high_cardinality", []):
        findings.append({
            "severity": "warning", "feature": int(f),
            "flag": "high_cardinality",
            "message": "categorical feature %d (%s) has ID-like "
                       "cardinality (categories >= %.0f%% of sampled "
                       "rows) — likely to overfit" %
                       (f, name(f), HIGH_CARDINALITY_FRAC * 100)})
    nd = label.get("n_distinct")
    if nd == 1:
        findings.append({
            "severity": "error", "flag": "single_class_label",
            "message": "label has a single distinct value — every tree "
                       "will be a stub"})
    elif (label.get("min_class_frac") is not None
          and label["min_class_frac"] < LABEL_IMBALANCE_FRAC):
        findings.append({
            "severity": "warning", "flag": "label_imbalance",
            "message": "label is heavily imbalanced (minority class "
                       "fraction %.4g < %g)" %
                       (label["min_class_frac"], LABEL_IMBALANCE_FRAC)})
    return findings


def emit_data_profile(obs, profile: dict, label: dict,
                      findings: List[dict], health_mode: str = "off",
                      dataset: str = "train") -> None:
    """Write the ``data_profile`` event and route findings through the
    health channel (mirrors health.HealthMonitors._resolve): every
    finding logs + emits a ``health`` event; under ``fatal`` the
    error-severity ones abort before training starts."""
    REGISTRY.counter(
        "dataset_quality_findings_total",
        "data-quality findings raised at dataset construction",
    ).inc(len(findings))
    obs.event("data_profile", dataset=dataset, label=label,
              findings=findings, **profile)
    if health_mode not in ("warn", "fatal") or not findings:
        return
    fatal = []
    for fd in findings:
        status = ("fatal" if (health_mode == "fatal"
                              and fd["severity"] == "error") else "warn")
        obs.event("health", check="data_profile", status=status, it=-1,
                  detail=fd)
        Log.warning("data_profile[%s] %s", status, fd["message"])
        if status == "fatal":
            fatal.append(fd["message"])
    if fatal:
        obs.flush()               # the timeline must survive the raise
        try:
            obs.flight("obs_health=fatal: data_profile",
                       extra={"findings": fatal})
        except Exception:
            pass
        Log.fatal("obs_health=fatal: degenerate dataset — %s"
                  % "; ".join(fatal))
