"""Multi-device data-parallel training on the virtual 8-device CPU mesh —
the reference's OpenCL-on-CPU / single-process-MPI trick (SURVEY.md §4)."""
import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                        make_data_mesh)
from lightgbm_tpu.ops.learner import SerialTreeLearner
from lightgbm_tpu.utils.config import Config


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    n, f = 1003, 8   # deliberately not divisible by 8 (padding path)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_data_parallel_tree_matches_serial(data):
    X, y = data
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 5, "verbose": -1})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    g = (1.0 / (1.0 + np.exp(-np.zeros(len(y)))) - y).astype(np.float32)
    h = np.full(len(y), 0.25, dtype=np.float32)

    serial = SerialTreeLearner(cfg, td)
    tree_s, leaf_s = serial.train(g, h)

    mesh = make_data_mesh(jax.devices())
    dp = DataParallelTreeLearner(cfg, td, mesh)
    tree_dev, leaf_d = dp.train_device(g, h)
    tree_d = dp.materialize(tree_dev)

    # identical structure and outputs (psum changes reduction order, so
    # float32 sums can differ in the last ulps -> identical splits expected
    # on well-separated gains)
    assert tree_d.num_leaves == tree_s.num_leaves
    np.testing.assert_array_equal(tree_d.split_feature[:tree_d.num_leaves - 1],
                                  tree_s.split_feature[:tree_s.num_leaves - 1])
    np.testing.assert_array_equal(tree_d.threshold_in_bin[:tree_d.num_leaves - 1],
                                  tree_s.threshold_in_bin[:tree_s.num_leaves - 1])
    np.testing.assert_allclose(tree_d.leaf_value[:tree_d.num_leaves],
                               tree_s.leaf_value[:tree_s.num_leaves],
                               rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(leaf_d), np.asarray(leaf_s))


def test_end_to_end_data_parallel_training(data):
    X, y = data
    train = lgb.Dataset(X, label=y)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "auc",
                     "tree_learner": "data", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    train, num_boost_round=20, valid_sets=[train],
                    evals_result=evals, verbose_eval=False)
    assert evals["training"]["auc"][-1] > 0.97
    p = bst.predict(X)
    assert (((p > 0.5) == (y > 0)).mean()) > 0.9


def test_voting_alias_and_feature_alias(data):
    X, y = data
    for ltype in ("feature", "voting"):
        train = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "tree_learner": ltype,
                         "verbose": -1, "num_leaves": 7,
                         "min_data_in_leaf": 5},
                        train, num_boost_round=5, verbose_eval=False)
        assert bst.num_trees() > 0


def _tree_signature(t):
    nl = t.num_leaves
    return (nl, t.split_feature[:nl - 1].tolist(),
            t.threshold_in_bin[:nl - 1].tolist(),
            np.round(t.leaf_value[:nl], 6).tolist())


@pytest.fixture(scope="module")
def wide_data():
    rng = np.random.default_rng(3)
    n, f = 1500, 23   # f not divisible by 8 (feature-padding path)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.7 * X[:, 1] * X[:, 2]
         + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
    cfg = Config({"num_leaves": 31, "min_data_in_leaf": 10, "verbose": -1,
                  "top_k": 64})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    g = (0.5 - y).astype(np.float32)
    h = np.full(n, 0.25, dtype=np.float32)
    return cfg, td, g, h


def test_feature_parallel_exact_match(wide_data):
    """Feature-sharded search must reproduce the serial tree bit-for-bit:
    same scans run, only the argmax-reduce location differs
    (feature_parallel_tree_learner.cpp:52-76)."""
    from lightgbm_tpu.parallel.mesh import FeatureParallelTreeLearner
    cfg, td, g, h = wide_data
    tree_s, leaf_s = SerialTreeLearner(cfg, td).train(g, h)
    fp = FeatureParallelTreeLearner(cfg, td)
    tree_f, leaf_f = fp.train(g, h)
    assert _tree_signature(tree_f) == _tree_signature(tree_s)
    np.testing.assert_array_equal(np.asarray(leaf_f), np.asarray(leaf_s))


def test_voting_parallel_exact_when_topk_covers(wide_data):
    """top_k >= num_features selects every feature, so voting must equal
    serial exactly (modulo psum reduction order)."""
    from lightgbm_tpu.parallel.mesh import VotingParallelTreeLearner
    cfg, td, g, h = wide_data
    tree_s, _ = SerialTreeLearner(cfg, td).train(g, h)
    vt = VotingParallelTreeLearner(cfg, td)
    tree_v = vt.materialize(vt.train_device(g, h)[0])
    assert _tree_signature(tree_v) == _tree_signature(tree_s)


def test_voting_parallel_topk_approximation(wide_data):
    """Small top_k still grows a full, useful tree (PV-Tree regime)."""
    from lightgbm_tpu.parallel.mesh import VotingParallelTreeLearner
    cfg, td, g, h = wide_data
    cfg_small = Config({"num_leaves": 31, "min_data_in_leaf": 10,
                        "verbose": -1, "top_k": 5})
    vt = VotingParallelTreeLearner(cfg_small, td)
    tree_v = vt.materialize(vt.train_device(g, h)[0])
    assert tree_v.num_leaves == 31


def test_end_to_end_voting_parallel_training(data):
    X, y = data
    train = lgb.Dataset(X, label=y)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "auc",
                     "tree_learner": "voting", "top_k": 3, "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    train, num_boost_round=20, valid_sets=[train],
                    evals_result=evals, verbose_eval=False)
    assert evals["training"]["auc"][-1] > 0.97


def test_end_to_end_feature_parallel_training(data):
    X, y = data
    train = lgb.Dataset(X, label=y)
    evals = {}
    bst = lgb.train({"objective": "binary", "metric": "auc",
                     "tree_learner": "feature", "verbose": -1,
                     "num_leaves": 15, "min_data_in_leaf": 5},
                    train, num_boost_round=20, valid_sets=[train],
                    evals_result=evals, verbose_eval=False)
    assert evals["training"]["auc"][-1] > 0.97


def test_feature_parallel_never_packs_nibbles():
    """max_bin<=15 + tpu_bin_pack=auto must NOT pack under the
    feature-parallel learner (its base ctor runs with psum_axis=None but
    a pre-sharded device matrix; packing there would shard nibble bytes
    as if they were bin columns). The tree must still match serial."""
    from lightgbm_tpu.parallel.mesh import FeatureParallelTreeLearner
    rng = np.random.default_rng(5)
    n, f = 1200, 11
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.5 * X[:, 3] > 0).astype(np.float64)
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 5, "verbose": -1,
                  "max_bin": 15, "tree_learner": "feature",
                  "enable_bundle": False})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    g = (0.5 - y).astype(np.float32)
    h = np.full(n, 0.25, dtype=np.float32)
    fp = FeatureParallelTreeLearner(cfg, td)
    assert fp.packed_cols == 0
    cfg_s = Config({"num_leaves": 15, "min_data_in_leaf": 5, "verbose": -1,
                    "max_bin": 15, "enable_bundle": False})
    td_s = TrainingData.from_matrix(X, label=y, config=cfg_s)
    tree_s, _ = SerialTreeLearner(cfg_s, td_s).train(g, h)
    tree_f, _ = fp.train(g, h)
    assert _tree_signature(tree_f) == _tree_signature(tree_s)
