"""The Python API end to end: train, validate, save, reload, predict.

Run from the repo root:  python examples/python-guide/simple_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(20_000, 10))
y = (X[:, 0] + np.sin(X[:, 1] * 2) + 0.3 * rng.normal(size=20_000) > 0)
X_train, X_test = X[:16_000], X[16_000:]
y_train, y_test = y[:16_000].astype(float), y[16_000:].astype(float)

train_set = lgb.Dataset(X_train, label=y_train)
valid_set = train_set.create_valid(X_test, label=y_test)

evals = {}
bst = lgb.train(
    {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
     "metric": ["auc", "binary_logloss"], "verbose": -1},
    train_set, num_boost_round=60, valid_sets=[valid_set],
    valid_names=["holdout"], early_stopping_rounds=10,
    callbacks=[lgb.record_evaluation(evals)])

print("best iteration:", bst.best_iteration)
print("holdout AUC:", evals["holdout"]["auc"][-1])

bst.save_model("/tmp/simple_example.model")
reloaded = lgb.Booster(model_file="/tmp/simple_example.model")
pred = reloaded.predict(X_test)
print("prediction head:", np.round(pred[:5], 4))

# sklearn flavor
clf = lgb.LGBMClassifier(n_estimators=40, num_leaves=31)
clf.fit(X_train, y_train.astype(int))
print("sklearn accuracy:", (clf.predict(X_test) == y_test).mean())
