"""Cross-run performance ledger (obs/ledger.py) + the layers above it.

Covers:
  * run_header provenance (schema 10): emitted by RunObserver, required
    by strict validation for new-schema headers, absent-but-valid on
    old-schema records;
  * ingest — record shape, idempotent re-ingest (events, timelines and
    the backfill tool), the comparability key (suite/shape/device);
  * crash-safety — corrupt index lines are skipped and the full run
    records under runs/ recover history the index lost;
  * rolling statistics — median/MAD with the noise floor, thin-history
    (< min) behavior, change-point detection on an injected step
    regression with git-rev attribution, `obs trend --check` exit
    semantics;
  * tools/bench_compare.py — the zero-baseline absolute-delta gate in
    both directions, and `--baseline rolling` (z-gate pass/fail,
    candidate-run exclusion, thin-history parent fallback notice).
"""
import importlib.util
import json
import os
import time

import pytest

from lightgbm_tpu.obs import SCHEMA_VERSION, read_events, validate_event
from lightgbm_tpu.obs.events import RunObserver, collect_provenance
from lightgbm_tpu.obs.ledger import (Ledger, change_points,
                                     comparable_entries,
                                     metrics_from_events,
                                     record_from_events, rolling_stats,
                                     sparkline)
from lightgbm_tpu.obs.query import main as obs_main

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

PROV = {"git_rev": "feedc0ffee12", "git_dirty": False,
        "hostname": "testhost", "argv": ["bench.py", "--dry"]}


def _events(run="r0", t=None, ips=5.0, first_s=1.5, git_rev=None,
            status="ok"):
    """A minimal finished-run event list with a deterministic rate."""
    t = time.time() if t is None else float(t)
    prov = dict(PROV, git_rev=git_rev or PROV["git_rev"])
    return [
        {"ev": "run_header", "run": run, "t": t,
         "schema": SCHEMA_VERSION, "backend": "cpu",
         "devices": [{"id": 0, "kind": "cpu"}], "provenance": prov,
         "context": {"tool": "bench"}},
        {"ev": "iter", "run": run, "t": t + 1, "it": 0,
         "time_s": 1.0 / ips},
        {"ev": "iter", "run": run, "t": t + 2, "it": 1,
         "time_s": 1.0 / ips},
        {"ev": "run_end", "run": run, "t": t + 3, "status": status,
         "entries": {"boost": {"first_s": first_s}}},
    ]


def _fill(led, n, ips=5.0, t0=1e9, suite="bench", **kw):
    for i in range(n):
        assert led.ingest_events(
            _events(run="r%03d" % i, t=t0 + 100 * i, ips=ips, **kw),
            suite=suite) == 1


# ------------------------------------------------------------ provenance

def test_run_header_carries_provenance(tmp_path):
    path = str(tmp_path / "tl.jsonl")
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=["cpu:0"], params={},
                   context={})
    obs.close()
    header = next(e for e in read_events(path)
                  if e["ev"] == "run_header")
    prov = header["provenance"]
    assert set(prov) >= {"git_rev", "git_dirty", "hostname", "argv"}
    assert isinstance(prov["git_dirty"], bool)
    assert isinstance(prov["argv"], list)
    # this repo IS a git work tree, so the rev must resolve here
    assert prov["git_rev"]


def test_provenance_is_cached_and_refreshable():
    a, b = collect_provenance(), collect_provenance()
    assert a == b and a is not b          # copy out, same content
    assert collect_provenance(refresh=True) == a


def test_strict_validation_requires_provenance_on_new_schema():
    rec = {"ev": "run_header", "t": 0.0, "run": "r",
           "schema": SCHEMA_VERSION, "backend": "cpu", "devices": [],
           "params": {}, "context": {}, "timing": "iter"}
    with pytest.raises(ValueError, match="provenance"):
        validate_event(rec, strict=True)
    validate_event(dict(rec, provenance=PROV), strict=True)
    # pre-provenance schemas stay valid without it (old timelines load)
    validate_event(dict(rec, schema=9), strict=True)


# --------------------------------------------------------------- ingest

def test_ingest_record_shape(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    assert led.ingest_events(_events(t=2e9), suite="bench") == 1
    (rec,) = led.entries()
    assert rec["suite"] == "bench"
    assert rec["device_kind"] == "cpu"
    assert rec["git_rev"] == PROV["git_rev"]
    assert rec["status"] == "ok"
    assert rec["metrics"]["iters_per_sec"] == pytest.approx(5.0)
    assert rec["metrics"]["compile_s"] == pytest.approx(1.5)


def test_ingest_is_idempotent(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    evs = _events(t=2e9)
    assert led.ingest_events(evs, suite="bench") == 1
    assert led.ingest_events(evs, suite="bench") == 0
    assert len(led.entries()) == 1


def test_ingest_timeline_idempotent_and_skips_unfinished(tmp_path):
    path = str(tmp_path / "tl.jsonl")
    finished = _events(run="done", t=2e9)
    unfinished = _events(run="wip", t=2e9 + 50)[:-1]   # no run_end
    with open(path, "w") as f:
        for e in finished + unfinished:
            f.write(json.dumps(e) + "\n")
    led = Ledger(str(tmp_path / "led"))
    assert led.ingest_timeline(path, suite="bench") == 1
    assert led.ingest_timeline(path, suite="bench") == 0
    assert [r["run"] for r in led.entries()] == ["done"]


def test_metrics_from_events_matches_bench_compare(tmp_path):
    """The ledger's reducer and bench_compare's must agree — rolling
    baselines would otherwise gate candidates against skewed history."""
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO, "tools", "bench_compare.py"))
    bc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bc)
    evs = _events(t=2e9)
    assert metrics_from_events(evs) == bc._from_timeline(evs)


# ----------------------------------------------------------- crash-safety

def test_corrupt_index_line_recovery(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    _fill(led, 3)
    # tear the middle index line (simulates a crash mid-append)
    with open(led.index_path) as f:
        lines = f.read().splitlines()
    lines[1] = lines[1][: len(lines[1]) // 2]
    with open(led.index_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    entries = led.entries()
    # the torn run comes back from its runs/ record, nothing is lost
    assert sorted(r["run"] for r in entries) == ["r000", "r001", "r002"]
    # and re-ingesting it is still a no-op (dedup sees the recovery)
    assert led.ingest_events(_events(run="r001", t=1e9 + 100),
                             suite="bench") == 0


def test_missing_ledger_dir_reads_empty(tmp_path):
    led = Ledger(str(tmp_path / "never_created"))
    assert led.entries() == []


# ------------------------------------------------------ rolling statistics

def test_rolling_stats_median_mad_and_noise_floor():
    st = rolling_stats([10.0, 10.2, 9.8, 10.1, 9.9], window=8)
    assert st["median"] == pytest.approx(10.0)
    assert st["sigma"] >= 0.01 * 10.0     # never below the 1% floor
    flat = rolling_stats([5.0] * 6, window=8)
    assert flat["mad"] == 0.0
    assert flat["sigma"] == pytest.approx(0.05)   # 1% of the median
    assert rolling_stats([], window=8) is None


def test_rolling_window_trims_history():
    vals = [1.0] * 10 + [2.0] * 8
    st = rolling_stats(vals, window=8)
    assert st["n"] == 8 and st["median"] == 2.0


def test_comparable_entries_filters(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    _fill(led, 3, suite="bench")
    assert led.ingest_events(_events(run="bad", t=5e9, status="aborted"),
                             suite="bench") == 1
    assert led.ingest_events(_events(run="other", t=6e9),
                             suite="serve") == 1
    entries = led.entries()
    comp = comparable_entries(entries, suite="bench",
                              metric="iters_per_sec")
    assert [r["run"] for r in comp] == ["r000", "r001", "r002"]
    # failed runs and foreign suites are out; exclusion drops self
    comp = comparable_entries(entries, suite="bench",
                              metric="iters_per_sec",
                              exclude_runs={"r001"})
    assert [r["run"] for r in comp] == ["r000", "r002"]


def test_trend_cells_key_on_world_size(tmp_path, capsys):
    """The backfill/gate guard (PR 14): a pod run is a DIFFERENT cell
    than single-host history — `obs trend --check` must not gate a
    2-rank run against 1-rank baselines, and comparable_entries must
    filter by world size."""
    led_dir = str(tmp_path / "led")
    led = Ledger(led_dir)
    _fill(led, 5, ips=5.0)               # 1-rank history
    # a 2-rank run at HALF the rate: against the 1-rank cell this is a
    # textbook >=3-MAD regression (test_obs_trend_check_exit_codes
    # proves exactly that shape trips the gate) — world_size keying
    # must keep it out of that cell entirely
    evs = _events(run="pod", t=1e9 + 900, ips=2.5,
                  git_rev="cafecafe1234")
    evs[0]["world_size"] = 2
    assert led.ingest_events(evs, suite="bench") == 1
    assert led.entries()[-1]["world_size"] == 2
    capsys.readouterr()
    assert obs_main(["trend", led_dir, "--check"]) == 0, \
        "2-rank run was gated against 1-rank history"

    entries = led.entries()
    comp2 = comparable_entries(entries, suite="bench",
                               metric="iters_per_sec", world_size=2)
    assert [r["run"] for r in comp2] == ["pod"]
    comp1 = comparable_entries(entries, suite="bench",
                               metric="iters_per_sec", world_size=1)
    assert "pod" not in [r["run"] for r in comp1] and len(comp1) == 5


def test_scaling_event_metrics_land_in_ledger(tmp_path):
    """bench.py --mp emits one `scaling` event (schema 12); the ledger
    must lift rows/sec/chip + weak-scaling efficiency out of it."""
    evs = _events(run="mp", t=1e9)
    evs[0]["world_size"] = 4
    sc = {"ev": "scaling", "run": "mp", "t": 1e9 + 2.5, "world_size": 4,
          "rows_per_sec_per_chip": 123.5, "efficiency": 0.91,
          "chips": 4, "mode": "weak"}
    assert validate_event(sc, strict=True) is sc   # schema-valid
    evs.insert(3, sc)
    m = metrics_from_events(evs)
    assert m["rows_per_sec_per_chip"] == 123.5
    assert m["weak_scaling_eff"] == 0.91
    from lightgbm_tpu.obs.ledger import METRIC_DIRECTIONS
    assert METRIC_DIRECTIONS["rows_per_sec_per_chip"] == 1
    assert METRIC_DIRECTIONS["weak_scaling_eff"] == 1

    led = Ledger(str(tmp_path / "led"))
    assert led.ingest_events(evs, suite="bench_mp") == 1
    rec = led.entries()[0]
    assert rec["world_size"] == 4
    assert rec["metrics"]["rows_per_sec_per_chip"] == 123.5


def test_change_point_on_injected_step(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    _fill(led, 5, ips=5.0)
    # a >= 3-MAD step down, attributed to the run that introduced it
    assert led.ingest_events(
        _events(run="regress", t=1e9 + 900, ips=2.5,
                git_rev="badbadbad123"), suite="bench") == 1
    cps = change_points(led.entries(), "iters_per_sec")
    assert len(cps) == 1
    cp = cps[0]
    assert cp["run"] == "regress"
    assert cp["git_rev"] == "badbadbad123"
    assert cp["regression"] is True
    assert cp["z"] < -3.0


def test_change_point_needs_min_history(tmp_path):
    led = Ledger(str(tmp_path / "led"))
    _fill(led, 2, ips=5.0)
    assert led.ingest_events(_events(run="step", t=1e9 + 900, ips=2.5),
                             suite="bench") == 1
    assert change_points(led.entries(), "iters_per_sec",
                         min_history=3) == []


def test_recovery_supersedes_regression(tmp_path):
    """A later good-direction shift ends the bad regime: --check must
    not keep failing after the regression is fixed."""
    led = Ledger(str(tmp_path / "led"))
    _fill(led, 4, ips=5.0)
    for i, ips in enumerate([2.5] * 4 + [5.0] * 4):
        assert led.ingest_events(
            _events(run="s%d" % i, t=1e9 + 1000 + 100 * i, ips=ips),
            suite="bench") == 1
    cps = change_points(led.entries(), "iters_per_sec")
    assert [c["regression"] for c in cps] == [True, False]


def test_sparkline():
    assert sparkline([1, 2, 3]) == "▁▅█"
    assert sparkline([2.0, 2.0]) == "▄▄"
    assert sparkline([]) == ""


# -------------------------------------------------- obs history/trend CLI

def test_obs_trend_check_exit_codes(tmp_path, capsys):
    led_dir = str(tmp_path / "led")
    led = Ledger(led_dir)
    _fill(led, 5, ips=5.0)
    assert obs_main(["trend", led_dir, "--check"]) == 0
    assert led.ingest_events(
        _events(run="regress", t=1e9 + 900, ips=2.5,
                git_rev="badbadbad123"), suite="bench") == 1
    capsys.readouterr()
    assert obs_main(["trend", led_dir, "--check"]) == 1
    out = capsys.readouterr().out
    # the gate must NAME the metric, the onset run and its git rev
    assert "iters_per_sec" in out
    assert "regress" in out
    assert "badbadbad123" in out


def test_obs_history_renders(tmp_path, capsys):
    led_dir = str(tmp_path / "led")
    _fill(Ledger(led_dir), 3)
    assert obs_main(["history", led_dir]) == 0
    out = capsys.readouterr().out
    assert "bench" in out and "iters_per_sec" in out
    assert obs_main(["history", str(tmp_path / "empty")]) == 0
    assert "empty" in capsys.readouterr().out


# -------------------------------------------------- bench_compare gating

def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO, "tools", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_zero_baseline_gates_on_absolute_delta():
    bc = _bench_compare()
    # lower-is-better from zero: any increase regresses, finite delta
    rows = bc.compare({"serve_shed_rate": 0.0}, {"serve_shed_rate": 0.2},
                      {})
    (name, b, c, delta, regressed, _tol) = rows[0]
    assert regressed and delta == pytest.approx(0.2)
    # higher-is-better from zero: a DROP regresses too (the old ratio
    # guard only caught the lower-is-better sign)
    rows = bc.compare({"final_eval_metric": 0.0},
                      {"final_eval_metric": -0.5}, {})
    assert rows[0][4] is True and rows[0][3] == pytest.approx(-0.5)
    # ... and matching zeros pass both ways
    for metric in ("serve_shed_rate", "final_eval_metric"):
        rows = bc.compare({metric: 0.0}, {metric: 0.0}, {})
        assert rows[0][4] is False and rows[0][3] == 0.0
    # epsilon widens the zero-baseline gate
    rows = bc.compare({"serve_shed_rate": 0.0}, {"serve_shed_rate": 0.1},
                      {}, zero_eps={"serve_shed_rate": 0.15})
    assert rows[0][4] is False


def test_zero_baseline_json_is_finite(tmp_path, capsys):
    bc = _bench_compare()
    base = tmp_path / "base.jsonl"
    cand = tmp_path / "cand.jsonl"
    base.write_text(json.dumps({"metric": "x", "value": 1.0,
                                "unit": "iters/sec",
                                "serve_shed_rate": 0.0}) + "\n")
    cand.write_text(json.dumps({"metric": "x", "value": 1.0,
                                "unit": "iters/sec",
                                "serve_shed_rate": 0.25}) + "\n")
    assert bc.main([str(base), str(cand), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)   # inf would not parse
    row = next(m for m in doc["metrics"]
               if m["metric"] == "serve_shed_rate")
    assert row["regressed"] and row["delta_kind"] == "abs"
    assert row["delta_frac"] == pytest.approx(0.25)


def _candidate_timeline(path, ips):
    with open(path, "w") as f:
        for e in _events(run="cand", t=3e9, ips=ips):
            f.write(json.dumps(e) + "\n")
    return str(path)


def test_rolling_mode_gates_against_ledger(tmp_path, capsys):
    bc = _bench_compare()
    led_dir = str(tmp_path / "led")
    _fill(Ledger(led_dir), 5, ips=5.0)
    ok = _candidate_timeline(tmp_path / "ok.jsonl", 4.95)
    bad = _candidate_timeline(tmp_path / "bad.jsonl", 2.0)
    args = ["--baseline", "rolling", "--ledger", led_dir,
            "--suite", "bench"]
    assert bc.main([ok, ok] + args) == 0
    capsys.readouterr()
    assert bc.main([ok, bad] + args + ["--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["mode"] == "rolling"
    row = next(m for m in doc["metrics"]
               if m["metric"] == "iters_per_sec")
    assert row["gate"] == "rolling" and row["z"] < -3.0
    assert row["baseline"] == pytest.approx(5.0)   # the rolling median


def test_rolling_mode_excludes_candidate_run(tmp_path):
    """A candidate already ingested (the observer lands runs on close)
    must not dilute its own baseline."""
    bc = _bench_compare()
    led_dir = str(tmp_path / "led")
    led = Ledger(led_dir)
    _fill(led, 3, ips=5.0)
    bad = _candidate_timeline(tmp_path / "bad.jsonl", 2.0)
    assert led.ingest_timeline(bad, suite="bench") == 1
    assert bc.main([bad, bad, "--baseline", "rolling", "--ledger",
                    led_dir, "--suite", "bench"]) == 1


def test_rolling_mode_thin_history_falls_back_to_parent(tmp_path,
                                                        capsys):
    bc = _bench_compare()
    led_dir = str(tmp_path / "led")
    _fill(Ledger(led_dir), 2, ips=5.0)          # < --min-history 3
    base = _candidate_timeline(tmp_path / "base.jsonl", 5.0)
    slow = _candidate_timeline(tmp_path / "slow.jsonl", 2.0)
    capsys.readouterr()
    assert bc.main([base, slow, "--baseline", "rolling", "--ledger",
                    led_dir, "--suite", "bench"]) == 1
    err = capsys.readouterr().err
    assert "falling back to parent compare" in err
    # parent says ok -> thin-history rolling says ok too
    assert bc.main([base, base, "--baseline", "rolling", "--ledger",
                    led_dir, "--suite", "bench"]) == 0


def test_rolling_mode_derives_cell_from_candidate(tmp_path, capsys):
    """Without --suite/--shape the gate scopes to the candidate's own
    ledger cell (suite from the header, device kind always) instead of
    pooling every run in the store."""
    bc = _bench_compare()
    led_dir = str(tmp_path / "led")
    led = Ledger(led_dir)
    _fill(led, 5, ips=5.0)                       # suite "bench", cpu
    bad = _candidate_timeline(tmp_path / "bad.jsonl", 2.0)
    # derived suite matches the history -> z-gates and fails, no flag
    assert bc.main([bad, bad, "--baseline", "rolling",
                    "--ledger", led_dir]) == 1
    # history in a foreign suite must not score this candidate: thin
    # in its own cell -> parent fallback -> self-compare passes
    led2_dir = str(tmp_path / "led2")
    _fill(Ledger(led2_dir), 5, ips=5.0, suite="other")
    capsys.readouterr()
    assert bc.main([bad, bad, "--baseline", "rolling",
                    "--ledger", led2_dir]) == 0
    assert "falling back to parent compare" in capsys.readouterr().err
    # same suite on a different device kind is equally incomparable
    led3_dir = str(tmp_path / "led3")
    led3 = Ledger(led3_dir)
    for i in range(5):
        evs = _events(run="tpu%03d" % i, t=1e9 + 100 * i, ips=5.0)
        evs[0]["backend"] = "tpu"
        evs[0]["devices"] = [{"id": 0, "kind": "tpu"}]
        assert led3.ingest_events(evs, suite="bench") == 1
    assert bc.main([bad, bad, "--baseline", "rolling",
                    "--ledger", led3_dir]) == 0


def test_rolling_mode_missing_ledger_is_thin_not_fatal(tmp_path):
    bc = _bench_compare()
    base = _candidate_timeline(tmp_path / "base.jsonl", 5.0)
    assert bc.main([base, base, "--baseline", "rolling", "--ledger",
                    str(tmp_path / "nothing")]) == 0


# -------------------------------------------------------------- backfill

def test_ledger_backfill_is_idempotent(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "ledger_backfill",
        os.path.join(REPO, "tools", "ledger_backfill.py"))
    bf = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bf)
    led_dir = str(tmp_path / "led")
    assert bf.main(["--ledger", led_dir]) == 0
    n = len(Ledger(led_dir).entries())
    assert n >= 10            # 5 bench + 5 multichip rounds minimum
    assert bf.main(["--ledger", led_dir]) == 0
    assert len(Ledger(led_dir).entries()) == n
    suites = {r["suite"] for r in Ledger(led_dir).entries()}
    assert suites >= {"flagship", "multichip"}


def test_observer_ingests_on_clean_close(tmp_path):
    """The automatic wiring: RunObserver(ledger_dir=...) lands the run
    when (and only when) it closes clean."""
    led_dir = str(tmp_path / "led")
    path = str(tmp_path / "tl.jsonl")
    obs = RunObserver(events_path=path, ledger_dir=led_dir,
                      ledger_suite="unit")
    obs.run_header(backend="cpu", devices=["cpu:0"], params={},
                   context={})
    obs.event("iter", it=0, time_s=0.5, fenced=True, phases={})
    obs.close()
    (rec,) = Ledger(led_dir).entries()
    assert rec["suite"] == "unit" and rec["run"] == obs.run_id
    # an aborted run must NOT land
    obs2 = RunObserver(events_path=str(tmp_path / "tl2.jsonl"),
                       ledger_dir=led_dir, ledger_suite="unit")
    obs2.run_header(backend="cpu", devices=["cpu:0"], params={},
                    context={})
    obs2.event("iter", it=0, time_s=0.5, fenced=True, phases={})
    obs2.close(status="aborted")
    assert len(Ledger(led_dir).entries()) == 1
