"""Low-overhead metrics registry: counters, gauges, fixed-bucket histograms.

The timeline (events.py) answers "what happened inside THIS run"; the
registry answers "how much work has this process done" — the aggregate
counters a serving deployment scrapes (the per-kernel counter discipline
of "XGBoost: Scalable GPU Accelerated Learning").  Instruments are
process-global (``REGISTRY``), cheap enough for the serving path (one
lock + an int add per observation), and export two ways:

* Prometheus textfile exposition format (``to_prometheus`` /
  ``REGISTRY.write("metrics.prom")``) for node-exporter style scraping;
* a JSON snapshot (``snapshot`` / ``to_json``) — the same dict the run
  observer embeds in ``metrics`` timeline events.

Training-path instruments are only touched when the run observer is
enabled (the disabled hot path stays allocation-free, pinned by the
overhead-guard test in tests/test_obs.py); the predict/serving path
records unconditionally.

Histogram semantics follow Prometheus: cumulative buckets keyed by
upper bound ``le`` (inclusive), plus ``_sum``/``_count``.
"""
from __future__ import annotations

import bisect
import json
import threading

# the content type Prometheus scrapers expect from a text exposition —
# served by the live /metrics endpoint (obs/live.py)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# default latency buckets (seconds) — the standard Prometheus ladder
# stretched to cover XLA compiles
TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# batch-size buckets (rows per predict call)
SIZE_BUCKETS = (1.0, 10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7)


def _fmt(v):
    """Prometheus sample formatting: integers without the trailing .0."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _series(name, labels):
    if not labels:
        return name
    inner = ",".join('%s="%s"' % (k, labels[k]) for k in sorted(labels))
    return "%s{%s}" % (name, inner)


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name, help="", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counter %s: negative increment %r"
                             % (self.name, amount))
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value

    def _export(self):
        return {"type": "counter", "value": self._value}


class Gauge:
    """A value that can go up and down (watermarks, in-flight counts)."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name, help="", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    def inc(self, amount=1):
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        self.inc(-amount)

    def max(self, value):
        """Watermark update: keep the larger of current and ``value``."""
        with self._lock:
            if value > self._value:
                self._value = value

    @property
    def value(self):
        return self._value

    def _export(self):
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket histogram, Prometheus ``le`` (inclusive upper bound)
    semantics with an implicit +Inf bucket."""

    __slots__ = ("name", "help", "labels", "buckets", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(self, name, help="", labels=None, buckets=TIME_BUCKETS):
        b = tuple(float(x) for x in buckets)
        if not b or list(b) != sorted(set(b)):
            raise ValueError("histogram %s: buckets must be strictly "
                             "increasing, got %r" % (name, buckets))
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.buckets = b
        self._counts = [0] * (len(b) + 1)      # last slot = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def cumulative(self):
        """[(le_str, cumulative_count), ...] ending with '+Inf'."""
        out = []
        acc = 0
        for le, c in zip(self.buckets, self._counts):
            acc += c
            out.append((_fmt(le), acc))
        out.append(("+Inf", acc + self._counts[-1]))
        return out

    def _export(self):
        return {"type": "histogram", "count": self._count,
                "sum": self._sum,
                "buckets": {le: c for le, c in self.cumulative()}}


class MetricsRegistry:
    """Named instruments, get-or-create.  One series per (name, labels);
    re-requesting an existing series returns the same instrument, and a
    type mismatch raises rather than silently forking the series."""

    _TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._series = {}          # (name, labels-key) -> instrument
        self._lock = threading.Lock()

    def _get(self, cls, name, help, labels, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            inst = self._series.get(key)
            if inst is None:
                inst = cls(name, help=help, labels=labels, **kw)
                self._series[key] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    "metric %r already registered as %s, requested %s"
                    % (name, type(inst).__name__, cls.__name__))
            return inst

    def counter(self, name, help="", labels=None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=None,
                  buckets=TIME_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def reset(self):
        """Drop every instrument (tests; a fresh process-global slate)."""
        with self._lock:
            self._series.clear()

    # ------------------------------------------------------------ export
    def snapshot(self):
        """{series: export-dict} — counters/gauges carry ``value``,
        histograms ``count``/``sum``/cumulative ``buckets``.  This is the
        payload of ``metrics`` timeline events."""
        with self._lock:
            series = list(self._series.values())
        return {_series(m.name, m.labels): m._export() for m in series}

    def to_json(self, indent=None):
        return json.dumps({"metrics": self.snapshot()}, indent=indent,
                          sort_keys=True)

    def to_prometheus(self):
        """Prometheus textfile exposition format (one HELP/TYPE block per
        metric family, series within a family grouped together)."""
        with self._lock:
            series = list(self._series.values())
        families = {}
        for m in series:
            families.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(families):
            fam = families[name]
            kind = {Counter: "counter", Gauge: "gauge",
                    Histogram: "histogram"}[type(fam[0])]
            help_text = next((m.help for m in fam if m.help), "")
            if help_text:
                lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            for m in fam:
                if isinstance(m, Histogram):
                    for le, c in m.cumulative():
                        lbl = dict(m.labels)
                        lbl["le"] = le
                        lines.append("%s %s"
                                     % (_series(name + "_bucket", lbl),
                                        _fmt(c)))
                    lines.append("%s %s" % (_series(name + "_sum", m.labels),
                                            _fmt(m._sum)))
                    lines.append("%s %s" % (_series(name + "_count",
                                                    m.labels),
                                            _fmt(m._count)))
                else:
                    lines.append("%s %s" % (_series(name, m.labels),
                                            _fmt(m.value)))
        return "\n".join(lines) + ("\n" if lines else "")

    def write(self, path):
        """Export to ``path``: Prometheus textfile format for ``.prom`` /
        ``.txt`` suffixes, JSON otherwise."""
        path = str(path)
        if path.endswith((".prom", ".txt")):
            body = self.to_prometheus()
        else:
            body = self.to_json(indent=2) + "\n"
        import os
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(body)
        return path


# the process-global registry every subsystem records into
REGISTRY = MetricsRegistry()


def observe_predict(rows, seconds):
    """Serving-path instrumentation: one call per predict request.
    Unconditional (no observer gate) — three lock/adds per request is
    noise next to a traversal, and the serving path has no training-run
    observer to gate on.  ``rows`` is the INPUT row count of the request
    (the caller computes it from the normalized feature matrix, not the
    output array, so 1-D converted outputs and multiclass matrices both
    count rows)."""
    REGISTRY.histogram(
        "lgbm_predict_seconds",
        "per-request predict latency (seconds)").observe(seconds)
    REGISTRY.histogram(
        "lgbm_predict_batch_rows",
        "rows per predict request", buckets=SIZE_BUCKETS).observe(rows)
    REGISTRY.counter(
        "lgbm_predict_rows_total", "total rows predicted").inc(int(rows))


def observe_serve_batch(route, rows, pad, bucket, queue_s, exec_s):
    """One coalesced serving microbatch (serve/scheduler.py flush):
    ``rows`` real rows, ``pad`` padding rows added to reach ``bucket``,
    ``queue_s`` the oldest request's coalescing wait, ``exec_s`` the
    encode+execute+split time.  The counter is labeled by the route
    KIND only (``route[0]``): full route tuples embed client-supplied
    early-stop freq/margin values, which would make label cardinality
    unbounded — the full tuple stays on the sampled serve_batch
    timeline events."""
    kind = route[0] if isinstance(route, tuple) and route else route
    REGISTRY.counter(
        "lgbm_serve_batches_total",
        "coalesced serving microbatches executed",
        labels={"route": str(kind)}).inc()
    REGISTRY.counter(
        "lgbm_serve_rows_total", "rows scored by the serving tier").inc(
            int(rows))
    REGISTRY.counter(
        "lgbm_serve_pad_rows_total",
        "bucket-padding rows scored and discarded").inc(int(pad))
    REGISTRY.histogram(
        "lgbm_serve_batch_rows", "real rows per serving microbatch",
        buckets=SIZE_BUCKETS).observe(rows)
    REGISTRY.histogram(
        "lgbm_serve_queue_seconds",
        "coalescing wait of the oldest request in a microbatch").observe(
            queue_s)
    REGISTRY.histogram(
        "lgbm_serve_exec_seconds",
        "microbatch encode+execute+split time").observe(exec_s)


def observe_serve_request(seconds):
    """End-to-end latency of one serving request (submit -> result)."""
    REGISTRY.histogram(
        "lgbm_serve_request_seconds",
        "per-request serving latency, submit to result").observe(seconds)


def observe_serve_shed(route, reason):
    """One request shed at admission (serve/scheduler.py overload
    protection).  ``reason`` is ``queue_full`` (bounded queue at
    ``serve_queue_limit``) or ``deadline`` (projected wait already
    exceeds the request's deadline).  Labeled by route KIND only, same
    cardinality discipline as observe_serve_batch."""
    kind = route[0] if isinstance(route, tuple) and route else route
    REGISTRY.counter(
        "lgbm_serve_shed_total",
        "requests rejected at admission by overload protection",
        labels={"route": str(kind), "reason": str(reason)}).inc()


def observe_serve_queue_age(seconds):
    """Age of the oldest queued request (0 when the queue is empty) —
    the gauge that makes a building backlog visible BEFORE shedding
    starts; updated on every admission and batch pop."""
    REGISTRY.gauge(
        "lgbm_serve_queue_age_seconds",
        "wait of the oldest request still in the microbatch queue").set(
            round(float(seconds), 6))
