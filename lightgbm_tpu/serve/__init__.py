"""Production serving tier: AOT-compiled predictors + async microbatching.

``Booster.serve()`` is the entry point; see docs/Serving.md for the
architecture and capacity-planning guidance.

* ``executable.py`` — ``PredictExecutableCache``: predict programs
  AOT-lowered per (batch-bucket, num_trees, k, raw/converted) with
  donated input buffers and the model replicated per device via
  NamedSharding, so steady-state scoring never touches the jit dispatch
  cache (zero recompiles after warmup, gated by ``obs recompiles
  --check``).
* ``scheduler.py`` — ``MicrobatchScheduler`` / ``ServingPredictor``: an
  async coalescer that batches concurrent requests into padded
  power-of-two buckets under a max-latency deadline, with early-stop and
  ``pred_contrib`` served through the same queue.  Overload protection
  sheds at admission (bounded queue / per-request deadlines) and fails
  shed futures fast with ``ServeOverloadError``; request traces, the
  rolling SLO engine and burn-rate alerts live in ``obs/serve.py``.
"""
from .executable import PredictExecutableCache, next_pow2
from .scheduler import (MicrobatchScheduler, ServeOverloadError,
                        ServingPredictor)

__all__ = ["MicrobatchScheduler", "PredictExecutableCache",
           "ServeOverloadError", "ServingPredictor", "next_pow2"]
