"""Run telemetry: event timeline, metrics registry, health monitors.

Every training run can self-instrument (the per-phase breakdowns that
"GPU-acceleration for Large-scale Tree Boosting" and "XGBoost: Scalable
GPU Accelerated Learning" ground their claims in, built into the loop):

* ``events``  — versioned JSONL event emitter (run header with params /
  backend / device topology, per-iteration phase records, compile events,
  memory snapshots, health verdicts, metrics snapshots) plus the
  ``RunObserver`` facade the training loop drives and the
  allocation-free ``NULL_OBSERVER`` it holds by default;
* ``timers``  — phase clocks and per-entry-point timers that fence with
  ``jax.block_until_ready`` for device-accurate timings and split the
  first-call (compile) cost from steady-state execute cost;
* ``memory``  — per-device ``memory_stats()`` snapshots at a cadence;
* ``profile`` — programmatic ``jax.profiler.trace`` windows over exactly
  the configured iterations (``obs_trace_iters=a:b`` + ``obs_trace_dir``);
* ``metrics`` — process-global counters/gauges/histograms with
  Prometheus-textfile and JSON export (``obs_metrics_path`` /
  ``obs_metrics_every``);
* ``health``  — non-finite guards, EMA loss divergence/plateau, memory
  watermark (``obs_health=off/warn/fatal``);
* ``compile`` — XLA compile-cache introspection: per-entry compile
  counts, signature diffs naming the offending axis, cost/memory
  analysis (``obs_compile=true`` -> schema-v3 ``compile_attr`` events);
* ``straggler`` — sampled per-shard arrival-skew profiling of the
  distributed learners (``obs_straggler_every`` /
  ``obs_straggler_warn_skew``);
* ``model``   — model observability: per-tree ``split_audit`` events
  (every realized split + the runner-up feature/gain margin from the
  split search) and top-k sparse ``importance`` evolution events
  (``obs_split_audit`` / ``obs_importance_every`` /
  ``obs_importance_topk``), read back via ``Booster.importance_history``;
* ``dataquality`` — dataset profiling at construction: per-feature
  missing rate, bin-occupancy entropy, constant/near-constant and
  high-cardinality flags, label balance — emitted as a ``data_profile``
  event and routed through the health channel so a degenerate dataset
  fails fast under ``obs_health=fatal``;
* ``roofline`` — roofline attribution: a device-peak registry (per
  ``device_kind`` FLOP/s, HBM and ICI bandwidth, VMEM — with a CPU
  fallback so the layer is testable off-TPU) joined against the
  ``compile_attr`` cost estimates and measured execute times to give
  every jitted entry achieved-vs-peak utilization, arithmetic
  intensity, a compute/memory/collective/host-orchestration bound and
  headroom seconds; emits the per-iteration ``utilization`` rollup
  (``obs_utilization_every``, schema 13) and stamps autotune probes;
* ``live``    — the in-run live telemetry plane (``obs_http_port`` /
  ``obs_http_addr``): a stdlib ThreadingHTTPServer daemon serving
  ``/metrics`` (Prometheus), ``/healthz`` (200/503), ``/statusz``
  (JSON run snapshot) and ``/events?after=N`` (ring-buffer JSONL tail)
  from host-side observer state only — zero hot-path syncs — plus the
  ``obs watch`` live-follow CLI over files, shard sets and URLs;
* ``drift``   — drift & online model-quality monitoring: at training
  time a per-feature binned fingerprint of the data world (histograms
  from the BinMapper sample + frozen mappers + training-score
  distribution + final eval snapshot) persists with the model text and
  the binned dataset dir; at serving time a ``DriftMonitor`` bins
  incoming traffic with the same frozen mappers into rolling windows,
  computing PSI/KS per feature and for the score distribution every
  ``obs_drift_every`` rows (schema-14 ``drift`` events,
  ``lgbm_drift_psi`` gauges, obs_health alerts), counts non-finite /
  out-of-range input anomalies, and joins delayed labels
  (``ServingPredictor.record_outcome``) into rolling online
  AUC/logloss vs the training reference (``online_quality`` events);
* ``incident`` — the incident engine (``obs_incident*``): taps every
  detector channel (health warn/fatal, SLO burn, straggler skew,
  watchdog near-expiry, steady-state recompiles, drift alerts, serve
  shed storms, operator POSTs), debounces co-occurring signals into one
  grouped incident (schema-15 ``incident_open`` / ``incident_evidence``
  / ``incident_close``), captures an evidence bundle at the moment of
  anomaly (ring slice, metrics snapshot, flight context, utilization
  rollup, /statusz snapshot, thread stacks, optional one-iteration
  armed profiler trace), and renders the ``obs incident`` triage
  report with cross-subsystem correlation and root-cause ranking;
* ``prof``    — continuous host sampling profiler (``obs_prof_hz``,
  default ~29 Hz, off at 0): a daemon thread walks
  ``sys._current_frames()`` on a jittered monotonic clock, folds each
  thread's stack into Brendan-Gregg collapsed-stack counts tagged with
  the live stage/phase/iteration/thread-role context, and rolls windows
  into schema-16 ``prof_profile`` events with a self-measured
  ``overhead_frac`` gated at <1%; read back via ``obs prof``
  (top-table, ``--flame`` HTML flamegraph, ``--check`` budget gate)
  and on demand via the live plane's ``GET /prof?seconds=N``;
* ``query``   — the one timeline reader behind ``python -m lightgbm_tpu
  obs summary|recompiles|stragglers|explain|roofline|serve|drift|
  incident|merge|diff|trace|watch|prof``;
* ``merge``   — cross-rank merge of per-rank timeline shards: barrier
  skew per host collective (aligned on ``seq``), per-rank phase
  comparison, slowest-rank attribution, and a merged critical-path
  timeline trace_summary/bench_compare ingest directly;
* ``watchdog`` — hang watchdog + flight recorder: no progress within
  ``obs_watchdog_secs`` (or SIGTERM, or an ``obs_health=fatal`` abort)
  dumps the event ring buffer, all thread stacks, device memory and a
  metrics snapshot to ``<events_path>.flight.json``;
* ``ledger``  — cross-run performance ledger: finished timelines land
  as per-run metric records in an append-only crash-safe store
  (``obs_ledger_dir`` / ``LGBM_TPU_LEDGER``), keyed by suite / shape /
  device kind + the run_header provenance (git rev, schema 10); rolling
  median/MAD baselines feed ``tools/bench_compare.py --baseline
  rolling`` and the ``obs history`` / ``obs trend --check`` CLI flags
  change-points attributed to the git rev that introduced them.

Distributed runs are rank-native (schema 4): each rank writes its own
timeline shard (``obs_events_path`` + ``.r{rank}``), every event
carries the rank, and the run header records rank/world_size/
coordinator.

Config surface (utils/config.py): ``obs_events_path``, ``obs_timing``,
``obs_memory_every``, ``obs_trace_iters``, ``obs_trace_dir``,
``obs_flush_every``, ``obs_fsync``, ``obs_health*``, ``obs_metrics*``,
``obs_compile``, ``obs_straggler_every``, ``obs_straggler_warn_skew``,
``obs_watchdog_secs``, ``obs_flight_events``, ``obs_split_audit``,
``obs_importance_every``, ``obs_importance_topk``, ``obs_data_profile``,
``obs_ledger_dir``, ``obs_ledger_suite``, ``obs_ledger_window``,
``obs_utilization_every``, ``obs_roofline_peaks``, ``obs_http_port``,
``obs_http_addr``, ``obs_drift_every``, ``obs_drift_window``,
``obs_drift_psi``, ``obs_drift_fingerprint``, ``obs_drift_topk``,
``obs_drift_min_labels``, ``obs_incident``, ``obs_incident_window_s``,
``obs_incident_dir``, ``obs_incident_trace``, ``obs_prof_hz``,
``obs_prof_window_s``, ``obs_prof_topk``.
See docs/Observability.md for the schema.
"""
from __future__ import annotations

from .events import (NULL_OBSERVER, SCHEMA_VERSION, EventWriter,
                     NullObserver, RingBuffer, RunObserver,
                     collect_provenance, current_observer, read_events,
                     resolve_rank_path, validate_event)
from .health import HealthMonitors
from .ledger import (Ledger, default_ledger_dir, metrics_from_events,
                     rolling_stats)
from .metrics import REGISTRY, MetricsRegistry
from ..utils.log import Log

__all__ = ["NULL_OBSERVER", "NullObserver", "RunObserver", "EventWriter",
           "RingBuffer", "SCHEMA_VERSION", "read_events", "validate_event",
           "current_observer", "resolve_rank_path", "collect_provenance",
           "observer_from_config", "HealthMonitors", "MetricsRegistry",
           "REGISTRY", "Ledger", "default_ledger_dir",
           "metrics_from_events", "rolling_stats"]

_TIMING_MODES = ("auto", "phase", "iter", "off")
_HEALTH_MODES = ("off", "warn", "fatal")


def observer_from_config(config, comm=None):
    """RunObserver from the ``obs_*`` config params, or NULL_OBSERVER when
    nothing is enabled — the disabled path must cost one attribute check.

    ``comm``: optional parallel.comm.HostComm — the observer then shards
    its timeline for that rank (``obs_events_path`` auto-suffixes
    ``.r{rank}``) and stamps every event with it.  Without a comm the
    rank is resolved from the thread's rank context (run_ranks) or
    jax.distributed, falling back to a rank-0 single-process run.

    ``obs_timing`` semantics: 'phase' fences every phase boundary with
    ``jax.block_until_ready`` (device-accurate per-phase times; breaks the
    async pipeline, so it costs throughput); 'iter' fences once per
    iteration (accurate per-iteration totals, phases are dispatch-only —
    the bench protocol); 'off' records wall times without any fencing
    (dispatch cost only); 'auto' = 'phase'.

    Any of ``obs_events_path`` / ``obs_trace_iters`` / ``obs_memory_every``
    / ``obs_health`` (non-off) / ``obs_metrics_path`` /
    ``obs_metrics_every`` / ``obs_compile`` / ``obs_straggler_every`` /
    ``obs_split_audit`` / ``obs_importance_every`` / ``obs_ledger_dir`` /
    ``obs_utilization_every`` / ``obs_drift_every`` / ``obs_incident``
    enables the observer; health, metrics, compile and model tracking
    work without an events path (in-memory timeline via
    Booster.telemetry()).  A non-empty ``obs_ledger_dir`` additionally
    ingests the finished run into the cross-run ledger on clean close.
    """
    events_path = str(getattr(config, "obs_events_path", "") or "")
    trace_iters = str(getattr(config, "obs_trace_iters", "") or "")
    memory_every = int(getattr(config, "obs_memory_every", 0) or 0)
    health_mode = str(getattr(config, "obs_health", "off")
                      or "off").strip().lower()
    if health_mode not in _HEALTH_MODES:
        Log.fatal("Unknown obs_health %s (expected off/warn/fatal)",
                  health_mode)
    metrics_path = str(getattr(config, "obs_metrics_path", "") or "")
    metrics_every = int(getattr(config, "obs_metrics_every", 0) or 0)
    compile_attr = bool(getattr(config, "obs_compile", False))
    straggler_every = int(getattr(config, "obs_straggler_every", 0) or 0)
    split_audit = bool(getattr(config, "obs_split_audit", False))
    importance_every = int(getattr(config, "obs_importance_every", 0) or 0)
    ledger_dir = str(getattr(config, "obs_ledger_dir", "") or "")
    utilization_every = int(getattr(config, "obs_utilization_every", 0)
                            or 0)
    drift_every = int(getattr(config, "obs_drift_every", 0) or 0)
    incident = bool(getattr(config, "obs_incident", False))
    # -1 = off; 0 is a real value (ephemeral port), so no `or` collapse
    http_port = getattr(config, "obs_http_port", -1)
    http_port = -1 if http_port is None else int(http_port)
    if (not events_path and not trace_iters and memory_every <= 0
            and health_mode == "off" and not metrics_path
            and metrics_every <= 0 and not compile_attr
            and straggler_every <= 0 and not split_audit
            and importance_every <= 0 and not ledger_dir
            and utilization_every <= 0 and http_port < 0
            and drift_every <= 0 and not incident):
        return NULL_OBSERVER
    timing = str(getattr(config, "obs_timing", "auto")).strip().lower()
    if timing not in _TIMING_MODES:
        Log.fatal("Unknown obs_timing %s (expected auto/phase/iter/off)",
                  timing)
    if timing == "auto":
        timing = "phase"
    trace_dir = str(getattr(config, "obs_trace_dir", "") or "")
    if trace_iters and not trace_dir:
        Log.fatal("obs_trace_iters requires obs_trace_dir (where the "
                  "jax.profiler trace is written)")
    health = None
    if health_mode != "off":
        health = HealthMonitors(
            mode=health_mode,
            every=int(getattr(config, "obs_health_every", 1) or 1),
            divergence=float(getattr(config, "obs_health_divergence",
                                     3.0) or 0.0),
            plateau=int(getattr(config, "obs_health_plateau", 0) or 0),
            mem_frac=float(getattr(config, "obs_health_mem_frac",
                                   0.9) or 0.0))
    rank = world_size = None
    coordinator = ""
    if comm is not None:
        rank, world_size = int(comm.rank), int(comm.size)
        coordinator = str(getattr(comm, "coordinator", "") or "")
    return RunObserver(events_path=events_path, timing=timing,
                       memory_every=memory_every, trace_iters=trace_iters,
                       trace_dir=trace_dir,
                       flush_every=int(getattr(config, "obs_flush_every",
                                               16) or 16),
                       health=health, metrics_every=metrics_every,
                       metrics_path=metrics_path,
                       compile_attr=compile_attr,
                       straggler_every=straggler_every,
                       straggler_warn_skew=float(
                           getattr(config, "obs_straggler_warn_skew",
                                   0.5) or 0.5),
                       rank=rank, world_size=world_size,
                       coordinator=coordinator,
                       fsync=bool(getattr(config, "obs_fsync", False)),
                       watchdog_secs=float(
                           getattr(config, "obs_watchdog_secs", 0.0)
                           or 0.0),
                       flight_events=int(
                           getattr(config, "obs_flight_events", 256)
                           or 256),
                       ledger_dir=ledger_dir,
                       ledger_suite=str(
                           getattr(config, "obs_ledger_suite", "")
                           or ""),
                       utilization_every=utilization_every,
                       roofline_peaks=str(
                           getattr(config, "obs_roofline_peaks", "")
                           or ""),
                       http_port=(http_port if http_port >= 0 else None),
                       http_addr=str(
                           getattr(config, "obs_http_addr", "127.0.0.1")
                           or "127.0.0.1"),
                       incident=incident,
                       incident_window_s=float(
                           getattr(config, "obs_incident_window_s", 5.0)
                           or 5.0),
                       incident_dir=str(
                           getattr(config, "obs_incident_dir", "") or ""),
                       incident_trace=bool(
                           getattr(config, "obs_incident_trace", False)),
                       # the profiler piggybacks on an otherwise-enabled
                       # observer; its default never flips the NULL
                       # short-circuit above
                       prof_hz=int(
                           getattr(config, "obs_prof_hz", 29) or 0),
                       prof_window_s=float(
                           getattr(config, "obs_prof_window_s", 5.0)
                           or 5.0),
                       prof_topk=int(
                           getattr(config, "obs_prof_topk", 20) or 20))
