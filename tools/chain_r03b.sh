#!/bin/bash
# Stage 2: after the suite drains, validate the compact-layout pallas_t
# kernel on chip at 1M, then re-run the flagship bench (full stderr kept).
cd /root/repo
while pgrep -f "bench_suite.py" > /dev/null; do sleep 60; done
echo "[chain2] suite done at $(date -u)" >> /tmp/chain_r03.log
python tools/tpu_ab2.py 999424 --r03b > /tmp/ab2_r03b.out 2>&1
echo "[chain2] ab rc=$? at $(date -u)" >> /tmp/chain_r03.log
python bench.py > /tmp/bench_r03b.out 2> /tmp/bench_r03b.err
echo "[chain2] bench rc=$? at $(date -u)" >> /tmp/chain_r03.log
cat /tmp/bench_r03b.out >> /tmp/chain_r03.log
