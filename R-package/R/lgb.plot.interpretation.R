# Contribution bar plot — parity with
# R-package/R/lgb.plot.interpretation.R, in base graphics.

#' Plot one observation's feature contributions
#'
#' @param tree_interpretation one element of lgb.interprete's output
#' @param top_n show the n largest absolute contributions
#' @export
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    cols = 1L, left_margin = 10L,
                                    cex = NULL, ...) {
  ti <- utils::head(tree_interpretation, top_n)
  ti <- ti[rev(seq_len(nrow(ti))), , drop = FALSE]
  op <- graphics::par(mar = c(3, left_margin, 2, 1))
  on.exit(graphics::par(op))
  graphics::barplot(ti$Contribution, names.arg = ti$Feature, horiz = TRUE,
                    las = 1, cex.names = cex,
                    col = ifelse(ti$Contribution > 0, "forestgreen",
                                 "firebrick"),
                    main = "Feature contribution", xlab = "Contribution",
                    ...)
  invisible(ti)
}
