"""Elastic shrink-and-resume: survive a lost rank, finish the run.

The driver is deliberately dumb: it does NOT try to re-admit a dead rank
into a live ``jax.distributed`` world (jaxlib offers no such surgery).
A failed attempt tears the whole world down, the flight evidence is
collected (per-rank output tails, where the PR-4 watchdog flight-record
paths land), and a FRESH, SMALLER world is launched — new processes, new
coordinator, new (smaller) global mesh, re-balanced binned row ranges
(io/dataset.py from_binned re-splits by the new world size), resuming
from the last compact checkpoint (models/checkpoint.py).  "Re-initialize
a smaller mesh" falls out of process lifetime instead of fragile
in-process re-initialization.

Two modes share the loop:

* ``run_elastic`` — subprocess mode over ``run_ranks_subprocess``
  (launch.py): real processes, real ``jax.distributed`` worlds.  Skips
  (raises MultiprocessUnsupported) where jaxlib lacks cross-process CPU
  collectives, same as every subprocess test.
* ``run_elastic_threads`` — thread mode over ``run_ranks`` (comm.py):
  one process, host-comm collectives, rank death injected as a raised
  exception / barrier timeout.  Runs everywhere, so CI drills the whole
  detect -> record -> shrink -> resume mechanism without a pod.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.log import Log
from .comm import BarrierTimeoutError, run_ranks
from .launch import (DEFAULT_WORKER_TIMEOUT, RankFailure,
                     run_ranks_subprocess)


class ElasticExhausted(RuntimeError):
    """Every allowed world size failed; carries the flight records."""

    def __init__(self, flight_records):
        self.flight_records = list(flight_records)
        super().__init__("elastic run failed at every world size tried: "
                         + ", ".join(str(r["world_size"])
                                     for r in flight_records))


def _strip_kill(payload: Optional[dict], extra_env: Optional[dict]):
    """Resumed attempts must not re-inject the rank kill."""
    p = dict(payload or {})
    p["kill_rank"] = -1
    env = dict(extra_env or {})
    env["LGBM_MP_KILL_RANK"] = "-1"
    return p, env


def run_elastic(size: int, spec: str, payload: Optional[dict] = None, *,
                min_size: int = 1, local_devices: int = 1,
                timeout: float = DEFAULT_WORKER_TIMEOUT,
                extra_env: Optional[dict] = None) -> Dict[str, Any]:
    """Run ``spec`` at world ``size``; on a rank death, shrink to the
    survivor count (never below ``min_size``) and relaunch resuming from
    the shared checkpoint.  Returns {"results", "world_size", "attempts",
    "flight_records"}.  Raises ElasticExhausted when min_size also
    fails, MultiprocessUnsupported where jaxlib cannot do this at all.
    """
    world = int(size)
    attempts = 0
    flight_records: List[dict] = []
    while True:
        attempts += 1
        try:
            results = run_ranks_subprocess(
                world, spec, payload, local_devices=local_devices,
                timeout=timeout, extra_env=extra_env)
            return {"results": results, "world_size": world,
                    "attempts": attempts,
                    "flight_records": flight_records}
        except RankFailure as rf:
            flight_records.append({
                "t": time.time(), "world_size": world,
                "failed_ranks": rf.failed, "returncodes": rf.returncodes,
                "tails": rf.tails,
            })
            survivors = world - len(rf.failed)
            new_world = max(int(min_size), survivors)
            if new_world >= world:       # nothing actually died, or
                new_world = world - 1    # only results went missing
            if new_world < int(min_size) or new_world < 1:
                raise ElasticExhausted(flight_records) from rf
            Log.warning("elastic: rank(s) %s died at world %d; "
                        "resuming at world %d from checkpoint",
                        rf.failed, world, new_world)
            payload, extra_env = _strip_kill(payload, extra_env)
            world = new_world


def run_elastic_threads(size: int, fn: Callable, *, min_size: int = 1,
                        fault=None,
                        barrier_timeout: Optional[float] = None
                        ) -> Dict[str, Any]:
    """Thread-mode drill: ``fn(comm)`` per simulated rank via
    ``run_ranks``.  A rank that raises (injected kill) strands the
    others at their next barrier (BarrierTimeoutError — their flight
    records dump through the PR-4 watchdog); the driver then reruns at
    the smaller world WITHOUT the fault.  Checkpoint resume works
    exactly as in subprocess mode because it is engine-level, not
    comm-level."""
    world = int(size)
    attempts = 0
    flight_records: List[dict] = []
    use_fault = fault
    while True:
        attempts += 1
        try:
            results = run_ranks(world, fn, fault=use_fault,
                                barrier_timeout=barrier_timeout)
            return {"results": results, "world_size": world,
                    "attempts": attempts,
                    "flight_records": flight_records}
        except (BarrierTimeoutError, RuntimeError) as e:
            flight_records.append({
                "t": time.time(), "world_size": world,
                "error": "%s: %s" % (type(e).__name__, e),
            })
            if world - 1 < int(min_size):
                raise ElasticExhausted(flight_records) from e
            Log.warning("elastic(threads): world %d failed (%s); "
                        "resuming at world %d", world, type(e).__name__,
                        world - 1)
            world -= 1
            use_fault = None             # never re-inject on resume
