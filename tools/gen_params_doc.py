"""Generate docs/Parameters.md from the live config registry.

The reference maintains docs/Parameters.md by hand; here the canonical
keys, types, defaults, and alias table are read straight from
lightgbm_tpu/utils/config.py so the document cannot drift from the code.
Run: python tools/gen_params_doc.py [output_path]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.utils.config import (ALIAS_TABLE,  # noqa: E402
                                       PARAMETER_SET, Config)

# short purpose lines for the keys users reach for most; everything else
# still gets its row (type/default/aliases) from the registry
NOTES = {
    "task": "train / predict / convert_model",
    "objective": "regression, regression_l1, huber, fair, poisson, binary,"
                 " multiclass, multiclassova, lambdarank",
    "boosting_type": "gbdt / dart / goss / infinite (InfiniteBoost)",
    "tree_learner": "serial / feature / data / voting — see "
                    "Parallel-Learning-Guide.md",
    "metric": "l1, l2, rmse, huber, fair, poisson, binary_logloss, "
              "binary_error, auc, multi_logloss, multi_error, ndcg, map",
    "num_leaves": "max leaves per tree (leaf-wise growth)",
    "max_bin": "max feature discretization bins; <=15 enables 4-bit packing",
    "learning_rate": "shrinkage rate",
    "num_iterations": "boosting rounds",
    "min_data_in_leaf": "minimal rows per leaf",
    "min_sum_hessian_in_leaf": "minimal hessian mass per leaf",
    "feature_fraction": "per-tree feature subsample",
    "bagging_fraction": "row subsample (with bagging_freq)",
    "bagging_freq": "re-bag every k iterations (0 = off)",
    "lambda_l1": "L1 regularization on leaf outputs",
    "lambda_l2": "L2 regularization on leaf outputs",
    "min_gain_to_split": "minimal gain to accept a split",
    "max_depth": "depth limit (<=0 = unlimited)",
    "early_stopping_round": "stop when no valid-set metric improves for k "
                            "rounds",
    "categorical_column": "categorical feature spec (indices or names)",
    "use_two_round_loading": "streaming two-round text ingest (bounded "
                             "host memory)",
    "is_save_binary_file": "save the binned dataset for fast reload",
    "histogram_pool_size": "MB budget for the per-leaf histogram cache; "
                           "-1 = auto (see docs/TPU-Tuning.md)",
    "top_k": "voting-parallel top-k (PV-Tree)",
    "num_machines": "process count for multi-host training",
    "is_unbalance": "auto-reweight unbalanced binary labels",
    "scale_pos_weight": "manual positive-class weight",
    "sigmoid": "sigmoid scale for binary/lambdarank",
    "label_gain": "lambdarank per-label gains",
    "max_position": "NDCG truncation for lambdarank",
    "ndcg_eval_at": "NDCG/MAP eval positions",
    "drop_rate": "DART tree drop probability",
    "xgboost_dart_mode": "use xgboost's DART normalization",
    "top_rate": "GOSS large-gradient keep fraction",
    "other_rate": "GOSS small-gradient sample fraction",
    "capacity": "InfiniteBoost ensemble capacity",
    "pred_early_stop": "margin-based prediction early stop",
    "use_missing": "enable missing-value handling",
    "tpu_growth": "auto / exact / wave — growth schedule (wave batches the "
                  "top-W splits per sweep on the MXU)",
    "tpu_wave_width": "W in wave growth; -1 = auto by num_leaves; 1 = the "
                      "reference's exact split order",
    "tpu_wave_order": "auto / batched / exact — wave commit order; exact "
                      "reproduces the leaf-wise split sequence bit-for-bit "
                      "at any W (auto: exact for lambdarank/DART/GOSS/"
                      "InfiniteBoost, batched otherwise)",
    "tpu_wave_chunk": "row-chunk of the wave sweep (VMEM vs scan-overhead "
                      "tradeoff; minimum 256, smaller values clamp)",
    "tpu_wave_lookup": "auto / onehot / compact / gather — the partition "
                       "sweep's per-row split-table lookup; compact "
                       "matches rows against only the W wave parents "
                       "(bit-identical trees, ~L/W less lookup traffic). "
                       "auto: compact on TPU, onehot elsewhere",
    "tpu_histogram_mode": "auto / onehot / scatter / pallas / pallas_t / "
                          "pallas_ct histogram kernels; auto on TPU "
                          "under the wave engine (f32, dense, "
                          "serial/data) = pallas_ct for narrow shapes "
                          "(ncols x bin-pad <= 2048), pallas_t for "
                          "wider VMEM-feasible ones, else onehot (TPU) "
                          "/ scatter",
    "tpu_hist_precision": "auto / hilo / bf16 — Pallas wave-kernel MXU "
                          "product precision: hilo = exact bf16 hi+lo "
                          "split (two dots); bf16 = single "
                          "round-to-nearest term, half the MXU work "
                          "(the reference GPU's single-precision "
                          "histogram trade); auto = hilo",
    "tpu_sparse_kernel": "true / false — with tpu_sparse, use the "
                         "entry-chunk MXU sparse store (Pallas kernel, "
                         "wave growth, serial learner) instead of the "
                         "segment_sum coordinate store",
    "tpu_score_update": "auto / gather / pallas — train-side score "
                        "update engine (score += leaf_value[leaf_id]): "
                        "XLA gather, or the bit-equal Pallas "
                        "compare-select kernel; auto = gather",
    "tpu_wave_compact": "true / false — spectator-row compaction for "
                        "the transposed Pallas wave kernels "
                        "(pallas_ct / pallas_t): late waves gather "
                        "only the rows whose leaf is still splitting "
                        "into capacity tiers (split structure "
                        "unchanged; float fields can drift by f32 "
                        "ulps at multi-tile N); opt-in",
    "tpu_bin_pack": "auto / true / false — 4-bit bin packing (at most 16 "
                    "bins/column: max_bin<=15 plus the reserved bin)",
    "tpu_autotune": "off / prior / measure / force — measured on-device "
                    "kernel autotuner for the wave cell (hist kernel, "
                    "wave width, precision, compaction): off = hand-tuned "
                    "heuristics only, prior = heuristics + decision "
                    "telemetry, measure = microbench the viable cells on "
                    "a cache miss, force = always re-measure; see "
                    "Autotuning.md",
    "tpu_autotune_cache": "autotune decision cache path (JSON); empty = "
                          "autotune_cache.json next to the XLA compile "
                          "cache",
    "tpu_autotune_waves": "timed waves per probed cell in measure/force "
                          "mode (plus one untimed warmup wave)",
    "tpu_fused_iter": "auto / on / off — run each boosting iteration as "
                      "ONE fused device program (gradients + tree growth "
                      "+ score update, ops/fused_iter.py) instead of the "
                      "staged entry chain; bit-identical models either "
                      "way.  auto = fuse where the Pallas wave kernels "
                      "are active or the autotuner measured the fused "
                      "cell as the winner; ineligible configs (DART/"
                      "GOSS/multiclass/custom fobj/obs_health) always "
                      "use the staged chain; see FusedIteration.md",
    "tpu_pallas_interpret": "true / false — run the Pallas wave kernels "
                            "in interpret mode (CPU-executable, for "
                            "tests and parity checks; ignored with a "
                            "warning on TPU)",
    "tpu_sparse": "true / false — device-side sparse bin store (exact "
                  "engine, serial + data-parallel; histograms from "
                  "nonzeros only)",
    "tpu_use_dp": "float64 histograms/scores (gpu_use_dp analog)",
    "tpu_predict": "auto / true / false — rank-encoded device bulk "
                   "prediction (f64-exact routing as int compares; auto "
                   "= device for >=100k-row batches on TPU)",
    "tpu_profile_dir": "write a jax.profiler trace per training run",
    "obs_events_path": "run telemetry: write a structured JSONL event "
                       "timeline (run header, per-iteration phase times, "
                       "compile-vs-execute split, memory snapshots) — "
                       "see Observability.md",
    "obs_timing": "auto / phase / iter / off — telemetry fencing policy: "
                  "phase fences every phase boundary (device-accurate, "
                  "breaks pipelining), iter fences once per iteration "
                  "(the bench protocol), off never fences; auto = phase",
    "obs_memory_every": "emit per-device memory_stats() snapshots every "
                        "N iterations (0 = off)",
    "obs_trace_iters": "a:b — open a jax.profiler trace window over "
                       "iterations [a, b) (requires obs_trace_dir)",
    "obs_trace_dir": "destination of the obs_trace_iters profiler window",
    "obs_flush_every": "flush the JSONL event writer every N events",
    "obs_health": "off / warn / fatal — training health monitors "
                  "(non-finite gradients/hessians/leaf values, EMA loss "
                  "divergence, plateau, memory watermark); warn logs + "
                  "emits health events, fatal additionally aborts the run",
    "obs_health_every": "run the health checks every N iterations",
    "obs_health_divergence": "fire loss_divergence when the gradient "
                             "magnitude exceeds this factor x its EMA on "
                             "two consecutive checks (0 = off)",
    "obs_health_plateau": "fire plateau (warn-only) after N consecutive "
                          "checks with relative EMA movement under 1e-4 "
                          "(0 = off)",
    "obs_health_mem_frac": "memory_watermark threshold: per-device "
                           "bytes_in_use / bytes_limit (0 = off; no-op "
                           "on backends without byte counters)",
    "obs_metrics_path": "export the process metrics registry at run end: "
                        ".prom/.txt = Prometheus textfile format, "
                        "otherwise JSON",
    "obs_metrics_every": "embed a metrics snapshot event into the "
                         "timeline every N iterations (0 = final "
                         "snapshot only when obs_metrics_path is set)",
    "obs_compile": "track the XLA compile cache per jitted entry: every "
                   "(re)compile emits a compile_attr event with the arg "
                   "shape/dtype/donation signature, a diff naming the "
                   "changed axis, and cost/memory analysis estimates",
    "obs_straggler_every": "sample per-shard arrival skew of the "
                           "distributed learners every N iterations "
                           "(each sample fences; 0 = off; no-op on a "
                           "single device)",
    "obs_straggler_warn_skew": "warn through the obs_health channel "
                               "when a straggler sample's skew — "
                               "(max-median)/total per-shard wait — "
                               "exceeds this fraction",
    "obs_watchdog_secs": "hang watchdog: dump a flight record after N "
                         "seconds without training progress (0 = off)",
    "obs_fsync": "os.fsync the timeline shard on run_end",
    "obs_flight_events": "event ring-buffer capacity snapshotted into "
                         "flight records",
    "obs_split_audit": "record every realized split per tree as "
                       "split_audit events: feature, bin/threshold, "
                       "gain, child counts, and the runner-up "
                       "feature + gain margin",
    "obs_importance_every": "emit top-k sparse split/gain importance "
                            "events every N iterations (0 = off) — the "
                            "trajectory behind Booster."
                            "importance_history()",
    "obs_importance_topk": "features kept per importance event "
                           "(<=0 = all used features)",
    "serve_max_batch": "serving tier: max rows per coalesced microbatch "
                       "(and the top executable bucket)",
    "serve_max_delay_ms": "max coalescing wait for the oldest queued "
                          "request before the batch flushes",
    "serve_bucket_min": "smallest AOT executable bucket (power-of-two "
                        "ladder up to serve_max_batch)",
    "serve_donate": "auto / true / false — donate input buffers to the "
                    "serve executables (auto = non-CPU backends)",
    "serve_batch_event_every": "emit every Nth microbatch as a "
                               "serve_batch timeline event (0 = off)",
    "serve_queue_limit": "overload protection: max queued requests "
                         "before admission sheds with "
                         "ServeOverloadError (0 = unbounded)",
    "serve_request_deadline_ms": "default per-request latency budget: "
                                 "admission sheds when the projected "
                                 "wait already exceeds it (0 = off)",
    "serve_request_event_every": "emit every Nth completed request as a "
                                 "serve_request trace event with its "
                                 "span breakdown (0 = off)",
    "serve_slo_p99_ms": "p99 latency target for the rolling SLO engine "
                        "+ burn-rate alerts (0 = no target)",
    "serve_slo_qps": "minimum-QPS target for the SLO verdicts "
                     "(0 = no target)",
    "serve_slo_window_s": "long SLO aggregation window; the burn "
                          "alert's short window is 1/6th of it",
    "serve_slo_every_s": "serve_slo snapshot cadence in seconds "
                         "(0 = snapshots off)",
    "obs_data_profile": "profile the binning sample at Dataset "
                        "construction (missing rates, bin-occupancy "
                        "entropy, constant/near-constant/ID-like "
                        "flags, label balance) into a data_profile "
                        "event; findings route through obs_health",
    "obs_ledger_dir": "cross-run performance ledger: ingest the "
                      "finished run's metrics into this directory on "
                      "clean close (empty = off); `obs trend --check` "
                      "and bench_compare --baseline rolling gate "
                      "against the accumulated history",
    "obs_ledger_suite": "ledger suite label — the coarse comparability "
                        "key rolling baselines group runs by (empty = "
                        "the run context's tool name)",
    "obs_ledger_window": "rolling-baseline window: median/MAD "
                         "statistics cover the last N comparable clean "
                         "runs of the same (suite, shape, device) cell",
    "obs_utilization_every": "roofline attribution: emit a utilization "
                             "rollup event every N iterations — "
                             "achieved-vs-peak FLOP/s and HBM bandwidth "
                             "plus a bound classification per jitted "
                             "entry (implies obs_compile; 0 = off) — "
                             "read back with `obs roofline`",
    "obs_roofline_peaks": "JSON file overriding the device-peak "
                          "registry (per device_kind: peak_flops_f32/"
                          "bf16, peak_hbm_bytes, peak_ici_bytes, "
                          "vmem_bytes); empty = built-in table with "
                          "CPU fallback",
    "obs_http_port": "live telemetry plane: serve /metrics, /healthz, "
                     "/statusz and /events?after=N over HTTP from a "
                     "daemon thread for the life of the run (-1 = off, "
                     "0 = ephemeral port — the bound port is logged and "
                     "stamped into the flight record); turns the "
                     "observer on by itself; zero hot-path syncs — "
                     "follow live with `obs watch <url>`",
    "obs_http_addr": "bind address for the live telemetry server; the "
                     "127.0.0.1 default keeps the plane loopback-only — "
                     "exposing it beyond the host (0.0.0.0) is a "
                     "deliberate act, the endpoints carry params and "
                     "provenance",
    "obs_drift_every": "serving-side drift monitoring: evaluate "
                       "PSI/KS divergence of the submitted traffic vs "
                       "the training-time fingerprint every N rows "
                       "(0 = off); verdicts land as schema-14 `drift` "
                       "events, `lgbm_drift_psi` gauges and the "
                       "obs_health warn channel — read back with "
                       "`obs drift`",
    "obs_drift_window": "rolling drift window in rows; counts reset "
                        "once the window fills so stale traffic "
                        "cannot mask fresh drift",
    "obs_drift_psi": "PSI alert threshold (0.2 is the classic "
                     "'significant shift' line); alerts clear with "
                     "hysteresis at half the threshold",
    "obs_drift_fingerprint": "capture the per-feature binned-histogram "
                             "+ score-distribution fingerprint at "
                             "training time and persist it in the "
                             "model text / binned dataset dir (the "
                             "serving reference; ~free, reuses the "
                             "BinMapper sample)",
    "obs_drift_topk": "features kept per drift event / "
                      "`lgbm_drift_psi` gauge series, ranked by "
                      "divergence",
    "obs_drift_min_labels": "joined (prediction, outcome) pairs "
                            "required before an `online_quality` "
                            "event (rolling online AUC/logloss vs the "
                            "training-time eval reference) is emitted",
    "obs_incident": "arm the incident engine (obs/incident.py): "
                    "detector signals — health warnings, SLO burn, "
                    "drift alerts, shed storms, watchdog near-expiry, "
                    "steady-state recompiles — are debounced and "
                    "grouped into `incident_open`/`incident_close` "
                    "events with an evidence bundle captured at open",
    "obs_incident_window_s": "debounce window: signals arriving within "
                             "this many seconds of the incident's last "
                             "signal join the same incident; a quiet "
                             "window closes it",
    "obs_incident_dir": "directory for evidence bundles (one "
                        "subdirectory per incident: ring slice, "
                        "metrics snapshot, statusz snapshot, flight "
                        "context, thread stacks); empty = alongside "
                        "`obs_events_path` + `.incidents`",
    "obs_incident_trace": "arm a one-iteration `jax.profiler` trace "
                          "window when an incident opens mid-training "
                          "(never on the serve hot path); the trace "
                          "lands in the evidence bundle",
    "obs_prof_hz": "continuous host sampling profiler (obs/prof.py): "
                   "samples per second for the daemon thread that "
                   "folds every thread's stack into schema-16 "
                   "`prof_profile` windows (0 = off; ~29 default, "
                   "prime-ish to avoid aliasing).  Piggybacks on an "
                   "otherwise-enabled observer — never turns the "
                   "observer on by itself; self-measured overhead "
                   "gated <1% by `obs prof --check`",
    "obs_prof_window_s": "profiler window length: samples aggregate "
                         "into one `prof_profile` event per window",
    "obs_prof_topk": "folded stacks kept per window; the dropped tail "
                     "is counted in the event's `truncated` field",
    "ooc_chunk_rows": "out-of-core streaming ingest: rows per chunk "
                      "(the host-memory budget unit; text chunks size "
                      "to it via a bytes-per-row estimate) — see "
                      "OutOfCore.md",
    "ooc_workers": "parallel two-pass binning worker processes "
                   "(0 = all cores; 1 or no fork support = serial)",
    "ooc_binned_dir": "stream the training file into this pre-binned "
                      "mmap-able dataset directory during "
                      "construction; later runs can train straight "
                      "from the directory with zero re-binning",
    "dist_coordinator": "multi-host pod bootstrap: coordinator "
                        "host:port for jax.distributed.initialize "
                        "(empty = JAX_COORDINATOR_ADDRESS env or "
                        "single-process) — see Distributed.md",
    "dist_num_processes": "world size of the pod (0 = "
                          "JAX_NUM_PROCESSES env or single-process)",
    "dist_process_id": "this process's rank in the pod (-1 = "
                       "JAX_PROCESS_ID env)",
    "checkpoint_every": "save a compact booster checkpoint (trees + "
                        "iteration + RNG seeds + config fingerprint) "
                        "every k rounds (0 = off); rank 0 writes "
                        "atomically into checkpoint_dir",
    "checkpoint_dir": "checkpoint directory; a resumable checkpoint "
                      "found here at train() start resumes the run "
                      "(elastic shrink-and-resume after a lost rank "
                      "re-opens re-balanced shards and continues) — "
                      "see Distributed.md",
}

GROUPS = [
    ("Core", ["task", "objective", "boosting_type", "tree_learner",
              "metric", "num_iterations", "learning_rate", "num_leaves",
              "max_depth", "num_class", "seed"]),
    ("Learning control", [
        "min_data_in_leaf", "min_sum_hessian_in_leaf", "feature_fraction",
        "feature_fraction_seed", "bagging_fraction", "bagging_freq",
        "bagging_seed", "lambda_l1", "lambda_l2", "min_gain_to_split",
        "early_stopping_round", "drop_rate", "skip_drop", "max_drop",
        "uniform_drop", "xgboost_dart_mode", "drop_seed", "top_rate",
        "other_rate", "capacity", "is_unbalance", "scale_pos_weight",
        "sigmoid", "boost_from_average", "huber_delta", "fair_c",
        "poisson_max_delta_step", "gaussian_eta", "label_gain",
        "max_position", "ndcg_eval_at"]),
    ("IO / dataset", [
        "data", "valid_data", "max_bin", "min_data_in_bin",
        "bin_construct_sample_cnt", "data_random_seed", "has_header",
        "label_column", "weight_column", "group_column", "ignore_column",
        "categorical_column", "use_two_round_loading",
        "is_save_binary_file",
        "enable_load_from_binary_file", "is_pre_partition",
        "is_enable_sparse", "sparse_threshold", "use_missing",
        "enable_bundle", "max_conflict_rate", "input_model",
        "output_model", "output_result", "snapshot_freq", "verbose",
        "metric_freq", "is_training_metric", "ooc_chunk_rows",
        "ooc_workers", "ooc_binned_dir"]),
    ("Prediction", [
        "num_iteration_predict", "is_predict_raw_score",
        "is_predict_leaf_index", "pred_early_stop", "pred_early_stop_freq",
        "pred_early_stop_margin", "convert_model",
        "convert_model_language"]),
    ("Distributed", [
        "num_machines", "top_k", "local_listen_port", "time_out",
        "machine_list_file", "histogram_pool_size",
        "dist_coordinator", "dist_num_processes", "dist_process_id",
        "checkpoint_every", "checkpoint_dir"]),
    ("TPU-native", [
        "tpu_growth", "tpu_wave_width", "tpu_wave_order", "tpu_wave_chunk",
        "tpu_wave_lookup", "tpu_wave_compact", "tpu_histogram_mode",
        "tpu_hist_precision", "tpu_score_update", "tpu_bin_pack",
        "tpu_sparse", "tpu_sparse_kernel", "tpu_use_dp", "tpu_predict",
        "tpu_fused_iter", "tpu_pallas_interpret", "tpu_profile_dir"]),
    ("Autotune", [
        "tpu_autotune", "tpu_autotune_cache", "tpu_autotune_waves"]),
    ("Observability", [
        "obs_events_path", "obs_timing", "obs_memory_every",
        "obs_trace_iters", "obs_trace_dir", "obs_flush_every",
        "obs_health", "obs_health_every", "obs_health_divergence",
        "obs_health_plateau", "obs_health_mem_frac", "obs_metrics_path",
        "obs_metrics_every", "obs_compile", "obs_straggler_every",
        "obs_straggler_warn_skew", "obs_watchdog_secs", "obs_fsync",
        "obs_flight_events", "obs_split_audit", "obs_importance_every",
        "obs_importance_topk", "obs_data_profile", "obs_ledger_dir",
        "obs_ledger_suite", "obs_ledger_window", "obs_utilization_every",
        "obs_roofline_peaks", "obs_http_port", "obs_http_addr",
        "obs_drift_every", "obs_drift_window", "obs_drift_psi",
        "obs_drift_fingerprint", "obs_drift_topk",
        "obs_drift_min_labels", "obs_incident",
        "obs_incident_window_s", "obs_incident_dir",
        "obs_incident_trace", "obs_prof_hz", "obs_prof_window_s",
        "obs_prof_topk"]),
    ("Serving", [
        "serve_max_batch", "serve_max_delay_ms", "serve_bucket_min",
        "serve_donate", "serve_batch_event_every", "serve_queue_limit",
        "serve_request_deadline_ms", "serve_request_event_every",
        "serve_slo_p99_ms", "serve_slo_qps", "serve_slo_window_s",
        "serve_slo_every_s"]),
]


def aliases_of(key):
    return sorted(a for a, c in ALIAS_TABLE.items() if c == key)


def fmt_default(typ, val):
    if val is None:
        return "(unset)"
    if typ == "bool":
        return "true" if val else "false"
    return str(val)


def render():
    """The full Parameters.md text from the live registry.

    Split out of main() so the doc-freshness consumers — the
    ``params-doc-stale`` lint rule (lightgbm_tpu/analysis/
    config_coherence.py) and the CI regen-diff gate — can compare
    against a fresh render without touching the file."""
    fields = dict(Config._FIELDS)
    # parameters accepted via PARAMETER_SET but handled outside the typed
    # field table (config-file plumbing, column-role strings, ...)
    for k in sorted(PARAMETER_SET):
        fields.setdefault(k, ("str", None))
    out = []
    out.append("# Parameters\n")
    out.append(
        "All parameter names, aliases, and defaults match the reference "
        "(include/LightGBM/config.h:87-489); `tpu_*` keys are this "
        "framework's additions.  GENERATED from the live registry by "
        "`tools/gen_params_doc.py` — edit that script, not this file.\n")
    covered = set()
    for title, keys in GROUPS:
        out.append("\n## %s\n" % title)
        out.append("| parameter | type | default | aliases | note |")
        out.append("|---|---|---|---|---|")
        for k in keys:
            if k not in fields:
                raise SystemExit("GROUPS key %r is not a known parameter"
                                 % k)
            covered.add(k)
            typ, dv = fields[k]
            al = ", ".join(aliases_of(k)) or ""
            note = NOTES.get(k, "")
            out.append("| %s | %s | %s | %s | %s |"
                       % (k, typ, fmt_default(typ, dv), al, note))
    rest = sorted(set(fields) - covered)
    if rest:
        out.append("\n## Other accepted keys\n")
        out.append("| parameter | type | default | aliases |")
        out.append("|---|---|---|---|")
        for k in rest:
            typ, dv = fields[k]
            out.append("| %s | %s | %s | %s |"
                       % (k, typ, fmt_default(typ, dv),
                          ", ".join(aliases_of(k))))
    return "\n".join(out) + "\n"


def main():
    text = render()
    path = (sys.argv[1] if len(sys.argv) > 1
            else os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "docs", "Parameters.md"))
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    print("wrote %s (%d lines)" % (path, text.count("\n")))


if __name__ == "__main__":
    main()
