"""Device-side tree application: traversal on binned data + score updates.

Replaces Tree::AddPredictionToScore (src/io/tree.cpp) and the train-side
ScoreUpdater::AddScore-via-partition (score_updater.hpp:91-99) with jitted
XLA programs so boosting iterations never synchronize with the host.
Decision semantics match dense_bin.hpp:190-222 (default-bin redirect,
numerical <=, categorical ==).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.common import kMaxTreeOutput


class TraversalArrays(NamedTuple):
    """Minimal device arrays needed to traverse a tree on binned data."""
    num_leaves: jnp.ndarray        # scalar i32
    split_feature: jnp.ndarray     # (L-1,) i32 (inner index)
    threshold_bin: jnp.ndarray     # (L-1,) i32
    default_bin_for_zero: jnp.ndarray  # (L-1,) i32
    default_bin: jnp.ndarray       # (L-1,) i32
    is_cat: jnp.ndarray            # (L-1,) i32
    left_child: jnp.ndarray        # (L-1,) i32
    right_child: jnp.ndarray       # (L-1,) i32
    leaf_value: jnp.ndarray        # (L,) f


def traversal_from_grow(tree_arrays) -> TraversalArrays:
    """View ops.grow.TreeArrays as TraversalArrays (shared buffers)."""
    return TraversalArrays(
        num_leaves=tree_arrays.num_leaves,
        split_feature=tree_arrays.split_feature,
        threshold_bin=tree_arrays.threshold_bin,
        default_bin_for_zero=tree_arrays.default_bin_for_zero,
        default_bin=tree_arrays.default_bin,
        is_cat=tree_arrays.is_cat,
        left_child=tree_arrays.left_child,
        right_child=tree_arrays.right_child,
        leaf_value=tree_arrays.leaf_value,
    )


def traversal_from_host_tree(tree, dtype=jnp.float32) -> TraversalArrays:
    """Upload a models.Tree (with bin thresholds) for device traversal."""
    ni = max(tree.num_leaves - 1, 1)
    nl = max(tree.num_leaves, 2)
    return TraversalArrays(
        num_leaves=jnp.asarray(tree.num_leaves, jnp.int32),
        split_feature=jnp.asarray(tree.split_feature_inner[:ni], jnp.int32),
        threshold_bin=jnp.asarray(tree.threshold_in_bin[:ni], jnp.int32),
        default_bin_for_zero=jnp.asarray(tree.default_bin_for_zero[:ni], jnp.int32),
        default_bin=jnp.asarray(tree.zero_bin[:ni], jnp.int32),
        is_cat=jnp.asarray(tree.decision_type[:ni], jnp.int32),
        left_child=jnp.asarray(tree.left_child[:ni], jnp.int32),
        right_child=jnp.asarray(tree.right_child[:ni], jnp.int32),
        leaf_value=jnp.asarray(tree.leaf_value[:nl], dtype),
    )


@functools.partial(jax.jit, static_argnames=("packed",))
def leaf_index_binned(tree: TraversalArrays, X, layout=None,
                      packed: bool = False):
    """Per-row leaf index by iterative descent (Tree::GetLeaf semantics on
    bins); returns zeros for single-leaf trees.

    layout: optional ops.grow.BundleArrays when X holds EFB group columns —
    bins are reconstructed per node feature (feature_group.h semantics).
    packed: X is 4-bit packed in the ops/pack.py split-half layout (logical
    column j < Fh lives in the low nibble of stored column j, j >= Fh in
    the high nibble of column j - Fh).
    """
    n = X.shape[0]
    rows = jnp.arange(n)
    fh = X.shape[1]                      # stored width (packed: ceil(F/2))

    def col_bins(f, nd):
        """Bin of each row at (possibly packed) device column f."""
        if not packed:
            return X[rows, f].astype(jnp.int32)
        p = jnp.where(f < fh, f, f - fh)
        raw = X[rows, p].astype(jnp.int32)
        return jnp.where(f < fh, raw & 15, raw >> 4)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        f = tree.split_feature[nd]
        if layout is None:
            b = col_bins(f, nd)
        else:
            v = col_bins(layout.group_of[f], nd)
            off = layout.bin_off[f]
            in_range = (v >= off) & (v < off + layout.bin_span[f])
            b = jnp.where(in_range, v - off + layout.bin_adj[f],
                          tree.default_bin[nd])
        thr = tree.threshold_bin[nd]
        cat = tree.is_cat[nd] > 0
        dbz = tree.default_bin_for_zero[nd]
        dflt = tree.default_bin[nd]
        go_left = jnp.where(cat, b == thr, b <= thr)
        def_left = jnp.where(cat, dbz == thr, dbz <= thr)
        go_left = jnp.where(b == dflt, def_left, go_left)
        nxt = jnp.where(go_left, tree.left_child[nd], tree.right_child[nd])
        return jnp.where(node >= 0, nxt, node)

    init = jnp.where(tree.num_leaves > 1,
                     jnp.zeros(n, jnp.int32), jnp.full(n, -1, jnp.int32))
    node = lax.while_loop(cond, body, init)
    return jnp.where(tree.num_leaves > 1, ~node, 0)


@functools.partial(jax.jit, static_argnames=("packed",))
def add_tree_to_score(score, X, tree: TraversalArrays, scale, layout=None,
                      packed: bool = False):
    """score += scale * clip(leaf_value)[leaf(X)] — Tree::AddPredictionToScore
    with the Shrinkage clamp (tree.h:110-118) applied at read time."""
    leaf = leaf_index_binned(tree, X, layout, packed=packed)
    vals = jnp.clip(tree.leaf_value * scale, -kMaxTreeOutput, kMaxTreeOutput)
    add = jnp.where(tree.num_leaves > 1, vals[leaf], 0.0)
    return score + add.astype(score.dtype)


@jax.jit
def update_score_from_partition(score, leaf_id, leaf_value, scale):
    """Train-side score update via the learner's final partition
    (score_updater.hpp:91-99): score += clip(scale * leaf_value)[leaf_id]."""
    vals = jnp.clip(leaf_value * scale, -kMaxTreeOutput, kMaxTreeOutput)
    return score + vals[jnp.clip(leaf_id, 0, leaf_value.shape[0] - 1)].astype(score.dtype)


@jax.jit
def add_constant_to_score(score, value):
    return score + value
