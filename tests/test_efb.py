"""Exclusive Feature Bundling (dataset.cpp:64-208, feature_group.h:30-117).

The key invariant: with max_conflict_rate=0 the bundled representation is
lossless, so training with EFB on must produce EXACTLY the trees of
training with enable_bundle=false.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.bundle import (bin_rows_grouped, build_layout,
                                    find_feature_groups, local_bins_np)
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.utils.config import Config


def _onehot_data(n=3000, cats=6, seed=0):
    """A one-hot encoded categorical (mutually exclusive by construction)
    plus two dense columns."""
    rng = np.random.default_rng(seed)
    c = rng.integers(0, cats, n)
    oh = np.eye(cats)[c]            # 0/1: each column needs 2 bins
    dense = rng.normal(size=(n, 2))
    X = np.concatenate([dense, oh], axis=1)
    y = ((c % 2 == 0) ^ (dense[:, 0] > 0)).astype(np.float64)
    return X, y


def test_bundles_form_on_onehot():
    X, y = _onehot_data()
    cfg = Config({"verbose": -1})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    assert td.bundle is not None
    assert td.bundle.num_groups < td.num_features
    assert td.binned.shape == (len(y), td.bundle.num_groups)
    # the 6 exclusive one-hot columns share one group
    sizes = sorted(len(g) for g in td.bundle.groups)
    assert sizes[-1] >= 5


def test_bundle_roundtrip_local_bins():
    """group bins -> local bins inverts the push mapping for every feature."""
    X, y = _onehot_data()
    cfg = Config({"verbose": -1})
    td_plain = TrainingData.from_matrix(X, label=y, config=Config(
        {"verbose": -1, "enable_bundle": False}))
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    assert td.bundle is not None
    for f in range(td.num_features):
        g = td.bundle.group_of[f]
        got = local_bins_np(td.binned[:, g], f, td.bundle,
                            int(td.default_bin_arr[f]))
        np.testing.assert_array_equal(got, td_plain.binned[:, f].astype(np.int64))


def test_efb_training_matches_plain():
    """Zero-conflict bundles are lossless up to f32 reduction order: the
    first tree is structurally identical (same scans, the default bin
    reconstructed by FixHistogram subtraction), and multi-round predictions
    agree to float noise — the same tolerance class as the reference's
    CPU-vs-GPU table (docs/GPU-Performance.md:134)."""
    X, y = _onehot_data()
    params = {"objective": "binary", "verbose": -1, "num_leaves": 31,
              "min_data_in_leaf": 5, "metric": "auc"}
    strip = lambda s: s.split("parameters:")[0]
    m1 = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=1)
    m2 = lgb.train(dict(params, enable_bundle=False),
                   lgb.Dataset(X, label=y), num_boost_round=1)
    assert strip(m1.model_to_string()) == strip(m2.model_to_string())

    m1 = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=15)
    m2 = lgb.train(dict(params, enable_bundle=False),
                   lgb.Dataset(X, label=y), num_boost_round=15)
    np.testing.assert_allclose(m1.predict(X), m2.predict(X), atol=1e-4)


def test_efb_with_valid_and_early_stopping():
    X, y = _onehot_data(seed=3)
    Xv, yv = _onehot_data(seed=4)
    train = lgb.Dataset(X, label=y)
    valid = lgb.Dataset(Xv, label=yv, reference=train)
    evals = {}
    bst = lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 15,
                     "metric": "auc"}, train, num_boost_round=20,
                    valid_sets=[valid], evals_result=evals,
                    verbose_eval=False)
    assert evals["valid_0"]["auc"][-1] > 0.95
    p = bst.predict(Xv)
    assert (((p > 0.5) == (yv > 0)).mean()) > 0.9


def test_efb_dart_and_goss():
    X, y = _onehot_data(seed=5)
    for boosting in ("dart", "goss"):
        bst = lgb.train({"objective": "binary", "verbose": -1,
                         "boosting": boosting, "num_leaves": 15},
                        lgb.Dataset(X, label=y), num_boost_round=8)
        p = bst.predict(X)
        assert (((p > 0.5) == (y > 0)).mean()) > 0.8


def test_efb_binary_dataset_roundtrip(tmp_path):
    X, y = _onehot_data(seed=6)
    td = TrainingData.from_matrix(X, label=y, config=Config({"verbose": -1}))
    assert td.bundle is not None
    fn = str(tmp_path / "ds.npz")
    td.save_binary(fn)
    td2 = TrainingData.load_binary(fn)
    assert td2.bundle is not None
    assert [list(g) for g in td2.bundle.groups] == \
        [list(g) for g in td.bundle.groups]
    np.testing.assert_array_equal(td2.binned, td.binned)


def test_efb_data_parallel_matches_serial():
    import jax
    from lightgbm_tpu.ops.learner import SerialTreeLearner
    from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                            make_data_mesh)
    X, y = _onehot_data(seed=7)
    cfg = Config({"verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5})
    td = TrainingData.from_matrix(X, label=y, config=cfg)
    assert td.bundle is not None
    g = (0.5 - y).astype(np.float32)
    h = np.full(len(y), 0.25, np.float32)
    tree_s, leaf_s = SerialTreeLearner(cfg, td).train(g, h)
    dp = DataParallelTreeLearner(cfg, td, make_data_mesh(jax.devices()))
    tree_d = dp.materialize(dp.train_device(g, h)[0])
    assert tree_d.num_leaves == tree_s.num_leaves
    np.testing.assert_array_equal(
        tree_d.split_feature[:tree_d.num_leaves - 1],
        tree_s.split_feature[:tree_s.num_leaves - 1])


def test_max_conflict_rate_budget():
    """Conflicting features bundle only when the budget allows."""
    rng = np.random.default_rng(8)
    n = 2000
    a = np.where(rng.uniform(size=n) < 0.5, rng.normal(size=n), 0.0)
    b = np.where(rng.uniform(size=n) < 0.5, rng.normal(size=n), 0.0)
    X = np.stack([a, b], axis=1)      # ~25% conflict rate
    y = (a + b > 0).astype(np.float64)
    td0 = TrainingData.from_matrix(X, label=y, config=Config(
        {"verbose": -1, "max_conflict_rate": 0.0, "max_bin": 63}))
    assert td0.bundle is None         # conflicts exceed zero budget
    td1 = TrainingData.from_matrix(X, label=y, config=Config(
        {"verbose": -1, "max_conflict_rate": 0.5, "max_bin": 63}))
    assert td1.bundle is not None and td1.bundle.num_groups == 1


def test_binary_dataset_arbitrary_extension(tmp_path):
    """save_binary must write EXACTLY the requested filename — numpy's
    savez appends '.npz' to alien extensions, which broke the reference's
    save-to-any-name contract (dataset.cpp:489 writes e.g. 'train.bin')."""
    X, y = _onehot_data(seed=8)
    fn = str(tmp_path / "train.bin")
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    ds.save_binary(fn)
    assert os.path.exists(fn) and not os.path.exists(fn + ".npz")
    ds2 = lgb.Dataset(fn)
    bst = lgb.train({"objective": "binary", "verbose": -1,
                     "num_leaves": 7}, ds2, num_boost_round=3)
    assert bst.predict(X).shape == (len(y),)
