"""pandas category-dtype parity — the reference's _data_from_pandas
semantics (python-package/lightgbm/basic.py:224-291): category columns are
coded, categorical_feature auto-populates, valid/predict frames re-code
against the train-time category lists, and the lists ride the model file.
"""
import numpy as np
import pandas as pd
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import LightGBMError

PARAMS = {"objective": "binary", "verbose": -1, "num_leaves": 15,
          "min_data_in_leaf": 5, "tpu_growth": "exact"}


def make_frame(n=600, seed=0):
    rng = np.random.default_rng(seed)
    cats = np.array(["red", "green", "blue", "violet"])
    c = rng.integers(0, 4, size=n)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    y = ((c == 2).astype(float) * 1.5 + x0 > 0.5).astype(np.float64)
    df = pd.DataFrame({
        "num0": x0,
        "color": pd.Categorical.from_codes(c, categories=list(cats)),
        "num1": x1,
    })
    return df, c, y


def test_category_frame_matches_int_codes():
    df, codes, y = make_frame()
    bst_df = lgb.train(PARAMS, lgb.Dataset(df, label=y),
                       num_boost_round=12, verbose_eval=False)
    X = np.column_stack([df["num0"].values, codes.astype(np.float64),
                         df["num1"].values])
    bst_mat = lgb.train(PARAMS, lgb.Dataset(X, label=y,
                                            categorical_feature=[1]),
                        num_boost_round=12, verbose_eval=False)
    # identical training decisions: same trees modulo feature names
    s_df = bst_df.model_to_string()
    s_mat = bst_mat.model_to_string()
    trees_df = s_df[s_df.index("Tree="):s_df.index("feature importances")]
    trees_mat = s_mat[s_mat.index("Tree="):s_mat.index("feature importances")]
    assert trees_df == trees_mat
    np.testing.assert_allclose(bst_df.predict(df), bst_mat.predict(X),
                               rtol=1e-12)


def test_valid_frame_realigns_category_order():
    df, codes, y = make_frame()
    # a valid frame whose categories arrive in a different order must be
    # re-coded against the train categories, not its own
    df_v, codes_v, y_v = make_frame(seed=9)
    shuffled = ["violet", "blue", "red", "green"]
    df_v["color"] = df_v["color"].cat.reorder_categories(shuffled)
    train = lgb.Dataset(df, label=y)
    valid = lgb.Dataset(df_v, label=y_v, reference=train)
    evals = {}
    lgb.train(PARAMS, train, num_boost_round=10, valid_sets=[valid],
              evals_result=evals, verbose_eval=False)
    # and the same data int-coded with the TRAIN order gives the same eval
    X = np.column_stack([df["num0"].values, codes.astype(np.float64),
                         df["num1"].values])
    Xv = np.column_stack([df_v["num0"].values, codes_v.astype(np.float64),
                          df_v["num1"].values])
    tr = lgb.Dataset(X, label=y, categorical_feature=[1])
    evals2 = {}
    lgb.train(PARAMS, tr, num_boost_round=10,
              valid_sets=[lgb.Dataset(Xv, label=y_v, reference=tr)],
              evals_result=evals2, verbose_eval=False)
    np.testing.assert_allclose(evals["valid_0"]["binary_logloss"],
                               evals2["valid_0"]["binary_logloss"],
                               rtol=1e-9)


def test_predict_applies_train_categories_after_roundtrip(tmp_path):
    df, codes, y = make_frame()
    bst = lgb.train(PARAMS, lgb.Dataset(df, label=y),
                    num_boost_round=10, verbose_eval=False)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    assert loaded.pandas_categorical == [["red", "green", "blue", "violet"]]
    # a predict frame with reordered categories must map back to train codes
    df_p = df.copy()
    df_p["color"] = df_p["color"].cat.reorder_categories(
        ["blue", "violet", "green", "red"])
    np.testing.assert_allclose(loaded.predict(df_p), bst.predict(df),
                               rtol=1e-12)


def test_mismatched_cat_columns_raise():
    df, _, y = make_frame()
    train = lgb.Dataset(df, label=y)
    df_v = df.drop(columns=["color"]).assign(extra=1.0)
    valid = lgb.Dataset(df_v, label=y, reference=train)
    with pytest.raises(LightGBMError, match="do not match"):
        lgb.train(PARAMS, train, num_boost_round=2, valid_sets=[valid],
                  verbose_eval=False)


def test_object_dtype_rejected():
    df = pd.DataFrame({"a": [1.0, 2.0], "b": ["x", "y"]})
    with pytest.raises(LightGBMError, match="int, float or bool"):
        lgb.Dataset(df, label=np.array([0.0, 1.0])).construct()


def test_feature_names_from_frame_columns():
    df, _, y = make_frame(n=200)
    bst = lgb.train(PARAMS, lgb.Dataset(df, label=y), num_boost_round=2,
                    verbose_eval=False)
    assert bst.feature_name() == ["num0", "color", "num1"]
