"""Incident engine: anomaly-triggered evidence capture and triage.

The stack has ~a dozen independent detectors — health monitors
(obs/health.py), SLO burn-rate alerts (obs/serve.py), the straggler
profiler, the hang watchdog, recompile attribution, drift PSI
(obs/drift.py), serve-queue shedding — and each fires isolated warn
events.  Nobody watches the warn stream in production, and by the time
a human reads it the evidence (ring buffer, metrics, run context) has
rolled over.  The ``IncidentEngine`` closes that gap in-process:

* **Subscribe** — the engine taps ``RunObserver.event()`` and
  classifies every record (health warn/fatal transitions, drift alert
  firing, steady-state recompiles); channels with no timeline event of
  their own (shed storms in serve/scheduler.py, watchdog near-expiry,
  the operator's ``POST /trigger/incident``) feed it directly via
  ``RunObserver.incident_signal(kind, detail)``.

* **Debounce & group** — the first qualifying signal opens an incident
  (schema 15 ``incident_open``); further signals within
  ``obs_incident_window_s`` of the last one join the SAME incident
  (per-kind counts, first/last occurrence).  After a quiet window — or
  at observer close — the incident closes (``incident_close`` with the
  grouped rollup, the correlation table's source of truth).

* **Capture** — on open the engine writes a time-boxed evidence bundle
  into ``<obs_incident_dir>/<incident id>/``: the RingBuffer slice
  around the trigger seq, a metrics-registry snapshot, the merged
  flight-provider context, the latest utilization/roofline rollup, a
  /statusz-equivalent run snapshot and the watchdog's thread stacks —
  one ``incident_evidence`` event per artifact.  With
  ``obs_incident_trace=true`` and training mid-run it additionally
  arms a one-iteration ``jax.profiler`` trace window at the next
  ``iter_begin`` (PR-1 plumbing via obs/profile.py; never armed on the
  serve hot path — serving has no iteration to scope a window to).

Everything here is host-side: dict copies, JSON writes, zero fences —
the bench drills assert ``fence_count()`` is flat across an injected
incident.  Capture is forensics-grade best-effort (the
dump_flight_record contract): an artifact that fails to write becomes
an ``incident_evidence`` record with an ``error`` field, never an
exception into the run.

The reader half (``python -m lightgbm_tpu obs incident <dir|timeline>
[--check]``) renders the triage report: grouped signals ordered by
first occurrence, a cross-subsystem correlation table, the evidence
inventory, and a root-cause ranking from a small deterministic
heuristic table.  ``--check`` is the CI gate: exit 1 when any incident
opened.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from .metrics import REGISTRY
from ..utils.log import Log

# signal kind -> subsystem, for the cross-subsystem correlation table.
# Unknown kinds (a future detector) render as "other" — the table must
# not reject what the engine accepted.
_SUBSYSTEM = {
    "nonfinite_gradients": "train",
    "nonfinite_leaf_values": "train",
    "loss_divergence": "train",
    "plateau": "train",
    "memory_watermark": "device",
    "watchdog": "runtime",
    "watchdog_near_expiry": "runtime",
    "straggler_skew": "dist",
    "recompile": "compile",
    "slo_burn_rate": "serve",
    "shed_storm": "serve",
    "serve_input": "serve",
    "drift": "serve",
    "online_quality": "serve",
    "operator": "operator",
}

# Ordered root-cause heuristics: (required signal kinds, diagnosis).
# A rule matches when every kind in its set occurred; ranking prefers
# more-specific (larger) matches, then more observed signal events,
# then table order.  Deterministic by construction — same incident,
# same ranking, every time.
_ROOT_CAUSES = (
    (frozenset(("straggler_skew", "slo_burn_rate")),
     "straggler-induced latency: shard skew rose before the SLO burn — "
     "check the slowest device/rank in the straggler report"),
    (frozenset(("recompile", "slo_burn_rate")),
     "jit-cache thrash on the serving path: steady-state recompiles "
     "line up with the SLO burn — check bucket churn / axis diffs"),
    (frozenset(("shed_storm", "slo_burn_rate")),
     "sustained overload: offered load exceeds capacity and the "
     "shed storm coincides with the SLO burn — scale out or raise "
     "queue_limit/deadline"),
    (frozenset(("nonfinite_gradients",)),
     "numeric instability: non-finite gradients — check learning rate, "
     "objective inputs and feature ranges"),
    (frozenset(("nonfinite_leaf_values",)),
     "numeric instability: non-finite leaf values — check hessian "
     "floors and regularization (lambda_l2)"),
    (frozenset(("loss_divergence",)),
     "training divergence: loss rising across the guard window — "
     "check learning rate and label encoding"),
    (frozenset(("watchdog",)),
     "hang/stall: the progress watchdog expired — read the flight "
     "record's thread stacks for the blocked collective"),
    (frozenset(("watchdog_near_expiry",)),
     "near-stall: an iteration or collective approached the watchdog "
     "deadline — a straggler or host-side pause is eating the budget"),
    (frozenset(("shed_storm",)),
     "overload: the serve queue shed a burst of requests — offered "
     "load exceeds capacity for the configured queue_limit/deadline"),
    (frozenset(("recompile",)),
     "recompile in steady state: an entry's jit signature changed "
     "mid-run — check the compile_attr axis diff"),
    (frozenset(("drift", )),
     "input distribution shift: serving traffic diverged from the "
     "training fingerprint (PSI/KS) — retrain or fix upstream features"),
    (frozenset(("serve_input",)),
     "serving input anomalies: non-finite or out-of-range rows on the "
     "predict path — validate the caller's feature pipeline"),
    (frozenset(("online_quality",)),
     "online model-quality regression: joined-label metrics degraded "
     "vs the training baseline — likely concept drift or label skew"),
    (frozenset(("memory_watermark",)),
     "memory pressure: device allocator watermark crossed — reduce "
     "batch/bin widths or enable out-of-core ingest"),
    (frozenset(("slo_burn_rate",)),
     "SLO burn without a correlated cause in this incident — inspect "
     "the serve_slo windows and batch traces around the open seq"),
    (frozenset(("plateau",)),
     "convergence plateau: eval metric flat across the guard window — "
     "consider early stopping or a learning-rate change"),
)

_FALLBACK_CAUSE = ("uncorrelated anomaly: no heuristic matched this "
                   "signal set — read the evidence bundle")

# evidence-bundle ring-slice bounds: enough context to see the lead-up
# without turning every bundle into a full ring dump
_RING_BEFORE = 160
_RING_AFTER = 64

# bounded closed-incident history held for /incidents and /statusz
_MAX_CLOSED = 32


def classify_signal(rec):
    """Map one timeline record to an incident signal kind, or None.

    health warn/fatal carry their check name as the kind (watchdog,
    slo_burn_rate, drift, straggler_skew, nonfinite_* ... — every
    detector that routes through the health channel comes in here);
    compile_attr with a per-signature recompile is "recompile"; a drift
    rollup whose alert state machine is firing is "drift".  Everything
    else — the 99.9% hot path — returns None on two dict reads.
    """
    ev = rec.get("ev")
    if ev == "health":
        if rec.get("status") not in ("warn", "fatal"):
            return None
        check = str(rec.get("check") or "")
        if check in ("", "stats"):
            return None
        return check
    if ev == "compile_attr":
        try:
            if int(rec.get("sig_compiles") or 1) > 1:
                return "recompile"
        except (TypeError, ValueError):
            return None
        return None
    if ev == "drift" and rec.get("alert") == "firing":
        return "drift"
    return None


def evidence_ring_slice(ring, around_seq, before=_RING_BEFORE,
                        after=_RING_AFTER):
    """Records within ``(around_seq - before, around_seq + after]`` of
    the flight RingBuffer, oldest first, each wrapped as
    ``{"seq": n, **rec}``.

    Works on whatever the ring still holds: a wrapped-around buffer
    yields only the surviving window, a cold-start empty ring yields
    ``[]``, and a writer appending concurrently costs at most one
    duplicated/skipped seq (the RingBuffer contract) — never a corrupt
    slice.  The bundle stays valid in all three cases.
    """
    around_seq = int(around_seq)
    lo = around_seq - max(0, int(before))
    hi = around_seq + max(0, int(after))
    out = []
    for seq, rec in list(ring._buf):
        if lo < seq <= hi:
            row = {"seq": seq}
            row.update(rec)
            out.append(row)
    return out


def _atomic_write(path, text):
    """The dump_flight_record write discipline: tmp + rename so a
    crash mid-write never leaves a torn artifact, fsync so the bundle
    survives the process."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        try:
            os.fsync(f.fileno())
        except OSError:
            pass
    os.replace(tmp, path)
    return os.path.getsize(path)


class _Incident:
    """Mutable state of one open incident (engine-lock protected)."""

    def __init__(self, iid, kind, detail, it, now, seq, path):
        self.id = iid
        self.trigger = kind
        self.open_t = now
        self.last_t = now
        self.open_seq = int(seq)
        self.dir = path
        self.artifacts = []            # [{artifact, path, bytes|error}]
        # kind -> {count, first_t, last_t, first_it}; insertion order IS
        # first-occurrence order (the correlation table's ordering)
        self.signals = {}
        self.add(kind, detail, it, now)

    def add(self, kind, detail, it, now):
        self.last_t = now
        sig = self.signals.get(kind)
        if sig is None:
            self.signals[kind] = sig = {
                "kind": kind, "count": 0, "first_t": now, "last_t": now,
                "first_it": (int(it) if it is not None else None),
                "detail": detail if isinstance(detail, dict) else None}
        sig["count"] += 1
        sig["last_t"] = now

    def meta(self, status, close_t=None, window_s=None):
        out = {"id": self.id, "status": status, "trigger": self.trigger,
               "open_t": self.open_t, "open_seq": self.open_seq,
               "signals": list(self.signals.keys()),
               "counts": {k: s["count"] for k, s in self.signals.items()},
               "signal_detail": [dict(s) for s in self.signals.values()],
               "artifacts": [dict(a) for a in self.artifacts],
               "dir": self.dir}
        if window_s is not None:
            out["window_s"] = window_s
        if close_t is not None:
            out["close_t"] = close_t
            out["duration_s"] = round(close_t - self.open_t, 6)
        return out


class IncidentEngine:
    """Debounce, group and evidence-capture anomaly signals (see module
    docstring).  One engine per RunObserver; at most one incident open
    at a time — co-occurring anomalies are one operational event, which
    is the entire point."""

    def __init__(self, obs, window_s=5.0, bundle_dir="", trace=False):
        self._obs = obs
        self.window_s = max(0.1, float(window_s or 5.0))
        self.bundle_dir = str(bundle_dir or "") or (
            obs.events_path + ".incidents" if obs.events_path else "")
        self.trace_enabled = bool(trace)
        self._lock = threading.RLock()
        self._emitting = False         # re-entrancy guard for the tap
        self._open = None              # _Incident or None
        self._closed_hist = []         # bounded closed-incident metas
        self._counter = 0
        self._max_signals = 0
        # armed trace window: {"id", "dir"} when pending, plus
        # "active" once jax.profiler actually started
        self._trace_state = None
        self._m_opened = REGISTRY.counter(
            "lgbm_incidents_total",
            "incidents opened by the anomaly-correlation engine")
        self._g_open = REGISTRY.gauge(
            "lgbm_incident_open",
            "1 while an incident is open, else 0")
        obs.add_flight_provider(self._flight_state)

    # -- signal intake -------------------------------------------------
    def observe(self, rec):
        """The RunObserver.event() tap: classify one record, feed the
        grouper, and tick the quiet-window close.  Host-only, and on
        the non-anomalous path two dict reads + one None check."""
        with self._lock:
            if self._emitting:
                return
            kind = classify_signal(rec)
            if kind is not None:
                self._signal_locked(kind, self._signal_detail(rec, kind),
                                    rec.get("it"))
            elif (self._open is not None
                    and time.time() - self._open.last_t > self.window_s):
                self._close_locked(time.time())

    @staticmethod
    def _signal_detail(rec, kind):
        d = rec.get("detail")
        if isinstance(d, dict):
            return d
        if rec.get("ev") == "compile_attr":
            return {"entry": rec.get("entry"),
                    "sig_compiles": rec.get("sig_compiles")}
        if rec.get("ev") == "drift":
            return {"psi_max": rec.get("psi_max"),
                    "score_psi": rec.get("score_psi")}
        return None

    def signal(self, kind, detail=None, it=None):
        """External intake (RunObserver.incident_signal): channels with
        no timeline event of their own.  Returns the open incident id."""
        with self._lock:
            return self._signal_locked(str(kind), detail, it)

    def _signal_locked(self, kind, detail, it):
        now = time.time()
        if (self._open is not None
                and now - self._open.last_t > self.window_s):
            self._close_locked(now)
        if self._open is None:
            self._open_incident(kind, detail, it, now)
        else:
            self._open.add(kind, detail, it, now)
            self._max_signals = max(self._max_signals,
                                    len(self._open.signals))
        return self._open.id

    # -- open / close --------------------------------------------------
    def _open_incident(self, kind, detail, it, now):
        self._counter += 1
        iid = "%s-%03d" % (self._obs.run_id, self._counter)
        path = (os.path.join(self.bundle_dir, iid)
                if self.bundle_dir else "")
        inc = self._open = _Incident(iid, kind, detail, it, now,
                                     self._obs._ring.last_seq, path)
        self._max_signals = max(self._max_signals, 1)
        self._m_opened.inc()
        self._g_open.set(1)
        self._emit("incident_open", id=iid, trigger=kind,
                   signals=[kind], seq=inc.open_seq,
                   it=(int(it) if it is not None else -1),
                   dir=path, detail=detail)
        Log.warning("obs: incident %s opened (trigger: %s)%s", iid, kind,
                    " -> %s" % path if path else "")
        self._capture_open_evidence(inc)
        if self.trace_enabled and self._trace_state is None \
                and self._obs._lifecycle == "train" and path:
            # armed, not started: the profiler opens at the NEXT
            # iter_begin so the window scopes exactly one iteration —
            # and never on the serve path, which has no iterations
            self._trace_state = {"id": iid,
                                 "dir": os.path.join(path, "trace")}

    def _close_locked(self, now):
        inc, self._open = self._open, None
        self._g_open.set(0)
        self._capture_close_evidence(inc)
        meta = inc.meta("closed", close_t=now, window_s=self.window_s)
        self._write_meta(inc, meta)
        self._closed_hist.append(meta)
        del self._closed_hist[:-_MAX_CLOSED]
        self._emit("incident_close", id=inc.id,
                   duration_s=meta["duration_s"],
                   signals=meta["signals"], counts=meta["counts"],
                   signal_detail=meta["signal_detail"],
                   artifacts=[a["artifact"] for a in inc.artifacts],
                   dir=inc.dir, window_s=self.window_s)
        Log.warning("obs: incident %s closed after %.2fs (%d signal "
                    "kind(s): %s)", inc.id, meta["duration_s"],
                    len(meta["signals"]), ", ".join(meta["signals"]))
        # a trace armed for this incident but never started (no training
        # iteration arrived) is disarmed; an ACTIVE one is left for
        # maybe_trace_stop so the window still closes cleanly
        if (self._trace_state is not None
                and self._trace_state["id"] == inc.id
                and not self._trace_state.get("active")):
            self._trace_state = None

    def finalize(self):
        """Observer close: close any open incident, stop an active
        armed trace, detach the flight provider, and return the run_end
        digest — zeros included, so the ledger's ``incidents_opened``
        cell has a real zero history to change-point against."""
        with self._lock:
            if self._trace_state is not None \
                    and self._trace_state.get("active"):
                self._trace_stop_locked(-1)
            if self._open is not None:
                self._close_locked(time.time())
            self._obs.remove_flight_provider(self._flight_state)
            return {"opened": self._counter,
                    "max_signals": self._max_signals}

    # -- evidence capture ----------------------------------------------
    def _emit(self, ev, **fields):
        """Emit through the observer with the tap re-entrancy guard up:
        the engine's own events must not be classified as signals."""
        self._emitting = True
        try:
            self._obs.event(ev, **fields)
        finally:
            self._emitting = False

    def _artifact(self, inc, name, filename, payload):
        """Write one bundle artifact (JSON for dicts, JSONL for lists),
        record it in the incident, emit incident_evidence.  Best-effort:
        failure becomes an ``error`` field, never a raise."""
        entry = {"artifact": name}
        try:
            path = os.path.join(inc.dir, filename)
            if isinstance(payload, list):
                text = "".join(json.dumps(r, default=str) + "\n"
                               for r in payload)
            else:
                text = json.dumps(payload, indent=2, default=str) + "\n"
            entry["path"] = path
            entry["bytes"] = _atomic_write(path, text)
        except Exception as e:
            entry["error"] = repr(e)
        inc.artifacts.append(entry)
        self._emit("incident_evidence", id=inc.id, **entry)

    def _capture_open_evidence(self, inc):
        """The time-boxed bundle, captured at the moment of anomaly.
        Host-side only — dict copies and file writes, zero fences."""
        obs = self._obs
        if not inc.dir:
            return
        try:
            os.makedirs(inc.dir, exist_ok=True)
        except OSError as e:
            inc.artifacts.append({"artifact": "bundle_dir",
                                  "error": repr(e)})
            self._emit("incident_evidence", id=inc.id,
                       artifact="bundle_dir", error=repr(e))
            return
        self._artifact(inc, "ring", "ring.jsonl",
                       evidence_ring_slice(obs._ring, inc.open_seq))
        self._artifact(inc, "metrics", "metrics.json",
                       obs._registry.snapshot())
        self._artifact(inc, "flight_context", "flight_context.json",
                       obs.flight_context())
        if obs._last_utilization is not None:
            self._artifact(inc, "utilization", "utilization.json",
                           dict(obs._last_utilization))
        try:
            from .live import status_snapshot
            snap = status_snapshot(obs)
        except Exception as e:
            snap = {"error": repr(e)}
        self._artifact(inc, "statusz", "statusz.json", snap)
        try:
            from .watchdog import _thread_stacks
            stacks = _thread_stacks()
        except Exception as e:
            stacks = [{"error": repr(e)}]
        self._artifact(inc, "threads", "threads.json", stacks)
        # sampled profile window (obs/prof.py): WHERE the host was
        # spending time across the anomaly, not just the one-shot
        # stacks above.  Prefers the live profiler's current window
        # (free — samples already collected); falls back to a short
        # synchronous burst when the sampler is off.
        try:
            from .prof import evidence_profile
            profile = evidence_profile(obs)
        except Exception as e:
            profile = {"error": repr(e)}
        self._artifact(inc, "profile", "profile.json", profile)
        self._write_meta(inc, inc.meta("open", window_s=self.window_s))

    def _capture_close_evidence(self, inc):
        """What happened AFTER the trigger: the post-open ring tail."""
        if not inc.dir or not os.path.isdir(inc.dir):
            return
        _, post = self._obs._ring.tail(inc.open_seq)
        self._artifact(inc, "ring_post", "ring_post.jsonl",
                       post[:_RING_AFTER])

    def _write_meta(self, inc, meta):
        if not inc.dir or not os.path.isdir(inc.dir):
            return
        try:
            _atomic_write(os.path.join(inc.dir, "incident.json"),
                          json.dumps(meta, indent=2, default=str) + "\n")
        except Exception as e:
            Log.warning("obs: incident %s meta write failed: %s",
                        inc.id, e)

    # -- armed trace window (obs_incident_trace) -----------------------
    def maybe_trace_start(self, it, obs):
        """iter_begin hook: open the armed profiler window.  One
        None-check on the common path."""
        with self._lock:
            st = self._trace_state
            if st is None or st.get("active") or st.get("done"):
                return
            from . import profile
            try:
                profile._start_trace(st["dir"])
            except Exception as exc:
                Log.warning("obs: incident trace start failed: %s", exc)
                self._trace_state = None
                return
            st["active"] = True
            st["it"] = int(it)
            self._emit("trace_window", action="start", dir=st["dir"],
                       it=it)

    def maybe_trace_stop(self, it, obs):
        """iter_end hook: close the one-iteration window."""
        with self._lock:
            st = self._trace_state
            if st is None or not st.get("active"):
                return
            self._trace_stop_locked(it)

    def _trace_stop_locked(self, it):
        st, self._trace_state = self._trace_state, None
        from . import profile
        try:
            profile._stop_trace()
        except Exception as exc:
            Log.warning("obs: incident trace stop failed: %s", exc)
            return
        self._emit("trace_window", action="stop", dir=st["dir"], it=it)
        entry = {"artifact": "trace", "path": st["dir"]}
        target = self._open if (self._open is not None
                                and self._open.id == st["id"]) else None
        if target is not None:
            target.artifacts.append(entry)
        self._emit("incident_evidence", id=st["id"], artifact="trace",
                   path=st["dir"], it=it)

    # -- live plane ----------------------------------------------------
    def listing(self):
        """The /incidents endpoint payload."""
        with self._lock:
            return {"enabled": True,
                    "opened": self._counter,
                    "open": ([self._open.meta("open",
                                              window_s=self.window_s)]
                             if self._open is not None else []),
                    "closed": [dict(m) for m in self._closed_hist]}

    def _flight_state(self):
        """Flight-provider hook: rides into every flight record and the
        /statusz ``flight.incidents`` section (the satellite contract)."""
        with self._lock:
            out = {"opened": self._counter, "open": 0}
            if self._open is not None:
                out["open"] = 1
                out["last"] = {"id": self._open.id,
                               "trigger": self._open.trigger,
                               "signals": list(self._open.signals),
                               "age_s": round(time.time()
                                              - self._open.open_t, 3)}
            elif self._closed_hist:
                last = self._closed_hist[-1]
                out["last"] = {"id": last["id"],
                               "trigger": last["trigger"],
                               "signals": list(last["signals"])}
            return {"incidents": out}


# -- reader: `python -m lightgbm_tpu obs incident <dir|timeline>` --------

def _normalize_from_events(events):
    """Reconstruct incident dicts (the incident.json meta shape) from a
    timeline's incident_open/incident_evidence/incident_close events."""
    incidents = {}
    order = []
    for rec in events:
        ev, iid = rec.get("ev"), rec.get("id")
        if ev == "incident_open":
            incidents[iid] = {
                "id": iid, "status": "open",
                "trigger": rec.get("trigger"),
                "open_t": rec.get("t"), "open_seq": rec.get("seq"),
                "signals": list(rec.get("signals") or ()),
                "counts": {}, "signal_detail": [], "artifacts": [],
                "dir": rec.get("dir") or ""}
            order.append(iid)
        elif ev == "incident_evidence" and iid in incidents:
            art = {k: rec[k] for k in ("artifact", "path", "bytes",
                                       "error") if k in rec}
            incidents[iid]["artifacts"].append(art)
        elif ev == "incident_close" and iid in incidents:
            inc = incidents[iid]
            inc["status"] = "closed"
            inc["close_t"] = rec.get("t")
            inc["duration_s"] = rec.get("duration_s")
            inc["signals"] = list(rec.get("signals") or inc["signals"])
            inc["counts"] = dict(rec.get("counts") or {})
            inc["signal_detail"] = list(rec.get("signal_detail") or ())
            inc["window_s"] = rec.get("window_s")
    return [incidents[i] for i in order]


def load_incidents(target):
    """Incident metas from a bundle dir (single or parent) or a JSONL
    timeline.  Raises OSError/ValueError on an unreadable target."""
    if os.path.isdir(target):
        meta = os.path.join(target, "incident.json")
        if os.path.isfile(meta):
            with open(meta) as f:
                return [json.load(f)]
        out = []
        for name in sorted(os.listdir(target)):
            sub = os.path.join(target, name, "incident.json")
            if os.path.isfile(sub):
                with open(sub) as f:
                    out.append(json.load(f))
        return out
    from .events import read_events
    return _normalize_from_events(read_events(target))


def rank_root_causes(signals, counts):
    """Deterministic heuristic ranking: (diagnosis, matched kinds),
    best first.  See _ROOT_CAUSES for the scoring contract."""
    present = set(signals)
    scored = []
    for idx, (needs, diagnosis) in enumerate(_ROOT_CAUSES):
        if needs <= present:
            weight = sum(int(counts.get(k, 1) or 1) for k in needs)
            scored.append((-len(needs), -weight, idx, diagnosis,
                           sorted(needs)))
    scored.sort()
    ranked = [(diag, kinds) for _, _, _, diag, kinds in scored]
    if not ranked:
        ranked = [(_FALLBACK_CAUSE, sorted(present))]
    return ranked


def _fmt_ts(t):
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(float(t)))
    except (TypeError, ValueError):
        return "?"


def _render_one(inc, out):
    def w(s=""):
        print(s, file=out)
    signals = list(inc.get("signals") or ())
    counts = dict(inc.get("counts") or {})
    n_events = sum(int(v or 0) for v in counts.values()) or len(signals)
    head = "incident %s  opened %s" % (inc.get("id"),
                                       _fmt_ts(inc.get("open_t")))
    if inc.get("status") == "closed":
        head += "  closed after %.2fs" % float(inc.get("duration_s") or 0)
    else:
        head += "  [STILL OPEN]"
    w(head)
    w("  trigger: %s   %d signal kind(s), %d signal event(s)"
      % (inc.get("trigger"), len(signals), n_events))
    detail = list(inc.get("signal_detail") or ())
    if detail:
        w()
        w("  signal correlation (first-occurrence order):")
        w("    %-9s %-24s %-10s %6s  %s"
          % ("offset", "kind", "subsystem", "count", "first it"))
        t0 = float(inc.get("open_t") or (detail[0].get("first_t") or 0))
        for sig in detail:
            it = sig.get("first_it")
            w("    %-9s %-24s %-10s %6d  %s"
              % ("+%.3fs" % (float(sig.get("first_t") or t0) - t0),
                 sig.get("kind"),
                 _SUBSYSTEM.get(sig.get("kind"), "other"),
                 int(sig.get("count") or 0),
                 it if it is not None else "-"))
    elif signals:
        w("  signals: %s" % ", ".join(str(s) for s in signals))
    arts = list(inc.get("artifacts") or ())
    w()
    if arts:
        w("  evidence (%s):" % (inc.get("dir") or "bundle"))
        for a in arts:
            if a.get("error"):
                w("    %-16s FAILED: %s" % (a.get("artifact"),
                                            a.get("error")))
            else:
                w("    %-16s %s  (%s bytes)"
                  % (a.get("artifact"),
                     os.path.basename(str(a.get("path") or "")),
                     a.get("bytes", "?")))
    else:
        w("  evidence: none captured (no bundle dir configured)")
    w()
    w("  root-cause ranking:")
    for i, (diag, kinds) in enumerate(
            rank_root_causes(signals, counts), 1):
        w("    %d. %s" % (i, diag))
        w("       matched: %s" % ", ".join(kinds))
    w()


def render_incident_report(target, out=None):
    """Render the triage report for every incident found at ``target``
    (bundle dir or timeline).  Returns the incident count — the
    ``--check`` gate exits 1 when it is non-zero."""
    out = out if out is not None else sys.stdout
    incidents = load_incidents(target)
    if not incidents:
        print("no incidents in %s" % target, file=out)
        return 0
    print("%d incident(s) in %s" % (len(incidents), target), file=out)
    print(file=out)
    for inc in incidents:
        _render_one(inc, out)
    return len(incidents)
