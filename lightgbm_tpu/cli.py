"""Command-line application — parity with src/application/application.cpp.

Usage:  python -m lightgbm_tpu config=train.conf [key=value ...]
CLI args override the config file (application.cpp:48-104).  Tasks: train,
predict, convert_model (emits compiled C++ if-else code like
GBDT::ModelToIfElse, or PMML — see run_convert_model).
Snapshots every ``snapshot_freq`` iterations (application.cpp:237-241).
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from .basic import Booster
from .metrics import create_metric
from .models.factory import create_boosting
from .objectives import create_objective
from .io.dataset import TrainingData
from .io import parser as _parser
from .utils.config import Config, key_alias_transform
from .utils.log import Log


def parse_cli_params(argv: List[str]) -> Dict[str, str]:
    """config= file + k=v overrides; CLI wins (application.cpp:48-104)."""
    cli: Dict[str, str] = {}
    for arg in argv:
        if "=" not in arg:
            Log.warning("Unknown argument: %s", arg)
            continue
        k, _, v = arg.partition("=")
        cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    conf_path = cli.get("config") or cli.get("config_file")
    if conf_path:
        with open(conf_path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, _, v = line.partition("=")
                params.setdefault(k.strip(), v.strip())
    params.update(cli)
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def run_train(cfg: Config) -> None:
    if not cfg.data:
        Log.fatal("No training data, application quit")
    # elastic checkpoint/resume (models/checkpoint.py) on the CLI
    # surface too — same contract as engine.train: a compatible
    # checkpoint in checkpoint_dir seeds the model and only the
    # remaining rounds run; an explicit input_model wins.  Peeked before
    # the data load because continuing needs the raw rows kept.
    ck_dir = str(cfg.raw.get("checkpoint_dir", "") or "")
    ck_every = int(cfg.raw.get("checkpoint_every", 0) or 0)
    resume_ck = None
    if ck_dir and not cfg.input_model:
        from .models import checkpoint as ckpt_mod
        resume_ck = ckpt_mod.load_checkpoint(ck_dir)
        if resume_ck is not None:
            ckpt_mod.check_resumable(resume_ck, dict(cfg.raw))
    Log.info("Loading train data...")
    # keep raw rows when continuing: loaded models predict on raw values
    train_td = TrainingData.from_file(
        cfg.data, cfg,
        keep_raw=bool(cfg.input_model) or resume_ck is not None)
    if getattr(train_td, "_binned_reader", None) is not None:
        Log.info("Train data is pre-binned (mmap-backed, %d shard(s), "
                 "zero re-binning)", train_td._binned_reader.num_shards)
    objective = create_objective(cfg.objective, cfg)
    if objective is not None:
        objective.init(train_td.metadata, train_td.num_data)
    training_metrics = []
    if cfg.is_training_metric:
        for name in cfg.metrics():
            m = create_metric(name, cfg)
            if m is not None:
                m.init(train_td.metadata, train_td.num_data)
                training_metrics.append(m)
    booster = create_boosting(cfg.boosting_type, cfg, train_td, objective,
                              training_metrics)
    if cfg.input_model:
        with open(cfg.input_model) as f:
            base = f.read()
        Log.info("Continued training from %s", cfg.input_model)
        booster.load_model_from_string(base)
        booster.reset_training_data(cfg, train_td, objective, training_metrics)
    rounds_done = 0
    if resume_ck is not None:
        booster.load_model_from_string(resume_ck["model"])
        booster.reset_training_data(cfg, train_td, objective,
                                    training_metrics)
        rounds_done = int(resume_ck["iteration"])
        Log.info("Resuming from checkpoint %s: %d round(s) done, "
                 "%d remain", ck_dir, rounds_done,
                 max(0, cfg.num_iterations - rounds_done))
    for i, vf in enumerate(cfg.valid_data or []):
        Log.info("Loading validation data %d...", i + 1)
        valid_td = TrainingData.from_file(vf, cfg, reference=train_td)
        metrics = []
        for name in cfg.metrics():
            m = create_metric(name, cfg)
            if m is not None:
                m.init(valid_td.metadata, valid_td.num_data)
                metrics.append(m)
        booster.add_valid_dataset(valid_td, metrics)
    Log.info("Started training...")
    import time
    # XLA-level tracing: the TIMETAG/#ifdef timers of the reference
    # (gbdt.cpp:21-30, serial_tree_learner.cpp:10-17) become a
    # jax.profiler trace viewable in TensorBoard/Perfetto
    profile_dir = cfg.raw.get("tpu_profile_dir", "")
    if profile_dir:
        import jax
        jax.profiler.start_trace(str(profile_dir))
        Log.info("jax.profiler trace -> %s", profile_dir)
    finished = False
    try:
        for it in range(rounds_done, cfg.num_iterations):
            t0 = time.time()
            stop = booster.train_one_iter(None, None, True)
            Log.info("%f seconds elapsed, finished iteration %d",
                     time.time() - t0, it + 1)
            if cfg.snapshot_freq > 0 and (it + 1) % cfg.snapshot_freq == 0:
                booster.save_model_to_file("%s.snapshot_iter_%d"
                                           % (cfg.output_model, it + 1))
            if ck_every > 0 and ck_dir and (it + 1) % ck_every == 0:
                from .models import checkpoint as ckpt_mod
                path = ckpt_mod.save_checkpoint(ck_dir, booster, it + 1,
                                                dict(cfg.raw))
                if booster._obs.enabled:
                    booster._obs.event(
                        "checkpoint", it=it + 1, path=path,
                        bytes=int(os.path.getsize(path)), world_size=1)
            if stop:
                break
        finished = True
    finally:
        if profile_dir:
            import jax
            jax.profiler.stop_trace()   # keep the trace on failures too
        # finalize run telemetry (lightgbm_tpu/obs): run_end + flush, so a
        # failed run still leaves a readable timeline (status=aborted)
        booster._obs.close(status="ok" if finished else "aborted")
    if cfg.obs_events_path:
        obs = booster._obs
        ep = (str(getattr(obs, "events_path", "") or "")
              or cfg.obs_events_path)
        if getattr(obs, "world_size", 1) > 1:
            Log.info("Telemetry timeline shard (rank %d/%d) -> %s "
                     "(cross-rank view: `python -m lightgbm_tpu obs "
                     "merge %s`)", obs.rank, obs.world_size, ep, ep)
        else:
            Log.info("Telemetry timeline -> %s (query with `python -m "
                     "lightgbm_tpu obs summary %s`)", ep, ep)
    if cfg.obs_metrics_path:
        Log.info("Metrics export -> %s", cfg.obs_metrics_path)
    booster.save_model_to_file(cfg.output_model)
    Log.info("Finished training")


def run_predict(cfg: Config) -> None:
    if not cfg.data:
        Log.fatal("No prediction data, application quit")
    with open(cfg.input_model) as f:
        model_str = f.read()
    booster = Booster(model_str=model_str)
    parsed = _parser.parse_file(cfg.data, has_header=cfg.has_header)
    num_iteration = cfg.num_iteration_predict
    out = booster.predict(parsed.features, num_iteration=num_iteration,
                          raw_score=cfg.is_predict_raw_score,
                          pred_leaf=cfg.is_predict_leaf_index)
    out = np.asarray(out)
    with open(cfg.output_result, "w") as f:
        if out.ndim == 1:
            for v in out:
                f.write("%.9g\n" % v)
        else:
            for row in out:
                f.write("\t".join("%.9g" % v for v in row) + "\n")
    Log.info("Finished prediction, results saved to %s", cfg.output_result)


def run_convert_model(cfg: Config) -> None:
    """Model -> C++ if-else source (GBDT::SaveModelToIfElse path,
    application.cpp ConvertModel)."""
    from .convert_model import model_to_cpp
    with open(cfg.input_model) as f:
        booster = Booster(model_str=f.read())
    with open(cfg.convert_model, "w") as f:
        f.write(model_to_cpp(booster._gbdt))
    Log.info("Model converted to %s", cfg.convert_model)


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if argv and argv[0] == "obs":
        # timeline query subcommand (docs/Observability.md):
        #   python -m lightgbm_tpu obs summary|recompiles|stragglers|
        #                              diff|trace ...
        from .obs.query import main as obs_main
        return obs_main(argv[1:])
    if argv and argv[0] == "lint":
        # graftlint static analyzer (docs/StaticAnalysis.md):
        #   python -m lightgbm_tpu lint [--check] [--json] [--baseline F]
        from .analysis.cli import main as lint_main
        return lint_main(argv[1:])
    params = parse_cli_params(argv)
    params = key_alias_transform(params, raise_unknown=False)
    cfg = Config(params)
    task = params.get("task", "train")
    if task == "train":
        run_train(cfg)
    elif task in ("predict", "prediction", "test"):
        run_predict(cfg)
    elif task == "convert_model":
        run_convert_model(cfg)
    else:
        Log.fatal("Unknown task: %s", task)
    return 0


def console_entry() -> None:
    """setuptools console-script entry (pyproject.toml)."""
    sys.exit(main())


if __name__ == "__main__":
    console_entry()
