// lightgbm_tpu native data plane — see include/lgbm_tpu_native.h.
//
// Fresh implementation of the reference's host-side semantics
// (src/io/bin.cpp GreedyFindBin/FindBin, src/io/parser.cpp format
// autodetect, tree.h GetLeaf), structured for batch/vectorized use from
// Python rather than the reference's per-object classes.

#include "../include/lgbm_tpu_native.h"

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <limits>
#include <sstream>
#include <string>
#include <vector>

namespace {

constexpr double kMissingValueRange = 1e-20;
const double kInf = std::numeric_limits<double>::infinity();

// Greedy distinct-value packing (semantics of src/io/bin.cpp:66-137).
std::vector<double> GreedyFindBin(const double* distinct, const int* counts,
                                  int n_distinct, int max_bin, int total_cnt,
                                  int min_data_in_bin) {
  std::vector<double> bounds;
  if (n_distinct <= max_bin) {
    int cur = 0;
    for (int i = 0; i < n_distinct - 1; ++i) {
      cur += counts[i];
      if (cur >= min_data_in_bin) {
        bounds.push_back((distinct[i] + distinct[i + 1]) / 2.0);
        cur = 0;
      }
    }
    bounds.push_back(kInf);
    return bounds;
  }
  if (min_data_in_bin > 0) {
    max_bin = std::max(1, std::min(max_bin, total_cnt / min_data_in_bin));
  }
  double mean_bin_size = static_cast<double>(total_cnt) / max_bin;
  int rest_bins = max_bin;
  int rest_cnt = total_cnt;
  std::vector<char> is_big(n_distinct, 0);
  for (int i = 0; i < n_distinct; ++i) {
    if (counts[i] >= mean_bin_size) {
      is_big[i] = 1;
      --rest_bins;
      rest_cnt -= counts[i];
    }
  }
  mean_bin_size = static_cast<double>(rest_cnt) / std::max(rest_bins, 1);
  std::vector<double> uppers(max_bin, kInf), lowers(max_bin, kInf);
  int bin_cnt = 0;
  lowers[0] = distinct[0];
  int cur = 0;
  const double half = 0.5f;
  for (int i = 0; i < n_distinct - 1; ++i) {
    if (!is_big[i]) rest_cnt -= counts[i];
    cur += counts[i];
    if (is_big[i] || cur >= mean_bin_size ||
        (is_big[i + 1] && cur >= std::max(1.0, mean_bin_size * half))) {
      uppers[bin_cnt] = distinct[i];
      ++bin_cnt;
      lowers[bin_cnt] = distinct[i + 1];
      if (bin_cnt >= max_bin - 1) break;
      cur = 0;
      if (!is_big[i]) {
        --rest_bins;
        mean_bin_size = static_cast<double>(rest_cnt) / std::max(rest_bins, 1);
      }
    }
  }
  ++bin_cnt;
  std::vector<double> out(bin_cnt);
  for (int i = 0; i < bin_cnt - 1; ++i) out[i] = (uppers[i] + lowers[i + 1]) / 2.0;
  out[bin_cnt - 1] = kInf;
  return out;
}

int ValueToBinScalar(const double* bounds, int num_bin, double v) {
  if (std::isnan(v)) return num_bin - 1;
  int l = 0, r = num_bin - 1;
  while (l < r) {
    int m = (r + l - 1) / 2;
    if (v <= bounds[m]) r = m; else l = m + 1;
  }
  return l;
}

bool NeedFilterNumerical(const std::vector<long long>& cnt_in_bin,
                         int total_cnt, int filter_cnt) {
  long long sum_left = 0;
  for (size_t i = 0; i + 1 < cnt_in_bin.size(); ++i) {
    sum_left += cnt_in_bin[i];
    if (sum_left >= filter_cnt && total_cnt - sum_left >= filter_cnt)
      return false;
  }
  return true;
}

}  // namespace

extern "C" int LGBMTPU_FindBinNumerical(
    const double* values, int32_t num_values, int32_t total_cnt,
    int32_t max_bin, int32_t min_data_in_bin, int32_t min_split_data,
    double* out_upper_bounds, int32_t* out_num_bin, int32_t* out_is_trivial,
    double* out_min_val, double* out_max_val, int32_t* out_default_bin,
    double* out_sparse_rate) {
  std::vector<double> vals(values, values + num_values);
  vals.erase(std::remove_if(vals.begin(), vals.end(),
                            [](double v) { return std::isnan(v); }),
             vals.end());
  std::sort(vals.begin(), vals.end());
  const int n = static_cast<int>(vals.size());
  const int zero_cnt = total_cnt - n;

  // distinct values with the zero block spliced in (bin.cpp:150-176)
  std::vector<double> distinct;
  std::vector<int> counts;
  if (n == 0 || (vals[0] > 0.0 && zero_cnt > 0)) {
    distinct.push_back(0.0);
    counts.push_back(zero_cnt);
  }
  if (n > 0) {
    distinct.push_back(vals[0]);
    counts.push_back(1);
  }
  for (int i = 1; i < n; ++i) {
    if (vals[i] != vals[i - 1]) {
      if (vals[i - 1] < 0.0 && vals[i] > 0.0) {
        distinct.push_back(0.0);
        counts.push_back(zero_cnt);
      }
      distinct.push_back(vals[i]);
      counts.push_back(1);
    } else {
      ++counts.back();
    }
  }
  if (n > 0 && vals[n - 1] < 0.0 && zero_cnt > 0) {
    distinct.push_back(0.0);
    counts.push_back(zero_cnt);
  }
  const int n_distinct = static_cast<int>(distinct.size());
  *out_min_val = distinct.front();
  *out_max_val = distinct.back();

  // split distinct values around the zero range (bin.cpp:178-228)
  long long left_cnt_data = 0, missing_cnt_data = 0, right_cnt_data = 0;
  for (int i = 0; i < n_distinct; ++i) {
    if (distinct[i] <= -kMissingValueRange) left_cnt_data += counts[i];
    else if (distinct[i] > kMissingValueRange) right_cnt_data += counts[i];
    else missing_cnt_data += counts[i];
  }
  int left_cnt = 0;
  for (int i = 0; i < n_distinct; ++i) {
    if (distinct[i] > -kMissingValueRange) { left_cnt = i; break; }
  }
  std::vector<double> bounds;
  if (left_cnt > 0) {
    long long denom = std::max<long long>(total_cnt - missing_cnt_data, 1);
    int left_max_bin = static_cast<int>(
        static_cast<double>(left_cnt_data) / denom * (max_bin - 1));
    bounds = GreedyFindBin(distinct.data(), counts.data(), left_cnt,
                           left_max_bin, static_cast<int>(left_cnt_data),
                           min_data_in_bin);
    bounds.back() = -kMissingValueRange;
  }
  int right_start = -1;
  for (int i = left_cnt; i < n_distinct; ++i) {
    if (distinct[i] > kMissingValueRange) { right_start = i; break; }
  }
  if (right_start >= 0) {
    int right_max_bin = max_bin - 1 - static_cast<int>(bounds.size());
    auto rb = GreedyFindBin(distinct.data() + right_start,
                            counts.data() + right_start,
                            n_distinct - right_start, right_max_bin,
                            static_cast<int>(right_cnt_data), min_data_in_bin);
    bounds.push_back(kMissingValueRange);
    bounds.insert(bounds.end(), rb.begin(), rb.end());
  } else {
    bounds.push_back(kInf);
  }
  const int num_bin = static_cast<int>(bounds.size());
  if (num_bin > max_bin) return -1;
  std::copy(bounds.begin(), bounds.end(), out_upper_bounds);
  *out_num_bin = num_bin;

  std::vector<long long> cnt_in_bin(num_bin, 0);
  {
    int i_bin = 0;
    for (int i = 0; i < n_distinct; ++i) {
      if (distinct[i] > bounds[i_bin]) ++i_bin;
      cnt_in_bin[i_bin] += counts[i];
    }
  }
  int trivial = num_bin <= 1 ? 1 : 0;
  if (!trivial &&
      NeedFilterNumerical(cnt_in_bin, total_cnt, min_split_data)) {
    trivial = 1;
  }
  *out_is_trivial = trivial;
  int default_bin = 0;
  if (!trivial) default_bin = ValueToBinScalar(bounds.data(), num_bin, 0.0);
  *out_default_bin = default_bin;
  *out_sparse_rate =
      static_cast<double>(cnt_in_bin[default_bin]) / std::max(total_cnt, 1);
  return 0;
}

extern "C" int LGBMTPU_ValueToBin(const double* upper_bounds, int32_t num_bin,
                                  const double* values, int64_t n,
                                  uint16_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = static_cast<uint16_t>(
        ValueToBinScalar(upper_bounds, num_bin, values[i]));
  }
  return 0;
}

// ----------------------------------------------------------- text parsing

namespace {

// format autodetect by separator counting (src/io/parser.cpp:10-70)
enum class Format { kCSV, kTSV, kSpace, kLibSVM };

Format DetectFormat(const std::vector<std::string>& lines) {
  int comma = INT32_MAX, tab = INT32_MAX, colon = INT32_MAX;
  int seen = 0;
  for (const auto& l : lines) {
    if (l.empty()) continue;
    int c = 0, t = 0, co = 0;
    for (char ch : l) {
      if (ch == ',') ++c;
      else if (ch == '\t') ++t;
      else if (ch == ':') ++co;
    }
    comma = std::min(comma, c);
    tab = std::min(tab, t);
    colon = std::min(colon, co);
    if (++seen == 2) break;
  }
  if (seen == 0) return Format::kCSV;
  if (colon > 0 && colon >= std::max(comma, tab)) return Format::kLibSVM;
  if (tab > 0 && tab >= comma) return Format::kTSV;
  if (comma > 0) return Format::kCSV;
  return Format::kSpace;
}

inline double FastAtof(const char* p, const char** end) {
  return std::strtod(p, const_cast<char**>(end));
}

}  // namespace

extern "C" int LGBMTPU_ParseFile(const char* path, int32_t has_header,
                                 int32_t label_idx, int64_t* out_rows,
                                 int32_t* out_cols, double** out_features,
                                 double** out_label) {
  std::ifstream in(path);
  if (!in.good()) return -1;
  std::vector<std::string> lines;
  std::string line;
  while (std::getline(in, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (!line.empty()) lines.push_back(std::move(line));
  }
  if (has_header && !lines.empty()) lines.erase(lines.begin());
  if (lines.empty()) return -2;
  Format fmt = DetectFormat(lines);
  const int64_t rows = static_cast<int64_t>(lines.size());

  if (fmt == Format::kLibSVM) {
    std::vector<double> labels(rows, 0.0);
    std::vector<std::vector<std::pair<int, double>>> pairs(rows);
    int max_feat = -1;
    for (int64_t r = 0; r < rows; ++r) {
      const char* p = lines[r].c_str();
      const char* end = p;
      // leading label (no colon before whitespace)
      const char* q = p;
      bool has_colon_first = false;
      while (*q && !std::isspace(static_cast<unsigned char>(*q))) {
        if (*q == ':') { has_colon_first = true; break; }
        ++q;
      }
      if (!has_colon_first) {
        labels[r] = FastAtof(p, &end);
        p = end;
      }
      while (*p) {
        while (*p && std::isspace(static_cast<unsigned char>(*p))) ++p;
        if (!*p) break;
        char* colon = const_cast<char*>(std::strchr(p, ':'));
        if (!colon) break;
        int fi = std::atoi(p);
        double v = FastAtof(colon + 1, &end);
        pairs[r].emplace_back(fi, v);
        if (fi > max_feat) max_feat = fi;
        p = end;
      }
    }
    const int cols = max_feat + 1;
    double* feat = static_cast<double*>(
        std::calloc(static_cast<size_t>(rows) * cols, sizeof(double)));
    double* lab = static_cast<double*>(std::malloc(rows * sizeof(double)));
    if (!feat || !lab) return -3;
    std::memcpy(lab, labels.data(), rows * sizeof(double));
    for (int64_t r = 0; r < rows; ++r)
      for (auto& kv : pairs[r]) feat[r * cols + kv.first] = kv.second;
    *out_rows = rows;
    *out_cols = cols;
    *out_features = feat;
    *out_label = lab;
    return 0;
  }

  const char sep = fmt == Format::kCSV ? ',' : (fmt == Format::kTSV ? '\t' : ' ');
  // column count from the first line
  int cols_total = 1;
  {
    const char* p = lines[0].c_str();
    if (fmt == Format::kSpace) {
      cols_total = 0;
      bool in_tok = false;
      for (; *p; ++p) {
        bool sp = std::isspace(static_cast<unsigned char>(*p));
        if (!sp && !in_tok) { ++cols_total; in_tok = true; }
        else if (sp) in_tok = false;
      }
    } else {
      for (; *p; ++p) if (*p == sep) ++cols_total;
    }
  }
  const bool has_label = label_idx >= 0 && label_idx < cols_total;
  const int cols = cols_total - (has_label ? 1 : 0);
  double* feat = static_cast<double*>(
      std::malloc(static_cast<size_t>(rows) * cols * sizeof(double)));
  double* lab = static_cast<double*>(std::calloc(rows, sizeof(double)));
  if (!feat || !lab) return -3;
  for (int64_t r = 0; r < rows; ++r) {
    const char* p = lines[r].c_str();
    const char* end;
    int out_c = 0;
    for (int c = 0; c < cols_total && *p; ++c) {
      while (*p == ' ' && fmt != Format::kSpace) ++p;
      double v = FastAtof(p, &end);
      if (end == p) {  // na / non-numeric token
        v = std::numeric_limits<double>::quiet_NaN();
        while (*p && *p != sep &&
               !(fmt == Format::kSpace &&
                 std::isspace(static_cast<unsigned char>(*p)))) ++p;
        end = p;
      }
      if (has_label && c == label_idx) lab[r] = std::isnan(v) ? 0.0 : v;
      else feat[r * cols + out_c++] = v;
      p = end;
      if (fmt == Format::kSpace) {
        while (*p && std::isspace(static_cast<unsigned char>(*p))) ++p;
      } else {
        if (*p == sep) ++p;
      }
    }
    for (; out_c < cols; ++out_c) feat[r * cols + out_c] = 0.0;
  }
  *out_rows = rows;
  *out_cols = cols;
  *out_features = feat;
  *out_label = lab;
  return 0;
}

extern "C" void LGBMTPU_Free(void* ptr) { std::free(ptr); }

// ------------------------------------------------------------- prediction

extern "C" int LGBMTPU_PredictRaw(
    int32_t n_trees, const int64_t* node_offsets, const int64_t* leaf_offsets,
    const int32_t* split_feature, const double* threshold,
    const int8_t* decision_type, const double* default_value,
    const int32_t* left_child, const int32_t* right_child,
    const double* leaf_value, const int32_t* tree_class, int32_t n_class,
    const double* features, int64_t n_rows, int32_t n_cols, double* out) {
  for (int64_t r = 0; r < n_rows; ++r) {
    const double* row = features + r * n_cols;
    double* orow = out + r * n_class;
    for (int t = 0; t < n_trees; ++t) {
      const int64_t no = node_offsets[t];
      const int64_t n_nodes = node_offsets[t + 1] - no;
      if (n_nodes <= 0) continue;  // single-leaf tree contributes 0
      const int64_t lo = leaf_offsets[t];
      int node = 0;
      while (node >= 0) {
        const int64_t k = no + node;
        double fv = row[split_feature[k]];
        if (fv > -kMissingValueRange && fv <= kMissingValueRange)
          fv = default_value[k];
        bool left;
        if (decision_type[k] == 0) {
          left = fv <= threshold[k];
        } else {
          left = static_cast<int64_t>(fv) ==
                 static_cast<int64_t>(threshold[k]);
        }
        node = left ? left_child[k] : right_child[k];
      }
      orow[tree_class[t]] += leaf_value[lo + (~node)];
    }
  }
  return 0;
}
