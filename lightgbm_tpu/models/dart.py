"""DART booster (src/boosting/dart.hpp:50-186).

Drops a random subset of prior trees each iteration (uniform or
weight-proportional), trains on the adjusted score, then re-normalizes the
dropped trees — the lightgbm ``k/(k+1)`` scheme or ``xgboost_dart_mode``.
Score adjustments run as device traversals of the dropped trees.

Deviation from the reference: tree indices account for the
boost_from_average stub tree (the reference indexes ``i * k + tid`` even when
models_[0] is the stub, dropping the wrong tree in that configuration).
"""
from __future__ import annotations

from typing import List

from ..utils.random import Random
from .gbdt import GBDT


class DART(GBDT):
    def __init__(self, config, train_data=None, objective=None,
                 training_metrics=()):
        super().__init__(config, train_data, objective, training_metrics)
        self.random_for_drop = Random(config.drop_seed)
        self.sum_weight = 0.0
        self.tree_weight: List[float] = []
        self.drop_index: List[int] = []
        self._score_dropped_this_iter = False

    def _stub_offset(self) -> int:
        return 1 if self.boost_from_average_used else 0

    def _tree_at(self, iteration: int, tid: int):
        return self.models[self._stub_offset()
                           + iteration * self.num_tree_per_iteration + tid]

    def train_one_iter(self, gradients=None, hessians=None,
                       is_eval: bool = True) -> bool:
        self._score_dropped_this_iter = False
        stop = super().train_one_iter(gradients, hessians, False)
        if stop:
            return stop
        self._normalize()
        if not self.config.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _score_for_objective(self):
        # DroppingTrees runs once per iteration the moment scores are read
        # (DART::GetTrainingScore, dart.hpp:69-79)
        if not self._score_dropped_this_iter:
            self._dropping_trees()
            self._score_dropped_this_iter = True
        return super()._score_for_objective()

    def _dropping_trees(self) -> None:
        cfg = self.config
        self.drop_index = []
        is_skip = self.random_for_drop.next_float() < cfg.skip_drop
        if not is_skip and self.iter > 0:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                inv_avg = len(self.tree_weight) / self.sum_weight \
                    if self.sum_weight > 0 else 0.0
                if cfg.max_drop > 0 and self.sum_weight > 0:
                    drop_rate = min(drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
                for i in range(self.iter):
                    if self.random_for_drop.next_float() < drop_rate * self.tree_weight[i] * inv_avg:
                        self.drop_index.append(i)
            else:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / float(self.iter))
                for i in range(self.iter):
                    if self.random_for_drop.next_float() < drop_rate:
                        self.drop_index.append(i)
        if self.drop_index:
            self._materialize()
        # remove dropped trees' contribution from the training score
        for i in self.drop_index:
            for tid in range(self.num_tree_per_iteration):
                tree = self._tree_at(i, tid)
                tree.shrink(-1.0)
                self._apply_tree_to_train(tree, tid)
        k = float(len(self.drop_index))
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + k)
        else:
            if not self.drop_index:
                self.shrinkage_rate = cfg.learning_rate
            else:
                self.shrinkage_rate = cfg.learning_rate / (cfg.learning_rate + k)

    def _normalize(self) -> None:
        """dart.hpp:139-176 three-step shrink dance."""
        cfg = self.config
        k = float(len(self.drop_index))
        for i in self.drop_index:
            for tid in range(self.num_tree_per_iteration):
                tree = self._tree_at(i, tid)
                if not cfg.xgboost_dart_mode:
                    tree.shrink(1.0 / (k + 1.0))
                    for vi in range(len(self.valid_data)):
                        self._apply_tree_to_valid(tree, vi, tid)
                    tree.shrink(-k)
                    self._apply_tree_to_train(tree, tid)
                else:
                    tree.shrink(self.shrinkage_rate)
                    for vi in range(len(self.valid_data)):
                        self._apply_tree_to_valid(tree, vi, tid)
                    tree.shrink(-k / cfg.learning_rate)
                    self._apply_tree_to_train(tree, tid)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + 1.0))
                    self.tree_weight[i] *= k / (k + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + cfg.learning_rate))
                    self.tree_weight[i] *= k / (k + cfg.learning_rate)
