"""Compare two bench/timeline artifacts and gate on perf regressions.

CI needs a yes/no answer to "did this PR make the bench slower", not a
human squinting at BENCH_*.json — the discipline 1809.04559 frames as
the hard part of GBDT perf work.  This tool loads two artifacts, lines
up the comparable metrics, applies per-metric tolerances, and exits
nonzero on regression so a workflow can gate on it.

Accepted artifact kinds (auto-detected per file):

* an obs JSONL timeline (``obs_events_path`` / ``bench.py --dry``) —
  iters/sec over the LAST run's fenced iter records, compile seconds
  from the run_end entry summaries (or compile events), recompile
  count from ``compile_attr`` events (``obs_compile=true``), peak
  device memory from memory snapshots (absent on CPU);
* a ``BENCH_r*.json`` lineage record — ``parsed.value`` with
  ``parsed.unit`` of iters/sec;
* a bare bench JSON line — ``{"metric": ..., "value": ...}`` as printed
  by ``bench.py --child``.

Direction is per metric: iters/sec regresses when the candidate drops
below baseline x (1 - tol); compile time and peak memory regress when
the candidate exceeds baseline x (1 + tol).  A ZERO baseline breaks the
relative form, so those cells gate on the absolute delta instead: any
bad-direction move past the (default 0) zero-baseline epsilon regresses.
Metrics present in only one artifact are reported and skipped; no
overlap at all is a usage error.

``--baseline rolling`` swaps the single parent for the cross-run ledger
(lightgbm_tpu/obs/ledger.py): each candidate metric is z-scored against
the median/MAD of the last N comparable clean runs (same suite/shape
filters) and regresses when it sits beyond ``--z`` noise-floored sigmas
in the bad direction.  Metrics with fewer than ``--min-history``
comparable runs fall back to the positional parent compare with a
stderr notice — thin history must not silently pass.

Usage:
    python tools/bench_compare.py BASELINE CANDIDATE \
        [--baseline rolling --ledger DIR --suite NAME --shape NxF \
         --window 8 --min-history 3 --z 3.0] \
        [--tol-ips 0.08] [--tol-compile 0.25] [--tol-mem 0.10] \
        [--tol-recompile 0] [--tol-eval 0.02] \
        [--tol-serve-qps 0.15] [--tol-serve-p99 0.30] \
        [--tol-serve-shed 0.25] [--tol-autotune 0.50] \
        [--tol-construct 0.30] [--tol-host-orch 0.50] [--json]

Exit codes: 0 pass, 1 regression beyond tolerance, 2 load/usage error.
"""
import argparse
import json
import os
import sys

EXIT_CODES = """\
exit codes:
  0  pass — every comparable metric within tolerance
  1  regression — at least one metric beyond its tolerance
  2  load/usage error — unreadable artifact or no comparable metrics\
"""

# metric -> (direction, default tolerance); direction +1 = higher is
# better, -1 = lower is better
METRICS = {
    "iters_per_sec": (+1, 0.08),
    "compile_s": (-1, 0.25),
    "peak_mem_bytes": (-1, 0.10),
    # compiles beyond the first per entry (compile_attr events);
    # tolerance 0: ANY new recompile vs a clean baseline is a failure
    "recompile_count": (-1, 0.0),
    # worst first-vs-last barrier arrival gap across ranks (merged
    # multi-rank timelines only — `obs merge` output); a growing skew
    # means a rank got slower relative to its peers
    "barrier_skew_max_s": (-1, 0.50),
    # model quality next to the perf numbers: the last `eval` event's
    # metric (bench --child records it as final_eval_metric).  Assumes a
    # higher-is-better metric (auc — the bench protocol's); a perf win
    # that costs more than 2% quality is a regression, not a win
    "final_eval_metric": (+1, 0.02),
    # serving-tier load numbers (bench_serve.py: the `serve_bench`
    # timeline event / JSON line).  Throughput and tail latency gate
    # separately — a QPS win that blows up p99 is not a win
    "serve_qps": (+1, 0.15),
    "serve_p99_s": (-1, 0.30),
    # fraction of offered requests shed at admission (overload
    # protection).  Zero-baseline rule applies: a non-overload baseline
    # sheds nothing, so ANY shedding in the candidate is a regression;
    # overload-vs-overload runs tolerate 25% load-generator noise
    "serve_shed_rate": (-1, 0.25),
    # total probe seconds the kernel autotuner paid this run (summed
    # over autotune_decision events, ops/autotune.py).  Zero on cache
    # hits / tuning off — the zero-baseline rule makes ANY candidate
    # probing vs a warm-cache baseline a regression, which is exactly
    # the "second run on the same shape performs zero probe waves"
    # contract; measure-vs-measure runs tolerate 50% timer noise
    "autotune_overhead_s": (-1, 0.50),
    # dataset construction wall seconds (summed over dataset_construct
    # events, io/streaming.py two-pass ingest).  A pre-binned reload
    # reports sketch_s == bin_s == 0, so candidate-vs-baseline catches
    # both slow binning AND accidental re-binning of a binned artifact
    "construct_s": (-1, 0.30),
    # mean host seconds between device program submissions per iteration
    # (schema v11 iter field, models/gbdt.py OrchestrationClock) — the
    # number the fused iteration (ops/fused_iter.py) drives to ~0.  A
    # fused baseline sits near zero where scheduler jitter is a large
    # relative move, so the tolerance is wide (50%) — the gate is for
    # real orchestration creep (a new host sync, a regrown glue path),
    # which shows up as multiples, not percentages
    "host_orchestration_s": (-1, 0.50),
    # roofline utilization rollups (schema v13, obs/roofline.py): the
    # last `utilization` event's exec-weighted achieved/peak fractions.
    # Higher is better — a drop means a kernel moved away from its
    # hardware roof even if wall time hid it behind compile or host
    # noise.  Utilization is a ratio of two timed quantities, so the
    # tolerance is wider than it/s (timer noise enters twice)
    "flop_util": (+1, 0.20),
    "hbm_util": (+1, 0.20),
}


def _from_timeline(events):
    """Metrics of the LAST run in an obs timeline."""
    run = events[-1].get("run")
    events = [e for e in events if e.get("run") == run]
    out = {}
    iters = [e for e in events if e.get("ev") == "iter"]
    total = sum(e["time_s"] for e in iters)
    if iters and total > 0:
        out["iters_per_sec"] = len(iters) / total
    run_end = next((e for e in events if e.get("ev") == "run_end"), None)
    entries = (run_end or {}).get("entries") or {}
    if entries:
        out["compile_s"] = sum(st.get("first_s", 0.0)
                               for st in entries.values())
    else:
        compiles = [e for e in events if e.get("ev") == "compile"]
        if compiles:
            out["compile_s"] = sum(e["first_call_s"] for e in compiles)
    peak = 0
    for e in events:
        if e.get("ev") != "memory":
            continue
        for d in e.get("devices", ()):
            peak = max(peak, d.get("peak_bytes_in_use",
                                   d.get("bytes_in_use", 0)))
    if peak:
        out["peak_mem_bytes"] = peak
    # compiles beyond the first, per entry (obs_compile=true runs only —
    # a timeline without compile_attr events just skips the metric)
    attr = [e for e in events if e.get("ev") == "compile_attr"]
    if attr:
        worst = {}
        for e in attr:
            worst[e.get("entry")] = max(worst.get(e.get("entry"), 0),
                                        int(e.get("n_compiles", 1)))
        out["recompile_count"] = sum(n - 1 for n in worst.values())
    # merged multi-rank timelines (`obs merge`) stamp per-collective
    # barrier skew; absent on single-rank shards
    skews = [float(e["skew_s"]) for e in events
             if e.get("ev") == "host_collective" and "skew_s" in e]
    if skews:
        out["barrier_skew_max_s"] = max(skews)
    # final model quality: the LAST eval event's first result (schema v5;
    # runs without metrics simply skip the gate)
    evals = [e for e in events if e.get("ev") == "eval"
             and e.get("results")]
    if evals:
        out["final_eval_metric"] = float(evals[-1]["results"][-1]["value"])
    # serving-tier load results (bench_serve.py timelines)
    serve = [e for e in events if e.get("ev") == "serve_bench"]
    if serve:
        out["serve_qps"] = float(serve[-1]["qps"])
        out["serve_p99_s"] = float(serve[-1]["p99_s"])
        if serve[-1].get("shed_rate") is not None:
            out["serve_shed_rate"] = float(serve[-1]["shed_rate"])
    # kernel-autotuner probe cost (schema v8): present whenever the run
    # recorded a decision, zero when the cache was warm or tuning off
    decs = [e for e in events if e.get("ev") == "autotune_decision"]
    if decs:
        out["autotune_overhead_s"] = sum(
            float(e.get("overhead_s", 0.0)) for e in decs)
    # host-orchestration glue (schema v11): mean over the run's iter
    # records; older timelines without the field simply skip the metric
    orch = [float(e["host_orchestration_s"]) for e in iters
            if "host_orchestration_s" in e]
    if orch:
        out["host_orchestration_s"] = sum(orch) / len(orch)
    # dataset-construction cost (schema v9): sum over dataset_construct
    # events of the run (train + valid sets all count toward the gate)
    cons = [e for e in events if e.get("ev") == "dataset_construct"]
    if cons:
        out["construct_s"] = sum(
            float(e.get("construct_s",
                        e.get("sketch_s", 0.0) + e.get("bin_s", 0.0)
                        + e.get("write_s", 0.0)))
            for e in cons)
    # pod scale-out summary (schema v12, bench.py --mp) — kept in
    # lockstep with obs/ledger.py metrics_from_events
    sc = [e for e in events if e.get("ev") == "scaling"]
    if sc:
        out["rows_per_sec_per_chip"] = float(
            sc[-1]["rows_per_sec_per_chip"])
        out["weak_scaling_eff"] = float(sc[-1]["efficiency"])
    # roofline rollup (schema v13): the LAST utilization event is the
    # steady-state one — also in lockstep with metrics_from_events
    utils = [e for e in events if e.get("ev") == "utilization"]
    if utils and utils[-1].get("flop_util") is not None:
        out["flop_util"] = float(utils[-1]["flop_util"])
        out["hbm_util"] = float(utils[-1].get("hbm_util", 0.0))
    return out


def _from_parsed(parsed):
    out = {}
    unit = str(parsed.get("unit", ""))
    value = parsed.get("value")
    if value is None:
        return out
    if "iters/sec" in unit or "iters_per_sec" in str(parsed.get("metric",
                                                                "")):
        out["iters_per_sec"] = float(value)
    if parsed.get("final_eval_metric") is not None:
        out["final_eval_metric"] = float(parsed["final_eval_metric"])
    if parsed.get("serve_qps") is not None:
        out["serve_qps"] = float(parsed["serve_qps"])
    if parsed.get("serve_p99_s") is not None:
        out["serve_p99_s"] = float(parsed["serve_p99_s"])
    if parsed.get("serve_shed_rate") is not None:
        out["serve_shed_rate"] = float(parsed["serve_shed_rate"])
    if parsed.get("construct_s") is not None:
        out["construct_s"] = float(parsed["construct_s"])
    if parsed.get("flop_util") is not None:
        out["flop_util"] = float(parsed["flop_util"])
    if parsed.get("hbm_util") is not None:
        out["hbm_util"] = float(parsed["hbm_util"])
    return out


def load_metrics(path):
    """{metric: value} from any accepted artifact kind."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise SystemExit2("cannot read %s: %s" % (path, e))
    records = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except ValueError:
            break
    else:
        if records and all(isinstance(r, dict) for r in records):
            if any(r.get("ev") for r in records):        # obs timeline
                return _from_timeline(records)
            for r in reversed(records):   # bench --child / lineage line
                got = _from_parsed(r["parsed"]
                                   if isinstance(r.get("parsed"), dict)
                                   else r)
                if got:
                    return got
            return {}
    # whole-file JSON (BENCH_r*.json lineage, or an indented export)
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise SystemExit2("%s is neither JSONL nor JSON: %s" % (path, e))
    if isinstance(doc, dict):
        if isinstance(doc.get("parsed"), dict):
            return _from_parsed(doc["parsed"])
        return _from_parsed(doc)
    return {}


class SystemExit2(Exception):
    """Load/usage failure -> exit 2 (distinct from regression -> 1)."""


def compare(base, cand, tols, zero_eps=None):
    """[(metric, base, cand, delta, regressed, tol)] over the metrics
    present in both artifacts.  ``delta`` is the relative change except
    against a zero baseline, where it is the finite ABSOLUTE delta
    (`c - b`) and gating switches to the per-metric ``zero_eps``
    epsilon (default 0: any bad-direction move regresses)."""
    zero_eps = zero_eps or {}
    rows = []
    for name, (direction, _) in METRICS.items():
        if name not in base or name not in cand:
            continue
        b, c = float(base[name]), float(cand[name])
        tol = tols.get(name, METRICS[name][1])
        if b == 0:
            # a zero baseline breaks the relative form (and the old
            # inf delta broke --json); gate on the absolute delta in
            # BOTH directions: recompile_count 0 -> 2 regresses, and so
            # does a higher-is-better metric going 0 -> negative
            eps = float(zero_eps.get(name, 0.0))
            delta = c - b
            regressed = (direction < 0 and c > eps) or \
                        (direction > 0 and c < -eps)
        else:
            delta = (c - b) / b
            regressed = (direction > 0 and c < b * (1.0 - tol)) or \
                        (direction < 0 and c > b * (1.0 + tol))
        rows.append((name, b, c, delta, regressed, tol))
    return rows


# ------------------------------------------------- rolling-ledger gating

def _ledger_mod():
    """Import lightgbm_tpu.obs.ledger from the repo this script lives
    in — lazy, so parent-compare runs never touch the package."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from lightgbm_tpu.obs import ledger
    return ledger


def _candidate_cell(path, led):
    """Ledger identity of the candidate timeline: {run, suite, shape,
    device_kind}, or None for non-timeline artifacts.  Derived the same
    way ingestion derives it (header params / context / shape bucket),
    so an un-flagged rolling compare gates against the candidate's OWN
    cell instead of pooling every suite in the ledger."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    events, run = [], None
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            return None
        if not isinstance(rec, dict) or not rec.get("ev"):
            return None
        events.append(rec)
        run = rec.get("run", run)
    if not events:
        return None
    events = [e for e in events if e.get("run", run) == run]
    header = next((e for e in events if e.get("ev") == "run_header"), {})
    params = header.get("params") or {}
    ctx = header.get("context") or {}
    suite = str(params.get("obs_ledger_suite") or ctx.get("tool")
                or ctx.get("suite") or "")
    return {"run": run, "suite": suite,
            "shape": led._shape_bucket(events, header),
            "device_kind": led._device_kind(header),
            "world_size": int(header.get("world_size", 1) or 1)}


def rolling_rows(args, tols, base, cand):
    """Rows gated against the ledger's rolling baseline.  Returns
    (rows, modes): rows shaped like compare()'s, modes[name] one of
    'rolling' (z-gate, base column = rolling median) or 'parent'
    (thin history -> positional-parent fallback, noticed on stderr)."""
    led = _ledger_mod()
    ledger = led.Ledger(args.ledger or led.default_ledger_dir())
    entries = ledger.entries()
    cell = _candidate_cell(args.candidate, led) or {}
    exclude = {cell["run"]} if cell.get("run") else set()
    suite = args.suite or cell.get("suite") or None
    shape = args.shape or cell.get("shape") or None
    device_kind = cell.get("device_kind") or None
    # world_size is part of the candidate's shape identity (schema 12):
    # a pod run only gates against same-world-size history
    world_size = cell.get("world_size")
    rows, modes = [], {}
    for name, (direction, _) in METRICS.items():
        if name not in cand:
            continue
        c = float(cand[name])
        comp = led.comparable_entries(
            entries, suite=suite, shape=shape, device_kind=device_kind,
            metric=name, exclude_runs=exclude, world_size=world_size)
        vals = [float(r["metrics"][name]) for r in comp]
        if len(vals) >= args.min_history:
            st = led.rolling_stats(vals, args.window)
            z = (c - st["median"]) / st["sigma"]
            regressed = direction * z < -args.z
            delta = (c - st["median"]) / st["median"] \
                if st["median"] else c - st["median"]
            rows.append((name, st["median"], c, delta, regressed, z))
            modes[name] = "rolling"
        elif name in base:
            print("notice: %s has %d comparable ledger run(s) "
                  "(< %d): falling back to parent compare"
                  % (name, len(vals), args.min_history), file=sys.stderr)
            rows.extend(compare({name: base[name]}, {name: c}, tols))
            modes[name] = "parent"
        else:
            print("notice: %s has %d comparable ledger run(s) "
                  "(< %d) and no parent value: skipped"
                  % (name, len(vals), args.min_history), file=sys.stderr)
    return rows, modes


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="compare two bench/timeline artifacts; nonzero exit "
                    "on perf regression beyond tolerance",
        epilog=EXIT_CODES,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument("--baseline", dest="baseline_mode",
                    choices=("parent", "rolling"), default="parent",
                    help="gate source: 'parent' compares against the "
                         "positional baseline artifact; 'rolling' "
                         "z-scores against the run ledger's rolling "
                         "median/MAD (thin history falls back to "
                         "parent per metric)")
    ap.add_argument("--ledger", default="",
                    help="ledger directory for --baseline rolling "
                         "(default: LGBM_TPU_LEDGER or "
                         "/tmp/lgbm_tpu_ledger)")
    ap.add_argument("--suite", default="",
                    help="restrict rolling history to this ledger suite")
    ap.add_argument("--shape", default="",
                    help="restrict rolling history to this shape bucket")
    ap.add_argument("--window", type=int, default=8,
                    help="rolling-baseline window (last N runs)")
    ap.add_argument("--min-history", type=int, default=3,
                    help="comparable runs required before the rolling "
                         "gate engages (below: parent fallback)")
    ap.add_argument("--z", type=float, default=3.0,
                    help="rolling-gate z-score threshold (MAD-based, "
                         "noise-floored sigma)")
    ap.add_argument("--tol-ips", type=float, default=METRICS[
        "iters_per_sec"][1], help="iters/sec relative tolerance")
    ap.add_argument("--tol-compile", type=float, default=METRICS[
        "compile_s"][1], help="compile-time relative tolerance")
    ap.add_argument("--tol-mem", type=float, default=METRICS[
        "peak_mem_bytes"][1], help="peak-memory relative tolerance")
    ap.add_argument("--tol-recompile", type=float, default=METRICS[
        "recompile_count"][1],
        help="recompile-count relative tolerance (0 = any new "
             "recompile vs a clean baseline fails)")
    ap.add_argument("--tol-eval", type=float, default=METRICS[
        "final_eval_metric"][1],
        help="final eval-metric relative tolerance (higher-is-better)")
    ap.add_argument("--tol-serve-qps", type=float, default=METRICS[
        "serve_qps"][1], help="serving QPS relative tolerance")
    ap.add_argument("--tol-serve-p99", type=float, default=METRICS[
        "serve_p99_s"][1],
        help="serving p99-latency relative tolerance")
    ap.add_argument("--tol-serve-shed", type=float, default=METRICS[
        "serve_shed_rate"][1],
        help="serving shed-rate relative tolerance (a zero-shed "
             "baseline fails on ANY candidate shedding)")
    ap.add_argument("--tol-autotune", type=float, default=METRICS[
        "autotune_overhead_s"][1],
        help="autotune probe-overhead relative tolerance (a warm-cache "
             "zero-overhead baseline fails on ANY candidate probing)")
    ap.add_argument("--tol-construct", type=float, default=METRICS[
        "construct_s"][1],
        help="dataset-construction time relative tolerance (a "
             "pre-binned zero-rebin baseline fails on ANY candidate "
             "re-binning)")
    ap.add_argument("--tol-host-orch", type=float, default=METRICS[
        "host_orchestration_s"][1],
        help="per-iteration host-orchestration seconds relative "
             "tolerance (schema v11; the fused-iteration gate)")
    ap.add_argument("--tol-flop-util", type=float, default=METRICS[
        "flop_util"][1],
        help="achieved/peak FLOP-utilization relative tolerance "
             "(schema v13 roofline rollups; higher is better)")
    ap.add_argument("--tol-hbm-util", type=float, default=METRICS[
        "hbm_util"][1],
        help="achieved/peak HBM-bandwidth-utilization relative "
             "tolerance (schema v13 roofline rollups)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdict on stdout")
    args = ap.parse_args(argv)
    tols = {"iters_per_sec": args.tol_ips, "compile_s": args.tol_compile,
            "peak_mem_bytes": args.tol_mem,
            "recompile_count": args.tol_recompile,
            "final_eval_metric": args.tol_eval,
            "serve_qps": args.tol_serve_qps,
            "serve_p99_s": args.tol_serve_p99,
            "serve_shed_rate": args.tol_serve_shed,
            "autotune_overhead_s": args.tol_autotune,
            "construct_s": args.tol_construct,
            "host_orchestration_s": args.tol_host_orch,
            "flop_util": args.tol_flop_util,
            "hbm_util": args.tol_hbm_util}
    try:
        base = load_metrics(args.baseline)
        cand = load_metrics(args.candidate)
    except SystemExit2 as e:
        print("error: %s" % e, file=sys.stderr)
        return 2
    modes = {}
    if args.baseline_mode == "rolling":
        try:
            rows, modes = rolling_rows(args, tols, base, cand)
        except Exception as e:
            print("error: rolling baseline unavailable: %s" % e,
                  file=sys.stderr)
            return 2
    else:
        rows = compare(base, cand, tols)
    if not rows:
        print("error: no comparable metrics between %s (%s) and %s (%s)"
              % (args.baseline, sorted(base) or "none",
                 args.candidate, sorted(cand) or "none"), file=sys.stderr)
        return 2
    regressed = [r for r in rows if r[4]]
    if args.json:
        print(json.dumps({
            "status": "regression" if regressed else "ok",
            "mode": args.baseline_mode,
            "metrics": [dict(
                {"metric": n, "baseline": b, "candidate": c,
                 "regressed": r},
                **({"z": round(t, 3), "delta_frac": round(d, 6),
                    "gate": "rolling"}
                   if modes.get(n) == "rolling" else
                   {"delta_frac": round(d, 6), "tolerance": t,
                    "gate": modes.get(n, "parent"),
                    "delta_kind": "abs" if b == 0 else "frac"}))
                        for n, b, c, d, r, t in rows]}))
    else:
        print("%-16s %14s %14s %9s %7s  verdict"
              % ("metric", "baseline", "candidate", "delta", "gate"))
        for n, b, c, d, r, t in rows:
            if modes.get(n) == "rolling":
                gate = "z%+.1f" % t
            else:
                gate = "%.0f%%" % (100 * t) if b != 0 else "abs"
            delta = "%+8.2f%%" % (100 * d) if b != 0 else "%+9.4g" % d
            print("%-16s %14.6g %14.6g %s %7s  %s"
                  % (n, b, c, delta, gate,
                     "REGRESSED" if r else "ok"))
        skipped = (set(base) | set(cand)) - {r[0] for r in rows}
        if skipped:
            print("skipped (present in only one artifact): %s"
                  % ", ".join(sorted(skipped)))
    if regressed:
        print("FAIL: %d metric(s) regressed beyond %s"
              % (len(regressed),
                 "the rolling noise band" if args.baseline_mode ==
                 "rolling" else "tolerance"), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
