"""One-shot TPU A/B: kernel microbench + end-to-end engine comparison.

Run when the chip is reachable:  python tools/tpu_ab.py [n_rows]
Probes the device first (fails fast if the axon tunnel is wedged), then
times the wave-histogram kernels (v1 row-major, v2 transposed, XLA scan
at several chunks) and the end-to-end engines (onehot / pallas /
pallas_t) at the 255-leaf recipe, appending everything to
tools/AB_RESULTS.md.
"""
import datetime
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def probe(seconds=90):
    """Shared subprocess probe (lightgbm_tpu.utils.common.probe_device)."""
    from lightgbm_tpu.utils.common import probe_device
    return probe_device(timeout=seconds)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 999_424
    backend = probe()
    lines = ["", "## %s UTC — backend=%s, n=%d"
             % (datetime.datetime.utcnow().isoformat(timespec="seconds"),
                backend, n)]
    print(lines[-1], flush=True)

    # ---- kernel microbench (round-trip-corrected)
    import tools.bench_pallas_kernel as kb
    import io
    import contextlib
    buf = io.StringIO()
    sys.argv = ["bench_pallas_kernel.py", str(n)]
    with contextlib.redirect_stdout(buf):
        kb.main()
    for ln in buf.getvalue().splitlines():
        lines.append("    " + ln)
        print("    " + ln, flush=True)

    # ---- end-to-end engines at the 255-leaf recipe
    from tools.bench_modes import make_data, run
    X, y = make_data(n)
    combos = [("onehot", 32), ("onehot", 64), ("pallas", 32),
              ("pallas_t", 32), ("pallas_ct", 32), ("pallas_ct", 64)]
    for mode, width in combos:
        t0 = time.time()
        try:
            dt, auc = run(X, y, mode, wave_width=width)
            ln = ("    engine %-8s W=%-2d: %.3f s/iter (%.2f it/s) "
                  "auc=%.4f [wall %.0fs]"
                  % (mode, width, dt, 1.0 / dt, auc, time.time() - t0))
        except Exception as e:  # record, keep going
            ln = "    engine %-8s W=%-2d: FAILED (%s)" % (mode, width, e)
        lines.append(ln)
        print(ln, flush=True)

    # ---- sparse store at a Bosch-like shape (exact vs wave over the
    # coordinate store vs the dense default) — segment_sum lowers to
    # scatter-add on TPU, so the CPU-mesh wins need on-chip numbers
    rng = np.random.default_rng(7)
    ns, fs = 1_000_000, 968
    nnz = int(ns * fs * 0.01)
    Xs = np.zeros((ns, fs), np.float32)
    Xs[rng.integers(0, ns, nnz), rng.integers(0, fs, nnz)] = \
        rng.normal(size=nnz)
    ys = (Xs[:, 0] + Xs[:, 1] > 0.02).astype(np.float64)
    sparse_combos = [
        ("sparse exact", {"tpu_sparse": True, "tpu_growth": "exact"}, 1),
        ("sparse wave8", {"tpu_sparse": True, "tpu_growth": "wave"}, 8),
        ("dense  exact", {"tpu_growth": "exact"}, 1),
    ]
    for name, extra, width in sparse_combos:
        t0 = time.time()
        try:
            dt, auc = run(Xs, ys, "auto", wave_width=width,
                          measured=5, extra=extra)
            ln = ("    bosch1Mx968 %-12s: %.3f s/iter auc=%.4f "
                  "[wall %.0fs]" % (name, dt, auc, time.time() - t0))
        except Exception as e:
            ln = "    bosch1Mx968 %-12s: FAILED (%s)" % (name, e)
        lines.append(ln)
        print(ln, flush=True)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "AB_RESULTS.md")
    header = not os.path.exists(out)
    with open(out, "a") as f:
        if header:
            f.write("# TPU A/B results (tools/tpu_ab.py)\n")
        f.write("\n".join(lines) + "\n")
    print("appended to", out, flush=True)


if __name__ == "__main__":
    main()
