#!/bin/bash
# Round-4 follow-up stage: once the main chain has released the chip,
# capture the never-yet-captured on-chip profiler trace of the wave
# engine (ROADMAP.md "wave-loop residue") at 1M and, time permitting,
# at the flagship 10.5M — ranks the partition scan / split finder /
# dispatch overhead for the next optimization round.
cd /root/repo || exit 1
LOG=/tmp/chain_r04.log
log() { echo "[chain4b] $(date -u +%F\ %T) $*" >> "$LOG"; }
log "armed (waits for chain_r04.sh)"
while pgrep -f "chain_r04\.sh" > /dev/null; do sleep 120; done
# hard stop: leave the chip alone within 75 min of the 12h round end
END=${CHAIN4B_END_EPOCH:-$(( $(date +%s) + 3600 ))}
probe_ok() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
from lightgbm_tpu.utils.common import probe_device
import sys
sys.exit(0 if probe_device(timeout=120) == "tpu" else 1)
EOF
}
while :; do
  now=$(date +%s)
  [ "$now" -ge "$END" ] && { log "budget spent; exit"; exit 0; }
  if probe_ok; then break; fi
  sleep 120
done
if [ "$(date +%s)" -ge "$(( END - 1200 ))" ]; then
  log "no budget for the 1M trace; exit"; exit 0
fi
log "profiling 1M trace"
timeout 1200 python tools/tpu_profile.py 999424 /tmp/tpu_trace_1m > /tmp/profile_1m.out 2>&1
log "profile 1M rc=$?"
if [ "$(date +%s)" -lt "$(( END - 1500 ))" ] && probe_ok; then
  log "profiling flagship trace"
  timeout 1500 python tools/tpu_profile.py 10500000 /tmp/tpu_trace_fs > /tmp/profile_fs.out 2>&1
  log "profile flagship rc=$?"
fi
log "chain4b complete"
