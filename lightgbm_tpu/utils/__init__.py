from .log import Log, LightGBMError
from .config import Config, key_alias_transform, param_dict_to_str
from .random import Random

__all__ = ["Log", "LightGBMError", "Config", "key_alias_transform",
           "param_dict_to_str", "Random"]
