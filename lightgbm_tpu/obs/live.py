"""Live observability plane: in-run HTTP scrape endpoints + watch tail.

Every other obs surface is post-hoc — a JSONL timeline analyzed after
``run_end``.  This module is the in-situ half: a stdlib
``ThreadingHTTPServer`` daemon the observer starts when
``obs_http_port`` is set (port 0 = ephemeral, bound port reported via
``RunObserver.live_url``), serving four read-only endpoints:

* ``/metrics``  — Prometheus textfile exposition of the process-global
  registry (obs/metrics.py), the node-exporter scrape target;
* ``/healthz``  — 200 while the run is healthy, 503 the moment a fatal
  health verdict lands (obs/health.py) or the run aborts — the
  liveness/readiness probe;
* ``/statusz``  — one JSON snapshot: run_header provenance, lifecycle,
  current iteration + EWMA it/s, health verdicts, the latest schema-13
  ``utilization`` rollup, and the merged flight-provider context (the
  serve scheduler's queue depth and the SLO engine's headline ride in
  through the PR-7 registry) — the operator's "what is this run doing
  right now";
* ``/events?after=N`` — JSONL tail of the watchdog ring buffer with a
  monotonic cursor (``X-Obs-Next-After`` response header), the feed
  behind ``obs watch <url>``;
* ``/incidents`` — open/closed incident listing from the incident
  engine (obs/incident.py), including each incident's grouped signals
  and evidence inventory;
* ``/prof?seconds=N`` — on-demand host profile burst (obs/prof.py):
  a synchronous collapsed-stack capture of every thread except the
  handler's own, rendered as Brendan-Gregg folded text.  Loopback
  peers only (the same rule as the POST controls): a capture spends
  real sampling time on the host it profiles.

Schema 15 adds operator CONTROL alongside the reads: ``POST
/trigger/flight`` dumps a flight record on demand and ``POST
/trigger/incident`` opens (or joins) an incident with an ``operator``
signal — on-demand evidence capture while the anomaly is still live.
Both are accepted **only from a loopback peer address**, whatever the
bind address: scraping may be fleet-wide, capture control is local by
construction.

The server thread only READS host-side state the observer already
maintains — no jax import anywhere in this module, no device access, no
fence: scraping a live run costs the hot path nothing (the module is
inside the graftlint hostsync scope to keep it that way).  The POST
handlers write evidence from the handler thread, never touching the
hot path.  Binding defaults to loopback
(``obs_http_addr=127.0.0.1``); exposing the plane on a pod means
choosing a routable bind address deliberately.

The second half is ``watch`` — the ``python -m lightgbm_tpu obs watch``
live-follow renderer.  It tails a growing timeline file (parsing only
complete lines, so a torn write never kills the tail), a per-rank shard
set (``--ranks``, shards discovered via obs/merge.py and iterations
aligned across ranks as they complete), or a live ``/events`` URL, and
renders iteration progress with an it/s sparkline, compile / health /
shed events and SLO verdicts as they happen.  ``--once`` renders what
is currently visible and exits — the CI-friendly snapshot mode.
"""
from __future__ import annotations

import collections
import http.server
import json
import socketserver
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from .metrics import PROMETHEUS_CONTENT_TYPE, REGISTRY
from ..utils.log import Log

__all__ = ["LiveServer", "status_snapshot", "health_verdict", "watch"]


# ======================================================================
# writer side: the in-process scrape server
# ======================================================================

def health_verdict(obs):
    """("ok"|"warn"|"fatal", detail dict) from the observer's host-side
    health state.  Fatal means /healthz serves 503: a recorded fatal
    health event, a fatal monitor verdict, or an aborted close."""
    detail = {}
    status = "ok"
    health = getattr(obs, "health", None)
    if health is not None:
        status = health.verdict()
        detail["counts"] = dict(health.counts)
    if getattr(obs, "_health_fatal", False):
        status = "fatal"
    if getattr(obs, "_lifecycle", "") == "aborted":
        status = "fatal"
        detail["aborted"] = True
    return status, detail


def status_snapshot(obs):
    """The /statusz payload: one JSON-safe dict assembled purely from
    host-side observer state (header, EWMA iteration clock, health,
    latest utilization rollup, flight-provider context)."""
    out = {
        "run": getattr(obs, "run_id", None),
        "rank": getattr(obs, "rank", 0),
        "world_size": getattr(obs, "world_size", 1),
        "lifecycle": getattr(obs, "_lifecycle", "unknown"),
        "iters": getattr(obs, "_iters", 0),
        "events_path": getattr(obs, "events_path", ""),
        "t": time.time(),
    }
    header = getattr(obs, "_header", None)
    if header:
        out["backend"] = header.get("backend")
        out["schema"] = header.get("schema")
        out["devices"] = len(header.get("devices") or ())
        out["timing"] = header.get("timing")
        if header.get("provenance"):
            out["provenance"] = header["provenance"]
    last_it = getattr(obs, "_last_it", None)
    if last_it is not None:
        out["last_it"] = last_it
    ewma = getattr(obs, "_ewma_iter_s", None)
    if ewma:
        out["ewma_iter_s"] = round(float(ewma), 6)
        out["iters_per_sec"] = round(1.0 / float(ewma), 3)
    verdict, detail = health_verdict(obs)
    out["health"] = {"status": verdict}
    out["health"].update(detail)
    util = getattr(obs, "_last_utilization", None)
    if util:
        out["utilization"] = {
            k: util.get(k)
            for k in ("it", "flop_util", "hbm_util", "bound",
                      "headroom_s", "device_kind")
            if util.get(k) is not None}
    ctx_stamp = getattr(obs, "_run_context", None)
    if ctx_stamp:
        # the training loop's stamp_context: iteration, tree count,
        # loop stage — what the run was doing at this instant
        out["context"] = dict(ctx_stamp)
    try:
        ctx = obs.flight_context()
    except Exception:
        ctx = {}
    if ctx:
        # serve queue depth, SLO headline and the incident engine's
        # open/opened counters land here via the flight-provider
        # registry (serve/scheduler.py, obs/incident.py)
        out["flight"] = ctx
    ring = getattr(obs, "_ring", None)
    if ring is not None:
        out["ring"] = {"seq": ring.last_seq, "len": len(ring),
                       "dropped": ring.dropped,
                       "capacity": ring.capacity}
    return out


class _LiveHTTPServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    """ThreadingHTTPServer with daemon handler threads: a scrape in
    flight never blocks interpreter shutdown."""

    daemon_threads = True
    allow_reuse_address = True
    observer = None                    # set by LiveServer before serving


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "lgbm-obs-live"
    protocol_version = "HTTP/1.1"

    # the stdlib default logs one stderr line per request — a scraped
    # training run would drown its own logs
    def log_message(self, fmt, *args):
        pass

    def _send(self, code, ctype, body, headers=()):
        data = body.encode("utf-8") if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("Cache-Control", "no-store")
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _send_json(self, code, payload, headers=()):
        self._send(code, "application/json",
                   json.dumps(payload, default=str) + "\n", headers)

    def do_GET(self):
        obs = self.server.observer
        try:
            parsed = urllib.parse.urlsplit(self.path)
            route = parsed.path.rstrip("/") or "/"
            if route == "/metrics":
                self._send(200, PROMETHEUS_CONTENT_TYPE,
                           REGISTRY.to_prometheus())
            elif route == "/healthz":
                verdict, detail = health_verdict(obs)
                payload = {"status": verdict}
                payload.update(detail)
                self._send_json(200 if verdict != "fatal" else 503,
                                payload)
            elif route == "/statusz":
                self._send_json(200, status_snapshot(obs))
            elif route == "/events":
                q = urllib.parse.parse_qs(parsed.query)
                try:
                    after = int(q.get("after", ["0"])[0])
                except ValueError:
                    after = 0
                seq, recs = obs.ring_tail(after)
                body = "".join(json.dumps(r, default=str) + "\n"
                               for r in recs)
                self._send(200, "application/x-ndjson", body,
                           headers=(("X-Obs-Next-After", str(seq)),))
            elif route == "/incidents":
                self._send_json(200, obs.incidents())
            elif route == "/prof":
                # on-demand host profile burst (obs/prof.py).  Loopback
                # peers only, like the POST controls: the capture spends
                # real sampling time on the host it profiles
                if not self._loopback_peer():
                    self._send_json(403, {"error": "/prof accepts "
                                                   "loopback peers only"})
                else:
                    q = urllib.parse.parse_qs(parsed.query)
                    try:
                        seconds = float(q.get("seconds", ["0.25"])[0])
                    except ValueError:
                        seconds = 0.25
                    seconds = max(0.05, min(5.0, seconds))
                    from .prof import burst, folded_text
                    payload = burst(
                        seconds=seconds,
                        context=getattr(obs, "_run_context", None),
                        source="live")
                    self._send(200, "text/plain; charset=utf-8",
                               folded_text(payload))
            elif route == "/":
                self._send_json(200, {"endpoints": ["/metrics", "/healthz",
                                                    "/statusz", "/events",
                                                    "/incidents",
                                                    "/prof?seconds=N",
                                                    "POST /trigger/flight",
                                                    "POST /trigger/incident"],
                                      "run": getattr(obs, "run_id", None)})
            else:
                self._send_json(404, {"error": "unknown path %s"
                                      % parsed.path})
        except Exception as e:      # a broken scrape must not kill serving
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass

    def _loopback_peer(self):
        peer = self.client_address[0] if self.client_address else ""
        return peer in ("127.0.0.1", "::1", "::ffff:127.0.0.1")

    def do_POST(self):
        """Operator control: on-demand flight dump and incident open.
        Loopback peers only — a routable bind address exposes the READ
        plane fleet-wide, never capture control."""
        obs = self.server.observer
        try:
            # drain the body first (HTTP/1.1 keep-alive contract),
            # whatever the verdict
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            raw = self.rfile.read(length) if length > 0 else b""
            if not self._loopback_peer():
                self._send_json(403, {"error": "control endpoints accept "
                                               "loopback POSTs only"})
                return
            try:
                body = json.loads(raw.decode("utf-8", "replace")) or {}
            except ValueError:
                body = {}
            if not isinstance(body, dict):
                body = {}
            reason = str(body.get("reason") or "operator request")[:200]
            route = urllib.parse.urlsplit(self.path).path.rstrip("/")
            if route == "/trigger/flight":
                path = obs.flight("operator: %s" % reason)
                self._send_json(200, {"triggered": "flight",
                                      "path": path or None})
            elif route == "/trigger/incident":
                iid = obs.incident_signal("operator", {"reason": reason})
                if iid is None:
                    self._send_json(409, {"error": "incident engine off "
                                                   "(obs_incident=false)"})
                else:
                    self._send_json(200, {"triggered": "incident",
                                          "id": iid})
            else:
                self._send_json(404, {"error": "unknown control path %s"
                                      % self.path})
        except Exception as e:      # a broken control call must not kill
            try:                    # the run it observes
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass


class LiveServer:
    """Lifecycle wrapper: bind, serve from a daemon thread, report the
    actual port (``port=0`` binds ephemeral), shut down cleanly."""

    def __init__(self, observer, port, addr="127.0.0.1"):
        self._observer = observer
        self._req_port = int(port)
        self.addr = str(addr or "127.0.0.1")
        self.port = None
        self.url = ""
        self._server = None
        self._thread = None

    def start(self):
        """Bind + serve; returns the URL.  Best-effort by contract: a
        bind failure logs and leaves the plane off rather than killing
        the training run it observes."""
        if self._server is not None:
            return self.url
        try:
            srv = _LiveHTTPServer((self.addr, self._req_port), _Handler)
        except OSError as e:
            Log.warning("obs: live server bind %s:%d failed: %s",
                        self.addr, self._req_port, e)
            return ""
        srv.observer = self._observer
        self._server = srv
        self.port = int(srv.server_address[1])
        self.url = "http://%s:%d" % (self.addr, self.port)
        self._thread = threading.Thread(
            target=srv.serve_forever, kwargs={"poll_interval": 0.1},
            name="lgbm-obs-live", daemon=True)
        self._thread.start()
        Log.debug("obs: live telemetry plane at %s "
                  "(/metrics /healthz /statusz /events)", self.url)
        return self.url

    def stop(self):
        srv, self._server = self._server, None
        if srv is None:
            return
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# ======================================================================
# reader side: `obs watch` — live-follow a timeline, shard set, or URL
# ======================================================================

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width=16):
    vals = list(values)[-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))]
                   for v in vals)


class _FileTail:
    """Incremental JSONL reader over a growing file: parses only
    complete lines, buffering a partial trailing line until the writer
    finishes it — a torn write mid-flush never kills the tail."""

    def __init__(self, path):
        self.path = str(path)
        self._pos = 0
        self._buf = ""

    def poll(self):
        try:
            with open(self.path) as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            return []
        if not chunk:
            return []
        lines = (self._buf + chunk).split("\n")
        self._buf = lines.pop()
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                pass                 # torn write: best-effort tail
        return out


class _UrlTail:
    """Cursor-based poller over a live /events endpoint."""

    def __init__(self, url, timeout_s=5.0):
        base = str(url).rstrip("/")
        if base.endswith("/events"):
            base = base[:-len("/events")]
        self.base = base
        self.after = 0
        self.timeout_s = float(timeout_s)

    def poll(self):
        req = "%s/events?after=%d" % (self.base, self.after)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            nxt = r.headers.get("X-Obs-Next-After")
            body = r.read().decode("utf-8", "replace")
        if nxt is not None:
            try:
                self.after = int(nxt)
            except ValueError:
                pass
        out = []
        for line in body.splitlines():
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
        return out

    def status(self):
        with urllib.request.urlopen(self.base + "/statusz",
                                    timeout=self.timeout_s) as r:
            return json.loads(r.read().decode("utf-8", "replace"))


class WatchRenderer:
    """Fold a stream of timeline events into operator-readable lines:
    iteration progress with an it/s sparkline, compile / health / shed
    events, SLO verdicts, and the run_end footer that ends a follow."""

    def __init__(self, out=None, show_rank=False):
        self.out = out or sys.stdout
        self.show_rank = bool(show_rank)
        self.done = False
        self.status = None
        self.iters = 0
        self._times = collections.deque(maxlen=64)

    def _w(self, s):
        self.out.write(s + "\n")
        try:
            self.out.flush()
        except Exception:
            pass

    def _tag(self, rank):
        return ("[r%s] " % rank) if (self.show_rank and rank is not None) \
            else ""

    def feed(self, rec, rank=None):
        ev = rec.get("ev")
        tag = self._tag(rank if rank is not None else rec.get("rank"))
        if ev == "run_header":
            prov = rec.get("provenance") or {}
            bits = ["run %s" % rec.get("run"),
                    "schema %s" % rec.get("schema"),
                    "backend %s" % rec.get("backend"),
                    "devices %d" % len(rec.get("devices") or ())]
            if int(rec.get("world_size", 1) or 1) > 1:
                bits.append("rank %s/%s" % (rec.get("rank"),
                                            rec.get("world_size")))
            if prov.get("git_rev"):
                bits.append("rev %s%s" % (prov["git_rev"],
                                          "+" if prov.get("git_dirty")
                                          else ""))
            self._w(tag + "▶ " + "  ".join(bits))
        elif ev == "iter":
            self.iters += 1
            dt = float(rec.get("time_s", 0.0))
            self._times.append(dt)
            window = list(self._times)[-8:]
            mean = sum(window) / len(window)
            ips = (1.0 / mean) if mean > 0 else 0.0
            self._w("%sit %-5s %8.4fs  %7.2f it/s  %s"
                    % (tag, rec.get("it"), dt, ips,
                       _sparkline(self._times)))
        elif ev == "compile":
            self._w("%scompile %s: first call %.3fs"
                    % (tag, rec.get("entry"),
                       float(rec.get("first_call_s", 0.0))))
        elif ev == "compile_attr" and int(rec.get("n_compiles", 1)) > 1:
            self._w("%sRECOMPILE %s: %s compiles"
                    % (tag, rec.get("entry"), rec.get("n_compiles")))
        elif ev == "health" and rec.get("check") != "stats":
            self._w("%shealth[%s] %s at it %s: %s"
                    % (tag, rec.get("status"), rec.get("check"),
                       rec.get("it"), rec.get("detail", "")))
        elif ev == "utilization":
            self._w("%sutil it %s: flop %.1f%%  hbm %.1f%%  %s"
                    % (tag, rec.get("it"),
                       100.0 * float(rec.get("flop_util", 0.0)),
                       100.0 * float(rec.get("hbm_util", 0.0)),
                       rec.get("bound", "?")))
        elif ev == "serve_slo":
            overall = rec.get("overall") or {}
            verdicts = rec.get("verdicts") or {}
            bits = ["qps %s" % overall.get("qps", "-")]
            if overall.get("p99_s") is not None:
                bits.append("p99 %.2fms" % (1e3 * overall["p99_s"]))
            for name, v in sorted(verdicts.items()):
                bits.append("%s=%s" % (name, v.upper()))
            if rec.get("alert") == "firing":
                bits.append("ALERT FIRING")
            self._w(tag + "slo: " + "  ".join(bits))
        elif ev == "drift":
            feats = rec.get("features") or []
            top = feats[0] if feats else None
            bits = ["psi_max %.3f" % float(rec.get("psi_max", 0.0))]
            if top:
                bits.append("top %s (psi %.3f)"
                            % (top.get("feature"),
                               float(top.get("psi", 0.0))))
            if rec.get("score_psi") is not None:
                bits.append("score %.3f" % float(rec["score_psi"]))
            bits.append("rows %s" % rec.get("rows"))
            # the WARN styling of the health lines: alerting windows
            # shout, stable ones stay lowercase
            head = ("DRIFT[warn] " if rec.get("alert") == "firing"
                    else "drift: ")
            self._w(tag + head + "  ".join(bits))
        elif ev == "online_quality":
            bits = ["n %s" % rec.get("n")]
            if rec.get("auc") is not None:
                bits.append("auc %.4f" % float(rec["auc"]))
                if rec.get("ref_auc") is not None:
                    bits.append("(train %.4f)" % float(rec["ref_auc"]))
            if rec.get("logloss") is not None:
                bits.append("logloss %.4f" % float(rec["logloss"]))
            self._w(tag + "online: " + "  ".join(bits))
        elif ev == "incident_open":
            sigs = ", ".join(str(s) for s in rec.get("signals") or ())
            self._w("%sINCIDENT OPEN [%s] trigger %s%s"
                    % (tag, rec.get("id"), rec.get("trigger"),
                       ("  -> %s" % rec["dir"]) if rec.get("dir") else ""))
            if sigs:
                self._w("%s  signals: %s" % (tag, sigs))
        elif ev == "incident_close":
            sigs = list(rec.get("signals") or ())
            counts = rec.get("counts") or {}
            total = sum(int(v or 0) for v in counts.values()) or len(sigs)
            self._w("%sINCIDENT CLOSE [%s] %d signal kind(s), %d event(s)"
                    " over %.1fs: %s"
                    % (tag, rec.get("id"), len(sigs), total,
                       float(rec.get("duration_s", 0.0) or 0.0),
                       ", ".join(str(s) for s in sigs)))
        elif ev == "serve_summary":
            shed = int(rec.get("shed_total", 0))
            self._w("%sserve: %s batches  %s rows  shed %d%s"
                    % (tag, rec.get("batches"), rec.get("rows"), shed,
                       "  ⚠" if shed else ""))
        elif ev == "mesh_shrink":
            self._w("%smesh shrink %s -> %s ranks at it %s"
                    % (tag, rec.get("world_size_from"),
                       rec.get("world_size_to"), rec.get("it")))
        elif ev == "run_end":
            self.done = True
            self.status = str(rec.get("status", "ok"))
            self._w("%s■ run end: status=%s  iters=%s"
                    % (tag, self.status, rec.get("iters")))

    def align(self, it, times):
        """One completed cross-rank iteration (--ranks): per-rank fenced
        times + skew, the live slice of the obs/merge.py view."""
        slowest = max(times, key=times.get)
        fastest = min(times, key=times.get)
        skew = times[slowest] - times[fastest]
        rel = skew / times[slowest] if times[slowest] > 0 else 0.0
        self.iters += 1
        self._times.append(times[slowest])
        self._w("it %-5s %s  skew %.1f%% (slowest r%s)  %s"
                % (it,
                   "  ".join("r%s %.4fs" % (r, times[r])
                             for r in sorted(times)),
                   100.0 * rel, slowest, _sparkline(self._times)))

    def render_status(self, status):
        """Footer from a /statusz snapshot (URL mode)."""
        bits = ["lifecycle %s" % status.get("lifecycle"),
                "iters %s" % status.get("iters")]
        if status.get("iters_per_sec") is not None:
            bits.append("%.2f it/s" % status["iters_per_sec"])
        h = status.get("health") or {}
        bits.append("health %s" % h.get("status", "?"))
        util = status.get("utilization")
        if util:
            bits.append("util flop %.1f%% hbm %.1f%% (%s)"
                        % (100.0 * float(util.get("flop_util", 0.0)),
                           100.0 * float(util.get("hbm_util", 0.0)),
                           util.get("bound", "?")))
        serve = (status.get("flight") or {}).get("serve")
        if serve:
            bits.append("queue %s" % serve.get("queue_depth"))
        slo = (status.get("flight") or {}).get("slo")
        if slo:
            overall = slo.get("overall") or {}
            if overall.get("p99_s") is not None:
                bits.append("p99 %.2fms" % (1e3 * overall["p99_s"]))
            if slo.get("alerting"):
                bits.append("SLO ALERT")
        drift = (status.get("flight") or {}).get("drift")
        if drift:
            last = drift.get("last") or {}
            if last.get("psi_max") is not None:
                bits.append("drift psi %.3f" % float(last["psi_max"]))
            if drift.get("alerting"):
                bits.append("DRIFT ALERT")
        inc = (status.get("flight") or {}).get("incidents")
        if inc:
            if inc.get("open"):
                last = inc.get("last") or {}
                bits.append("INCIDENT OPEN (%s)"
                            % (last.get("trigger") or "?"))
            elif inc.get("opened"):
                bits.append("incidents %s" % inc.get("opened"))
        self._w("status: " + "  ".join(bits))


def watch(target, once=False, ranks=False, interval_s=0.5, out=None,
          max_wall_s=0.0):
    """The ``obs watch`` implementation; returns a process exit code.

    ``target`` is a timeline file, a shard base (``--ranks`` tails every
    ``.rN`` sibling, aligning iterations across ranks), or an
    ``http://`` URL of a live plane (its ``/events`` feed).  ``--once``
    renders everything currently visible and exits 0; follow mode runs
    until the tailed run ends (exit 0), the server goes away (exit 0),
    or Ctrl-C.  ``max_wall_s`` is a follow-mode safety stop for
    scripted callers (0 = no limit)."""
    out = out or sys.stdout
    target = str(target)
    is_url = target.startswith(("http://", "https://"))
    renderer = WatchRenderer(out=out, show_rank=ranks)

    if is_url:
        tail = _UrlTail(target)
        tails = [(None, tail)]
    elif ranks:
        from .merge import discover_shards, _shard_rank_of
        try:
            paths = discover_shards(target)
        except OSError as e:
            print("error: %s" % e, file=sys.stderr)
            return 2
        tails = [(_shard_rank_of(p), _FileTail(p)) for p in paths]
        print("watching %d shard(s): %s" % (len(paths),
                                            "  ".join(paths)), file=out)
    else:
        tails = [(None, _FileTail(target))]

    # cross-rank iteration alignment (--ranks): print one line per
    # iteration once every tailed rank has reported it
    by_it = {}
    n_ranks = len(tails)

    def _drain():
        got = 0
        for rank, tail in tails:
            for rec in tail.poll():
                got += 1
                if ranks and rec.get("ev") == "iter":
                    r = rec.get("rank", rank)
                    times = by_it.setdefault(int(rec["it"]), {})
                    times[r] = float(rec.get("time_s", 0.0))
                    if len(times) == n_ranks:
                        renderer.align(rec["it"], by_it.pop(rec["it"]))
                    continue
                renderer.feed(rec, rank=rank)
        return got

    t0 = time.monotonic()
    try:
        total = _drain()
        if once:
            if is_url:
                try:
                    renderer.render_status(tails[0][1].status())
                except Exception as e:
                    print("statusz unavailable: %s" % e, file=sys.stderr)
            if total == 0 and renderer.iters == 0:
                print("no events yet (%s)" % target, file=out)
            return 0
        while not renderer.done:
            if max_wall_s and time.monotonic() - t0 > max_wall_s:
                print("watch: wall limit %.1fs reached" % max_wall_s,
                      file=out)
                return 0
            time.sleep(max(0.05, float(interval_s)))
            try:
                _drain()
            except (OSError, urllib.error.URLError):
                # the live server tore down at run_end before we saw it
                print("watch: source went away (run ended?)", file=out)
                return 0
    except KeyboardInterrupt:
        return 0
    return 0
