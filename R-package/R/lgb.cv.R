# lgb.cv — parity with R-package/R/lgb.cv.R over engine.py cv()
# (stratified/shuffled folds, per-iteration mean/stdv records).

#' Cross validation
#'
#' @param params list of training parameters
#' @param data lgb.Dataset
#' @param nrounds boosting rounds
#' @param nfold number of folds
#' @param stratified stratify folds by label (classification)
#' @param folds optional list of test-index vectors (1-based); overrides
#'   nfold/stratified
#' @export
lgb.cv <- function(params = list(), data, nrounds = 10L, nfold = 5L,
                   label = NULL, stratified = TRUE, folds = NULL,
                   early_stopping_rounds = NULL, eval = NULL,
                   verbose = 1L, seed = 0L, callbacks = list(), ...) {
  if (!lgb.is.Dataset(data)) stop("lgb.cv: data must be an lgb.Dataset")
  lgb <- .lgb_py()
  if (!is.null(label)) setinfo(data, "label", label)
  py_folds <- NULL
  if (!is.null(folds)) {
    n <- dim(data)[1L]
    # length-1 index vectors cross reticulate as bare scalars; box ONLY
    # those (boxing a large vector element-wise is orders slower)
    box1 <- function(v) if (length(v) == 1L) as.list(v) else v
    py_folds <- lapply(folds, function(test_idx) {
      test0 <- as.integer(test_idx - 1L)
      train0 <- as.integer(setdiff(seq_len(n) - 1L, test0))
      list(box1(train0), box1(test0))
    })
  }
  out <- lgb$cv(params = .as_py_params(c(params, list(...))),
                train_set = data, num_boost_round = as.integer(nrounds),
                nfold = as.integer(nfold), stratified = stratified,
                folds = py_folds, metrics = eval,
                early_stopping_rounds = .as_int_or_null(early_stopping_rounds),
                callbacks = if (length(callbacks)) unname(callbacks) else NULL,
                verbose_eval = verbose > 0L, seed = as.integer(seed))
  rec <- reticulate::py_to_r(out)
  structure(list(record_evals = rec,
                 best_iter = max(lengths(rec), 0L)),
            class = "lgb.CVBooster")
}

#' @export
print.lgb.CVBooster <- function(x, ...) {
  cat(sprintf("<lgb.CVBooster: %d recorded metrics over %d iterations>\n",
              length(x$record_evals), x$best_iter))
  invisible(x)
}
