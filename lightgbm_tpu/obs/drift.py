"""Drift & online model-quality monitoring: serving traffic vs the
training-time data world.

Training observability ends the moment the model ships; what a serving
process sees — and how well the frozen trees score it — is exactly the
signal the continuous-training loop needs before "retrain now" can be
anything but a guess.  The benchmarking literature (arxiv 1809.04559)
ties GBDT quality tightly to the input distribution the trees were
grown on, so a feature or score distribution shift is the earliest
actionable warning that train-time AUC parity no longer holds.

Three pieces:

* **Fingerprint** (training side) — ``feature_fingerprint`` captures
  per-feature binned histograms straight from the BinMapper sample (the
  same pass obs/dataquality.py profiles, with the bin counts kept
  instead of discarded), each with its frozen mapper so a serving
  process can re-bin without the dataset; ``attach_scores`` adds the
  raw-score distribution on the training set (quantile-edged histogram,
  plus the converted-output distribution when the objective has one)
  and the final eval snapshot.  The fingerprint persists as one JSON
  ``drift_fingerprint=`` header line in the model text format
  (models/gbdt.py) and as a header field of the pre-binned dataset dir
  (io/binned_format.py), so any serving process loads its reference
  for free.

* **DriftMonitor** (serving side) — hooked into ``ServingPredictor``
  / ``Booster.predict``: bins incoming feature values with the frozen
  mappers (host-side searchsorted + bincount over arrays already in
  hand — zero device work, zero fences) and sketches prediction scores
  into rolling windows.  Every ``obs_drift_every`` rows it computes
  PSI and KS divergence per feature and for the score distribution,
  emits a schema-14 ``drift`` event, updates the
  ``lgbm_drift_psi{feature=...}`` gauges, and drives an alert state
  machine routed through the ``obs_health`` channel (warn-only, like
  slo_burn_rate: drift is a retrain signal — killing the server that
  detected it only makes the outage total).  A delayed-label channel
  (``ServingPredictor.record_outcome``) joins ground truth when it
  arrives for rolling online AUC/logloss vs the training-time
  reference (``online_quality`` events, ``lgbm_online_auc``).  The
  monitor also guards serving-input quality: non-finite or
  out-of-bin-range values — which otherwise vanish into the generic
  missing-bin path — count per feature into
  ``lgbm_serve_input_anomalies_total`` with a first-occurrence health
  warning reusing the dataquality finding shape.

* **render_drift_report** (reader side) — ``python -m lightgbm_tpu obs
  drift <timeline> [--check]``: features ranked by divergence with a
  train-vs-serve histogram diff table; ``--check`` exits 1 on a fired
  drift alert or a timeline with no drift events at all.
"""
from __future__ import annotations

import sys
import threading
import time

import numpy as np

from .metrics import REGISTRY
from ..utils.log import Log

FINGERPRINT_VERSION = 1
# fingerprint covers at most this many features (the dataquality
# MAX_PROFILE_ARRAYS discipline: beyond it the bytes outweigh the story)
MAX_FINGERPRINT_FEATURES = 512
# score histograms use this many quantile bins of the training scores
SCORE_BINS = 20
# Laplace smoothing mass per bin for PSI (keeps ln finite on empty bins)
_SMOOTH = 0.5
# PSI buckets per feature at evaluation time: the raw mapper bins (up
# to max_bin=255) coalesce into this many equal-reference-mass groups.
# PSI's small-sample bias is ~(B-1)*(1/N_ref + 1/N_cur) — over 255 bins
# a 512-row window sits at ~0.5 PSI of pure noise, over 16 groups at
# ~0.03, comfortably under the 0.1 'stable' line (the convention of
# 10-20 PSI buckets exists for exactly this reason)
DRIFT_GROUPS = 16
# PSI interpretation convention: < 0.1 stable, 0.1-0.25 moderate,
# >= 0.25 major shift; the default alert threshold sits between
DEFAULT_PSI_THRESHOLD = 0.2
# an evaluation needs at least this many window rows to be meaningful
MIN_EVAL_ROWS = 64


# ======================================================================
# fingerprint (training side)
# ======================================================================

def feature_fingerprint(bin_mappers, get_col, n_features, sample_size,
                        feature_names=None,
                        max_features=MAX_FINGERPRINT_FEATURES):
    """Per-feature reference histograms from the binning sample.

    Same access pattern as dataquality.profile_columns — ``get_col(f)``
    returns feature f's sampled values — but the bin-aligned counts are
    the product here, not a discarded intermediate: PSI needs mass per
    bin INDEX, aligned with what the frozen mapper will produce at
    serving time.  Features whose mapper cannot discriminate (missing,
    trivial, single-bin) are skipped; a shifted stream cannot drift on
    a feature the model never splits."""
    feats = []
    for f in range(int(n_features)):
        if len(feats) >= max_features:
            Log.warning("drift fingerprint capped at %d features "
                        "(of %d)", max_features, n_features)
            break
        m = bin_mappers[f] if f < len(bin_mappers) else None
        if m is None or m.num_bin <= 1 or m.is_trivial:
            continue
        col = np.asarray(get_col(f), dtype=np.float64)
        bins = np.asarray(m.value_to_bin(col), dtype=np.int64)
        counts = np.bincount(bins, minlength=m.num_bin)
        name = (feature_names[f]
                if feature_names and f < len(feature_names)
                else "Column_%d" % f)
        feats.append({"index": int(f), "name": str(name),
                      "counts": [int(c) for c in counts],
                      "mapper": m.to_dict()})
    return {"version": FINGERPRINT_VERSION,
            "sample_size": int(sample_size),
            "features": feats}


def score_histogram(values, bins=SCORE_BINS):
    """Quantile-edged histogram of a score sample: interior edges at the
    i/bins quantiles (deduplicated), counts per edge interval.  Quantile
    edges make the reference roughly uniform, the shape PSI is most
    sensitive on."""
    v = np.asarray(values, dtype=np.float64).reshape(-1)
    v = v[np.isfinite(v)]
    if v.size == 0:
        return None
    qs = np.linspace(0.0, 1.0, int(bins) + 1)[1:-1]
    edges = np.unique(np.quantile(v, qs))
    counts = np.bincount(np.searchsorted(edges, v, side="left"),
                         minlength=len(edges) + 1)
    return {"edges": [float(e) for e in edges],
            "counts": [int(c) for c in counts]}


def attach_scores(fingerprint, train_score=None, objective=None,
                  eval_results=None):
    """Complete a feature fingerprint with the training-time score
    distribution(s) and the final eval snapshot.  ``train_score`` is
    the (k, num_data) raw-score matrix; the converted-output histogram
    is added when the objective transforms scores (the space a default
    ``predict()`` serves in)."""
    fp = dict(fingerprint or
              {"version": FINGERPRINT_VERSION, "sample_size": 0,
               "features": []})
    scores = {}
    if train_score is not None:
        raw = np.asarray(train_score, dtype=np.float64).reshape(-1)
        h = score_histogram(raw)
        if h is not None:
            scores["raw"] = h
        if objective is not None:
            try:
                conv = np.asarray(objective.convert_output(
                    np.asarray(train_score, dtype=np.float64)))
                if not np.allclose(conv.reshape(-1), raw,
                                   equal_nan=True):
                    h = score_histogram(conv)
                    if h is not None:
                        scores["output"] = h
            except Exception as e:   # fingerprinting must never break train
                Log.warning("drift fingerprint: convert_output failed "
                            "(%s); raw-score reference only", e)
    if scores:
        fp["scores"] = scores
    if eval_results:
        fp["eval"] = [{"dataset": str(r.get("dataset")),
                       "metric": str(r.get("metric")),
                       "value": float(r.get("value"))}
                      for r in eval_results]
    return fp


# ======================================================================
# divergence
# ======================================================================

def psi(ref_counts, cur_counts):
    """Population stability index between two aligned count vectors,
    with Laplace smoothing so empty bins stay finite.  Symmetric-ish,
    >= 0, ~0 for same distribution."""
    p = np.asarray(ref_counts, dtype=np.float64) + _SMOOTH
    q = np.asarray(cur_counts, dtype=np.float64) + _SMOOTH
    p /= p.sum()
    q /= q.sum()
    return float(np.sum((q - p) * np.log(q / p)))


def ks_stat(ref_counts, cur_counts):
    """Kolmogorov-Smirnov statistic over binned data: max |CDF diff|."""
    p = np.asarray(ref_counts, dtype=np.float64)
    q = np.asarray(cur_counts, dtype=np.float64)
    if p.sum() <= 0 or q.sum() <= 0:
        return 0.0
    return float(np.max(np.abs(np.cumsum(p / p.sum())
                               - np.cumsum(q / q.sum()))))


def _bin_diff_table(ref_counts, cur_counts, top=3):
    """The most-shifted bins of one feature: [(bin, ref_frac,
    cur_frac)] ranked by |ref - cur| mass — the per-feature evidence
    row of the report's histogram diff table."""
    p = np.asarray(ref_counts, dtype=np.float64)
    q = np.asarray(cur_counts, dtype=np.float64)
    p = p / p.sum() if p.sum() > 0 else p
    q = q / q.sum() if q.sum() > 0 else q
    order = np.argsort(-np.abs(p - q), kind="stable")[:top]
    return [{"bin": int(b), "ref": round(float(p[b]), 4),
             "cur": round(float(q[b]), 4)} for b in order]


def _auc(scores, labels):
    """Rank-based AUC (average ranks on ties); None when degenerate."""
    y = np.asarray(labels, dtype=np.float64)
    s = np.asarray(scores, dtype=np.float64)
    pos = y > 0
    npos = int(pos.sum())
    nneg = int(y.size - npos)
    if npos == 0 or nneg == 0:
        return None
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(y.size, dtype=np.float64)
    ranks[order] = np.arange(1, y.size + 1, dtype=np.float64)
    # average ranks over tied scores
    sorted_s = s[order]
    i = 0
    while i < y.size:
        j = i
        while j + 1 < y.size and sorted_s[j + 1] == sorted_s[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    return float((ranks[pos].sum() - npos * (npos + 1) / 2.0)
                 / (npos * nneg))


def _logloss(probs, labels):
    p = np.clip(np.asarray(probs, dtype=np.float64), 1e-15, 1 - 1e-15)
    y = np.asarray(labels, dtype=np.float64)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


# ======================================================================
# DriftMonitor (serving side)
# ======================================================================

def _group_map(ref_counts, max_groups=DRIFT_GROUPS):
    """Greedy equal-reference-mass packing of bin index -> group index;
    returns (map array, group count).  Deterministic from the reference
    counts, so every serving process derives the same grouping."""
    ref = np.asarray(ref_counts, dtype=np.float64)
    total = ref.sum()
    if total <= 0 or len(ref) <= max_groups:
        n = max(len(ref), 1)
        return np.arange(n, dtype=np.int64), n
    target = total / max_groups
    gmap = np.empty(len(ref), dtype=np.int64)
    g = 0
    acc = 0.0
    for i, c in enumerate(ref):
        gmap[i] = g
        acc += c
        if acc >= target and g < max_groups - 1:
            g += 1
            acc = 0.0
    return gmap, g + 1


def _anomaly_counter(feature, kind):
    """Per-(feature, kind) series of the serving-input anomaly counter
    (get-or-create; the registry keys one instrument per label set)."""
    return REGISTRY.counter(
        "lgbm_serve_input_anomalies_total",
        "serving-input anomalies (non-finite or out-of-bin-range "
        "feature values) by feature and kind",
        labels={"feature": feature, "kind": kind})


class _FeatureState:
    __slots__ = ("index", "name", "mapper", "gmap", "ref", "counts",
                 "non_finite", "out_of_range", "warned")

    def __init__(self, index, name, mapper, ref):
        self.index = index
        self.name = name
        self.mapper = mapper
        # PSI works over DRIFT_GROUPS equal-reference-mass groups of
        # the raw mapper bins (see the bias note at DRIFT_GROUPS)
        self.gmap, n_groups = _group_map(ref)
        self.ref = np.bincount(
            self.gmap, weights=np.asarray(ref, dtype=np.float64),
            minlength=n_groups).astype(np.int64)
        self.counts = np.zeros(n_groups, dtype=np.int64)
        self.non_finite = 0
        self.out_of_range = 0
        self.warned = False


class DriftMonitor:
    """Rolling-window drift + online-quality monitor for a serving
    process.  Thread-safe; fed host-side numpy from the submit path —
    binning is searchsorted/bincount on arrays the caller already
    materialized, so monitoring adds no device work and no fences.

    ``clock`` is injectable for tests, mirroring obs/serve.SloEngine.
    """

    def __init__(self, fingerprint, observer=None, mode="warn",
                 every_rows=2048, window_rows=8192,
                 psi_threshold=DEFAULT_PSI_THRESHOLD, topk=10,
                 min_labels=100, clock=time.monotonic):
        from .events import NULL_OBSERVER
        from .health import MODES
        from ..io.binning import BinMapper
        self.observer = observer if observer is not None else NULL_OBSERVER
        mode = str(mode or "warn").strip().lower()
        if mode not in MODES:
            raise ValueError("drift mode %r (expected off/warn/fatal)"
                             % (mode,))
        self.mode = mode
        self.every_rows = max(1, int(every_rows))
        self.window_rows = max(self.every_rows, int(window_rows))
        self.psi_threshold = float(psi_threshold)
        self.topk = max(1, int(topk))
        self.min_labels = max(1, int(min_labels))
        self.clock = clock
        fp = fingerprint or {}
        self._feats = []
        for entry in fp.get("features") or ():
            try:
                m = BinMapper.from_dict(entry["mapper"])
                self._feats.append(_FeatureState(
                    int(entry["index"]), str(entry["name"]),
                    m, entry["counts"]))
            except (KeyError, TypeError, ValueError) as e:
                Log.warning("drift: skipping malformed fingerprint "
                            "feature (%s)", e)
        # score references per space ("raw" / "output"); serving output
        # lands in whichever space the route produced
        self._score_ref = {}
        self._score_counts = {}
        for space, h in (fp.get("scores") or {}).items():
            edges = np.asarray(h.get("edges") or (), dtype=np.float64)
            self._score_ref[space] = (edges,
                                      np.asarray(h.get("counts"),
                                                 dtype=np.int64))
            self._score_counts[space] = np.zeros(len(edges) + 1,
                                                 dtype=np.int64)
        self._ref_eval = list(fp.get("eval") or ())
        self._lock = threading.Lock()
        self._rows = 0             # lifetime rows observed
        self._win_rows = 0         # rows in the current rolling window
        self._since_eval = 0
        # delayed-label join: id -> (prob-space score); bounded so a
        # caller that never records outcomes cannot leak memory
        self._pending = {}
        self._pending_cap = 65536
        self._outcomes = []        # rolling (prob, label) pairs
        self._outcome_cap = max(self.window_rows, 4096)
        self.alerting = False
        self.alerts_fired = 0
        self.alerts_cleared = 0
        self._last_psi = {}        # host-side snapshot for /statusz
        self._last_eval_out = None
        self._last_quality = None
        self._m_psi_max = REGISTRY.gauge(
            "lgbm_drift_psi_max",
            "largest per-feature PSI vs the training fingerprint at "
            "the last drift evaluation")
        self._m_alerts = REGISTRY.counter(
            "lgbm_drift_alerts_total",
            "drift alerts fired against the training fingerprint")

    @property
    def enabled(self):
        return bool(self._feats or self._score_ref)

    # ------------------------------------------------------------ writing
    def observe_features(self, X):
        """One block of submitted feature rows (host float64).  Bins
        every fingerprinted feature with its frozen mapper and counts
        input anomalies; triggers an evaluation when ``every_rows``
        rows have accumulated since the last one."""
        X = np.asarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        n = X.shape[0]
        if n == 0 or not self._feats:
            return
        warn_feats = []
        with self._lock:
            for fs in self._feats:
                if fs.index >= X.shape[1]:
                    continue
                col = X[:, fs.index]
                finite = np.isfinite(col)
                n_bad = int(col.size - finite.sum())
                n_oor = 0
                m = fs.mapper
                from ..io.binning import NUMERICAL
                if m.bin_type == NUMERICAL and np.isfinite(m.min_val) \
                        and np.isfinite(m.max_val):
                    fv = col[finite]
                    n_oor = int(((fv < m.min_val)
                                 | (fv > m.max_val)).sum())
                if n_bad:
                    fs.non_finite += n_bad
                    _anomaly_counter(fs.name, "non_finite").inc(n_bad)
                if n_oor:
                    fs.out_of_range += n_oor
                    _anomaly_counter(fs.name, "out_of_range").inc(n_oor)
                if (n_bad or n_oor) and not fs.warned:
                    fs.warned = True
                    warn_feats.append((fs, n_bad, n_oor))
                bins = np.asarray(m.value_to_bin(col), dtype=np.int64)
                np.clip(bins, 0, len(fs.gmap) - 1, out=bins)
                fs.counts += np.bincount(fs.gmap[bins],
                                         minlength=len(fs.counts))
            self._rows += n
            self._win_rows += n
            self._since_eval += n
            due = self._since_eval >= self.every_rows
            if due:
                self._since_eval = 0
        for fs, n_bad, n_oor in warn_feats:
            self._warn_anomaly(fs, n_bad, n_oor)
        if due:
            self.evaluate()

    def _warn_anomaly(self, fs, n_bad, n_oor):
        """First-occurrence serving-input quality warning, reusing the
        dataquality finding shape (severity/feature/flag/message) so
        every data-quality consumer reads one dialect.  These values
        previously vanished into the generic missing-bin path."""
        flag = "non_finite" if n_bad else "out_of_range"
        finding = {
            "severity": "warning", "feature": int(fs.index),
            "flag": flag,
            "message": "serving input anomaly on feature %d (%s): %d "
                       "non-finite, %d out-of-bin-range value(s) — "
                       "binned into the missing bin; see "
                       "lgbm_serve_input_anomalies_total"
                       % (fs.index, fs.name, n_bad, n_oor)}
        Log.warning("serve input[warn] %s", finding["message"])
        if self.mode == "off":
            return
        obs = self.observer
        if obs.enabled:
            obs.event("health", check="serve_input", status="warn",
                      it=-1, detail=finding)

    def observe_scores(self, scores, raw=False):
        """One block of prediction outputs.  ``raw`` selects which
        training-time reference distribution these scores compare
        against; multiclass blocks flatten (the reference did too)."""
        space = "raw" if raw else "output"
        ref = self._score_ref.get(space)
        if ref is None and not raw:
            # an objective with no output transform serves raw scores
            space, ref = "raw", self._score_ref.get("raw")
        if ref is None:
            return
        edges, _ = ref
        v = np.asarray(scores, dtype=np.float64).reshape(-1)
        v = v[np.isfinite(v)]
        if v.size == 0:
            return
        idx = np.searchsorted(edges, v, side="left")
        due = False
        with self._lock:
            self._score_counts[space] += np.bincount(
                idx, minlength=len(edges) + 1)
            if not self._feats:
                # score-only fingerprint: the cadence counters have no
                # feature stream to ride, so rows count here instead
                self._rows += v.size
                self._win_rows += v.size
                self._since_eval += v.size
                due = self._since_eval >= self.every_rows
                if due:
                    self._since_eval = 0
        if due:
            self.evaluate()

    def note_predictions(self, ids, scores, raw=False):
        """Remember per-request prediction scores (probability space)
        keyed by caller ids, awaiting ``record_outcome``.  Bounded:
        oldest entries fall out once the cap is hit."""
        s = np.asarray(scores, dtype=np.float64).reshape(-1)
        if raw:   # store probabilities so online logloss is well-defined
            s = 1.0 / (1.0 + np.exp(-s))
        with self._lock:
            for i, sid in enumerate(ids):
                if i >= s.size:
                    break
                if len(self._pending) >= self._pending_cap:
                    self._pending.pop(next(iter(self._pending)))
                self._pending[sid] = float(s[i])

    def record_outcome(self, ids, labels):
        """The delayed-label channel: join ground-truth labels with the
        predictions recorded for those ids.  Returns how many joined.
        Online AUC/logloss emit on the next evaluation once
        ``min_labels`` outcomes accumulated."""
        labels = np.asarray(labels, dtype=np.float64).reshape(-1)
        joined = 0
        with self._lock:
            for i, sid in enumerate(ids):
                if i >= labels.size:
                    break
                p = self._pending.pop(sid, None)
                if p is None:
                    continue
                self._outcomes.append((p, float(labels[i])))
                joined += 1
            if len(self._outcomes) > self._outcome_cap:
                del self._outcomes[:len(self._outcomes)
                                   - self._outcome_cap]
        return joined

    # --------------------------------------------------------- evaluation
    def evaluate(self, force=False):
        """Compute per-feature + score divergence over the current
        window, emit ``drift`` (and ``online_quality``) events, update
        gauges and the alert state machine.  Rolling windows: once the
        window reaches ``window_rows`` the counts reset so stale
        traffic cannot mask fresh drift."""
        with self._lock:
            empty = self._win_rows < (1 if force else
                                      min(MIN_EVAL_ROWS,
                                          self.every_rows))
            if empty:
                if not force:
                    return None
                # the window just reset (or nothing was ever observed):
                # no divergence to score, but joined outcomes must
                # still leave their online_quality verdict at close
                outcomes = list(self._outcomes)
                pending = len(self._pending)
        if empty:
            self._emit_quality(outcomes, pending)
            return None
        with self._lock:
            feats = []
            for fs in self._feats:
                if fs.counts.sum() <= 0:
                    continue
                feats.append({
                    "feature": fs.name, "index": fs.index,
                    "psi": round(psi(fs.ref, fs.counts), 4),
                    "ks": round(ks_stat(fs.ref, fs.counts), 4),
                    "bins": _bin_diff_table(fs.ref, fs.counts)})
            score = {}
            for space, (edges, ref_counts) in self._score_ref.items():
                cur = self._score_counts[space]
                if cur.sum() <= 0:
                    continue
                score[space] = {
                    "psi": round(psi(ref_counts, cur), 4),
                    "ks": round(ks_stat(ref_counts, cur), 4),
                    "n": int(cur.sum())}
            anomalies = {fs.name: {"non_finite": fs.non_finite,
                                   "out_of_range": fs.out_of_range}
                         for fs in self._feats
                         if fs.non_finite or fs.out_of_range}
            rows, win_rows = self._rows, self._win_rows
            outcomes = list(self._outcomes)
            pending = len(self._pending)
            if self._win_rows >= self.window_rows:
                for fs in self._feats:
                    fs.counts[:] = 0
                for c in self._score_counts.values():
                    c[:] = 0
                self._win_rows = 0
        feats.sort(key=lambda f: -f["psi"])
        psi_max = feats[0]["psi"] if feats else 0.0
        score_psi = max((s["psi"] for s in score.values()), default=0.0)
        self._m_psi_max.set(psi_max)
        for f in feats[:self.topk]:
            REGISTRY.gauge(
                "lgbm_drift_psi",
                "per-feature PSI vs the training fingerprint at the "
                "last drift evaluation (top-k features only)",
                labels={"feature": f["feature"]}).set(f["psi"])
        for space, s in score.items():
            REGISTRY.gauge(
                "lgbm_drift_score_psi",
                "prediction-score PSI vs the training distribution",
                labels={"space": space}).set(s["psi"])
        transition = self._update_alert(psi_max, score_psi, feats)
        out = {"rows": rows, "window_rows": win_rows,
               "psi_max": psi_max, "score_psi": round(score_psi, 4),
               "alert": "firing" if self.alerting else "clear"}
        self._last_psi = {f["feature"]: f["psi"]
                          for f in feats[:self.topk]}
        self._last_eval_out = out
        obs = self.observer
        if obs.enabled:
            obs.event("drift", rows=rows, window_rows=win_rows,
                      psi_max=psi_max, score_psi=round(score_psi, 4),
                      features=feats[:self.topk], score=score,
                      anomalies=anomalies,
                      threshold=self.psi_threshold,
                      alert=out["alert"])
        if transition is not None:
            self._emit_alert(transition, psi_max, score_psi, feats)
        self._emit_quality(outcomes, pending)
        return out

    def _update_alert(self, psi_max, score_psi, feats):
        # Feature PSI drives the alert.  The score reference is the
        # *in-sample* training-score distribution: an overfit model
        # concentrates train scores near the extremes, so out-of-sample
        # serving scores legitimately diverge from it even on i.i.d.
        # traffic — alerting on that would page on every well-fit
        # model.  Score PSI is still reported (events, gauges, the
        # ``obs drift`` table) and takes over as the alert signal only
        # when the fingerprint carries no feature references.
        worst = psi_max if self._feats else score_psi
        if not self.alerting and worst >= self.psi_threshold:
            self.alerting = True
            self.alerts_fired += 1
            self._m_alerts.inc()
            return "firing"
        # hysteresis: clear at half-threshold so a distribution
        # hovering at the line doesn't flap the pager
        if self.alerting and worst < 0.5 * self.psi_threshold:
            self.alerting = False
            self.alerts_cleared += 1
            return "cleared"
        return None

    def _emit_alert(self, transition, psi_max, score_psi, feats):
        top = feats[0] if feats else None
        signal = psi_max if feats else score_psi
        detail = {"psi_max": psi_max,
                  "score_psi": round(score_psi, 4),
                  "threshold": self.psi_threshold,
                  "top_feature": top["feature"] if top else None,
                  "cleared": transition == "cleared"}
        if transition == "firing":
            Log.warning(
                "drift: alert FIRING — PSI %.3f >= %.3f vs the "
                "training fingerprint (top feature %s); the model is "
                "scoring traffic it was not trained on — retrain-now "
                "signal", signal, self.psi_threshold,
                top["feature"] if top else "score distribution")
        else:
            Log.warning("drift: alert cleared (PSI %.3f)", signal)
        if self.mode == "off":
            return
        obs = self.observer
        if not obs.enabled:
            return
        from .health import _WARN_ONLY
        status = ("warn" if (self.mode == "warn"
                             or "drift" in _WARN_ONLY) else "fatal")
        if transition == "cleared":
            status = "ok"
        obs.event("health", check="drift", status=status, it=-1,
                  detail=detail)
        obs.flush()

    def _emit_quality(self, outcomes, pending):
        """Rolling online quality from the joined (prediction, label)
        pairs, compared against the training-time eval reference."""
        if len(outcomes) < self.min_labels:
            return
        probs = np.asarray([p for p, _ in outcomes])
        labels = np.asarray([y for _, y in outcomes])
        auc = _auc(probs, labels)
        ll = _logloss(probs, labels)
        ref_auc = ref_ll = None
        for r in self._ref_eval:
            name = str(r.get("metric", "")).lower()
            if ref_auc is None and "auc" in name:
                ref_auc = float(r["value"])
            if ref_ll is None and "logloss" in name:
                ref_ll = float(r["value"])
        rec = {"n": len(outcomes), "logloss": round(ll, 6),
               "pending": pending}
        if auc is not None:
            rec["auc"] = round(auc, 6)
            REGISTRY.gauge(
                "lgbm_online_auc",
                "rolling online AUC from delayed-label outcomes").set(
                    round(auc, 6))
        REGISTRY.gauge(
            "lgbm_online_logloss",
            "rolling online logloss from delayed-label outcomes").set(
                round(ll, 6))
        if ref_auc is not None:
            rec["ref_auc"] = ref_auc
        if ref_ll is not None:
            rec["ref_logloss"] = ref_ll
        self._last_quality = rec
        obs = self.observer
        if obs.enabled:
            obs.event("online_quality", **rec)

    # ------------------------------------------------------------ reading
    def summary(self):
        return {"rows": self._rows, "alerting": self.alerting,
                "alerts_fired": self.alerts_fired,
                "alerts_cleared": self.alerts_cleared,
                "features": len(self._feats),
                "threshold": self.psi_threshold}

    def headline(self):
        """Live one-dict drift digest for /statusz (registered as a
        flight provider by ServingPredictor)."""
        out = self.summary()
        if self._last_eval_out is not None:
            out["last"] = dict(self._last_eval_out)
        if self._last_psi:
            out["psi"] = dict(self._last_psi)
        if self._last_quality is not None:
            out["online"] = dict(self._last_quality)
        return out

    def close(self):
        """Final forced evaluation so a short-lived server still leaves
        its drift verdict on the timeline."""
        try:
            self.evaluate(force=True)
        except Exception as e:     # forensics must never break close
            Log.warning("drift: final evaluation failed: %s", e)


# ======================================================================
# reader side: timeline -> drift report (obs drift)
# ======================================================================

def drift_metrics(events):
    """Fold a timeline's drift / online_quality events into one dict."""
    drifts = [e for e in events if e.get("ev") == "drift"]
    quality = [e for e in events if e.get("ev") == "online_quality"]
    alerts = [e for e in events if e.get("ev") == "health"
              and e.get("check") == "drift"]
    out = {"present": bool(drifts or quality)}
    if not out["present"]:
        return out
    if drifts:
        out["last"] = drifts[-1]
        out["evals"] = len(drifts)
        out["psi_max"] = max(float(e.get("psi_max", 0.0))
                             for e in drifts)
    if quality:
        out["quality"] = quality[-1]
    fired = [a for a in alerts if a.get("status") != "ok"]
    out["alerts"] = {"fired": len(fired),
                     "cleared": len(alerts) - len(fired),
                     "active": bool(alerts)
                     and alerts[-1].get("status") != "ok"}
    return out


def drift_headline(events):
    """One-line drift digest for ``obs summary``."""
    m = drift_metrics(events)
    if not m.get("present"):
        return None
    head = {"evals": m.get("evals", 0),
            "psi_max": m.get("psi_max"),
            "alerts_fired": m["alerts"]["fired"]}
    q = m.get("quality")
    if q:
        head["online_auc"] = q.get("auc")
    return head


def render_drift_report(events, out=None, check=False):
    """Print the drift report; returns the list of problems (empty =
    no drift).  ``--check`` semantics: a timeline with no drift events
    is a problem too — a gate that silently skipped monitoring must
    not pass as 'no drift'."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    m = drift_metrics(events)
    problems = []
    w("== drift report ==")
    if not m.get("present"):
        w("no drift events in this timeline (enable obs_drift_every "
          "on a fingerprinted model)")
        problems.append("no drift events in timeline")
        return problems
    last = m.get("last") or {}
    w("evaluations %d   rows %s   window %s   psi_max %.4f   alert %s"
      % (m.get("evals", 0), last.get("rows", "-"),
         last.get("window_rows", "-"),
         float(m.get("psi_max", 0.0)), last.get("alert", "-")))
    feats = last.get("features") or []
    if feats:
        w("")
        w("features by divergence (last evaluation, PSI threshold %g):"
          % last.get("threshold", DEFAULT_PSI_THRESHOLD))
        w("  %-24s %8s %8s  %s" % ("feature", "psi", "ks",
                                   "train-vs-serve bins (ref%->cur%)"))
        for f in feats:
            bins = "  ".join(
                "b%d %.1f->%.1f" % (b["bin"], 100 * b["ref"],
                                    100 * b["cur"])
                for b in (f.get("bins") or ()))
            w("  %-24s %8.4f %8.4f  %s"
              % (str(f.get("feature"))[:24], float(f.get("psi", 0.0)),
                 float(f.get("ks", 0.0)), bins))
    score = last.get("score") or {}
    for space in sorted(score):
        s = score[space]
        w("  score[%s]: psi %.4f  ks %.4f  n %d"
          % (space, float(s.get("psi", 0.0)), float(s.get("ks", 0.0)),
             int(s.get("n", 0))))
    anomalies = last.get("anomalies") or {}
    if anomalies:
        w("")
        w("input anomalies (lgbm_serve_input_anomalies_total):")
        for name in sorted(anomalies):
            a = anomalies[name]
            w("  %-24s non_finite %d  out_of_range %d"
              % (name[:24], int(a.get("non_finite", 0)),
                 int(a.get("out_of_range", 0))))
    q = m.get("quality")
    w("")
    if q:
        ref = "".join(filter(None, [
            ("  (train auc %.4f)" % q["ref_auc"])
            if q.get("ref_auc") is not None else "",
            ("  (train logloss %.4f)" % q["ref_logloss"])
            if q.get("ref_logloss") is not None else ""]))
        w("online quality: n %d  auc %s  logloss %s%s"
          % (int(q.get("n", 0)),
             "-" if q.get("auc") is None else "%.4f" % q["auc"],
             "-" if q.get("logloss") is None else "%.4f" % q["logloss"],
             ref))
    else:
        w("online quality: no outcomes recorded "
          "(ServingPredictor.record_outcome)")
    a = m["alerts"]
    w("drift alerts: %d fired, %d cleared%s"
      % (a["fired"], a["cleared"], "  [ACTIVE]" if a["active"] else ""))
    if a["fired"]:
        problems.append("%d drift alert(s) fired" % a["fired"])
    w("")
    if problems:
        w("verdict: %s — %s" % ("FAIL" if check else "DRIFTING",
                                "; ".join(problems)))
    else:
        w("verdict: %s" % ("PASS" if check else "stable"))
    return problems
