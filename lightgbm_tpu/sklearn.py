"""scikit-learn API wrappers — parity with python-package/sklearn.py:15-623.

LGBMModel/LGBMRegressor/LGBMClassifier/LGBMRanker with the same constructor
surface, custom objective closure wrapping (grad/hess signatures,
sklearn.py:15-121), eval-set handling, early stopping, pickling via the text
model format.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train
from .utils.log import LightGBMError

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.preprocessing import LabelEncoder
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover - sklearn is in the image
    _SKLEARN_INSTALLED = False

    class BaseEstimator:  # type: ignore
        pass

    class ClassifierMixin:  # type: ignore
        pass

    class RegressorMixin:  # type: ignore
        pass


def _objective_function_wrapper(func: Callable):
    """Wrap sklearn-style objective fun(y_true, y_pred [,group]) -> (g,h)
    into the engine's fobj(preds, dataset) (sklearn.py:15-76)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError("Self-defined objective should have 2 or 3 arguments")
        return grad, hess
    return inner


def _eval_function_wrapper(func: Callable):
    """Wrap fun(y_true, y_pred [,weight [,group]]) -> (name, val, is_higher_better)
    (sklearn.py:78-121)."""

    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError("Self-defined eval function should have 2, 3, or 4 arguments")
    return inner


class LGBMModel(BaseEstimator):
    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, max_bin: int = 255,
                 subsample_for_bin: int = 200000, objective: Optional[str] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 1, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: int = 0, n_jobs: int = -1, silent: bool = True,
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self._other_params = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._objective_default = "regression"

    # sklearn clone support
    def get_params(self, deep=True):
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "max_bin", "subsample_for_bin", "objective",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "silent")}
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for k, v in params.items():
            setattr(self, k, v)
            if k not in self.get_params():
                self._other_params[k] = v
        return self

    def _make_params(self) -> Dict[str, Any]:
        obj = self.objective
        fobj = None
        if callable(obj):
            fobj = _objective_function_wrapper(obj)
            obj = "none"
        elif obj is None:
            obj = self._objective_default
        params = {
            "boosting_type": self.boosting_type,
            "objective": obj,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "subsample_for_bin": self.subsample_for_bin,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "seed": self.random_state,
            "verbose": -1 if self.silent else 1,
        }
        params.update(self._other_params)
        return params, fobj

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose=False, feature_name="auto",
            categorical_feature="auto", callbacks=None):
        """Fit the estimator (scikit-learn contract)."""
        params, fobj = self._make_params()
        feval = _eval_function_wrapper(eval_metric) if callable(eval_metric) else None
        if isinstance(eval_metric, (str, list)):
            params["metric"] = eval_metric

        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            free_raw_data=False)
        valid_sets: List[Dataset] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(train_set.create_valid(
                    vx, vy, weight=vw, group=vg, init_score=vi))
        self._evals_result = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=eval_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            feature_name=feature_name, categorical_feature=categorical_feature,
            callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = np.asarray(X).shape[1]
        return self

    def predict(self, X, raw_score=False, num_iteration=-1):
        """Predict targets for X."""
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        return self._Booster.predict(
            X, raw_score=raw_score,
            num_iteration=self._resolve_num_iteration(num_iteration))

    @property
    def n_features_(self):
        return self._n_features

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def booster_(self):
        return self._Booster

    def _resolve_num_iteration(self, num_iteration: int) -> int:
        """<=0 falls back to the early-stopped best iteration (shared by
        predict/predict_proba/apply so they always agree)."""
        if num_iteration <= 0 and self._best_iteration > 0:
            return self._best_iteration
        return num_iteration

    def apply(self, X, num_iteration=-1):
        """Per-row leaf indices of every tree (sklearn.py apply); uses
        the early-stopped best iteration like predict()."""
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster.predict(
            X, num_iteration=self._resolve_num_iteration(num_iteration),
            pred_leaf=True)

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster.feature_importance()

    # deprecated method-form aliases kept for drop-in compatibility
    # (sklearn.py:457-463)
    def booster(self):
        """Deprecated alias of :attr:`booster_` (emits DeprecationWarning)."""
        warnings.warn("Use attribute booster_ instead.",
                      DeprecationWarning)
        return self.booster_

    def feature_importance(self):
        """Deprecated alias of :attr:`feature_importances_` (emits
        DeprecationWarning)."""
        warnings.warn("Use attribute feature_importances_ instead.",
                      DeprecationWarning)
        return self.feature_importances_


class LGBMRegressor(LGBMModel, RegressorMixin):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._objective_default = "regression"


class LGBMClassifier(LGBMModel, ClassifierMixin):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._objective_default = "binary"

    def fit(self, X, y, **kwargs):
        """Fit the classifier; encodes labels and picks the objective."""
        self._le = LabelEncoder().fit(y) if _SKLEARN_INSTALLED else None
        if self._le is not None:
            y_enc = self._le.transform(y)
            self._classes = self._le.classes_
        else:
            self._classes = np.unique(y)
            y_enc = np.searchsorted(self._classes, y)
        self._n_classes = len(self._classes)
        if self._n_classes > 2:
            if not callable(self.objective):
                self.objective = self.objective or "multiclass"
            self._other_params.setdefault("num_class", self._n_classes)
            self._objective_default = "multiclass"
        else:
            self._objective_default = "binary"
        return super().fit(X, y_enc.astype(np.float64), **kwargs)

    def predict(self, X, raw_score=False, num_iteration=-1):
        probs = self.predict_proba(X, raw_score=raw_score,
                                   num_iteration=num_iteration)
        if raw_score:
            return probs
        if probs.ndim > 1:
            idx = np.argmax(probs, axis=1)
        else:
            idx = (probs > 0.5).astype(int)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=-1):
        """Class probability estimates for X."""
        out = super().predict(X, raw_score=raw_score,
                              num_iteration=num_iteration)
        if raw_score:
            return out
        if out.ndim == 1:
            return np.stack([1.0 - out, out], axis=1) if not raw_score else out
        return out

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._objective_default = "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        """Fit the ranker; group gives query sizes."""
        if group is None:
            raise ValueError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)
