# lgb.train — parity with R-package/R/lgb.train.R (valids, eval record,
# early stopping, continued training) over the Python engine
# (engine.py:17-203 semantics).

#' Train a boosting model
#'
#' @param params list of training parameters (reference names/aliases)
#' @param data training lgb.Dataset
#' @param nrounds boosting rounds
#' @param valids named list of validation lgb.Datasets
#' @param early_stopping_rounds stop when no metric improves this long
#' @param init_model path or lgb.Booster to continue from
#' @param verbose verbosity (<=0 silences per-iteration lines)
#' @param categorical_feature forwarded to the Dataset when given
#' @param colnames feature names override
#' @export
lgb.train <- function(params = list(), data, nrounds = 10L,
                      valids = list(), early_stopping_rounds = NULL,
                      init_model = NULL, verbose = 1L, eval_freq = 1L,
                      categorical_feature = NULL, colnames = NULL,
                      callbacks = list(), ...) {
  if (!lgb.is.Dataset(data)) stop("lgb.train: data must be an lgb.Dataset")
  lgb <- .lgb_py()
  if (!is.null(categorical_feature)) {
    lgb.Dataset.set.categorical(data, categorical_feature)
  }
  if (!is.null(colnames)) {
    data$set_feature_name(as.list(as.character(colnames)))
  }
  evals <- reticulate::dict()
  bst <- lgb$train(
    params = .as_py_params(c(params, list(...))), train_set = data,
    num_boost_round = as.integer(nrounds),
    valid_sets = unname(valids), valid_names = names(valids),
    early_stopping_rounds = .as_int_or_null(early_stopping_rounds),
    init_model = init_model,
    evals_result = evals,
    callbacks = if (length(callbacks)) unname(callbacks) else NULL,
    verbose_eval = if (verbose > 0L) as.integer(eval_freq) else FALSE)
  bst <- .lgb_tag_booster(bst)
  attr(bst, "record_evals") <- reticulate::py_to_r(evals)
  attr(bst, "best_iter") <- as.integer(bst$best_iteration)
  bst
}
