"""Rank-encoded device bulk prediction (ops/predict.py RankedPredictor):
leaf ROUTING must be bit-equal to the host f64 predictor — the ranks
encode every f64 threshold compare — including the zero-range default
redirect, NaN-goes-right, and integer-cast categorical equality; scores
match the host f64 sums to f32 rounding."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import predict as dev_predict


def _train(X, y, params, rounds=10):
    p = dict({"verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5},
             **params)
    return lgb.train(p, lgb.Dataset(X, label=y, params=p),
                     num_boost_round=rounds)


def _routing_and_scores(bst, Xq):
    g = bst._gbdt
    g._materialize()
    k = g.num_tree_per_iteration
    rp = dev_predict.build_ranked_predictor(g.models, k, Xq.shape[1])
    V, D = dev_predict.rank_encode(rp, Xq)
    import jax.numpy as jnp
    leaves = np.asarray(dev_predict.ranked_leaf_indices_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D)))
    score = np.asarray(dev_predict.ranked_predict_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D), k))
    host_leaves = np.stack(
        [t.predict_leaf_index(np.asarray(Xq, np.float64))
         for t in g.models], axis=1)
    host_raw = np.zeros((len(Xq), k))
    for t, tree in enumerate(g.models):
        host_raw[:, t % k] += tree.predict(np.asarray(Xq, np.float64))
    return leaves, host_leaves, score, host_raw


def test_routing_bit_equal_binary_with_zeros_and_nan():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(4000, 6))
    X[rng.random(X.shape) < 0.2] = 0.0          # exercise zero redirect
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = _train(X, y, {"objective": "binary"})
    Xq = X.copy()
    Xq[rng.random(Xq.shape) < 0.05] = np.nan    # NaN -> right
    Xq[rng.random(Xq.shape) < 0.05] = 0.0
    leaves, host_leaves, score, host_raw = _routing_and_scores(bst, Xq)
    np.testing.assert_array_equal(leaves, host_leaves)
    np.testing.assert_allclose(score, host_raw, rtol=2e-6, atol=2e-6)


def test_routing_bit_equal_categorical_multiclass():
    rng = np.random.default_rng(4)
    X = rng.normal(size=(3000, 5))
    X[:, 0] = rng.integers(0, 12, size=3000)
    X[:, 1] = rng.integers(0, 5, size=3000)
    y = rng.integers(0, 3, size=3000).astype(np.float64)
    bst = _train(X, y, {"objective": "multiclass", "num_class": 3,
                        "categorical_feature": [0, 1]}, rounds=5)
    Xq = X.copy()
    Xq[:50, 0] = 99.0                           # unseen category
    leaves, host_leaves, score, host_raw = _routing_and_scores(bst, Xq)
    np.testing.assert_array_equal(leaves, host_leaves)
    np.testing.assert_allclose(score, host_raw, rtol=2e-6, atol=2e-6)


def test_bulk_predict_engages_and_matches(monkeypatch):
    """tpu_predict=true forces the device path through Booster.predict;
    results match the host path (tpu_predict=false) to f32 rounding."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(2500, 6))
    y = X[:, 0] * 2 + X[:, 2] + 0.1 * rng.normal(size=2500)
    bst = _train(X, y, {"objective": "regression"})
    g = bst._gbdt
    g.config = g.config.copy_with(tpu_predict="true")
    p_dev = bst.predict(X)
    calls = {"n": 0}
    orig_one = dev_predict.ranked_predict_device
    orig_sh = dev_predict.ranked_predict_sharded

    def spy_one(*a, **kw):
        calls["n"] += 1
        return orig_one(*a, **kw)

    def spy_sh(*a, **kw):
        calls["n"] += 1
        return orig_sh(*a, **kw)
    # multi-device backends route through the sharded program instead
    monkeypatch.setattr(dev_predict, "ranked_predict_device", spy_one)
    monkeypatch.setattr(dev_predict, "ranked_predict_sharded", spy_sh)
    g.config = g.config.copy_with(tpu_predict="true")
    g._ranked_pred_key = None
    p_dev2 = bst.predict(X)
    assert calls["n"] >= 1, "device path did not engage"
    g.config = g.config.copy_with(tpu_predict="false")
    p_host = bst.predict(X)
    np.testing.assert_allclose(p_dev, p_host, rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(p_dev2, p_host, rtol=2e-6, atol=2e-6)


def test_loaded_model_device_predict(tmp_path):
    """A Booster loaded from a model FILE (real-valued thresholds only)
    routes identically on device."""
    rng = np.random.default_rng(6)
    X = rng.normal(size=(2000, 5))
    y = (X[:, 0] - 0.3 * X[:, 4] > 0).astype(np.float64)
    bst = _train(X, y, {"objective": "binary"})
    fn = str(tmp_path / "m.txt")
    bst.save_model(fn)
    loaded = lgb.Booster(model_file=fn)
    g = loaded._gbdt
    g._materialize()
    rp = dev_predict.build_ranked_predictor(
        g.models, g.num_tree_per_iteration, X.shape[1])
    V, D = dev_predict.rank_encode(rp, X)
    import jax.numpy as jnp
    leaves = np.asarray(dev_predict.ranked_leaf_indices_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D)))
    host_leaves = np.stack(
        [t.predict_leaf_index(np.asarray(X, np.float64))
         for t in g.models], axis=1)
    np.testing.assert_array_equal(leaves, host_leaves)

def test_sharded_predict_matches_single_device():
    """ranked_predict_sharded over the 8-device CPU mesh is bit-identical
    to the single-device program — prediction is pure data parallelism
    (rows shard, trees replicate, zero collectives)."""
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(11)
    X = rng.normal(size=(1003, 6))          # deliberately not %8 == 0
    X[rng.random(X.shape) < 0.1] = 0.0
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = _train(X, y, {"objective": "binary"})
    g = bst._gbdt
    g._materialize()
    k = g.num_tree_per_iteration
    rp = dev_predict.build_ranked_predictor(g.models, k, X.shape[1])
    V, D = dev_predict.rank_encode(rp, X)
    single = np.asarray(dev_predict.ranked_predict_device(
        rp.dev, jnp.asarray(V), jnp.asarray(D), k))
    sharded, nrows = dev_predict.ranked_predict_sharded(
        rp, V, D, k, devices=jax.devices()[:8])
    assert nrows == len(X)
    np.testing.assert_array_equal(np.asarray(sharded)[:nrows], single)
    # ctx is cached: a second call reuses the replicated tree stack
    ctx1 = rp._shard_ctx
    sharded2, _ = dev_predict.ranked_predict_sharded(
        rp, V, D, k, devices=jax.devices()[:8])
    assert rp._shard_ctx is ctx1
    np.testing.assert_array_equal(np.asarray(sharded2), np.asarray(sharded))


def test_sharded_predict_through_booster(monkeypatch):
    """tpu_predict=true on a multi-device backend routes Booster.predict
    through the sharded program and matches the host predictor."""
    rng = np.random.default_rng(12)
    X = rng.normal(size=(2000, 5))
    y = X[:, 0] - X[:, 3] + 0.1 * rng.normal(size=2000)
    bst = _train(X, y, {"objective": "regression"})
    g = bst._gbdt
    calls = {"n": 0}
    orig = dev_predict.ranked_predict_sharded

    def spy(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)
    monkeypatch.setattr(dev_predict, "ranked_predict_sharded", spy)
    g.config = g.config.copy_with(tpu_predict="true")
    p_dev = bst.predict(X)
    assert calls["n"] >= 1, "sharded path did not engage"
    g.config = g.config.copy_with(tpu_predict="false")
    p_host = bst.predict(X)
    np.testing.assert_allclose(p_dev, p_host, rtol=2e-6, atol=2e-6)


def test_chunked_pipeline_predict_matches(monkeypatch):
    """The one-deep chunk pipeline assembles multi-chunk predictions in
    the right slots (chunk forced tiny so several chunks flow through a
    single predict call)."""
    from lightgbm_tpu.models import gbdt as gbdt_mod
    rng = np.random.default_rng(13)
    X = rng.normal(size=(1500, 5))
    y = X[:, 0] - 0.4 * X[:, 2] + 0.05 * rng.normal(size=1500)
    bst = _train(X, y, {"objective": "regression"})
    g = bst._gbdt
    g.config = g.config.copy_with(tpu_predict="false")
    host = bst.predict(X)
    calls = {"n": 0}
    real_encode = dev_predict.rank_encode

    def spy(rp, part):
        calls["n"] += 1
        return real_encode(rp, part)
    monkeypatch.setattr(dev_predict, "rank_encode", spy)
    monkeypatch.setattr(gbdt_mod.GBDT, "_predict_chunk_rows",
                        staticmethod(lambda nf, nd: 400))
    g.config = g.config.copy_with(tpu_predict="true")
    g._ranked_pred_key = None
    piped = bst.predict(X)
    assert calls["n"] == 4, calls     # 1500 rows / 400-row chunks
    np.testing.assert_allclose(piped, host, rtol=2e-6, atol=2e-6)


def test_score_update_pallas_bit_equal():
    """tpu_score_update=pallas (compare-select kernel) must be BIT-equal
    to the XLA gather form — same clipped f32 leaf values selected and
    added once per row (ops/predict.py)."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import (_update_score_gather,
                                          _update_score_pallas,
                                          kMaxTreeOutput)
    rng = np.random.default_rng(11)
    for n, L in [(5000, 255), (8192, 31), (777, 7)]:
        score = rng.normal(size=n).astype(np.float32)
        # include out-of-range sentinels: both engines clamp to [0, L-1]
        lid = rng.integers(-1, L + 1, size=n).astype(np.int32)
        lv = rng.normal(size=L).astype(np.float32) * 60  # hits the clamp
        scale = np.float32(1.7)
        want = _update_score_gather(jnp.asarray(score), jnp.asarray(lid),
                                    jnp.asarray(lv), jnp.asarray(scale))
        vals = jnp.clip(jnp.asarray(lv) * scale,
                        -kMaxTreeOutput, kMaxTreeOutput)
        got = _update_score_pallas(jnp.asarray(score), jnp.asarray(lid),
                                   vals, interpret=True)
        assert np.array_equal(np.asarray(want), np.asarray(got)), (n, L)


def test_score_update_engine_validation():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    import lightgbm_tpu as lgb
    import pytest
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.train({"objective": "binary", "num_boost_round": 1,
                   "tpu_score_update": "vmem", "verbose": -1},
                  lgb.Dataset(X, label=y))
    # explicit gather trains
    bst = lgb.train({"objective": "binary", "num_boost_round": 2,
                     "tpu_score_update": "gather", "verbose": -1},
                    lgb.Dataset(X, label=y))
    assert bst.predict(X).shape == (300,)
    # round-5 promoted auto (BENCH_NOTES.md "Armed decks", measured
    # bit-equal + faster at the 10.5M flagship): auto resolves to the
    # pallas engine — the dispatch in ops/predict.py still falls back
    # to the gather off-TPU / at num_leaves>512 / on f64 scores, so
    # training on CPU must keep working
    bst2 = lgb.train({"objective": "binary", "num_boost_round": 2,
                      "verbose": -1}, lgb.Dataset(X, label=y))
    assert bst2._gbdt._score_engine == "pallas"
    assert bst2.predict(X).shape == (300,)
