#!/usr/bin/env python
"""CI smoke for the measured kernel autotuner (ops/autotune.py) — on CPU.

Exercises the full decide -> probe -> persist -> reuse path without a
TPU by installing the injectable bench hook (deterministic synthetic
timings, no kernels executed) and forcing the wave growth schedule:

  run 1 (cold cache): measure mode probes >0 cells, emits one
         autotune_decision with source "measured", writes the cache;
  run 2 (warm cache): zero probe waves, source "cache", same winning
         cell — the contract bench_compare's autotune_overhead_s
         metric gates in production.

Also asserts `obs explain` renders the decision section.  Exits
nonzero on any violation.  See docs/Autotuning.md.

``--fused`` runs the fused-iteration smoke instead (ops/fused_iter.py,
docs/FusedIteration.md): trains with ``tpu_fused_iter=on`` on CPU,
asserts the model is bit-identical to the staged chain, that the
``fused_iter`` entry compiled, and that the fused run passes the same
same-signature-recompile check as ``obs recompiles --check`` (the
single-compile contract — a fused program that recompiles per
iteration would silently give back everything fusion buys).
"""
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fake_bench(cell, bucket):
    """Synthetic s_per_wave: wider is faster, bf16 beats hilo, ct pays a
    startup tax at this scale, compaction a small win.  Deterministic, so
    the winner is stable across runs and platforms."""
    s = 1.0 / max(1, cell.wave_width)
    if cell.hist_hilo:
        s += 0.1
    if cell.hist_mode == "pallas_ct":
        s += 0.5
    if cell.compact:
        s -= 0.01
    return s


def events_of(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def train_once(lgb, X, y, cache_dir, events_path):
    params = {
        "objective": "binary", "num_leaves": 15, "max_bin": 255,
        "min_data_in_leaf": 5, "verbose": -1,
        "tpu_growth": "wave", "tpu_histogram_mode": "pallas_t",
        "tpu_autotune": "measure", "tpu_autotune_cache":
            os.path.join(cache_dir, "autotune_cache.json"),
        "obs_events_path": events_path,
    }
    lgb.train(params, lgb.Dataset(X, label=y, params=params),
              num_boost_round=2)
    return events_of(events_path)


def fused_main():
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import query

    rng = np.random.default_rng(1)
    X = rng.standard_normal((1500, 10)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float32)

    fails = []

    def check(cond, msg):
        if not cond:
            fails.append(msg)
            print("FAIL: %s" % msg)

    with tempfile.TemporaryDirectory() as tmp:
        ev_path = os.path.join(tmp, "fused.jsonl")
        fused_params = {
            "objective": "binary", "num_leaves": 15,
            "min_data_in_leaf": 5, "verbose": -1,
            "tpu_fused_iter": "on", "obs_events_path": ev_path,
            "obs_compile": True,
            # the fused program hides the staged g/h the health/audit
            # instruments read between stages
            "obs_health": "off", "obs_split_audit": False,
            "obs_importance_every": 0, "obs_ledger_dir": "",
        }
        staged_params = dict(fused_params, tpu_fused_iter="off",
                             obs_events_path="")
        bst_f = lgb.train(fused_params,
                          lgb.Dataset(X, label=y, params=fused_params),
                          num_boost_round=6)
        bst_s = lgb.train(staged_params,
                          lgb.Dataset(X, label=y, params=staged_params),
                          num_boost_round=6)

        check(bst_f._gbdt._fused_state[0] is not None,
              "tpu_fused_iter=on did not resolve to the fused program")
        check(bst_f.model_to_string() == bst_s.model_to_string(),
              "fused model differs from the staged chain")
        check((bst_f.predict(X) == bst_s.predict(X)).all(),
              "fused predictions differ from the staged chain")

        evs = events_of(ev_path)
        check(any(e.get("ev") == "compile"
                  and e.get("entry") == "fused_iter" for e in evs),
              "fused run never compiled the fused_iter entry")
        iters = [e for e in evs if e.get("ev") == "iter"]
        check(bool(iters) and all(
            e.get("host_orchestration_s", -1.0) >= 0.0 for e in iters),
            "fused timeline missing host_orchestration_s")

        # the `obs recompiles --check` gate on the fused timeline: no
        # entry may recompile a signature it already compiled
        import io
        buf = io.StringIO()
        thrash = query.render_recompiles(evs, out=buf)
        check(thrash is False,
              "fused run thrashed the jit cache:\n%s" % buf.getvalue())

    if fails:
        print("fused smoke: %d failure(s)" % len(fails))
        return 1
    print("fused smoke: OK (fused == staged over 6 rounds, "
          "single fused_iter compile)")
    return 0


def main():
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops import autotune

    rng = np.random.default_rng(0)
    X = rng.standard_normal((2000, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)

    fails = []

    def check(cond, msg):
        if not cond:
            fails.append(msg)
            print("FAIL: %s" % msg)

    with tempfile.TemporaryDirectory() as tmp:
        autotune.install_probe_hooks(bench=fake_bench)
        try:
            ev1 = train_once(lgb, X, y, tmp,
                             os.path.join(tmp, "run1.jsonl"))
            ev2 = train_once(lgb, X, y, tmp,
                             os.path.join(tmp, "run2.jsonl"))
        finally:
            autotune.clear_probe_hooks()

        d1 = [e for e in ev1 if e.get("ev") == "autotune_decision"]
        p1 = [e for e in ev1 if e.get("ev") == "autotune_probe"]
        d2 = [e for e in ev2 if e.get("ev") == "autotune_decision"]
        p2 = [e for e in ev2 if e.get("ev") == "autotune_probe"]

        check(len(d1) == 1, "run1: expected 1 decision, got %d" % len(d1))
        check(len(p1) > 0, "run1: expected >0 probes (cold cache)")
        check(d1 and d1[0].get("source") == "measured",
              "run1: source %r != 'measured'" % (d1 and d1[0].get("source")))
        check(len(d2) == 1, "run2: expected 1 decision, got %d" % len(d2))
        check(len(p2) == 0,
              "run2: expected 0 probes on warm cache, got %d" % len(p2))
        check(d2 and d2[0].get("source") == "cache",
              "run2: source %r != 'cache'" % (d2 and d2[0].get("source")))
        check(d2 and d2[0].get("cache_hit") is True, "run2: cache_hit false")
        if d1 and d2:
            check(d1[0].get("cell") == d2[0].get("cell"),
                  "cached cell differs from measured winner: %r vs %r"
                  % (d1[0].get("cell"), d2[0].get("cell")))
        cache = os.path.join(tmp, "autotune_cache.json")
        check(os.path.exists(cache), "cache file not written")
        if os.path.exists(cache):
            with open(cache) as f:
                blob = json.load(f)
            check(blob.get("entries"), "cache file has no entries")

        import io

        from lightgbm_tpu.obs import query
        buf = io.StringIO()
        query.render_explain(
            query.load_timeline(os.path.join(tmp, "run1.jsonl")), out=buf)
        check("autotune" in buf.getvalue(),
              "obs explain does not mention autotune")

    if fails:
        print("autotune smoke: %d failure(s)" % len(fails))
        return 1
    print("autotune smoke: OK (run1 probed %d cells -> %s; "
          "run2 cache hit, 0 probes)"
          % (len(p1), d1[0]["cell"] if d1 else "?"))
    return 0


if __name__ == "__main__":
    sys.exit(fused_main() if "--fused" in sys.argv[1:] else main())
