"""Live observability plane (lightgbm_tpu/obs/live.py).

Covers the in-run HTTP scrape server (all read endpoints, ephemeral
port-0 binding, teardown at run_end, the /healthz 503 flip on a fatal
health verdict, the /events cursor protocol), the `obs watch` live
tail (single file, growing file with a concurrent writer, multi-rank
shard set, URL mode), the opt-in default (no server unless
obs_http_port is set), the EventWriter time-based flush, and the
in-progress `obs summary` handling of a timeline with no run_end.
"""
import io
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from lightgbm_tpu.obs import observer_from_config
from lightgbm_tpu.obs.events import (NULL_OBSERVER, EventWriter,
                                     RingBuffer, RunObserver)
from lightgbm_tpu.obs.live import watch
from lightgbm_tpu.obs.query import (load_timeline, main as query_main,
                                    render_summary, timeline_metrics)
from lightgbm_tpu.utils.config import Config


def _get(url, timeout=5.0):
    """(status, headers, body) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def _live_obs(tmp_path, **kw):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"),
                      timing="off", http_port=0, **kw)
    assert obs.live_url.startswith("http://127.0.0.1:")
    return obs


def _run_a_bit(obs, iters=3):
    obs.run_header("cpu", [{"id": 0, "kind": "cpu"}],
                   {"num_leaves": 31}, {})
    for it in range(iters):
        obs.iter_begin(it)
        obs.iter_end(it)


# ---------------------------------------------------------------- server

def test_port_zero_binds_ephemeral_and_tears_down(tmp_path):
    obs = _live_obs(tmp_path)
    url = obs.live_url
    port = int(url.rsplit(":", 1)[1])
    assert port > 0                      # 0 requested, real port bound
    code, _, _ = _get(url + "/healthz")
    assert code == 200
    obs.close()
    assert obs.live_url == ""
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(url + "/healthz", timeout=1.0)


def test_metrics_endpoint_is_prometheus_text(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs)
        code, headers, body = _get(obs.live_url + "/metrics")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert "lgbm_train_iterations_total" in body
        assert "# TYPE" in body
    finally:
        obs.close()


def test_statusz_snapshot_schema(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs, iters=4)
        code, _, body = _get(obs.live_url + "/statusz")
        assert code == 200
        s = json.loads(body)
        assert s["lifecycle"] == "train"
        assert s["iters"] == 4 and s["last_it"] == 3
        assert s["backend"] == "cpu" and s["devices"] == 1
        assert s["health"]["status"] == "ok"
        assert s["ewma_iter_s"] > 0 and s["iters_per_sec"] > 0
        assert s["ring"]["seq"] >= s["ring"]["len"] > 0
        assert s["events_path"].endswith("ev.jsonl")
    finally:
        obs.close()


def test_events_endpoint_cursor_protocol(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs, iters=2)
        code, headers, body = _get(obs.live_url + "/events?after=0")
        assert code == 200
        assert headers["Content-Type"].startswith("application/x-ndjson")
        recs = [json.loads(l) for l in body.splitlines()]
        assert [r["ev"] for r in recs][:1] == ["run_header"]
        cursor = int(headers["X-Obs-Next-After"])
        assert cursor == len(recs)
        # nothing newer than the cursor -> empty tail, same cursor
        code, headers, body = _get(obs.live_url
                                   + "/events?after=%d" % cursor)
        assert code == 200 and body == ""
        assert int(headers["X-Obs-Next-After"]) == cursor
        # one more iteration -> exactly the new records
        obs.iter_begin(2)
        obs.iter_end(2)
        _, headers, body = _get(obs.live_url + "/events?after=%d" % cursor)
        new = [json.loads(l) for l in body.splitlines()]
        assert all(r["ev"] == "iter" for r in new) and len(new) == 1
    finally:
        obs.close()


def test_healthz_flips_503_on_fatal_health_event(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs)
        code, _, body = _get(obs.live_url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        obs.event("health", check="loss_divergence", status="fatal",
                  it=2, detail={"factor": 9.0})
        code, _, body = _get(obs.live_url + "/healthz")
        assert code == 503 and json.loads(body)["status"] == "fatal"
        code, _, body = _get(obs.live_url + "/statusz")
        assert json.loads(body)["health"]["status"] == "fatal"
    finally:
        obs.close()


def test_unknown_route_404_and_index(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        code, _, _ = _get(obs.live_url + "/nope")
        assert code == 404
        code, _, body = _get(obs.live_url + "/")
        assert code == 200
        assert set(json.loads(body)["endpoints"]) == {
            "/metrics", "/healthz", "/statusz", "/events", "/incidents",
            "/prof?seconds=N", "POST /trigger/flight",
            "POST /trigger/incident"}
    finally:
        obs.close()


def test_no_server_unless_param_set(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "ev.jsonl"), timing="off")
    try:
        assert obs.live_url == ""
        assert obs._live is None
    finally:
        obs.close()
    cfg = Config({"obs_events_path": str(tmp_path / "e2.jsonl")})
    obs = observer_from_config(cfg)
    try:
        assert obs.live_url == ""
    finally:
        obs.close()
    assert NULL_OBSERVER.live_url == ""
    assert NULL_OBSERVER.ensure_live_server(0) == ""


def test_http_port_alone_enables_observer(tmp_path):
    cfg = Config({"obs_http_port": 0})
    obs = observer_from_config(cfg)
    try:
        assert obs is not NULL_OBSERVER
        assert obs.enabled
        assert obs.live_url.startswith("http://127.0.0.1:")
    finally:
        obs.close()
    # default stays the null observer
    assert observer_from_config(Config({})) is NULL_OBSERVER


def test_ensure_live_server_idempotent_and_closed_guard(tmp_path):
    obs = _live_obs(tmp_path)
    url = obs.live_url
    assert obs.ensure_live_server(0) == url       # second call: same plane
    obs.close()
    assert obs.ensure_live_server(0) == ""        # closed observer: off


# ---------------------------------------------------------------- ring

def test_ring_tail_cursor():
    ring = RingBuffer(capacity=4)
    for i in range(6):                  # wraps: only 4 newest retained
        ring.append({"ev": "iter", "it": i})
    seq, recs = ring.tail(0)
    assert seq == 6
    assert [r["it"] for r in recs] == [2, 3, 4, 5]
    seq2, recs2 = ring.tail(seq)
    assert seq2 == 6 and recs2 == []
    _, recs3 = ring.tail(4)
    assert [r["it"] for r in recs3] == [4, 5]
    # snapshot keeps its seq-free contract (flight records)
    assert ring.snapshot()[-1] == {"ev": "iter", "it": 5}


# ---------------------------------------------------------------- writer

def test_event_writer_time_based_flush(tmp_path):
    path = tmp_path / "t.jsonl"
    w = EventWriter(path, flush_every=1000, flush_interval_s=0.05)
    w.emit({"ev": "iter", "it": 0})     # within interval: may sit buffered
    time.sleep(0.08)
    w.emit({"ev": "iter", "it": 1})     # interval elapsed -> flush
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 2
    w.close()


def test_event_writer_interval_zero_disables_clock(tmp_path):
    path = tmp_path / "t.jsonl"
    w = EventWriter(path, flush_every=1000, flush_interval_s=0.0)
    w.emit({"ev": "iter", "it": 0})
    time.sleep(0.02)
    w.emit({"ev": "iter", "it": 1})
    assert path.read_text() == ""       # count trigger only
    w.close()


# ---------------------------------------------------------------- watch

def _write_timeline(path, iters=3, run_end=True, rank=None):
    with open(path, "w") as f:
        hdr = {"ev": "run_header", "run": "r1", "schema": 13,
               "backend": "cpu", "devices": [{"id": 0}], "params": {},
               "context": {}, "timing": "off", "provenance": {},
               "t": time.time()}
        if rank is not None:
            hdr["rank"], hdr["world_size"] = rank, 2
        f.write(json.dumps(hdr) + "\n")
        for it in range(iters):
            rec = {"ev": "iter", "it": it, "run": "r1",
                   "time_s": 0.01 * (1 + (rank or 0)), "phases": {},
                   "fenced": False, "t": time.time()}
            if rank is not None:
                rec["rank"] = rank
            f.write(json.dumps(rec) + "\n")
        if run_end:
            f.write(json.dumps({"ev": "run_end", "status": "ok",
                                "iters": iters, "phase_totals": {},
                                "entries": {}, "run": "r1",
                                "t": time.time()}) + "\n")


def test_watch_once_renders_snapshot(tmp_path):
    path = tmp_path / "ev.jsonl"
    _write_timeline(path, iters=3)
    out = io.StringIO()
    assert watch(str(path), once=True, out=out) == 0
    text = out.getvalue()
    assert "run r1" in text and "backend cpu" in text
    assert "it 0" in text and "it/s" in text
    assert "run end: status=ok" in text


def test_watch_once_while_writer_appends(tmp_path):
    """--once against a timeline another thread is actively growing:
    renders what is visible, tolerates a torn trailing line."""
    path = tmp_path / "ev.jsonl"
    stop = threading.Event()

    def writer():
        with open(path, "w") as f:
            f.write(json.dumps({"ev": "run_header", "run": "r1",
                                "schema": 13, "backend": "cpu",
                                "devices": [], "timing": "off"}) + "\n")
            f.flush()
            it = 0
            while not stop.is_set():
                f.write(json.dumps({"ev": "iter", "it": it,
                                    "time_s": 0.001}) + "\n")
                f.flush()
                it += 1
                time.sleep(0.002)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        deadline = time.time() + 5.0
        while not path.exists() and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)                 # let a few iters land
        out = io.StringIO()
        assert watch(str(path), once=True, out=out) == 0
        text = out.getvalue()
        assert "run r1" in text and "it 0" in text
        assert "no events yet" not in text
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_watch_follow_ends_at_run_end(tmp_path):
    path = tmp_path / "ev.jsonl"

    def writer():
        time.sleep(0.05)
        _write_timeline(path, iters=2, run_end=True)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    out = io.StringIO()
    rc = watch(str(path), interval_s=0.05, out=out, max_wall_s=10.0)
    t.join(timeout=5.0)
    assert rc == 0
    assert "run end: status=ok" in out.getvalue()


def test_watch_once_empty_target(tmp_path):
    out = io.StringIO()
    assert watch(str(tmp_path / "missing.jsonl"), once=True, out=out) == 0
    assert "no events yet" in out.getvalue()


def test_watch_ranks_aligns_shards(tmp_path):
    base = tmp_path / "ev.jsonl"
    _write_timeline(str(base) + ".r0", iters=2, rank=0)
    _write_timeline(str(base) + ".r1", iters=2, rank=1)
    out = io.StringIO()
    assert watch(str(base), once=True, ranks=True, out=out) == 0
    text = out.getvalue()
    assert "watching 2 shard(s)" in text
    assert "r0 0.0100s" in text and "r1 0.0200s" in text
    assert "skew" in text and "slowest r1" in text


def test_watch_ranks_missing_shards_exit_2(tmp_path):
    assert watch(str(tmp_path / "none.jsonl"), once=True, ranks=True,
                 out=io.StringIO()) == 2


def test_watch_url_mode_live_server(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs, iters=3)
        out = io.StringIO()
        assert watch(obs.live_url, once=True, out=out) == 0
        text = out.getvalue()
        assert "it 0" in text
        assert "status: lifecycle train" in text    # /statusz footer
    finally:
        obs.close()


def test_watch_cli_dispatch(tmp_path, capsys):
    path = tmp_path / "ev.jsonl"
    _write_timeline(path, iters=2)
    assert query_main(["watch", str(path), "--once"]) == 0
    assert "run end" in capsys.readouterr().out


# --------------------------------------------------- in-progress summary

def test_summary_reports_in_progress_without_run_end(tmp_path):
    path = tmp_path / "ev.jsonl"
    _write_timeline(path, iters=3, run_end=False)
    events = load_timeline(str(path))
    m = timeline_metrics(events)
    assert m["status"] == "in_progress"
    assert m["in_progress"] is True
    assert 0.0 <= m["last_event_age_s"] < 60.0
    out = io.StringIO()
    render_summary(events, out=out)
    text = out.getvalue()
    assert "run in progress" in text and "obs watch" in text


def test_summary_finished_run_not_in_progress(tmp_path):
    path = tmp_path / "ev.jsonl"
    _write_timeline(path, iters=3, run_end=True)
    m = timeline_metrics(load_timeline(str(path)))
    assert m.get("status") == "ok"
    assert "in_progress" not in m


def test_prof_endpoint_returns_folded_burst(tmp_path):
    obs = _live_obs(tmp_path)
    try:
        _run_a_bit(obs)
        code, headers, body = _get(obs.live_url + "/prof?seconds=0.1")
        assert code == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert body.startswith("# samples=")
        # unparseable seconds falls back to the default burst length
        code, _, body = _get(obs.live_url + "/prof?seconds=bogus")
        assert code == 200 and body.startswith("# samples=")
        code, _, idx = _get(obs.live_url + "/")
        assert "/prof" in idx
    finally:
        obs.close()
