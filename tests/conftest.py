"""Test harness config: force the CPU backend with 8 virtual devices.

This is the moral equivalent of the reference testing its GPU code on an
OpenCL CPU driver and MPI single-process (.travis.yml:15-25,45-59): the
multi-device psum paths run on a virtual 8-device CPU mesh, no TPU pod
needed (SURVEY.md §4).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
