"""Serving-tier observability (lightgbm_tpu/obs/serve + the scheduler's
overload protection).

What production actually pages on: the rolling SLO engine's burn-rate
alert must fire on a real breach and clear on recovery, admission
control must shed with ``ServeOverloadError`` (never silently), sampled
request traces must carry their span breakdown, and a wedged serve
worker must leave a flight record naming the queue state it died
holding."""
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.obs import RunObserver, read_events
from lightgbm_tpu.obs.events import SCHEMA_VERSION, validate_event
from lightgbm_tpu.obs.serve import (SloEngine, render_serve_report,
                                    route_kind, serve_metrics)
from lightgbm_tpu.serve import MicrobatchScheduler, ServeOverloadError


def _runner(route, feats):
    return feats[:, :1] * 2.0


class _Capture:
    """Observer stub for SloEngine unit tests: records event() calls."""
    enabled = True

    def __init__(self):
        self.events = []

    def event(self, ev, **kw):
        self.events.append(dict(kw, ev=ev))

    def flush(self):
        pass


# ------------------------------------------------------------- SLO engine
def test_route_kind_collapses_tuples():
    assert route_kind(("dev", True)) == "dev"
    assert route_kind(("contrib", 28)) == "contrib"
    assert route_kind("host") == "host"


def test_burn_rate_alert_fires_and_clears():
    obs = _Capture()
    t = [0.0]
    eng = SloEngine(observer=obs, mode="warn", p99_ms=10.0,
                    window_s=6.0, every_s=1.0, clock=lambda: t[0])
    # three seconds of requests ALL over the 10ms target: both burn
    # windows hit 1.0/0.01 = 100x of the error budget
    for sec in range(3):
        t[0] = float(sec)
        for _ in range(20):
            eng.record(("dev", True), 0.5)
    t[0] = 3.0
    eng.evaluate(t[0])
    assert eng.alerting and eng.alerts_fired == 1
    fired = [e for e in obs.events if e["ev"] == "health"]
    assert fired, "no health event on alert transition"
    assert fired[0]["check"] == "slo_burn_rate"
    assert fired[0]["status"] == "warn"        # warn-only, never fatal
    assert fired[0]["detail"]["burn_long"] >= 2.0

    # recovery: fast requests push the short-window burn under threshold
    for sec in range(4, 12):
        t[0] = float(sec)
        for _ in range(20):
            eng.record(("dev", True), 0.001)
    t[0] = 12.0
    eng.evaluate(t[0])
    assert not eng.alerting and eng.alerts_cleared == 1
    cleared = [e for e in obs.events if e["ev"] == "health"][-1]
    assert cleared["status"] == "ok" and cleared["detail"]["cleared"]
    # alert count never re-fired during recovery
    assert eng.alerts_fired == 1
    snaps = [e for e in obs.events if e["ev"] == "serve_slo"]
    assert snaps and snaps[-1]["alert"] == "clear"


def test_slo_snapshot_events_schema_valid(tmp_path):
    path = str(tmp_path / "slo.jsonl")
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    t = [0.0]
    eng = SloEngine(observer=obs, p99_ms=100.0, qps=1.0, window_s=12.0,
                    every_s=1.0, clock=lambda: t[0])
    for sec in range(3):
        t[0] = float(sec)
        for _ in range(20):
            eng.record(("dev", True), 0.002)
    eng.close()                     # forced final snapshot
    obs.close()
    evs = read_events(path)         # schema-validates every record
    slos = [e for e in evs if e["ev"] == "serve_slo"]
    assert slos
    last = slos[-1]
    assert last["verdicts"] == {"p99": "ok", "qps": "ok"}
    assert last["routes"]["dev"]["n"] == 60
    assert last["targets"] == {"p99_ms": 100.0, "qps": 1.0}


# ------------------------------------------------------ overload protection
def test_queue_limit_sheds_with_overload_error():
    gate = threading.Event()

    def runner(route, feats):
        gate.wait(5.0)
        return feats[:, :1]

    sched = MicrobatchScheduler(runner, max_batch=8, max_delay_ms=1.0,
                                queue_limit=2)
    try:
        # the first request wedges the worker inside the runner; the
        # next two fill the bounded queue; the fourth must shed
        first = sched.submit("r", np.zeros((1, 2)), 1)
        time.sleep(0.1)
        ok = [sched.submit("r", np.zeros((1, 2)), 1) for _ in range(2)]
        shed = sched.submit("r", np.zeros((1, 2)), 1)
        with pytest.raises(ServeOverloadError) as ei:
            shed.result(timeout=1)
        assert ei.value.reason == "queue_full"
        gate.set()
        first.result(timeout=5)
        for f in ok:
            f.result(timeout=5)
    finally:
        gate.set()
        sched.close()
    st = sched.stats()
    assert st["shed"] == {"queue_full": 1} and st["shed_total"] == 1


def test_deadline_shed_on_projected_wait():
    with MicrobatchScheduler(_runner, max_batch=4,
                             max_delay_ms=1.0) as sched:
        # a COLD scheduler (no completed batch, EWMA unknown) must never
        # deadline-shed on a guess, however tight the budget
        sched.submit("r", np.zeros((1, 2)), 1,
                     deadline_s=1e-6).result(timeout=5)
        # now pretend batches take a second: a 0.5s budget is doomed
        sched._ewma_exec_s = 1.0
        doomed = sched.submit("r", np.zeros((1, 2)), 1, deadline_s=0.5)
        with pytest.raises(ServeOverloadError) as ei:
            doomed.result(timeout=1)
        assert ei.value.reason == "deadline"
        # a roomy budget is admitted and completes normally
        sched.submit("r", np.zeros((1, 2)), 1,
                     deadline_s=30.0).result(timeout=5)
    st = sched.stats()
    assert st["shed"] == {"deadline": 1}
    assert st["requests"] == 2


def test_shed_feeds_slo_engine():
    eng = SloEngine(p99_ms=50.0, window_s=6.0, every_s=0.0)
    gate = threading.Event()

    def runner(route, feats):
        gate.wait(5.0)
        return feats[:, :1]

    sched = MicrobatchScheduler(runner, max_batch=8, max_delay_ms=1.0,
                                queue_limit=1, slo=eng)
    try:
        sched.submit("r", np.zeros((1, 2)), 1)
        time.sleep(0.1)
        sched.submit("r", np.zeros((1, 2)), 1)
        with pytest.raises(ServeOverloadError):
            sched.submit("r", np.zeros((1, 2)), 1).result(timeout=1)
    finally:
        gate.set()
        sched.close()
    overall = eng.evaluate()
    assert overall["shed"] == 1


# ------------------------------------------------------------ request traces
def test_request_trace_events_sampled(tmp_path):
    path = str(tmp_path / "serve.jsonl")
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    with MicrobatchScheduler(_runner, max_batch=4, max_delay_ms=1.0,
                             observer=obs,
                             request_event_every=2) as sched:
        futs = [sched.submit(("dev", True), np.zeros((1, 2)), 1)
                for _ in range(8)]
        for f in futs:
            f.result(timeout=5)
    obs.close()
    evs = read_events(path)
    reqs = [e for e in evs if e["ev"] == "serve_request"]
    assert len(reqs) == 4           # every 2nd of 8 requests
    for e in reqs:
        assert e["kind"] == "dev"
        assert e["bucket"] >= e["rows"] == 1
        assert {"queue_s", "exec_s", "respond_s"} <= set(e["spans"])
        assert e["total_s"] >= e["spans"]["queue_s"]


def test_serve_batch_event_requires_full_field_set():
    rec = {"ev": "serve_batch", "run": "x", "t": 0.0,
           "schema": SCHEMA_VERSION, "route": "('dev', True)",
           "kind": "dev", "rows": 4, "bucket": 8, "pad": 4,
           "requests": 2, "queue_s": 0.001, "exec_s": 0.002}
    validate_event(rec, strict=True)
    for key in ("queue_s", "exec_s", "pad", "requests"):
        bad = dict(rec)
        bad.pop(key)
        with pytest.raises(ValueError):
            validate_event(bad, strict=True)


# -------------------------------------------------- watchdog + flight record
def test_watchdog_flight_record_from_wedged_serve_worker(tmp_path):
    path = str(tmp_path / "serve.jsonl")
    obs = RunObserver(events_path=path, watchdog_secs=0.15)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    release = threading.Event()

    # the fault-injection hook wedges the batch INSIDE the armed window,
    # exactly like a hung device call would
    def fault(route, batch):
        release.wait(5.0)

    sched = MicrobatchScheduler(_runner, max_batch=8, max_delay_ms=1.0,
                                observer=obs, fault_hook=fault)
    fp = path + ".flight.json"
    try:
        wedged = sched.submit(("dev", True), np.zeros((2, 3)), 2)
        # different-route requests cannot coalesce into the wedged
        # batch: they stay queued, so the flight record has pending
        # state to show
        extra = [sched.submit(("host",), np.zeros((1, 3)), 1)
                 for _ in range(3)]
        deadline = time.monotonic() + 10.0
        while not os.path.exists(fp) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert os.path.exists(fp), "watchdog never dumped flight record"
        release.set()
        wedged.result(timeout=5)
        for f in extra:
            f.result(timeout=5)
    finally:
        release.set()
        sched.close()
        obs.close()
    with open(fp) as f:
        rec = json.load(f)
    assert rec["reason"] == "watchdog timeout"
    assert "serve batch route=dev rows=2" in rec["label"]
    serve_ctx = rec["context"]["serve"]
    assert serve_ctx["queue_depth"] == 3
    assert serve_ctx["pending_routes"] == {"host": 3}
    assert serve_ctx["oldest_wait_s"] >= 0.0


def test_flight_provider_registry_merges_and_survives_errors(tmp_path):
    obs = RunObserver(events_path=str(tmp_path / "t.jsonl"))

    def good():
        return {"mine": {"depth": 7}}

    def bad():
        raise RuntimeError("provider exploded")

    obs.add_flight_provider(good)
    obs.add_flight_provider(bad)
    ctx = obs.flight_context()
    assert ctx["mine"] == {"depth": 7}
    assert ctx["provider_errors"]
    obs.remove_flight_provider(good)
    obs.remove_flight_provider(bad)
    assert obs.flight_context() == {}
    obs.close()


# ------------------------------------------------------- reader + CLI gate
def _serve_timeline(tmp_path, name="ok.jsonl"):
    path = str(tmp_path / name)
    obs = RunObserver(events_path=path)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    with MicrobatchScheduler(_runner, max_batch=4, max_delay_ms=1.0,
                             observer=obs, batch_event_every=1,
                             request_event_every=1) as sched:
        futs = [sched.submit(("dev", True), np.zeros((1, 2)), 1)
                for _ in range(6)]
        for f in futs:
            f.result(timeout=5)
    obs.close()
    return path


def test_serve_metrics_and_report_on_clean_timeline(tmp_path):
    evs = read_events(_serve_timeline(tmp_path))
    m = serve_metrics(evs)
    assert m["present"]
    assert m["totals"]["sampled"] is True      # no serve_summary record
    assert m["totals"]["rows"] == 6
    assert m["routes"]["dev"]["n"] == 6
    assert m["batch_routes"]["dev"]["rows"] == 6
    buf = io.StringIO()
    assert render_serve_report(evs, out=buf, check=True) == []
    assert "verdict: PASS" in buf.getvalue()


def test_obs_serve_cli_check_exit_codes(tmp_path):
    from lightgbm_tpu.obs.query import main as obs_main
    ok = _serve_timeline(tmp_path)
    assert obs_main(["serve", ok, "--check"]) in (0, None)
    # a timeline with NO serving events must fail the gate loudly
    empty = str(tmp_path / "train_only.jsonl")
    obs = RunObserver(events_path=empty)
    obs.run_header(backend="cpu", devices=[], params={}, context={})
    obs.close()
    assert obs_main(["serve", empty, "--check"]) == 1
