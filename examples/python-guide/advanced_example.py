"""Advanced Python API usage (reference python-guide/advanced_example.py
scope, reimplemented for this framework): weighted datasets, continued
training, per-iteration learning-rate schedules, custom objective and
metric, JSON model inspection, cross-validation.

Run from the repo root:  python examples/python-guide/advanced_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(7)
n = 30_000
X = rng.normal(size=(n, 8))
X[:, 3] = rng.integers(0, 6, size=n)          # a categorical column
logit = X[:, 0] + (X[:, 3] == 2) * 1.5 + 0.8 * rng.normal(size=n)
y = (logit > 0).astype(float)
w = np.where(y > 0, 2.0, 1.0)                 # upweight positives

X_tr, X_te = X[: n - 5000], X[n - 5000:]
y_tr, y_te = y[: n - 5000], y[n - 5000:]

# ---- weighted Dataset with an explicit categorical column
train_set = lgb.Dataset(X_tr, label=y_tr, weight=w[: n - 5000],
                        categorical_feature=[3])
valid_set = train_set.create_valid(X_te, label=y_te)

params = {"objective": "binary", "num_leaves": 31, "metric": "auc",
          "verbose": -1}

# ---- stage 1: 30 rounds, then CONTINUE from the saved model
bst = lgb.train(params, train_set, num_boost_round=30,
                valid_sets=[valid_set], verbose_eval=False)
bst.save_model("/tmp/advanced_stage1.model")
print("stage 1 trees:", bst.num_trees())

bst = lgb.train(params, train_set, num_boost_round=30,
                init_model="/tmp/advanced_stage1.model",
                valid_sets=[valid_set], verbose_eval=False,
                # decay the learning rate as training continues
                callbacks=[lgb.reset_parameter(
                    learning_rate=lambda it: 0.1 * (0.99 ** it))])
print("after continuation:", bst.num_trees(), "trees")

# ---- custom objective + metric (logistic, error rate)
def sigmoid_obj(preds, train_data):
    labels = train_data.get_label()
    p = 1.0 / (1.0 + np.exp(-preds))
    return p - labels, p * (1.0 - p)

def error_rate(preds, eval_data):
    labels = eval_data.get_label()
    return "error", float(((preds > 0) != labels).mean()), False

bst2 = lgb.train({"num_leaves": 31, "verbose": -1}, train_set,
                 num_boost_round=25, valid_sets=[valid_set],
                 fobj=sigmoid_obj, feval=error_rate, verbose_eval=False)
print("custom-objective model trees:", bst2.num_trees())

# ---- JSON dump inspection
dump = bst.dump_model()
first = dump["tree_info"][0]["tree_structure"]
print("first split: feature %d, threshold %r"
      % (first["split_feature"], first.get("threshold")))

# ---- cross-validation with explicit metrics
cv_hist = lgb.cv(params, lgb.Dataset(X_tr, label=y_tr), num_boost_round=20,
                 nfold=4, stratified=True, seed=3, verbose_eval=False)
print("cv final auc: %.4f (+/- %.4f)"
      % (cv_hist["auc-mean"][-1], cv_hist["auc-stdv"][-1]))
