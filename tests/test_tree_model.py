"""Tree model: split bookkeeping, prediction semantics, text round-trip."""
import numpy as np
import pytest

from lightgbm_tpu.models.tree import Tree


def build_small_tree():
    t = Tree(4)
    # root split on feature 0, threshold 0.5, zero->left (dbz 0 <= thr bin 1)
    right = t.split(0, 0, False, 1, 0, 0.5, -1.0, 1.0, 10, 20, 5.0, 0, 0, 0.0)
    assert right == 1
    # split right leaf on feature 2, threshold -0.2
    right2 = t.split(1, 2, False, 3, 2, -0.2, 0.5, 2.0, 8, 12, 3.0, 1, 1, 0.0)
    assert right2 == 2
    return t


def test_split_structure():
    t = build_small_tree()
    assert t.num_leaves == 3
    assert t.left_child[0] == ~0
    assert t.right_child[0] == 1       # internal node 1
    assert t.left_child[1] == ~1
    assert t.right_child[1] == ~2
    assert t.leaf_parent[0] == 0
    assert t.leaf_parent[1] == 1
    assert t.leaf_parent[2] == 1
    assert t.internal_count[0] == 30


def test_predict_decision_path():
    t = build_small_tree()
    X = np.array([
        [0.4, 0.0, 0.0],    # f0<=0.5 -> leaf0 (-1.0)
        [0.6, 0.0, -0.5],   # f0>0.5, f2<=-0.2 -> leaf1 (0.5)
        [0.6, 0.0, 0.3],    # f0>0.5, f2>-0.2 -> leaf2 (2.0)
    ])
    np.testing.assert_allclose(t.predict(X), [-1.0, 0.5, 2.0])


def test_zero_default_redirect():
    t = Tree(2)
    # threshold 0.5 but zero-values redirect to default_value 1.0 (-> right)
    t.split(0, 0, False, 1, 0, 0.5, -1.0, 1.0, 10, 20, 5.0, 0, 2, 1.0)
    X = np.array([[0.0], [1e-21], [0.3]])
    out = t.predict(X)
    assert out[0] == 1.0    # zero redirected to 1.0 > 0.5 -> right
    assert out[1] == 1.0
    assert out[2] == -1.0


def test_shrinkage_clamp():
    t = build_small_tree()
    t.leaf_value[0] = 5000.0
    t.shrink(0.1)
    assert t.leaf_value[0] == 100.0  # kMaxTreeOutput clamp (tree.h:110-118)
    assert t.shrinkage == pytest.approx(0.1)


def test_text_roundtrip_exact():
    t = build_small_tree()
    t.shrink(0.1)
    s = t.to_string()
    t2 = Tree.from_string(s)
    assert t2.num_leaves == t.num_leaves
    np.testing.assert_array_equal(t2.left_child[:2], t.left_child[:2])
    np.testing.assert_array_equal(t2.right_child[:2], t.right_child[:2])
    np.testing.assert_array_equal(t2.split_feature[:2], t.split_feature[:2])
    np.testing.assert_allclose(t2.threshold[:2], t.threshold[:2])
    np.testing.assert_allclose(t2.leaf_value[:3], t.leaf_value[:3])
    rng = np.random.default_rng(0)
    X = rng.normal(size=(50, 3))
    np.testing.assert_allclose(t2.predict(X), t.predict(X))


def test_field_order_matches_reference_format():
    t = build_small_tree()
    lines = [l.split("=")[0] for l in t.to_string().splitlines() if "=" in l]
    assert lines == ["num_leaves", "split_feature", "split_gain", "threshold",
                     "decision_type", "default_value", "left_child",
                     "right_child", "leaf_parent", "leaf_value", "leaf_count",
                     "internal_value", "internal_count", "shrinkage",
                     "has_categorical"]


def test_categorical_decision():
    t = Tree(2)
    t.split(0, 0, True, 2, 0, 7.0, -1.0, 1.0, 10, 20, 5.0, 0, 0, 0.0)
    X = np.array([[7.0], [7.4], [3.0]])
    out = t.predict(X)
    assert out[0] == -1.0   # int(7.0) == 7 -> left
    assert out[1] == -1.0   # int cast truncates
    assert out[2] == 1.0
