"""Multi-process (DCN) data-parallel training with REAL OS processes.

The reference proves its distributed path by running MPI in CI
(.travis.yml:45-52); the TPU-native analog is jax.distributed over a
localhost coordinator: N OS processes, each with 2 virtual CPU devices,
form one 2N-device global mesh (N=2 and N=4 below).  Histograms psum
ACROSS the process boundaries (the DCN hops of a multi-host pod), bin
mappers are constructed distributed via JaxProcessComm, and every
process must emerge with identical trees — which must also equal the
single-process oracle on the concatenated data.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(nproc, mode="dense", extra_env=None):
    coordinator = "127.0.0.1:%d" % _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if extra_env:
        env.update(extra_env)
    # worker output goes to FILES: a failing rank can dump >64 KB
    # (pipe-buffer size) of tracebacks, which with stdout=PIPE would
    # block it while the parent waits on another rank — a 540 s stall
    # that also loses the diagnostics
    import tempfile
    logs = [tempfile.NamedTemporaryFile("w+", suffix="_r%d.log" % r,
                                        delete=False)
            for r in range(nproc)]
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "mp_worker.py"),
         coordinator, str(nproc), str(r), mode],
        env=env, cwd=REPO, stdout=logs[r], stderr=subprocess.STDOUT,
        text=True) for r in range(nproc)]
    try:
        for p in procs:
            p.wait(timeout=540)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs = []
    for f in logs:
        f.flush()
        f.seek(0)
        outs.append(f.read())
        f.close()
        os.unlink(f.name)
    for p, out in zip(procs, outs):
        if (p.returncode != 0
                and "Multiprocess computations aren't implemented"
                in out):
            # this jaxlib's CPU client has no cross-process collectives:
            # an environment limit, not a code failure — same class as
            # the reference skipping MPI tests without an MPI install
            pytest.skip("jax CPU backend on this host lacks "
                        "multiprocess collectives")
        assert p.returncode == 0, "worker failed:\n%s" % out[-3000:]
    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines()
                if ln.startswith("MPRESULT ")][-1]
        r = json.loads(line[len("MPRESULT "):])
        results[r["rank"]] = r
    assert set(results) == set(range(nproc))
    return results


import pytest


@pytest.fixture(scope="module")
def dense_two_process():
    return _run_workers(2)


def test_four_process_ranks_agree():
    """4 OS processes x 2 virtual devices = an 8-device global mesh with
    three DCN hops; every rank must emerge with the identical model."""
    results = _run_workers(4)
    trees = [results[r]["trees"] for r in range(4)]
    assert all(t == trees[0] for t in trees[1:])
    assert all(t["num_leaves"] > 4 for t in trees[0])


def test_two_process_data_parallel_training(dense_two_process):
    results = dense_two_process

    # both processes must hold the identical model
    t0, t1 = results[0]["trees"], results[1]["trees"]
    assert t0 == t1, "ranks disagree on the trained model"
    assert all(t["num_leaves"] > 4 for t in t0)

    # single-process oracle on the concatenated data with the SAME bin
    # mappers: distributed bin finding samples per-rank shards, so the
    # oracle reproduces the mapper construction through the thread-comm
    # simulator (identical ranks/seeds) and bins the full data with it
    sys.path.insert(0, HERE)
    import mp_worker
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.ops.learner import SerialTreeLearner
    from lightgbm_tpu.parallel.comm import run_ranks
    from lightgbm_tpu.utils.config import Config
    X0, y0 = mp_worker.make_data(0, 2)
    X1, y1 = mp_worker.make_data(1, 2)
    X = np.concatenate([X0, X1]); y = np.concatenate([y0, y1])
    cfg = Config({"num_leaves": 15, "min_data_in_leaf": 5, "max_bin": 63,
                  "verbose": -1, "tpu_growth": "exact",
                  "enable_bundle": False})
    tds = run_ranks(2, lambda comm: TrainingData.from_matrix(
        mp_worker.make_data(comm.rank, 2)[0],
        label=mp_worker.make_data(comm.rank, 2)[1].astype(np.float64),
        config=cfg, comm=comm))
    td = TrainingData.from_matrix(X, label=y.astype(np.float64), config=cfg,
                                  reference=tds[0])
    # 4-device single-process data mesh == the 2-process global mesh's
    # shard layout, so histogram psums reduce in the same order (a serial
    # learner differs by float reduction order on near-tie splits)
    import jax
    from lightgbm_tpu.parallel.mesh import (DataParallelTreeLearner,
                                            make_data_mesh)
    learner = DataParallelTreeLearner(cfg, td,
                                      make_data_mesh(jax.devices()[:4]))
    import jax.numpy as jnp
    from lightgbm_tpu.ops import predict as dev_predict
    score = jnp.zeros(len(y), jnp.float32)
    y_dev = jnp.asarray(y, jnp.float32)
    for i in range(mp_worker.ROUNDS):
        p = 1.0 / (1.0 + jnp.exp(-score))
        tree_dev, leaf_id = learner.train_device(
            np.asarray(p - y_dev, np.float32),
            np.asarray(p * (1 - p), np.float32))
        score = dev_predict.update_score_from_partition(
            score, leaf_id, tree_dev.leaf_value,
            jnp.asarray(0.2, jnp.float32))
        got = t0[i]
        assert got["num_leaves"] == int(tree_dev.num_leaves)
        assert got["split_feature"] == np.asarray(
            tree_dev.split_feature).tolist()
        # cross-process psum reduces in a different order than the
        # single-process mesh, so an exact-tie threshold may flip by one
        # bin (same f32 tie sensitivity as serial vs feature-parallel);
        # allow at most one +-1 wobble per tree, everything else exact
        want = np.asarray(tree_dev.threshold_bin)
        have = np.asarray(got["threshold_bin"])
        diff = have != want
        assert diff.sum() <= 1 and np.abs(have - want)[diff].max(
            initial=0) <= 1, (have.tolist(), want.tolist())


def test_two_process_obs_shards_and_merge(tmp_path):
    """Distributed observability over REAL processes: each rank writes
    its own timeline shard (auto-suffixed .r<rank>), the run headers
    carry rank/world_size, the loading collectives land as
    host_collective events with aligned seq numbers, and `obs merge`
    attributes the injected slow rank nonzero skew."""
    base = str(tmp_path / "mp_events.jsonl")
    _run_workers(2, extra_env={"LGBM_MP_OBS_PATH": base,
                               "LGBM_MP_SLOW_RANK": "1",
                               "LGBM_MP_SLOW_SECS": "0.3"})

    from lightgbm_tpu.obs.merge import (discover_shards, load_shards,
                                        merge_shards)
    shards = discover_shards(base + ".r0")
    assert [os.path.basename(p) for p in shards] == [
        "mp_events.jsonl.r0", "mp_events.jsonl.r1"]

    ranks = load_shards(shards)
    assert set(ranks) == {0, 1}
    for r, events in ranks.items():
        hdr = events[0]
        assert hdr["ev"] == "run_header"
        assert hdr["rank"] == r and hdr["world_size"] == 2
        assert any(e["ev"] == "host_collective" for e in events), \
            "rank %d shard has no collective events" % r
        assert events[-1]["ev"] == "run_end"
        assert events[-1]["status"] == "ok"

    merged, report = merge_shards(ranks)
    assert report["world_size"] == 2
    assert report["ranks"] == [0, 1]
    # every collective must have both ranks aligned on its seq
    assert report["collectives"]
    for row in report["collectives"]:
        assert row["ranks"] == [0, 1]
        assert row["missing_ranks"] == []
    # rank 1 slept 0.3 s before the load: the skew analysis must see it
    assert report["collective_skew_max_s"] > 0.1
    worst = max(report["collectives"], key=lambda r: r["skew_s"])
    assert worst["last_rank"] == 1
    # merged timeline stays a valid single-run view
    hdr = merged[0]
    assert hdr["ev"] == "run_header" and hdr["merged"] is True
    assert merged[-1]["ev"] == "run_end"
    assert merged[-1]["status"] == "ok"


def test_two_process_sparse_store_matches_dense(dense_two_process):
    """tpu_sparse under REAL multi-process training: per-process
    coordinate stores with an allgathered nnz/col_cap agreement must
    produce the identical model on every rank AND the same trees as the
    dense two-process run."""
    sparse = _run_workers(2, mode="sparse")
    assert sparse[0]["trees"] == sparse[1]["trees"]
    dense = dense_two_process
    for ts, td_ in zip(sparse[0]["trees"], dense[0]["trees"]):
        assert ts["num_leaves"] == td_["num_leaves"]
        assert ts["split_feature"] == td_["split_feature"]
        assert ts["threshold_bin"] == td_["threshold_bin"]
