"""Structured JSONL event timeline for training runs.

One line per event, append-only, versioned via ``schema`` in the run
header.  Every record carries ``ev`` (type), ``t`` (unix time) and
``run`` (random id) — multiple runs may share one file (cv folds,
repeated bench children) and readers group by ``run``.

Event types and their required keys (beyond ev/t/run):

=============  =========================================================
run_header     schema, backend, devices, params, context, timing
               (+ provenance — git_rev/git_dirty/hostname/argv — from
               schema 10 on: the attribution key the cross-run ledger
               in obs/ledger.py groups and blames regressions by)
iter           it, time_s, phases, fenced
compile        entry, first_call_s, fenced
compile_attr   entry, n_compiles, sig (schema 3; obs/compile.py — per-
               compile signature, axis-level diff, cost/memory analysis)
straggler      it, devices, skew (schema 3; obs/straggler.py — per-shard
               arrival waits + slowest-device attribution)
memory         it, devices
trace_window   action, dir, it
collectives    learner (plus learner-specific topology/byte estimates)
host_collective op, seq, dur_s (schema 4; parallel/comm.py — one host
               barrier/allgather with its monotonic sequence number)
health         check, status, it (schema 2; obs/health.py monitors)
metrics        it, scrape (schema 2; obs/metrics.py registry snapshot)
split_audit    it, tree, splits (schema 5; obs/model.py — every realized
               split's feature/threshold/gain + runner-up margin)
importance     it, features (schema 5; obs/model.py — top-k sparse
               split/gain importance snapshot)
data_profile   n_features (schema 5; obs/dataquality.py — per-feature
               missing rate / entropy / degeneracy flags, label balance)
eval           it, results (schema 5; per-iteration eval-metric values,
               the convergence surface `obs explain` reads)
serve_batch    route, rows, bucket, pad, requests, queue_s, exec_s
               (schema 6; serve/scheduler.py — one coalesced microbatch;
               schema 7 declares the full field set it always carried)
serve_bench    qps, p50_s, p99_s (schema 6; bench_serve.py — sustained
               load-generator summary, the gated serving metrics)
serve_request  route, rows, bucket, spans (schema 7; serve/scheduler.py —
               one sampled request trace: enqueue → coalesce-wait → pad →
               execute → respond, with batch id and bucket)
serve_slo      window_s, routes (schema 7; obs/serve.py — periodic
               rolling-window SLO snapshot: per-route QPS and latency
               quantiles, burn rates, alert state, target verdicts)
serve_summary  batches, rows, shed_total (schema 7; serve/scheduler.py —
               ServingPredictor lifetime totals emitted on close(), the
               run_end of a serving session)
autotune_probe cell, s_per_wave (schema 8; ops/autotune.py — one
               microbenched candidate kernel cell with its measured
               seconds per wave)
autotune_decision mode, source, cell (schema 8; ops/autotune.py — the
               kernel-selection decision for one learner construction:
               chosen cell vs the heuristic prior, every probed cell's
               s/wave, winner margin, probe overhead, cache hit/path)
wave_band_escape width_from, width_to (schema 8; ops/learner.py — the
               auto wave width escaped the measured pathological
               hist-block band; previously silent, BENCH_NOTES.md)
dataset_construct rows, chunks, sketch_s, bin_s, write_s,
               peak_rss_bytes, workers (schema 9; io/dataset.py +
               io/streaming.py — one dataset construction: source kind,
               two-pass phase seconds, worker-pool width, RSS watermark;
               `construct_s` is gated by tools/bench_compare.py)
utilization    it, entries (schema 13; obs/roofline.py — per-iteration
               roofline rollup: exec-weighted flop_util / hbm_util
               against the device-peak registry, dominant bound, total
               headroom seconds; the ledger cells bench_compare gates)
incident_open  id, trigger, signals (schema 15; obs/incident.py — the
               anomaly-correlation engine grouped co-occurring detector
               signals into one incident and captured its evidence
               bundle at the moment of anomaly)
incident_evidence id, artifact (schema 15; one captured bundle artifact
               — ring slice, metrics snapshot, statusz snapshot, flight
               context, utilization rollup, thread stacks, trace dir)
incident_close id, duration_s, signals (schema 15; the quiet-window
               close with per-kind counts in first-occurrence order —
               the correlation table `obs incident` renders)
prof_profile   samples, dur_s, hz, cost_s (schema 16; obs/prof.py — one
               aggregated window of the continuous host sampling
               profiler: top-K folded stacks + truncated tail, per-
               role/stage/phase totals, self-measured overhead — the
               gated budget `obs prof --check` enforces)
run_end        iters, phase_totals, entries (+ status: ok|aborted)
=============  =========================================================

Schema 4 makes the timeline rank-native: the run header carries
``rank``/``world_size``/``coordinator``, every event of a multi-rank
run carries ``rank``, ``iter`` events carry a monotonic ``seq``, and
``obs_events_path`` becomes a per-rank template (``{rank}`` placeholder,
or an automatic ``.r{rank}`` suffix when world_size > 1) — see
obs/merge.py for the cross-rank view.

``RunObserver`` is the facade the training loop drives; ``NULL_OBSERVER``
is the shared disabled instance — every method is a no-op and the hot
path pays one attribute check and an empty call, with no fencing and no
event objects allocated.

Crash safety: the writer flushes every ``flush_every`` events, the
observer registers an ``atexit`` finalizer, and both are context
managers — a run killed mid-iteration still ends with a parseable
timeline whose last record is ``run_end`` with ``status="aborted"``
whenever the interpreter gets to unwind.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import socket
import subprocess
import sys
import threading
import time

from .memory import MemorySampler, device_memory_stats
from .profile import TraceWindow
from .timers import EntryTimers, PhaseClock, fence
from ..utils.log import Log

SCHEMA_VERSION = 16
# schema 1 (no health/metrics), 2 (no compile_attr/straggler),
# 3 (rank-less, no host_collective), 4 (no model/data events),
# 5 (no serving events), 6 (no request traces / SLO snapshots),
# 7 (no autotune/band-escape events), 8 (no dataset_construct),
# 9 (no run_header provenance), 10 (no host_orchestration_s iter
# field — schema 11 adds the host-glue seconds between device program
# submissions, models/gbdt.py OrchestrationClock), 11 (no pod
# scale-out events — schema 12 adds scaling / mesh_shrink / checkpoint
# and the sharded-ingest dataset_construct fields), 12 (no roofline
# attribution — schema 13 adds the per-iteration ``utilization``
# rollup and the ``autotune_probe.roofline`` cell stamp, obs/
# roofline.py), 13 (no drift monitoring — schema 14 adds the
# ``drift`` / ``online_quality`` serving-side distribution-shift
# events and the serve_summary ``drift`` digest, obs/drift.py) and
# 14 (no incident engine — schema 15 adds the ``incident_open`` /
# ``incident_evidence`` / ``incident_close`` anomaly-correlation
# events and the run_end ``incidents`` digest, obs/incident.py) and
# 15 (no host profiler — schema 16 adds the continuous sampling
# profiler's ``prof_profile`` window rollup, obs/prof.py) timelines
# still parse.  wave_band_escape stays accepted for old timelines
# even though nothing emits it anymore (the band prior died in PR-11;
# ops/pallas_wave.py tile planner post-mortem).
_ACCEPTED_SCHEMAS = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
                     16)

# ev -> keys that must be present (beyond the common ev/t/run)
_REQUIRED = {
    "run_header": ("schema", "backend", "devices", "params", "context",
                   "timing"),
    "iter": ("it", "time_s", "phases", "fenced"),
    "compile": ("entry", "first_call_s", "fenced"),
    "compile_attr": ("entry", "n_compiles", "sig"),
    "straggler": ("it", "devices", "skew"),
    "memory": ("it", "devices"),
    "trace_window": ("action", "dir", "it"),
    "collectives": ("learner",),
    # schema 4 (parallel/comm.py): one host-level collective with its
    # monotonic per-rank sequence number — obs/merge.py aligns shards
    # on (op, seq) to measure barrier skew
    "host_collective": ("op", "seq", "dur_s"),
    "health": ("check", "status", "it"),
    "metrics": ("it", "scrape"),
    # schema 5 (obs/model.py + obs/dataquality.py): model & data
    # observability — split audit trail, importance evolution, dataset
    # profile, per-iteration eval values
    "split_audit": ("it", "tree", "splits"),
    "importance": ("it", "features"),
    "data_profile": ("n_features",),
    "eval": ("it", "results"),
    # schema 6 (lightgbm_tpu/serve/): the serving tier — one coalesced
    # microbatch per serve_batch (sampled via serve_batch_event_every),
    # one serve_bench summary per bench_serve.py measurement window.
    # Schema 7 declares the full serve_batch field set (the scheduler
    # always emitted pad/requests/queue_s/exec_s — the schema just
    # under-promised), so strict validation and downstream tooling see
    # every field; PR-6 timelines still validate.
    "serve_batch": ("route", "rows", "bucket", "pad", "requests",
                    "queue_s", "exec_s"),
    "serve_bench": ("qps", "p50_s", "p99_s"),
    # schema 7 (obs/serve.py + serve/scheduler.py): serving-tier
    # observability — sampled per-request trace spans, periodic
    # rolling-window SLO snapshots, and the close-time lifetime summary
    "serve_request": ("route", "rows", "bucket", "spans"),
    "serve_slo": ("window_s", "routes"),
    "serve_summary": ("batches", "rows", "shed_total"),
    # schema 8 (ops/autotune.py + ops/learner.py): measured kernel
    # selection — per-cell probe timings, the per-learner decision
    # (with prior, margin and cache provenance), and the previously
    # silent pathology-band width escape
    "autotune_probe": ("cell", "s_per_wave"),
    "autotune_decision": ("mode", "source", "cell"),
    "wave_band_escape": ("width_from", "width_to"),
    # schema 9 (io/dataset.py + io/streaming.py): out-of-core ingest —
    # one event per dataset construction with the two-pass phase split
    # (quantile sketch / binning / shard write), chunk count, worker-pool
    # width and the host RSS watermark; bench_compare gates construct_s
    "dataset_construct": ("rows", "chunks", "sketch_s", "bin_s",
                          "write_s", "peak_rss_bytes", "workers"),
    # schema 12 (parallel/ + bench.py --mp + engine.py): pod scale-out —
    # one scaling summary per measured world size (the weak-scaling
    # ledger cells, obs/ledger.py), one mesh_shrink per elastic
    # shrink-and-resume, one checkpoint per compact booster save
    "scaling": ("world_size", "rows_per_sec_per_chip", "efficiency"),
    "mesh_shrink": ("world_size_from", "world_size_to", "it"),
    "checkpoint": ("it",),
    # schema 13 (obs/roofline.py): per-iteration roofline rollup —
    # exec-weighted achieved/peak utilization across the timed entries,
    # joined from CompileTracker cost estimates and the device-peak
    # registry (obs_utilization_every)
    "utilization": ("it", "entries"),
    # schema 14 (obs/drift.py): serving-side drift monitoring — one
    # ``drift`` rollup per obs_drift_every rows (per-feature + score
    # PSI/KS vs the training fingerprint), one ``online_quality`` per
    # evaluation once enough delayed labels joined via
    # ServingPredictor.record_outcome
    "drift": ("rows", "window_rows", "psi_max"),
    "online_quality": ("n", "logloss"),
    # schema 15 (obs/incident.py): anomaly correlation — one
    # incident_open when the first qualifying detector signal arrives
    # (with the evidence bundle captured at that moment), one
    # incident_evidence per captured artifact, one incident_close after
    # a quiet window with the grouped per-kind signal rollup
    "incident_open": ("id", "trigger", "signals"),
    "incident_evidence": ("id", "artifact"),
    "incident_close": ("id", "duration_s", "signals"),
    # schema 16 (obs/prof.py): the continuous host sampling profiler —
    # one aggregated window per obs_prof_window_s with the folded-stack
    # counts and the sampler's self-measured cost (the overhead budget
    # bench.py --dry and `obs prof --check` gate on)
    "prof_profile": ("samples", "dur_s", "hz", "cost_s"),
    "run_end": ("iters", "phase_totals", "entries"),
}

# ev -> keys a writer MAY attach beyond _REQUIRED.  Every field any
# in-tree emit site produces must be declared in one of the two tables:
# the event-schema lint pass (analysis/events_schema.py) rejects an
# emit-site keyword found in neither, so a new field is a deliberate
# schema decision here rather than silent drift (the PR-6->7
# ``serve_batch`` under-promise, re-litigated statically).  Readers must
# still treat these as optional — old timelines predate them.
_OPTIONAL = {
    "run_header": ("rank", "world_size", "coordinator", "provenance",
                   # obs/merge.py synthetic pod-merged header
                   "merged", "merged_ranks"),
    "iter": ("seq", "stopped", "host_orchestration_s",
             # obs/merge.py critical-path merge
             "rank_times", "skew_s", "slowest_rank"),
    "compile": (),
    # attribution extras (obs/compile.py / serve/executable.py):
    # per-signature counts, field-level diff, jit cache size, AOT
    # cost/memory analysis when the backend exposes them
    "compile_attr": ("sig_compiles", "diff", "cache_size", "cost",
                     "memory"),
    "straggler": ("axis", "slowest", "total_s"),
    "memory": (),
    "trace_window": (),
    # parallel/mesh.py collective_info(): static topology + per-collective
    # byte estimates; exact keys vary by learner
    "collectives": ("axis", "n_devices", "n_processes", "global_rows",
                    "estimates", "psum", "allgather",
                    "num_voting_machines"),
    "host_collective": ("t_start", "nbytes",
                        # obs/merge.py aligned-collective merge
                        "skew_s", "first_rank", "last_rank", "arrivals",
                        "missing_ranks"),
    "health": ("detail",),
    "metrics": (),
    "split_audit": ("num_leaves", "shrinkage", "truncated"),
    "importance": ("n_features", "n_used", "split", "gain"),
    # the profile payload (io/dataset.py _profile_quality) rides in via
    # **profile; its stat keys are the profiler's contract, not ours
    "data_profile": ("dataset", "label", "findings", "n_rows", "stats"),
    "eval": (),
    "serve_batch": ("kind",),
    # bench_serve.py load-generator summary extras
    "serve_bench": ("requests", "rows", "rows_per_s", "threads",
                    "wall_s", "batches", "pad_rows", "buckets",
                    "offered", "shed", "shed_rate", "deadline_ms",
                    "steady_state_compiles"),
    "serve_request": ("kind", "batch", "requests", "total_s",
                      "deadline_s"),
    "serve_slo": ("short_s", "overall", "alert", "burn_short",
                  "burn_long", "targets", "verdicts"),
    "serve_summary": ("pad_rows", "max_queue_depth", "requests", "shed",
                      "executables", "slo", "drift"),
    # schema 13: every probed cell carries its analytic roofline stamp
    # (flop/hbm utilization at the measured s/wave, dominant bound) so
    # `obs explain` can say why the winner won — obs/roofline.py
    "autotune_probe": ("bucket", "waves", "roofline"),
    "autotune_decision": ("bucket", "device_kind", "prior", "cells",
                          "margin", "overhead_s", "cache_hit",
                          "cache_path"),
    # dead writer (band prior removed in PR-11) — field set preserved for
    # the old-timeline renderer in obs/query.py
    "wave_band_escape": ("band_lo_mb", "band_hi_mb", "block_mb", "ncols",
                         "bin_pad"),
    # load_s / rss_growth_bytes ride in from the pre-binned open path;
    # row_range / world_size from a rank-sharded open (schema 12)
    "dataset_construct": ("source", "construct_s", "load_s",
                          "rss_growth_bytes", "row_range", "world_size"),
    "scaling": ("chips", "rows", "iters", "psum_bytes", "mode",
                "baseline_rows_per_sec", "rows_per_sec"),
    "mesh_shrink": ("reason", "checkpoint", "lost_ranks"),
    "checkpoint": ("path", "bytes", "world_size"),
    "utilization": ("flop_util", "hbm_util", "bound", "headroom_s",
                    "device_kind", "roof_source"),
    # schema 14: the drift rollup carries its top-k feature evidence
    # (per-feature psi/ks + most-shifted bins), the score-space
    # divergence, the input-anomaly counters and the alert state
    "drift": ("score_psi", "features", "score", "anomalies",
              "threshold", "alert"),
    "online_quality": ("auc", "pending", "ref_auc", "ref_logloss"),
    # schema 15: the open event carries the trigger's detail and the
    # ring seq it anchors to; the close carries the full correlation
    # rollup (per-kind counts + first/last occurrence) and the bundle
    # inventory
    "incident_open": ("it", "seq", "dir", "detail"),
    "incident_evidence": ("path", "bytes", "error", "it"),
    "incident_close": ("counts", "artifacts", "signal_detail", "dir",
                       "it", "window_s"),
    # schema 16: the window's top-K folded stacks (+ how many samples
    # the truncation dropped), per-thread-role / loop-stage / phase
    # sample totals, the iteration span covered, the self-measured
    # overhead fraction, and — on a wedged sampler — the error that
    # stopped it (``obs prof --check`` fails loud on it)
    "prof_profile": ("stacks", "truncated", "topk", "roles", "stages",
                     "phases", "iter_lo", "iter_hi", "overhead_frac",
                     "error", "source"),
    "run_end": ("status", "health", "compile_attr", "stragglers",
                # obs/merge.py merged-timeline summary
                "rank_report",
                # schema 15: incident digest ({opened, max_signals}) —
                # present whenever the engine ran, zeros included, so
                # the ledger records a real zero history
                "incidents"),
}

# fields event()/emit() stamp on every record regardless of type
_COMMON_FIELDS = ("ev", "t", "run", "rank")


def declared_fields(ev):
    """Frozenset of every field the schema knows for ``ev`` (required +
    optional + common), or None for an unknown event type.  The static
    analyzer keys its unknown-field rule on this."""
    if ev not in _REQUIRED:
        return None
    return frozenset(_REQUIRED[ev]) | frozenset(_OPTIONAL.get(ev, ())) \
        | frozenset(_COMMON_FIELDS)


# -- run provenance ------------------------------------------------------
# Stamped into every schema-10 run_header: the git rev (and whether the
# tree was dirty), the host, and the CLI argv that launched the run.
# This is the attribution key of the cross-run ledger (obs/ledger.py) —
# a change-point in a metric trend is blamed on the first git rev that
# shifted it — and on its own turns any flight record into "what code,
# where, launched how".  Cached per process: two git subprocesses once,
# never on the hot path.
_PROVENANCE = None
_PROVENANCE_LOCK = threading.Lock()


def _git(args):
    out = subprocess.run(["git"] + args, capture_output=True, text=True,
                         timeout=10)
    if out.returncode != 0:
        raise RuntimeError(out.stderr.strip() or "git rc=%d"
                           % out.returncode)
    return out.stdout


def collect_provenance(refresh=False):
    """{git_rev, git_dirty, hostname, argv} of this process, cached.

    Best-effort by design: outside a git work tree (or with git missing)
    ``git_rev`` is ``""`` and ``git_dirty`` False — a run observer must
    never fail because of where it was launched from."""
    global _PROVENANCE
    with _PROVENANCE_LOCK:
        if _PROVENANCE is not None and not refresh:
            return dict(_PROVENANCE)
        rev, dirty = "", False
        try:
            rev = _git(["rev-parse", "--short=12", "HEAD"]).strip()
            dirty = bool(_git(["status", "--porcelain",
                               "--untracked-files=no"]).strip())
        except Exception:
            rev, dirty = rev or "", bool(dirty)
        try:
            host = socket.gethostname()
        except Exception:
            host = ""
        # bounded: argv can carry huge inline configs; the ledger and
        # flight records only need "what command was this"
        argv = [str(a)[:200] for a in sys.argv[:16]]
        _PROVENANCE = {"git_rev": rev, "git_dirty": dirty,
                       "hostname": host, "argv": argv}
        return dict(_PROVENANCE)


def resolve_rank_path(path, rank, world_size):
    """Per-rank shard path from the ``obs_events_path`` template.

    An explicit ``{rank}`` placeholder is always substituted; otherwise
    multi-rank runs (world_size > 1) auto-suffix ``.r{rank}`` so N ranks
    never interleave writes into one file, and single-process runs keep
    the configured path byte-for-byte."""
    path = str(path or "")
    if not path:
        return path
    if "{rank}" in path:
        return path.replace("{rank}", str(int(rank)))
    if int(world_size or 1) > 1:
        return "%s.r%d" % (path, int(rank))
    return path


class RingBuffer:
    """Fixed-capacity ring of the most recent events — the flight
    recorder's view of "what was the run doing right before it died".
    Appends are lock-free (GIL-atomic deque ops) because the watchdog
    thread snapshots while rank threads append.

    Every append is stamped with a process-lifetime monotonic sequence
    number so the live /events endpoint (obs/live.py) can hand scrapers
    a resumable cursor (``tail(after)``) instead of re-sending the
    whole ring each poll.  The counter is best-effort under concurrent
    appends — a duplicated seq costs a tailer one duplicate or skipped
    event, never a corrupt record."""

    def __init__(self, capacity=256):
        self.capacity = max(1, int(capacity))
        self._buf = collections.deque(maxlen=self.capacity)
        self.dropped = 0
        self._seq = 0

    def append(self, rec):
        if len(self._buf) == self._buf.maxlen:
            self.dropped += 1
        self._seq += 1
        self._buf.append((self._seq, rec))

    def snapshot(self):
        """List copy of the records, oldest first."""
        return [rec for _, rec in list(self._buf)]

    @property
    def last_seq(self):
        return self._seq

    def tail(self, after=0):
        """(last_seq, records with seq > ``after``, oldest first) — the
        cursor contract of the /events?after=N endpoint."""
        items = list(self._buf)
        return self._seq, [rec for s, rec in items if s > int(after)]

    def __len__(self):
        return len(self._buf)


# -- live-observer registry ----------------------------------------------
# parallel/comm.py emits host_collective events and arms the hang
# watchdog around barriers without holding an observer reference: each
# RunObserver registers itself per creating thread (run_ranks simulates
# one rank per thread, so thread-locality IS rank-locality) plus a
# process-global list for main-thread lookups and SIGTERM flight dumps.
_TLS = threading.local()
_LIVE = []
_LIVE_LOCK = threading.Lock()


def _register_observer(obs):
    _TLS.observer = obs
    with _LIVE_LOCK:
        _LIVE.append(obs)


def _unregister_observer(obs):
    if getattr(_TLS, "observer", None) is obs:
        _TLS.observer = None
    with _LIVE_LOCK:
        try:
            _LIVE.remove(obs)
        except ValueError:
            pass


def current_observer():
    """The live observer of the calling thread (its simulated rank), or —
    only from the main thread, where cross-wiring is impossible — the
    most recent live observer."""
    obs = getattr(_TLS, "observer", None)
    if obs is not None and not obs._closed:
        return obs
    if threading.current_thread() is threading.main_thread():
        with _LIVE_LOCK:
            for cand in reversed(_LIVE):
                if not cand._closed:
                    return cand
    return None


def live_observers():
    """All live observers (flight-dump fan-out on SIGTERM)."""
    with _LIVE_LOCK:
        return [o for o in _LIVE if not o._closed]


def _default_rank_info():
    """Process rank for an observer that wasn't told one explicitly:
    the comm rank context if a HostComm is active on this thread
    (simulated run_ranks ranks included), else jax.distributed's
    process index/count, else rank 0 of 1."""
    try:
        from ..parallel.comm import rank_context
        info = rank_context()
        if info is not None:
            return info
    except Exception:
        pass
    try:
        import jax
        n = int(jax.process_count())
        if n > 1:
            return {"rank": int(jax.process_index()), "world_size": n,
                    "coordinator": os.environ.get(
                        "JAX_COORDINATOR_ADDRESS", "")}
    except Exception:
        pass
    return {"rank": 0, "world_size": 1, "coordinator": ""}


def validate_event(rec, strict=False):
    """Raise ValueError unless ``rec`` is a schema-valid event dict.

    Unknown event types pass untouched by default — a v3 reader must not
    choke on a v4 timeline (forward compatibility is why the schema is
    versioned at all).  ``strict=True`` additionally rejects unknown
    ``ev`` values, for writers validating their own output.
    """
    if not isinstance(rec, dict):
        raise ValueError("event is not a dict: %r" % (rec,))
    ev = rec.get("ev")
    if ev not in _REQUIRED:
        if strict:
            raise ValueError("unknown event type %r" % (ev,))
        return rec
    for key in ("t", "run"):
        if key not in rec:
            raise ValueError("event %r missing %r" % (ev, key))
    missing = [k for k in _REQUIRED[ev] if k not in rec]
    if missing:
        raise ValueError("event %r missing keys %s" % (ev, missing))
    if ev == "run_header":
        if rec["schema"] not in _ACCEPTED_SCHEMAS:
            raise ValueError("unsupported schema version %r"
                             % (rec["schema"],))
        # schema 10 declares run provenance; older headers predate it
        if isinstance(rec["schema"], int) and rec["schema"] >= 10 \
                and "provenance" not in rec:
            raise ValueError("run_header schema %r missing provenance"
                             % (rec["schema"],))
    return rec


def read_events(path, validate=True):
    """Parse a JSONL event file into a list of dicts."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if validate:
                validate_event(rec)
            out.append(rec)
    return out


class EventWriter:
    """Append-mode JSONL writer, flushed every ``flush_every`` events
    (and on close) so a killed run still leaves a readable timeline.

    A monotonic-clock interval (``flush_interval_s``, ~1 s) flushes
    alongside the count trigger: a live tailer (``obs watch``, the
    /events endpoint's file-based cousins) sees events promptly during
    slow iterations instead of up to ``flush_every`` events late.  The
    clock is only consulted when an emit arrives — an idle writer costs
    nothing.

    ``run_end`` is flushed UNCONDITIONALLY the moment it is emitted,
    whatever ``flush_every`` says — a crash right after finalize must
    not lose the one record every reader keys on.  ``fsync=True``
    (``obs_fsync``) additionally fsyncs on those barriers, surviving
    OS-level death (OOM-kill, node power loss), not just interpreter
    death.  Emits are lock-serialized: the hang watchdog writes its
    final events from its own thread."""

    def __init__(self, path, flush_every=16, fsync=False,
                 flush_interval_s=1.0):
        self.path = str(path)
        self.flush_every = max(1, int(flush_every))
        self.flush_interval_s = max(0.0, float(flush_interval_s or 0.0))
        self.fsync = bool(fsync)
        self._f = None
        self._pending = 0
        self._last_flush = time.monotonic()
        self._lock = threading.Lock()

    def emit(self, rec):
        with self._lock:
            if self._f is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._f = open(self.path, "a")
            self._f.write(json.dumps(rec, default=str) + "\n")
            self._pending += 1
            if self._pending >= self.flush_every \
                    or rec.get("ev") == "run_end" \
                    or (self.flush_interval_s > 0.0
                        and time.monotonic() - self._last_flush
                        >= self.flush_interval_s):
                self._flush_locked(sync=(self.fsync and
                                         rec.get("ev") == "run_end"))

    def _flush_locked(self, sync=False):
        self._f.flush()
        self._pending = 0
        self._last_flush = time.monotonic()
        if sync:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._flush_locked(sync=self.fsync)

    def close(self):
        with self._lock:
            if self._f is not None:
                self._flush_locked(sync=self.fsync)
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class NullObserver:
    """The disabled observer: every hook is a no-op.  A single shared
    instance (NULL_OBSERVER) sits on GBDT/learner objects by default so
    the enabled check is one attribute load."""

    enabled = False
    timeline = ()
    health = None
    rank = 0
    world_size = 1
    _closed = False
    live_url = ""

    def event(self, ev, **fields):
        pass

    def ensure_live_server(self, port, addr="127.0.0.1"):
        return ""

    def ring_tail(self, after=0):
        return 0, []

    def watchdog_arm(self, label):
        pass

    def watchdog_disarm(self):
        pass

    def flight(self, reason, extra=None):
        pass

    def add_flight_provider(self, fn):
        pass

    def remove_flight_provider(self, fn):
        pass

    def incident_signal(self, kind, detail=None):
        return None

    def incidents(self):
        return {"enabled": False, "open": [], "closed": []}

    def stamp_context(self, **fields):
        pass

    def prof_arm(self):
        return None

    def prof_disarm(self):
        pass

    def iter_begin(self, it):
        pass

    def lap(self, name, value=None):
        pass

    def iter_end(self, it, value=None, **fields):
        pass

    def entry_start(self):
        return 0.0

    def entry_args(self, name, fn, args, names=None, donate=()):
        pass

    def entry_end(self, name, t0, value=None):
        pass

    def straggler_sample(self, it, value):
        pass

    def memory_snapshot(self, it):
        pass

    def flush(self):
        pass

    def close(self, status="ok"):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(status="aborted" if exc_type is not None else "ok")
        return False


NULL_OBSERVER = NullObserver()


class RunObserver(NullObserver):
    """Live observer: drives the phase clock, entry timers, memory
    sampler and trace window, and appends every event to both the
    in-memory ``timeline`` (exposed via Booster.telemetry() and the
    record_telemetry callback) and the JSONL writer."""

    enabled = True

    def __init__(self, events_path="", timing="phase", memory_every=0,
                 trace_iters="", trace_dir="", flush_every=16,
                 health=None, metrics_every=0, metrics_path="",
                 compile_attr=False, straggler_every=0,
                 straggler_warn_skew=0.5, rank=None, world_size=None,
                 coordinator="", fsync=False, watchdog_secs=0.0,
                 flight_events=256, ledger_dir="", ledger_suite="",
                 utilization_every=0, roofline_peaks="",
                 http_port=None, http_addr="127.0.0.1",
                 incident=False, incident_window_s=5.0,
                 incident_dir="", incident_trace=False,
                 prof_hz=0, prof_window_s=5.0, prof_topk=20):
        from . import metrics as metrics_mod
        if rank is None or world_size is None:
            info = _default_rank_info()
            rank = info["rank"] if rank is None else rank
            world_size = (info["world_size"] if world_size is None
                          else world_size)
            coordinator = coordinator or info.get("coordinator", "")
        self.rank = int(rank)
        self.world_size = max(1, int(world_size))
        self.coordinator = str(coordinator or "")
        self.run_id = os.urandom(4).hex()
        self.timing = timing
        self.timeline = []
        self.events_path = resolve_rank_path(events_path, self.rank,
                                             self.world_size)
        self._writer = (EventWriter(self.events_path, flush_every,
                                    fsync=fsync)
                        if self.events_path else None)
        self._ring = RingBuffer(flight_events)
        self._flight_dumped = False
        self._flight_providers = []
        self._seq = 0
        self._clock = PhaseClock(fence_laps=(timing == "phase"))
        self._entries = EntryTimers()
        self._memory = MemorySampler(memory_every)
        self._trace = TraceWindow(trace_iters, trace_dir)
        self._iters = 0
        self._closed = False
        self.health = health                 # HealthMonitors or None
        self._metrics_every = max(0, int(metrics_every))
        self._metrics_path = str(metrics_path or "")
        self._registry = metrics_mod.REGISTRY
        self._compile = None
        if compile_attr:
            from .compile import CompileTracker
            self._compile = CompileTracker(self._registry)
        # roofline rollup cadence (obs_utilization_every): needs the
        # compile tracker's cost estimates, so it implies obs_compile
        self._utilization_every = max(0, int(utilization_every or 0))
        self._roofline_peaks_path = str(roofline_peaks or "")
        self._roofline_peaks = None          # resolved lazily, once
        if self._utilization_every and self._compile is None:
            from .compile import CompileTracker
            self._compile = CompileTracker(self._registry)
        self._straggler = None
        if int(straggler_every or 0) > 0:
            from .straggler import StragglerProfiler
            self._straggler = StragglerProfiler(
                every=straggler_every, warn_skew=straggler_warn_skew,
                registry=self._registry)
        self._m_iter_s = self._registry.histogram(
            "lgbm_train_iter_seconds",
            "per-iteration wall time as timed by the run observer "
            "(fencing per obs_timing)")
        self._m_iters = self._registry.counter(
            "lgbm_train_iterations_total", "boosting iterations completed")
        self._ledger_dir = str(ledger_dir or "")
        self._ledger_suite = str(ledger_suite or "")
        self._watchdog = None
        if float(watchdog_secs or 0.0) > 0.0:
            from .watchdog import Watchdog
            self._watchdog = Watchdog(self, float(watchdog_secs))
            self._watchdog.start()
        # host-side live state the scrape plane (obs/live.py) reads: the
        # server thread must never touch device values or fence
        self._header = None
        self._lifecycle = "startup"
        self._last_it = None
        self._ewma_iter_s = None
        self._last_utilization = None
        self._health_fatal = False
        # host-side run context stamped by the training loop
        # (stamp_context): what the run was doing, for /statusz and the
        # incident evidence bundle
        self._run_context = {}
        self._incident = None
        if incident:
            from .incident import IncidentEngine
            self._incident = IncidentEngine(
                self, window_s=float(incident_window_s or 5.0),
                bundle_dir=str(incident_dir or ""),
                trace=bool(incident_trace))
        # continuous host sampling profiler (obs/prof.py, schema 16):
        # constructed lazily by prof_arm() — the training loop arms it
        # at run start (models/gbdt.py) and close() disarms, flushing
        # the final window before run_end
        self._prof = None
        self._prof_hz = max(0, int(prof_hz or 0))
        self._prof_window_s = float(prof_window_s or 5.0)
        self._prof_topk = max(1, int(prof_topk or 20))
        self._live = None
        if http_port is not None and int(http_port) >= 0:
            self.ensure_live_server(int(http_port), http_addr)
        # a killed run must still end in a flushed, parseable timeline
        atexit.register(self._finalize_at_exit)
        _register_observer(self)

    # -- live telemetry plane (obs/live.py) -----------------------------
    @property
    def live_url(self):
        """URL of the in-run scrape server, or "" when the plane is off."""
        return self._live.url if self._live is not None else ""

    def ensure_live_server(self, port, addr="127.0.0.1"):
        """Start the live scrape server if it is not already up
        (``Booster.serve()`` calls this so a serving process exposes the
        same plane a training run does).  Returns the URL ("" when the
        observer is closed or the bind failed)."""
        if self._closed:
            return ""
        if self._live is not None:
            return self._live.url
        from .live import LiveServer
        self._live = LiveServer(self, port, addr)
        return self._live.start()

    def ring_tail(self, after=0):
        """(last_seq, records newer than ``after``) from the event ring
        — the /events endpoint's cursor read."""
        return self._ring.tail(after)

    # -- raw emission --------------------------------------------------
    def event(self, ev, **fields):
        rec = {"ev": ev, "t": time.time(), "run": self.run_id}
        if self.world_size > 1:
            rec["rank"] = self.rank
        rec.update(fields)
        # live-state captures for the scrape plane: two string compares
        # per event, host-only
        if ev == "utilization":
            self._last_utilization = rec
        elif ev == "health" and fields.get("status") == "fatal":
            self._health_fatal = True
        self.timeline.append(rec)
        self._ring.append(rec)
        if self._writer is not None:
            self._writer.emit(rec)
        # incident tap LAST, after the record landed: a signal that
        # opens an incident emits its own events re-entrantly and they
        # must sort after their trigger in the timeline
        if self._incident is not None:
            self._incident.observe(rec)
        return rec

    def run_header(self, backend, devices, params, context):
        self._header = self.event(
            "run_header", schema=SCHEMA_VERSION, backend=backend,
            devices=devices, params=params, context=context,
            timing=self.timing, rank=self.rank,
            world_size=self.world_size, coordinator=self.coordinator,
            provenance=collect_provenance())

    # -- per-iteration hooks ------------------------------------------
    def iter_begin(self, it):
        self._lifecycle = "train"
        if self._watchdog is not None:
            self._watchdog.arm("iter %d" % it)
        self._trace.maybe_start(it, self)
        if self._incident is not None:
            self._incident.maybe_trace_start(it, self)
        self._clock.begin()

    def lap(self, name, value=None):
        self._clock.lap(name, value)

    def iter_end(self, it, value=None, **fields):
        if self.timing in ("phase", "iter"):
            fence(value)
        total, phases = self._clock.end()
        seq = self._seq
        self._seq += 1
        self._iters += 1
        self._last_it = int(it)
        self._ewma_iter_s = (total if self._ewma_iter_s is None
                             else 0.7 * self._ewma_iter_s + 0.3 * total)
        self._m_iter_s.observe(total)
        self._m_iters.inc()
        self.event("iter", it=it, seq=seq, time_s=total, phases=phases,
                   fenced=(self.timing in ("phase", "iter")), **fields)
        if self._watchdog is not None:
            self._watchdog.pet("iter %d done" % it)
        devices = self._memory.maybe(it)
        if devices is not None:
            self.event("memory", it=it, devices=devices)
            for d in devices:
                if "bytes_in_use" in d:
                    self._registry.gauge(
                        "lgbm_device_bytes_in_use",
                        "device allocator bytes in use at the last snapshot",
                        labels={"device": str(d["id"])}).set(
                            d["bytes_in_use"])
        if self.health is not None and self.health.due(it):
            # may raise under obs_health=fatal — the iter event above and
            # the writer flush in the monitor keep the timeline parseable
            self.health.check_memory(self, it, devices)
        if self._metrics_every and it % self._metrics_every == 0:
            self.event("metrics", it=it, scrape=self._registry.snapshot())
        if self._utilization_every and it % self._utilization_every == 0:
            self._emit_utilization(it)
        self._trace.maybe_stop(it, self)
        if self._incident is not None:
            self._incident.maybe_trace_stop(it, self)

    def _emit_utilization(self, it):
        """The schema-13 roofline rollup (obs/roofline.py): exec-weighted
        achieved/peak utilization of every timed entry with a cost
        estimate.  No fence, no device work — it joins numbers the
        observer already holds, so the cadence costs host time only."""
        from . import roofline
        if self._roofline_peaks is None:
            overrides = roofline.load_peak_overrides(
                self._roofline_peaks_path)
            self._roofline_peaks = roofline.peaks_for(
                roofline.device_kind(), overrides)
        rollup = roofline.utilization_rollup(
            self._entries.summary(),
            self._compile.costs() if self._compile is not None else {},
            self._roofline_peaks, world_size=self.world_size)
        if rollup is not None:
            self.event("utilization", it=it, **rollup)
            self._registry.gauge(
                "lgbm_flop_utilization",
                "exec-weighted achieved/peak FLOP fraction at the last "
                "utilization rollup").set(rollup["flop_util"])
            self._registry.gauge(
                "lgbm_hbm_utilization",
                "exec-weighted achieved/peak HBM-bandwidth fraction at "
                "the last utilization rollup").set(rollup["hbm_util"])

    # -- jitted entry points ------------------------------------------
    def entry_start(self):
        return time.perf_counter()

    def entry_args(self, name, fn, args, names=None, donate=()):
        """Pre-call hook (obs_compile): snapshot the entry's argument
        signature and jit-cache size so entry_end can attribute a
        recompile to the axis/dtype/donation that changed."""
        if self._compile is not None:
            self._compile.before_call(name, fn, args, names=names,
                                      donate=donate)

    def entry_end(self, name, t0, value=None):
        fenced = self.timing == "phase"
        if fenced:
            fence(value)
        dt = time.perf_counter() - t0
        if self._entries.record(name, dt):
            self.event("compile", entry=name, first_call_s=dt, fenced=fenced)
        if self._compile is not None:
            self._compile.after_call(name, self)

    def straggler_sample(self, it, value):
        """Sampled per-shard arrival timing (obs_straggler_every); a
        fence, so the profiler's cadence gates it."""
        if self._straggler is not None and self._straggler.due(it):
            self._straggler.sample(self, it, value)

    # -- hang forensics (obs/watchdog.py) ------------------------------
    def watchdog_arm(self, label):
        """Arm the hang watchdog around a blocking region (a host
        collective): no progress for obs_watchdog_secs from now dumps a
        flight record naming ``label``."""
        if self._watchdog is not None:
            self._watchdog.arm(label)

    def watchdog_disarm(self):
        """The blocking region completed; fall back to the per-iteration
        progress deadline."""
        if self._watchdog is not None:
            self._watchdog.pet("idle")

    def flight(self, reason, extra=None):
        """Dump a flight record now (watchdog expiry, SIGTERM,
        obs_health=fatal).  Works with the watchdog off — the ring
        buffer is always live.  Returns the path written, or None when
        there is no events path to anchor the dump next to."""
        from .watchdog import dump_flight_record
        return dump_flight_record(self, reason, extra=extra)

    def add_flight_provider(self, fn):
        """Register a zero-arg callable returning a dict of live context
        to merge into every flight record (serve/scheduler.py registers
        its queue state here: depth, queued rows, pending routes).
        Providers must be best-effort — a provider that raises is
        skipped, never propagated into the dump."""
        self._flight_providers.append(fn)

    def remove_flight_provider(self, fn):
        try:
            self._flight_providers.remove(fn)
        except ValueError:
            pass

    def flight_context(self):
        """Merged provider dicts; forensics-grade best-effort."""
        out = {}
        for fn in list(self._flight_providers):
            try:
                out.update(fn() or {})
            except Exception as e:
                out.setdefault("provider_errors", []).append(repr(e))
        return out

    @property
    def flight_path(self):
        if self._writer is None:
            return ""
        return self._writer.path + ".flight.json"

    def ring_snapshot(self):
        return self._ring.snapshot()

    # -- incident engine (obs/incident.py) -----------------------------
    def incident_signal(self, kind, detail=None):
        """Feed one anomaly signal into the incident engine from a
        channel that does not emit timeline events itself (the serve
        scheduler's shed storm, the watchdog's near-expiry warning, the
        POST /trigger/incident operator endpoint).  Returns the open
        incident id, or None when the engine is off."""
        if self._incident is None:
            return None
        return self._incident.signal(str(kind), detail=detail)

    def incidents(self):
        """Open/closed incident listing for the /incidents endpoint."""
        if self._incident is None:
            return {"enabled": False, "open": [], "closed": []}
        return self._incident.listing()

    def stamp_context(self, **fields):
        """Update the host-side run-context dict (iteration, tree count,
        loop stage) that /statusz, incident evidence bundles and the
        sampling profiler's stage tags read — a plain dict update,
        never a fence."""
        self._run_context.update(fields)

    # -- continuous host profiler (obs/prof.py, schema 16) --------------
    def prof_arm(self):
        """Start the sampling profiler when ``obs_prof_hz > 0``
        (idempotent — the daemon thread is constructed once and
        restarted if a previous disarm stopped it).  Returns the
        profiler, or None when sampling is off or the observer closed."""
        if self._prof_hz <= 0 or self._closed:
            return None
        if self._prof is None:
            from .prof import HostProfiler
            self._prof = HostProfiler(
                emit=self.event, hz=self._prof_hz,
                window_s=self._prof_window_s, topk=self._prof_topk,
                context=self._run_context,
                phase_of=lambda: self._clock.current,
                iter_of=lambda: self._last_it)
        self._prof.start()
        return self._prof

    def prof_disarm(self):
        """Stop the sampler and flush its final partial window as a
        ``prof_profile`` event (idempotent; ``close()`` calls this
        before ``run_end`` so the last window sorts inside the run)."""
        if self._prof is not None:
            self._prof.stop()

    # -- misc ----------------------------------------------------------
    def memory_snapshot(self, it):
        self.event("memory", it=it, devices=device_memory_stats())

    def flush(self):
        if self._writer is not None:
            self._writer.flush()

    def close(self, status="ok"):
        if self._closed:
            return
        self._lifecycle = "closed" if status == "ok" else "aborted"
        if status == "aborted" and not self._flight_dumped:
            # the flight record is the black box: write it BEFORE the
            # run_end path below can fail.  A record the watchdog (or
            # obs_health=fatal) already dumped names the actual hang —
            # don't overwrite it with this generic one.
            try:
                self.flight("run aborted")
            except Exception:
                pass
        self._closed = True
        if self._watchdog is not None:
            self._watchdog.stop()
        _unregister_observer(self)
        try:
            atexit.unregister(self._finalize_at_exit)
        except Exception:
            pass
        self._trace.force_stop(self)
        # stop the sampling profiler and flush its final window BEFORE
        # run_end so the last prof_profile sorts inside the run (and the
        # ledger's prof_overhead_frac cell sees every window)
        try:
            self.prof_disarm()
        except Exception:
            pass
        # close any open incident BEFORE run_end so incident_close sorts
        # inside the run; the digest rides on run_end (zeros included)
        incidents_digest = None
        if self._incident is not None:
            try:
                incidents_digest = self._incident.finalize()
            except Exception:
                incidents_digest = None
        metrics_on = self._metrics_every or self._metrics_path
        if metrics_on:
            self.event("metrics", it=self._iters,
                       scrape=self._registry.snapshot())
        end = {"iters": self._iters, "phase_totals": self._clock.totals(),
               "entries": self._entries.summary(), "status": status}
        if incidents_digest is not None:
            end["incidents"] = incidents_digest
        if self.health is not None:
            end["health"] = self.health.summary()
        if self._compile is not None:
            end["compile_attr"] = self._compile.summary()
        if self._straggler is not None:
            end["stragglers"] = self._straggler.summary()
        self.event("run_end", **end)
        if self._metrics_path:
            try:
                self._registry.write(self._metrics_path)
                Log.debug("obs: metrics export -> %s", self._metrics_path)
            except OSError as e:
                Log.warning("obs: metrics export to %s failed: %s",
                            self._metrics_path, e)
        if self._writer is not None:
            self._writer.close()
            Log.debug("obs: wrote %d events to %s", len(self.timeline),
                      self._writer.path)
        # cross-run ledger (obs_ledger_dir): only CLEAN runs become
        # baseline history — an aborted run's partial metrics would
        # poison the rolling statistics.  Best-effort: the ledger must
        # never take a finished run down.
        if self._ledger_dir and status == "ok":
            try:
                from .ledger import Ledger
                if Ledger(self._ledger_dir).ingest_events(
                        list(self.timeline), suite=self._ledger_suite):
                    Log.debug("obs: run %s ingested into ledger %s",
                              self.run_id, self._ledger_dir)
            except Exception as e:
                Log.warning("obs: ledger ingest into %s failed: %s",
                            self._ledger_dir, e)
        # live plane teardown LAST: /healthz and /statusz stay
        # scrapeable through finalize, then the ephemeral port frees
        if self._live is not None:
            self._live.stop()
            self._live = None

    def _finalize_at_exit(self):
        """atexit hook: a run that never reached finalize (crash, sys.exit,
        uncaught signal that still unwinds) ends aborted but parseable."""
        try:
            self.close(status="aborted")
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(status="aborted" if exc_type is not None else "ok")
        return False
