"""Text parsers: CSV / TSV / LibSVM with format auto-detection.

Parity target: src/io/parser.hpp:15-77 and src/io/parser.cpp:10-101 — format
is detected by counting separator occurrences and colons in the first two
non-empty lines; LibSVM when ':' pairs dominate, else tab vs comma vs space.
Vectorized with numpy for the dense formats.
"""
from __future__ import annotations

import io
from typing import List, Optional, Tuple

import numpy as np

from ..utils.log import Log


def _count_stats(line: str) -> Tuple[int, int, int]:
    """(num_commas, num_tabs, num_colon_pairs) in one line."""
    return line.count(","), line.count("\t"), line.count(":")


def detect_format(sample_lines: List[str]) -> str:
    """Return 'csv' | 'tsv' | 'libsvm' (parser.cpp:10-70 semantics)."""
    lines = [l for l in sample_lines if l.strip()][:2]
    if not lines:
        return "csv"
    stats = [_count_stats(l) for l in lines]
    comma = min(s[0] for s in stats)
    tab = min(s[1] for s in stats)
    colon = min(s[2] for s in stats)
    if colon > 0 and colon >= max(comma, tab):
        return "libsvm"
    if tab > 0 and tab >= comma:
        return "tsv"
    if comma > 0:
        return "csv"
    # space-separated falls into the TSV code path with ' ' separator
    return "space"


_SEP = {"csv": ",", "tsv": "\t", "space": None}


class ParsedData:
    """Dense row-major matrix + label column, the parser output."""

    def __init__(self, features: np.ndarray, label: np.ndarray,
                 fmt: str, label_idx: int):
        self.features = features
        self.label = label
        self.format = fmt
        self.label_idx = label_idx

    @property
    def num_data(self) -> int:
        return self.features.shape[0]

    @property
    def num_features(self) -> int:
        return self.features.shape[1]


def parse_file(filename: str, has_header: bool = False, label_idx: int = 0,
               max_lines: Optional[int] = None) -> ParsedData:
    with open(filename, "r") as f:
        text = f.read()
    return parse_text(text, has_header=has_header, label_idx=label_idx,
                      max_lines=max_lines)


def read_header(filename: str) -> List[str]:
    with open(filename, "r") as f:
        first = f.readline().rstrip("\r\n")
    fmt = detect_format([first])
    sep = _SEP.get(fmt)
    return first.split(sep) if sep else first.split()


def parse_text(text: str, has_header: bool = False, label_idx: int = 0,
               max_lines: Optional[int] = None) -> ParsedData:
    lines = text.splitlines()
    if has_header and lines:
        lines = lines[1:]
    lines = [l for l in lines if l.strip()]
    if max_lines is not None:
        lines = lines[:max_lines]
    if not lines:
        Log.fatal("Data file is empty")
    fmt = detect_format(lines)
    if fmt == "libsvm":
        return _parse_libsvm(lines, label_idx)
    sep = _SEP[fmt]
    return _parse_dense(lines, sep, fmt, label_idx)


def _parse_dense(lines: List[str], sep: Optional[str], fmt: str,
                 label_idx: int) -> ParsedData:
    buf = io.StringIO("\n".join(lines))
    try:
        mat = np.loadtxt(buf, delimiter=sep, dtype=np.float64, ndmin=2)
    except ValueError:
        # tolerate 'na'/'nan'/'inf' mixes by per-token conversion fallback
        rows = []
        for l in lines:
            toks = l.split(sep) if sep else l.split()
            rows.append([_safe_float(t) for t in toks])
        mat = np.asarray(rows, dtype=np.float64)
    if label_idx >= 0 and label_idx < mat.shape[1]:
        label = mat[:, label_idx].copy()
        feats = np.delete(mat, label_idx, axis=1)
    else:
        label = np.zeros(mat.shape[0], dtype=np.float64)
        feats = mat
    return ParsedData(np.ascontiguousarray(feats), label, fmt, label_idx)


def _safe_float(tok: str) -> float:
    tok = tok.strip()
    if not tok or tok.lower() in ("na", "nan", "null", "none"):
        return np.nan
    try:
        return float(tok)
    except ValueError:
        return np.nan


def _parse_libsvm(lines: List[str], label_idx: int) -> ParsedData:
    labels = np.empty(len(lines), dtype=np.float64)
    pairs: List[List[Tuple[int, float]]] = []
    max_feat = -1
    for i, l in enumerate(lines):
        toks = l.split()
        if toks and ":" not in toks[0]:
            labels[i] = float(toks[0])
            toks = toks[1:]
        else:
            labels[i] = 0.0
        row = []
        for t in toks:
            if ":" not in t:
                continue
            k, _, v = t.partition(":")
            fi = int(k)
            row.append((fi, float(v)))
            if fi > max_feat:
                max_feat = fi
        pairs.append(row)
    feats = np.zeros((len(lines), max_feat + 1), dtype=np.float64)
    for i, row in enumerate(pairs):
        for fi, v in row:
            feats[i, fi] = v
    return ParsedData(feats, labels, "libsvm", label_idx)
