#!/usr/bin/env python
"""CI smoke for the out-of-core streaming ingest (io/streaming.py +
io/binned_format.py) — on CPU.

Exercises the full two-pass CSV pipeline end to end:

  run 1 (stream + persist): a synthetic CSV is streamed chunk-by-chunk
         through the parallel sketch/bin worker pool (ooc_workers=2)
         straight into a pre-binned mmap-able directory
         (ooc_binned_dir), and a model is trained on it;
  run 2 (pre-binned reload): training is pointed at the binned
         directory; the dataset_construct event must report
         sketch_s == bin_s == 0 (ZERO re-binning — the contract
         bench_compare's construct_s metric gates) and the trained
         model must be byte-identical to run 1's.

Finishes with a bench_compare self-compare of the run-2 timeline so
the construct_s extraction path is exercised by CI too.  Exits nonzero
on any violation.  See docs/OutOfCore.md.

Usage: python tools/ooc_smoke.py [WORKDIR]
(WORKDIR keeps the timelines for artifact upload; default: a tempdir.)
"""
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_ROWS, N_COLS = 4000, 10


def events_of(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def write_csv(path, rng):
    import numpy as np
    X = rng.standard_normal((N_ROWS, N_COLS)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    with open(path, "w") as f:
        for i in range(N_ROWS):
            f.write("%d,%s\n" % (y[i],
                                 ",".join("%.6g" % v for v in X[i])))


def main():
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    fails = []

    def check(cond, msg):
        if not cond:
            fails.append(msg)
            print("FAIL: %s" % msg)

    work = sys.argv[1] if len(sys.argv) > 1 else None
    tmp_ctx = tempfile.TemporaryDirectory() if work is None else None
    work = work or tmp_ctx.name
    os.makedirs(work, exist_ok=True)

    csv = os.path.join(work, "ooc_train.csv")
    bindir = os.path.join(work, "ooc_binned")
    ev1_path = os.path.join(work, "ooc_run1.jsonl")
    ev2_path = os.path.join(work, "ooc_run2.jsonl")
    write_csv(csv, rng)

    base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
            "min_data_in_leaf": 5, "verbose": -1}

    # run 1: stream the CSV through the two-pass pipeline into bindir
    p1 = dict(base, ooc_binned_dir=bindir, ooc_workers=2,
              ooc_chunk_rows=512, obs_events_path=ev1_path)
    b1 = lgb.train(p1, lgb.Dataset(csv, params=p1), num_boost_round=3)
    ev1 = events_of(ev1_path)
    c1 = [e for e in ev1 if e.get("ev") == "dataset_construct"]
    check(len(c1) == 1, "run1: expected 1 dataset_construct, got %d"
          % len(c1))
    if c1:
        check(c1[0].get("source") == "stream:text",
              "run1: source %r != 'stream:text'" % c1[0].get("source"))
        check(c1[0].get("rows") == N_ROWS,
              "run1: rows %r != %d" % (c1[0].get("rows"), N_ROWS))
        check(c1[0].get("chunks", 0) > 1,
              "run1: expected multi-chunk streaming, got %r chunks"
              % c1[0].get("chunks"))
    check(os.path.isfile(os.path.join(bindir, "header.json")),
          "binned dir missing header.json")

    # run 2: retrain straight from the pre-binned directory
    p2 = dict(base, obs_events_path=ev2_path)
    b2 = lgb.train(p2, lgb.Dataset(bindir, params=p2), num_boost_round=3)
    ev2 = events_of(ev2_path)
    c2 = [e for e in ev2 if e.get("ev") == "dataset_construct"]
    check(len(c2) == 1, "run2: expected 1 dataset_construct, got %d"
          % len(c2))
    if c2:
        check(c2[0].get("source") == "binned",
              "run2: source %r != 'binned'" % c2[0].get("source"))
        check(c2[0].get("sketch_s") == 0 and c2[0].get("bin_s") == 0,
              "run2: pre-binned reload re-binned the data "
              "(sketch_s=%r bin_s=%r)" % (c2[0].get("sketch_s"),
                                          c2[0].get("bin_s")))
    check(b1.model_to_string() == b2.model_to_string(),
          "model trained from binned dir differs from streamed run")

    # bench_compare must extract construct_s from the timeline and a
    # self-compare must pass
    cmp_cmd = [sys.executable,
               os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_compare.py"),
               ev2_path, ev2_path, "--json"]
    r = subprocess.run(cmp_cmd, capture_output=True, text=True)
    check(r.returncode == 0, "bench_compare self-compare failed (rc=%d):"
          " %s" % (r.returncode, r.stderr.strip()))
    if r.returncode == 0:
        verdict = json.loads(r.stdout)
        names = [m["metric"] for m in verdict.get("metrics", [])]
        check("construct_s" in names,
              "bench_compare did not extract construct_s: %r" % names)

    if tmp_ctx is not None:
        tmp_ctx.cleanup()
    if fails:
        print("ooc smoke: %d failure(s)" % len(fails))
        return 1
    print("ooc smoke: OK (streamed %d rows in %d chunks -> %s; "
          "reload sketch_s=%s bin_s=%s; models identical)"
          % (N_ROWS, c1[0]["chunks"], os.path.basename(bindir),
             c2[0]["sketch_s"], c2[0]["bin_s"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
