"""Continuous host sampling profiler (schema 16, ``prof_profile``).

``host_orchestration_s`` (schema 11) says HOW MUCH host time each
iteration spends between device program submissions; this module says
WHERE.  A daemon thread walks ``sys._current_frames()`` on a jittered
monotonic clock (``obs_prof_hz``, default ~29 Hz — a prime-ish rate so
the sampler cannot alias against a periodic training cadence; ``0``
disables), folds every thread's stack into Brendan-Gregg collapsed-stack
counts, and tags each sample with the live context the observer already
maintains: the stamped loop stage (``stamp_context`` — boost / eval /
checkpoint), the phase-timer lap most recently crossed, the current
iteration, and the thread's role (its name — every in-tree daemon
thread carries a stable ``lgbm-<role>`` name exactly so these profiles
attribute by role instead of ``Thread-7``).

Samples aggregate into one ``prof_profile`` event per
``obs_prof_window_s`` window: the top-K folded stacks plus a
truncated-tail count, per-role / per-stage / per-phase sample totals,
the iteration span the window covered, and — first class, because an
always-on profiler that silently eats the run is worse than none — the
sampler's **self-measured cost** (``cost_s`` / ``overhead_frac``).
``bench.py --dry`` asserts ``overhead_frac < 1%``, the ledger records
it as a gated cell, and ``obs prof --check`` exits 1 when any window
blew the budget, carries a sampler ``error``, or saw zero samples while
iterations advanced (a wedged sampler must be loud, not silent).

Consumers:

* ``python -m lightgbm_tpu obs prof <timeline|dir> [--check]
  [--flame out.html] [--top N]`` — terminal top-table +
  self-contained (d3-free) HTML flamegraph;
* ``GET /prof?seconds=N`` on the live plane (obs/live.py) — on-demand
  synchronous burst capture, loopback peers only;
* incident evidence bundles (obs/incident.py) — a sampled profile
  window lands next to the one-shot thread stacks;
* ``tools/tpu_profile.py`` — the host top-table printed next to the
  device trace, so one command shows both halves of the pipeline.

Everything here is pure stdlib and host-side: no jax import, no
fence — sampling must never perturb the async dispatch pipeline it
measures.  ``capture_thread_stacks`` is the one shared stack-capture
path: the watchdog's flight records and incident evidence delegate
here, so there is exactly one ``sys._current_frames`` walker in tree.
"""
from __future__ import annotations

import html as _html
import json
import os
import random
import sys
import threading
import time
import traceback

from ..utils.log import Log

# the gated overhead budget: self-measured sampling cost per window as a
# fraction of the window's wall time.  bench.py --dry and `obs prof
# --check` both gate on this constant.
OVERHEAD_BUDGET_FRAC = 0.01

_PKG_MARKER = os.sep + "lightgbm_tpu" + os.sep


# ---------------------------------------------------------------- folding

def _short_path(path):
    """Shorten a code filename for stack labels: files under the package
    root keep their ``lightgbm_tpu/...`` suffix (so "top stack lands in
    lightgbm_tpu code" is a substring check), everything else collapses
    to ``parent/file.py``."""
    i = path.rfind(_PKG_MARKER)
    if i >= 0:
        return "lightgbm_tpu/" + path[i + len(_PKG_MARKER):].replace(
            os.sep, "/")
    base = os.path.basename(path)
    parent = os.path.basename(os.path.dirname(path))
    return (parent + "/" + base) if parent else base


# code objects are immutable and long-lived, so the label each one
# folds to is computed once — the memo keeps every sampling tick to a
# dict hit per frame instead of two basename walks and a format
_LABEL_MEMO = {}


def _frame_label(code):
    label = _LABEL_MEMO.get(code)
    if label is None:
        label = "%s:%s" % (_short_path(code.co_filename), code.co_name)
        _LABEL_MEMO[code] = label
    return label


def fold_frames(frame):
    """Root->leaf ``shortpath:func`` labels for one thread's live stack
    (the Brendan-Gregg collapsed-stack frame list, minus line numbers —
    line-level splits would shred the counts across samples)."""
    labels = []
    while frame is not None:
        labels.append(_frame_label(frame.f_code))
        frame = frame.f_back
    labels.reverse()
    return labels


def thread_roles():
    """{ident: thread name} for every live thread — the role map both
    the sampler and the flight-record capture attribute by."""
    return {t.ident: t.name for t in threading.enumerate()}


# leaves a thread parks in while doing nothing: selector/socket waits,
# lock/event waits, queue gets.  A stack whose leaf is one of these AND
# that never passes through lightgbm_tpu code is an idle stdlib thread
# (an http server's select loop, a parked pool worker) — pure wait, not
# cost, so the sampler skips it (py-spy's default --idle=false).  In-tree
# threads are always kept, whatever their leaf: a blocked EventWriter or
# serve worker passes through lightgbm_tpu frames, and seeing WHERE it
# waits is the point.
_IDLE_LEAF_NAMES = frozenset((
    "select", "poll", "epoll", "kqueue", "wait", "_wait_for_tstate_lock",
    "accept", "acquire", "get", "sleep", "_recv", "recv", "read",
    "readinto"))


def _is_idle_stack(labels):
    if not labels:
        return True
    if any(lb.startswith("lightgbm_tpu/") for lb in labels):
        return False
    return labels[-1].rsplit(":", 1)[-1] in _IDLE_LEAF_NAMES


def capture_thread_stacks():
    """One-shot ``{"name (ident)": [formatted frame lines]}`` for every
    live Python thread — the flight-record / incident-evidence shape
    (obs/watchdog.py delegates here; keep the shape stable)."""
    names = thread_roles()
    out = {}
    for ident, frame in sys._current_frames().items():
        label = "%s (%d)" % (names.get(ident, "?"), ident)
        out[label] = [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)]
    return out


# ------------------------------------------------------------- the window

class _Window:
    """One aggregation window of samples.  ``samples`` counts sampler
    ticks (each tick walks every thread, so per-role totals can exceed
    it); ``cost_s`` is the sampler's own accumulated per-tick cost."""

    __slots__ = ("t0", "samples", "cost_s", "stacks", "roles", "stages",
                 "phases", "iter_lo", "iter_hi", "error")

    def __init__(self, t0):
        self.t0 = t0
        self.samples = 0
        self.cost_s = 0.0
        self.stacks = {}          # "role;frame;frame;..." -> tick count
        self.roles = {}
        self.stages = {}
        self.phases = {}
        self.iter_lo = None
        self.iter_hi = None
        self.error = ""


def aggregate_window(window, dur_s, hz, topk):
    """Reduce a ``_Window`` to the ``prof_profile`` event payload:
    top-K stacks (deterministic count-then-name order), truncated-tail
    count, per-dimension totals, and the self-measured overhead.
    ``topk <= 0`` keeps every stack (burst captures)."""
    ranked = sorted(window.stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    keep = ranked if topk <= 0 else ranked[:topk]
    truncated = sum(c for _, c in ranked[len(keep):])
    dur_s = max(float(dur_s), 1e-9)
    payload = {
        "samples": window.samples,
        "dur_s": round(dur_s, 6),
        "hz": hz,
        "cost_s": round(window.cost_s, 6),
        "overhead_frac": round(window.cost_s / dur_s, 6),
        "stacks": dict(keep),
        "truncated": truncated,
        "topk": max(0, topk),
        "roles": dict(window.roles),
        "stages": dict(window.stages),
        "phases": dict(window.phases),
    }
    if window.iter_lo is not None:
        payload["iter_lo"] = window.iter_lo
        payload["iter_hi"] = window.iter_hi
    if window.error:
        payload["error"] = window.error
    return payload


# ------------------------------------------------------------ the sampler

class HostProfiler:
    """The always-on sampling profiler behind ``obs_prof_hz``.

    ``emit(ev, **fields)`` receives one ``prof_profile`` payload per
    flushed window (RunObserver passes its ``event`` method).  The
    clock and the frame source are injectable so the window/fold/
    truncation logic unit-tests against a fake clock, and a test can
    wedge the sampler on purpose (``frames_fn`` that raises) to prove
    the failure is loud: the loop catches the exception, stamps it as
    the window's ``error``, flushes that window, and stops — one
    poisoned window on the timeline instead of a silent gap.

    ``context`` is the observer's live ``_run_context`` dict (read
    racily, never locked — a torn read tags one sample with a stale
    stage, which the aggregate does not care about); ``phase_of`` /
    ``iter_of`` are zero-arg callables for the phase-timer lap and the
    current iteration.
    """

    def __init__(self, emit, hz=29, window_s=5.0, topk=20, context=None,
                 phase_of=None, iter_of=None, clock=time.monotonic,
                 frames_fn=None, source="train"):
        self._emit = emit
        self.hz = max(1, int(hz))
        self.window_s = float(window_s)
        self.topk = int(topk)
        self.source = str(source)
        self._context = context if context is not None else {}
        self._phase_of = phase_of
        self._iter_of = iter_of
        self._clock = clock
        self._frames = frames_fn or sys._current_frames
        self._lock = threading.Lock()
        self._window = _Window(clock())
        self._stop_evt = threading.Event()
        self._thread = None
        self.windows_emitted = 0
        self.wedged = False

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Start the daemon sampler thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt = threading.Event()
        self.wedged = False
        with self._lock:
            self._window = _Window(self._clock())
        self._thread = threading.Thread(target=self._loop,
                                        name="lgbm-obs-prof", daemon=True)
        self._thread.start()

    def stop(self):
        """Stop sampling and flush the final partial window (so a short
        run still lands >= 1 ``prof_profile`` on its timeline).
        Idempotent; a window that never saw a tick is dropped rather
        than emitted as a spurious zero-sample record."""
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop_evt.set()
        thread.join(timeout=2.0)
        with self._lock:
            has_content = self._window.samples > 0 or self._window.error
        if has_content:
            self.flush_now()

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------- sampling
    def tick(self, exclude_ident=None):
        """One sampling tick: walk every thread's live frame, fold, tag
        with stage/phase/iteration, accumulate self-cost.  Public so
        fake-clock tests and burst captures drive it directly."""
        t0 = self._clock()
        if exclude_ident is None and self._thread is not None:
            exclude_ident = self._thread.ident
        frames = self._frames()
        names = thread_roles()
        try:
            stage = str(self._context.get("stage") or "-")
        except Exception:
            stage = "-"
        phase = "-"
        if self._phase_of is not None:
            try:
                phase = str(self._phase_of() or "-")
            except Exception:
                phase = "-"
        it = None
        if self._iter_of is not None:
            try:
                it = self._iter_of()
            except Exception:
                it = None
        with self._lock:
            w = self._window
            w.samples += 1
            for ident, frame in frames.items():
                if ident == exclude_ident:
                    continue
                labels = fold_frames(frame)
                if _is_idle_stack(labels):
                    continue
                role = names.get(ident, "thread-%d" % ident)
                key = ";".join([role] + labels)
                w.stacks[key] = w.stacks.get(key, 0) + 1
                w.roles[role] = w.roles.get(role, 0) + 1
            w.stages[stage] = w.stages.get(stage, 0) + 1
            w.phases[phase] = w.phases.get(phase, 0) + 1
            if it is not None:
                it = int(it)
                if w.iter_lo is None:
                    w.iter_lo = it
                w.iter_hi = (it if w.iter_hi is None
                             else max(it, w.iter_hi))
            w.cost_s += max(0.0, self._clock() - t0)

    def flush_now(self, now=None):
        """Swap the window out and emit it as a ``prof_profile`` event.
        Best-effort: a failed emit logs, never raises into the run."""
        now = self._clock() if now is None else now
        with self._lock:
            w, self._window = self._window, _Window(now)
        payload = aggregate_window(w, now - w.t0, self.hz, self.topk)
        payload["source"] = self.source
        try:
            self._emit("prof_profile", **payload)
            self.windows_emitted += 1
        except Exception as e:
            Log.warning("obs: prof window emit failed: %s", e)
        return payload

    def peek(self):
        """Aggregate of the current partial window WITHOUT flushing —
        the incident-evidence snapshot (best-effort, lock held only for
        the copy)."""
        with self._lock:
            w = self._window
            snap = _Window(w.t0)
            snap.samples = w.samples
            snap.cost_s = w.cost_s
            snap.stacks = dict(w.stacks)
            snap.roles = dict(w.roles)
            snap.stages = dict(w.stages)
            snap.phases = dict(w.phases)
            snap.iter_lo, snap.iter_hi = w.iter_lo, w.iter_hi
            snap.error = w.error
        payload = aggregate_window(snap, self._clock() - snap.t0,
                                   self.hz, self.topk)
        payload["source"] = self.source
        return payload

    # ---------------------------------------------------------------- loop
    def _loop(self):
        period = 1.0 / self.hz
        # deterministic jitter stream: +/-20% around the nominal period
        # so the sampler cannot phase-lock onto the iteration cadence
        rng = random.Random(0x5EED)
        while not self._stop_evt.is_set():
            self._stop_evt.wait(period * (0.8 + 0.4 * rng.random()))
            if self._stop_evt.is_set():
                return
            try:
                self.tick()
            except Exception as e:
                # the wedged-sampler contract: stamp the window, flush
                # it (so --check sees the error), stop sampling — loud
                # exactly once, never a silent gap
                with self._lock:
                    self._window.error = repr(e)
                self.wedged = True
                self.flush_now()
                Log.warning("obs: host profiler wedged, sampling "
                            "stopped: %s", e)
                return
            now = self._clock()
            with self._lock:
                due = now - self._window.t0 >= self.window_s
            if due:
                self.flush_now(now)


def burst(seconds=0.25, hz=97, topk=0, context=None, phase_of=None,
          iter_of=None, source="burst"):
    """Synchronous capture from the calling thread: sample every OTHER
    thread for ``seconds`` at ``hz`` and return the aggregated window
    payload (untruncated by default).  Pure host work, zero fences —
    the ``GET /prof`` endpoint, incident evidence and the bench
    fence-flatness assert all run through here."""
    payloads = []
    prof = HostProfiler(emit=lambda ev, **f: payloads.append(f),
                        hz=hz, window_s=float("inf"), topk=topk,
                        context=context, phase_of=phase_of,
                        iter_of=iter_of, source=source)
    me = threading.get_ident()
    period = 1.0 / float(hz)
    deadline = time.monotonic() + max(0.0, float(seconds))
    while True:
        prof.tick(exclude_ident=me)
        if time.monotonic() >= deadline:
            break
        time.sleep(period)
    prof.flush_now()
    return payloads[-1]


def evidence_profile(obs, seconds=0.15):
    """The incident-evidence payload: the live profiler's current
    partial window when one is armed (free — no extra sampling at the
    moment of anomaly), else a short synchronous burst."""
    prof = getattr(obs, "_prof", None)
    if prof is not None and prof.running:
        return prof.peek()
    return burst(seconds=seconds,
                 context=getattr(obs, "_run_context", None),
                 source="incident")


# ========================================================================
# reader side: `obs prof` — top table, flamegraph, the --check gate
# ========================================================================

def profile_events(events):
    return [e for e in events if e.get("ev") == "prof_profile"]


def merged_profile(profs):
    """Merge a run's windows into one rollup: summed stack counts,
    per-dimension totals, total samples/duration/cost."""
    out = {"windows": len(profs), "samples": 0, "dur_s": 0.0,
           "cost_s": 0.0, "truncated": 0, "stacks": {}, "roles": {},
           "stages": {}, "phases": {}, "errors": []}
    for p in profs:
        out["samples"] += int(p.get("samples", 0) or 0)
        out["dur_s"] += float(p.get("dur_s", 0.0) or 0.0)
        out["cost_s"] += float(p.get("cost_s", 0.0) or 0.0)
        out["truncated"] += int(p.get("truncated", 0) or 0)
        for field in ("stacks", "roles", "stages", "phases"):
            for k, v in (p.get(field) or {}).items():
                out[field][k] = out[field].get(k, 0) + int(v)
        if p.get("error"):
            out["errors"].append(str(p["error"]))
    out["overhead_frac"] = (out["cost_s"] / out["dur_s"]
                            if out["dur_s"] > 0 else 0.0)
    return out


def check_profiles(events, budget=OVERHEAD_BUDGET_FRAC):
    """The gate behind ``obs prof --check``: list of problem strings
    (empty = pass).  Fails on a sampler ``error`` window, a run whose
    total sampling overhead (summed cost over summed duration — the
    same number the ledger records) blows the budget, or a zero-sample
    window on a timeline whose iterations advanced (a wedged sampler
    next to a live training loop).  The budget gates the run, not each
    window: a short final flush amplifies per-window noise without
    costing the run anything.  A timeline with no ``prof_profile``
    events at all passes — the profiler may simply be off
    (``obs_prof_hz=0``)."""
    problems = []
    iters_advanced = sum(1 for e in events if e.get("ev") == "iter") >= 2
    profs = profile_events(events)
    for i, p in enumerate(profs):
        if p.get("error"):
            problems.append("window %d: sampler error: %s"
                            % (i, p["error"]))
        if int(p.get("samples", 0) or 0) == 0 and iters_advanced:
            problems.append(
                "window %d: zero samples while iterations advanced "
                "(wedged sampler)" % i)
    if profs:
        m = merged_profile(profs)
        if m["overhead_frac"] > budget:
            problems.append(
                "run: sampling overhead %.3f%% blows the %.1f%% budget"
                % (100.0 * m["overhead_frac"], 100.0 * budget))
    return problems


def _leaf(folded):
    return folded.rsplit(";", 1)[-1]


def render_top(events, top=20, out=None):
    """Terminal top-table over a run's merged windows: headline totals,
    per-role / per-stage / per-phase attribution, then the hottest
    folded stacks with their leaf frame.  Returns the merged rollup
    (None when the timeline has no profile windows)."""
    out = out or sys.stdout
    profs = profile_events(events)
    if not profs:
        print("no prof_profile events (profiler off? obs_prof_hz=0)",
              file=out)
        return None
    m = merged_profile(profs)
    print("host profile: %d window(s), %d sample(s) over %.1fs  "
          "overhead %.3f%% (budget %.1f%%)"
          % (m["windows"], m["samples"], m["dur_s"],
             100.0 * m["overhead_frac"], 100.0 * OVERHEAD_BUDGET_FRAC),
          file=out)
    for err in m["errors"]:
        print("  sampler error: %s" % err, file=out)
    for field, title in (("roles", "thread roles"),
                         ("stages", "loop stages"),
                         ("phases", "phases")):
        cells = sorted(m[field].items(), key=lambda kv: (-kv[1], kv[0]))
        if cells:
            print("  %s: %s" % (title,
                                "  ".join("%s=%d" % kv for kv in cells)),
                  file=out)
    ranked = sorted(m["stacks"].items(), key=lambda kv: (-kv[1], kv[0]))
    total = sum(m["stacks"].values()) or 1
    print("\n%7s %6s  %s" % ("samples", "pct", "hottest stacks "
                             "(role;root;...;leaf)"), file=out)
    for folded, count in ranked[:max(1, int(top))]:
        print("%7d %5.1f%%  %s" % (count, 100.0 * count / total,
                                   _leaf(folded)), file=out)
        print("%s%s" % (" " * 16, folded), file=out)
    shown = sum(c for _, c in ranked[:max(1, int(top))])
    tail = total - shown + m["truncated"]
    if tail > 0:
        print("%7d %5.1f%%  (truncated tail)" % (tail,
                                                 100.0 * tail / total),
              file=out)
    return m


# ------------------------------------------------------------- flamegraph

def _flame_tree(stacks):
    root = {"name": "all", "value": 0, "children": {}}
    for folded, count in stacks.items():
        count = int(count)
        root["value"] += count
        node = root
        for part in folded.split(";"):
            child = node["children"].setdefault(
                part, {"name": part, "value": 0, "children": {}})
            child["value"] += count
            node = child
    return root


def _flame_color(name):
    # deterministic warm hue per frame label (classic flamegraph look)
    h = 0
    for ch in name:
        h = (h * 131 + ord(ch)) & 0xFFFFFF
    return "hsl(%d,%d%%,%d%%)" % (20 + h % 40, 60 + (h >> 8) % 30,
                                  52 + (h >> 16) % 16)


def _flame_node_html(node, total, parts):
    share = 100.0 * node["value"] / max(total, 1)
    if share < 0.1:                     # sub-pixel slivers render as noise
        return
    label = _html.escape(node["name"])
    # the node fills the wrapper its parent sized for it; only the
    # wrapper (below) carries a proportional width
    parts.append(
        '<div class="node">'
        '<div class="lbl" style="background:%s" title="%s — %d samples '
        '(%.1f%%)">%s</div>' % (_flame_color(node["name"]), label,
                                node["value"], share, label))
    children = sorted(node["children"].values(),
                      key=lambda c: (-c["value"], c["name"]))
    if children:
        parts.append('<div class="row">')
        for child in children:
            # child width is relative to THIS node's box
            parts.append('<div style="width:%.4f%%">'
                         % (100.0 * child["value"]
                            / max(node["value"], 1)))
            _flame_node_html(child, total, parts)
            parts.append('</div>')
        parts.append('</div>')
    parts.append('</div>')


def render_flame(events, out_path):
    """Self-contained HTML flamegraph (no d3, no external JS — nested
    proportional-width divs with hover tooltips) over the merged
    windows.  Returns the total sample count rendered."""
    profs = profile_events(events)
    merged = merged_profile(profs) if profs else {"stacks": {},
                                                  "samples": 0,
                                                  "dur_s": 0.0,
                                                  "overhead_frac": 0.0,
                                                  "windows": 0}
    tree = _flame_tree(merged["stacks"])
    parts = []
    _flame_node_html(tree, tree["value"], parts)
    body = "".join(parts) or "<p>no samples</p>"
    doc = (
        "<!doctype html><html><head><meta charset=\"utf-8\">"
        "<title>lightgbm_tpu host flamegraph</title><style>"
        "body{font:12px monospace;margin:12px}"
        ".row{display:flex;width:100%%}"
        ".node{overflow:hidden}"
        ".lbl{border:1px solid #fff;border-radius:2px;padding:0 3px;"
        "white-space:nowrap;overflow:hidden;text-overflow:ellipsis;"
        "cursor:default;font-size:11px;line-height:15px}"
        "</style></head><body>"
        "<h3>host sampling profile — %d window(s), %d sample(s) over "
        "%.1fs, overhead %.3f%%</h3>"
        "<p>width &prop; samples; hover a frame for its count. "
        "Stacks grow downward (root at top).</p>%s</body></html>"
        % (merged.get("windows", 0), tree["value"],
           merged.get("dur_s", 0.0),
           100.0 * merged.get("overhead_frac", 0.0), body))
    d = os.path.dirname(out_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        f.write(doc)
    return tree["value"]


def resolve_target(target):
    """``obs prof`` accepts a timeline file or a directory: a directory
    resolves to its newest ``*.jsonl`` (an incident bundle's ``ring``
    slice, a run directory, ...)."""
    if os.path.isdir(target):
        cands = [os.path.join(target, n) for n in os.listdir(target)
                 if n.endswith(".jsonl")]
        if not cands:
            raise ValueError("no .jsonl timeline in directory %s"
                             % target)
        return max(cands, key=lambda p: os.path.getmtime(p))
    return target


def render_prof_report(target, top=20, flame="", check=False, out=None):
    """The ``obs prof`` subcommand body: load the timeline (file or
    directory), print the top table, optionally write the flamegraph,
    and return the ``--check`` problem list."""
    from .query import last_run, load_timeline
    out = out or sys.stdout
    events = last_run(load_timeline(resolve_target(target)))
    render_top(events, top=top, out=out)
    if flame:
        n = render_flame(events, flame)
        print("\nwrote flamegraph (%d samples) -> %s" % (n, flame),
              file=out)
    problems = check_profiles(events)
    if problems:
        print("\nPROF CHECK: %d problem(s)" % len(problems), file=out)
        for p in problems:
            print("  - %s" % p, file=out)
    elif check:
        print("\nPROF CHECK: ok", file=out)
    return problems


def folded_text(payload):
    """One ``stack count`` line per folded stack (the py-spy /
    flamegraph.pl collapsed format) — the ``GET /prof`` body."""
    stacks = payload.get("stacks") or {}
    lines = ["%s %d" % (k, v) for k, v in
             sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))]
    header = ("# samples=%d dur_s=%.3f overhead_frac=%.5f"
              % (payload.get("samples", 0), payload.get("dur_s", 0.0),
                 payload.get("overhead_frac", 0.0)))
    return "\n".join([header] + lines) + "\n"


if __name__ == "__main__":          # pragma: no cover - debugging aid
    print(json.dumps(burst(seconds=0.5), indent=2))
