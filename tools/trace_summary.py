"""Summarize a jax.profiler trace directory into a ranked op-time table.

There is no TensorBoard/Perfetto UI in this image, so the flagship
residue analysis (ROADMAP.md: ~130 ms/wave outside the histogram
kernel) needs a programmatic reader.  jax.profiler.trace() writes a
Perfetto-format ``*.trace.json.gz`` under
``<outdir>/plugins/profile/<run>/``; this tool aggregates complete
('ph' == 'X') events per track, ranks device-side op time, and prints
the top offenders plus per-track totals.

Usage:  python tools/trace_summary.py /tmp/tpu_trace_1m [top_n]
"""
import collections
import glob
import gzip
import json
import os
import sys


def load_events(trace_dir):
    pats = [os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json.gz")]
    paths = []
    for p in pats:
        paths = sorted(glob.glob(p, recursive=True))
        if paths:
            break
    if not paths:
        raise SystemExit("no *.trace.json.gz under %s" % trace_dir)
    path = paths[-1]                      # newest run
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    return path, data.get("traceEvents", [])


def main():
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_trace"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30

    path, events = load_events(trace_dir)
    # pid/tid -> human-readable track names from metadata events
    proc = {}
    thread = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc[e.get("pid")] = e["args"].get("name", "")
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            thread[(e.get("pid"), e.get("tid"))] = e["args"].get("name", "")

    per_track = collections.Counter()          # track -> total us
    per_op = collections.defaultdict(lambda: [0.0, 0])   # (track, op) -> [us, n]
    for e in events:
        if e.get("ph") != "X":
            continue
        pid, tid = e.get("pid"), e.get("tid")
        track = proc.get(pid, str(pid))
        tname = thread.get((pid, tid), "")
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        key = "%s/%s" % (track, tname) if tname else track
        per_track[key] += dur
        per_op[(key, name)][0] += dur
        per_op[(key, name)][1] += 1

    print("trace: %s" % path)
    print("\n== total busy time per track (ms) ==")
    for track, us in per_track.most_common(12):
        print("  %10.2f  %s" % (us / 1e3, track))

    # rank ops on device-ish tracks (XLA Ops / TensorFlow Op / stream
    # tracks); fall back to all tracks if nothing matches
    def devicey(track):
        t = track.lower()
        return ("xla op" in t or "tensorflow op" in t or "/device" in t
                or "tpu" in t.split("/")[0] or "stream" in t)

    rows = [(v[0], v[1], tr, op) for (tr, op), v in per_op.items()
            if devicey(tr)]
    if not rows:
        rows = [(v[0], v[1], tr, op) for (tr, op), v in per_op.items()]
    rows.sort(reverse=True)
    print("\n== top %d ops by total time ==" % top_n)
    print("  %10s %8s  %s" % ("total_ms", "count", "op [track]"))
    for us, n, tr, op in rows[:top_n]:
        print("  %10.2f %8d  %s  [%s]" % (us / 1e3, n, op[:100], tr[:60]))


if __name__ == "__main__":
    main()
