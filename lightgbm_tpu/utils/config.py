"""Parameter system: canonical keys, aliases, typed defaults, conflict checks.

Parity target: include/LightGBM/config.h:87-489 and src/io/config.cpp.  The
parameter names and alias table are the de-facto API of the reference and are
kept verbatim.  New device type ``tpu`` joins ``cpu``/``gpu`` (the whole point
of this framework); unknown parameters raise, as in
``ParameterAlias::KeyAliasTransform`` (config.h:479).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .log import Log

# alias -> canonical   (config.h:362-450)
ALIAS_TABLE: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "random_seed": "seed",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "tranining_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    # multi-host pod bootstrap (parallel/comm.py distributed_init) and
    # elastic checkpoint/resume (models/checkpoint.py)
    "coordinator": "dist_coordinator",
    "coordinator_address": "dist_coordinator",
    "dist_world_size": "dist_num_processes",
    "dist_rank": "dist_process_id",
    "checkpoint_freq": "checkpoint_every",
    "checkpoint_path": "checkpoint_dir",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    # out-of-core streaming ingest (io/streaming.py + io/binned_format.py)
    "stream_chunk_rows": "ooc_chunk_rows",
    "ooc_chunk": "ooc_chunk_rows",
    "stream_workers": "ooc_workers",
    "binning_workers": "ooc_workers",
    "save_binned": "ooc_binned_dir",
    "save_binned_dir": "ooc_binned_dir",
    "binned_dir": "ooc_binned_dir",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    "bagging_fraction_seed": "bagging_seed",
    "obs_events_file": "obs_events_path",
    "obs_events": "obs_events_path",
    "obs_profile_iters": "obs_trace_iters",
    "obs_profile_dir": "obs_trace_dir",
    "obs_memory_freq": "obs_memory_every",
    "obs_health_mode": "obs_health",
    "obs_health_freq": "obs_health_every",
    "obs_metrics_file": "obs_metrics_path",
    "obs_metrics": "obs_metrics_path",
    "obs_metrics_freq": "obs_metrics_every",
    "obs_compile_attr": "obs_compile",
    "obs_recompile_attr": "obs_compile",
    "obs_straggler_freq": "obs_straggler_every",
    "obs_straggler_skew": "obs_straggler_warn_skew",
    "obs_watchdog": "obs_watchdog_secs",
    "obs_events_fsync": "obs_fsync",
    "obs_ring_events": "obs_flight_events",
    "obs_audit": "obs_split_audit",
    "obs_audit_splits": "obs_split_audit",
    "obs_importance_freq": "obs_importance_every",
    "obs_importance_k": "obs_importance_topk",
    "obs_profile_data": "obs_data_profile",
    "obs_dataset_profile": "obs_data_profile",
    "obs_ledger": "obs_ledger_dir",
    "ledger_dir": "obs_ledger_dir",
    "ledger_suite": "obs_ledger_suite",
    "ledger_window": "obs_ledger_window",
    "obs_ledger_n": "obs_ledger_window",
    "obs_utilization_freq": "obs_utilization_every",
    "obs_roofline_every": "obs_utilization_every",
    "obs_roofline_peaks_path": "obs_roofline_peaks",
    "obs_http": "obs_http_port",
    "obs_port": "obs_http_port",
    "obs_http_host": "obs_http_addr",
    "obs_http_address": "obs_http_addr",
    "obs_drift_rows": "obs_drift_every",
    "obs_drift_freq": "obs_drift_every",
    "obs_drift_window_rows": "obs_drift_window",
    "obs_drift_psi_threshold": "obs_drift_psi",
    "obs_drift_threshold": "obs_drift_psi",
    "obs_fingerprint": "obs_drift_fingerprint",
    "obs_drift_k": "obs_drift_topk",
    "obs_incidents": "obs_incident",
    "obs_incident_window": "obs_incident_window_s",
    "obs_incident_path": "obs_incident_dir",
    "obs_profile_hz": "obs_prof_hz",
    "obs_prof_rate": "obs_prof_hz",
    "obs_prof_window": "obs_prof_window_s",
    "obs_prof_top_k": "obs_prof_topk",
    "serve_microbatch_max": "serve_max_batch",
    "serve_deadline_ms": "serve_max_delay_ms",
    "serve_min_bucket": "serve_bucket_min",
    "serve_donate_buffers": "serve_donate",
    "serve_batch_events": "serve_batch_event_every",
    "serve_max_queue": "serve_queue_limit",
    "serve_queue_max": "serve_queue_limit",
    "serve_timeout_ms": "serve_request_deadline_ms",
    "serve_request_events": "serve_request_event_every",
    "serve_slo_p99": "serve_slo_p99_ms",
    "serve_slo_window": "serve_slo_window_s",
    "serve_slo_snapshot_every": "serve_slo_every_s",
    "autotune": "tpu_autotune",
    "autotune_mode": "tpu_autotune",
    "autotune_cache": "tpu_autotune_cache",
    "autotune_cache_path": "tpu_autotune_cache",
    "autotune_waves": "tpu_autotune_waves",
    "fused_iter": "tpu_fused_iter",
}

# canonical parameters accepted without aliasing (config.h:451-478), plus the
# handful the reference reads outside the set (task/device/metric aliases) and
# tpu-specific additions.
PARAMETER_SET = {
    "config", "config_file", "task", "device", "device_type",
    "num_threads", "seed", "boosting_type", "objective", "data",
    "output_model", "input_model", "output_result", "valid_data",
    "is_enable_sparse", "is_pre_partition", "is_training_metric",
    "ndcg_eval_at", "min_data_in_leaf", "min_sum_hessian_in_leaf",
    "num_leaves", "feature_fraction", "num_iterations",
    "bagging_fraction", "bagging_freq", "learning_rate", "tree_learner",
    "num_machines", "local_listen_port", "use_two_round_loading",
    "machine_list_file", "is_save_binary_file", "early_stopping_round",
    "verbose", "has_header", "label_column", "weight_column", "group_column",
    "ignore_column", "categorical_column", "is_predict_raw_score",
    "is_predict_leaf_index", "min_gain_to_split", "top_k",
    "lambda_l1", "lambda_l2", "num_class", "is_unbalance",
    "max_depth", "subsample_for_bin", "max_bin", "bagging_seed",
    "drop_rate", "skip_drop", "max_drop", "uniform_drop",
    "xgboost_dart_mode", "drop_seed", "top_rate", "other_rate",
    "min_data_in_bin", "data_random_seed", "bin_construct_sample_cnt",
    "num_iteration_predict", "pred_early_stop", "pred_early_stop_freq",
    "pred_early_stop_margin", "use_missing", "sigmoid", "huber_delta",
    "fair_c", "poission_max_delta_step", "scale_pos_weight",
    "boost_from_average", "max_position", "label_gain",
    "metric", "metric_freq", "time_out",
    "gpu_platform_id", "gpu_device_id", "gpu_use_dp",
    "convert_model", "convert_model_language",
    "feature_fraction_seed", "enable_bundle", "data_filename",
    "valid_data_filenames", "snapshot_freq", "sparse_threshold",
    "enable_load_from_binary_file", "max_conflict_rate",
    "ooc_chunk_rows", "ooc_workers", "ooc_binned_dir",
    # multi-host pod bootstrap + elastic checkpoint/resume
    "dist_coordinator", "dist_num_processes", "dist_process_id",
    "checkpoint_every", "checkpoint_dir",
    "poisson_max_delta_step", "gaussian_eta", "histogram_pool_size",
    "output_freq", "is_provide_training_metric", "machine_list_filename",
    "capacity",
    # tpu-native additions
    "tpu_use_dp", "tpu_histogram_mode", "tpu_profile_dir", "feature_name",
    "tpu_growth", "tpu_wave_width", "tpu_bin_pack", "tpu_wave_chunk",
    "tpu_sparse", "tpu_wave_order", "tpu_predict", "tpu_wave_lookup",
    "tpu_sparse_kernel", "tpu_hist_precision", "tpu_score_update",
    "tpu_wave_compact",
    # measured kernel autotuner (ops/autotune.py)
    "tpu_autotune", "tpu_autotune_cache", "tpu_autotune_waves",
    # fused boosting iteration (ops/fused_iter.py)
    "tpu_fused_iter", "tpu_pallas_interpret",
    # observability (lightgbm_tpu/obs/)
    "obs_events_path", "obs_timing", "obs_memory_every",
    "obs_trace_iters", "obs_trace_dir", "obs_flush_every",
    "obs_health", "obs_health_every", "obs_health_divergence",
    "obs_health_plateau", "obs_health_mem_frac",
    "obs_metrics_path", "obs_metrics_every",
    "obs_compile", "obs_straggler_every", "obs_straggler_warn_skew",
    "obs_watchdog_secs", "obs_fsync", "obs_flight_events",
    "obs_split_audit", "obs_importance_every", "obs_importance_topk",
    "obs_data_profile",
    # cross-run performance ledger (obs/ledger.py)
    "obs_ledger_dir", "obs_ledger_suite", "obs_ledger_window",
    # roofline attribution (obs/roofline.py)
    "obs_utilization_every", "obs_roofline_peaks",
    # live telemetry plane (obs/live.py)
    "obs_http_port", "obs_http_addr",
    # drift & online model-quality monitoring (obs/drift.py)
    "obs_drift_every", "obs_drift_window", "obs_drift_psi",
    "obs_drift_fingerprint", "obs_drift_topk", "obs_drift_min_labels",
    # incident engine (obs/incident.py)
    "obs_incident", "obs_incident_window_s", "obs_incident_dir",
    "obs_incident_trace",
    # continuous host profiler (obs/prof.py)
    "obs_prof_hz", "obs_prof_window_s", "obs_prof_topk",
    # serving tier (lightgbm_tpu/serve/)
    "serve_max_batch", "serve_max_delay_ms", "serve_bucket_min",
    "serve_donate", "serve_batch_event_every",
    # serving observability & overload protection (obs/serve.py)
    "serve_queue_limit", "serve_request_deadline_ms",
    "serve_request_event_every", "serve_slo_p99_ms", "serve_slo_qps",
    "serve_slo_window_s", "serve_slo_every_s",
}

_TRUE_SET = {"1", "true", "yes", "on", "+"}
_FALSE_SET = {"0", "false", "no", "off", "-"}


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in _TRUE_SET:
        return True
    if s in _FALSE_SET:
        return False
    Log.fatal("Parameter: value %s cannot be parsed as bool", v)


def _to_int(v: Any) -> int:
    if isinstance(v, bool):
        return int(v)
    try:
        return int(v)
    except (TypeError, ValueError):
        return int(float(v))


def _to_double_vec(v: Any) -> List[float]:
    if isinstance(v, (list, tuple)):
        return [float(x) for x in v]
    s = str(v).strip()
    if not s:
        return []
    return [float(x) for x in s.replace(",", " ").split()]


def _to_int_vec(v: Any) -> List[int]:
    return [int(round(x)) for x in _to_double_vec(v)]


def param_dict_to_str(data: Optional[dict]) -> str:
    """Serialize params the way python-package/basic.py:124 does."""
    if not data:
        return ""
    pairs = []
    for key, val in data.items():
        if isinstance(val, (list, tuple, set)):
            pairs.append(str(key) + "=" + ",".join(map(str, val)))
        elif isinstance(val, (str, int, float, bool)):
            pairs.append(str(key) + "=" + str(val))
        elif val is not None:
            Log.fatal("Unknown type of parameter:%s, got:%s", key, type(val).__name__)
    return " ".join(pairs)


def key_alias_transform(params: Dict[str, Any], raise_unknown: bool = False) -> Dict[str, Any]:
    """Canonicalise keys via the alias table (config.h:479-489 semantics).

    A canonical key present in the input wins over any alias of it.  Unknown
    keys are warned about (the CLI path raises, matching ``Log::Fatal``).
    """
    out: Dict[str, Any] = {}
    aliased: Dict[str, Any] = {}
    for key, val in params.items():
        if key in ALIAS_TABLE:
            aliased.setdefault(ALIAS_TABLE[key], val)
        else:
            if key not in PARAMETER_SET:
                if raise_unknown:
                    Log.fatal("Unknown parameter: %s", key)
                Log.warning("Unknown parameter: %s", key)
            out[key] = val
    for key, val in aliased.items():
        out.setdefault(key, val)
    return out


class Config:
    """Typed view over a canonical parameter dict.

    Flat rather than the reference's nested sub-config structs — every field
    of IOConfig/ObjectiveConfig/MetricConfig/TreeConfig/BoostingConfig/
    NetworkConfig/OverallConfig (config.h:87-354) is present with the same
    default.
    """

    _FIELDS = {
        # OverallConfig
        "task": ("str", "train"),
        "seed": ("int", 0),
        "num_threads": ("int", 0),
        "boosting_type": ("str", "gbdt"),
        "objective": ("str", "regression"),
        "metric": ("strvec", None),            # resolved by boosting layer
        "convert_model_language": ("str", ""),
        # IOConfig
        "max_bin": ("int", 255),
        "num_class": ("int", 1),
        "data_random_seed": ("int", 1),
        "data": ("str", ""),
        "valid_data": ("strvec", None),
        "snapshot_freq": ("int", 100),
        "output_model": ("str", "LightGBM_model.txt"),
        "output_result": ("str", "LightGBM_predict_result.txt"),
        "convert_model": ("str", "gbdt_prediction.cpp"),
        "input_model": ("str", ""),
        "verbose": ("int", 1),
        "num_iteration_predict": ("int", -1),
        "is_pre_partition": ("bool", False),
        "is_enable_sparse": ("bool", True),
        "sparse_threshold": ("float", 0.8),
        "use_two_round_loading": ("bool", False),
        "is_save_binary_file": ("bool", False),
        "enable_load_from_binary_file": ("bool", True),
        "bin_construct_sample_cnt": ("int", 200000),
        # out-of-core streaming ingest (io/streaming.py): row-chunk size
        # for array/sparse sources, worker-pool width (0 = all cores),
        # and an optional directory to persist the pre-binned mmap format
        # (io/binned_format.py) during construction
        "ooc_chunk_rows": ("int", 262144),
        "ooc_workers": ("int", 0),
        "ooc_binned_dir": ("str", ""),
        "is_predict_leaf_index": ("bool", False),
        "is_predict_raw_score": ("bool", False),
        "min_data_in_leaf": ("int", 20),
        "min_data_in_bin": ("int", 5),
        "max_conflict_rate": ("float", 0.0),
        "enable_bundle": ("bool", True),
        "has_header": ("bool", False),
        "label_column": ("str", ""),
        "weight_column": ("str", ""),
        "group_column": ("str", ""),
        "ignore_column": ("str", ""),
        "categorical_column": ("str", ""),
        "device_type": ("str", "tpu"),
        "pred_early_stop": ("bool", False),
        "pred_early_stop_freq": ("int", 10),
        "pred_early_stop_margin": ("float", 10.0),
        # ObjectiveConfig
        "sigmoid": ("float", 1.0),
        "huber_delta": ("float", 1.0),
        "fair_c": ("float", 1.0),
        "gaussian_eta": ("float", 1.0),
        "poisson_max_delta_step": ("float", 0.7),
        "label_gain": ("floatvec", None),
        "max_position": ("int", 20),
        "is_unbalance": ("bool", False),
        "scale_pos_weight": ("float", 1.0),
        # MetricConfig
        "ndcg_eval_at": ("intvec", None),
        "metric_freq": ("int", 1),
        # TreeConfig
        "min_sum_hessian_in_leaf": ("float", 1e-3),
        "lambda_l1": ("float", 0.0),
        "lambda_l2": ("float", 0.0),
        "min_gain_to_split": ("float", 0.0),
        "num_leaves": ("int", 31),
        "feature_fraction_seed": ("int", 2),
        "feature_fraction": ("float", 1.0),
        "histogram_pool_size": ("float", -1.0),
        "max_depth": ("int", -1),
        "top_k": ("int", 20),
        "gpu_platform_id": ("int", -1),
        "gpu_device_id": ("int", -1),
        "gpu_use_dp": ("bool", False),
        "use_missing": ("bool", True),
        # BoostingConfig
        "output_freq": ("int", 1),
        "is_training_metric": ("bool", False),
        "num_iterations": ("int", 100),
        "learning_rate": ("float", 0.1),
        "bagging_fraction": ("float", 1.0),
        "bagging_seed": ("int", 3),
        "bagging_freq": ("int", 0),
        "early_stopping_round": ("int", 0),
        "drop_rate": ("float", 0.1),
        "max_drop": ("int", 50),
        "skip_drop": ("float", 0.5),
        "xgboost_dart_mode": ("bool", False),
        "uniform_drop": ("bool", False),
        "drop_seed": ("int", 4),
        "top_rate": ("float", 0.2),
        "other_rate": ("float", 0.1),
        "capacity": ("float", 50.0),
        "boost_from_average": ("bool", True),
        "tree_learner": ("str", "serial"),
        # NetworkConfig
        "num_machines": ("int", 1),
        "local_listen_port": ("int", 12400),
        "time_out": ("int", 120),
        "machine_list_file": ("str", ""),
        # multi-host pod bootstrap (parallel/comm.py distributed_init):
        # coordinator "host:port" ("" = env autodetect via
        # JAX_COORDINATOR_ADDRESS), process count (0 = autodetect) and
        # this process's id (-1 = autodetect)
        "dist_coordinator": ("str", ""),
        "dist_num_processes": ("int", 0),
        "dist_process_id": ("int", -1),
        # elastic fault tolerance (models/checkpoint.py): save a compact
        # booster checkpoint every N iterations (0 = off) into
        # checkpoint_dir so a shrunk mesh can resume mid-train
        "checkpoint_every": ("int", 0),
        "checkpoint_dir": ("str", ""),
        # tpu-native additions
        "tpu_use_dp": ("bool", False),
        # 'auto' | 'true' | 'false' — rank-encoded device bulk prediction
        # (ops/predict.py): f64-exact routing as int32 compares on TPU.
        # auto = device for >=100k-row batches on TPU, host otherwise.
        "tpu_predict": ("str", "auto"),
        # 'auto' | 'scatter' | 'onehot' | 'pallas' | 'pallas_t' |
        # 'pallas_ct' — histogram kernel ('pallas' = exact-engine
        # per-leaf kernel, 'pallas_t' = wave kernel with MXU-native
        # transposed operands, 'pallas_ct' = fused partition+histogram
        # wave kernel, compact split table, one read of X_t per wave).
        # auto, on TPU when the wave engine runs it (f32, dense,
        # serial/data learner): pallas_ct for narrow shapes
        # (ncols * bin_pad <= 2048 — measured winner at 10.5M x 28 and
        # 1M x 28, r4), pallas_t for wider VMEM-feasible shapes; else
        # onehot on TPU, scatter elsewhere.  (pallas_f/pallas_ft were
        # deleted in r4: lost every on-chip A/B, padded-operand OOM
        # liability — tools/AB_RESULTS.md.)
        "tpu_histogram_mode": ("str", "auto"),
        # 'auto' | 'exact' | 'wave' — growth schedule (ops/wave.py):
        # 'exact' is the reference's one-split-at-a-time leaf-wise order;
        # 'wave' batches the top-W pending splits per sweep for the MXU.
        # auto -> wave on TPU, exact elsewhere.
        "tpu_growth": ("str", "auto"),
        # W in 'wave' growth: splits the top-W pending leaves per sweep
        # (same greedy frontier as leaf-wise, batched; quality parity in
        # tests/test_wave.py).  -1 = auto, scaled to num_leaves (measured
        # on v5e: W=16 fastest at 63 leaves, W=32 at 255); set 1 to
        # reproduce the reference's exact split sequence.
        "tpu_wave_width": ("int", -1),
        # 'auto' | 'batched' | 'exact' — wave COMMIT ORDER.  'batched'
        # commits all W top-gain splits per sweep (fastest; the greedy
        # frontier approximates the leaf-wise ORDER).  'exact' computes
        # the same W candidate histograms per sweep but commits only the
        # prefix the reference's leaf-wise order would have produced
        # (rolling the rest back with a leaf-id remap) — trees match
        # tpu_wave_width=1 bit-for-bit at wave-level HBM economics.
        # auto -> exact for order-sensitive configs (lambdarank, DART,
        # GOSS, InfiniteBoost), batched otherwise.
        "tpu_wave_order": ("str", "auto"),
        # 'auto' | 'onehot' | 'compact' | 'gather' — how the wave
        # partition scan looks up each row's pending split: 'onehot'
        # contracts a (chunk, num_leaves) leaf one-hot against the
        # (L, 10) split table on the MXU; 'compact' matches rows against
        # only the W wave parents (<=1 match per row, so the masked sum
        # is exact) — W/L of the one-hot footprint; 'gather' indexes the
        # table directly.  auto -> compact on TPU (measured +12% over
        # onehot-lookup on v5e at the flagship recipe), onehot elsewhere.
        "tpu_wave_lookup": ("str", "auto"),
        # 'auto' | 'hilo' | 'bf16' — MXU product precision of the Pallas
        # wave histogram kernels.  'hilo' (exact bf16 hi+lo split, two
        # dots, ~2^-17 relative products) is the quality-first default;
        # 'bf16' (single round-to-nearest bf16 term, ~2^-9 products,
        # f32 accumulation) HALVES the kernel's MXU work — the analog of
        # the reference GPU's default single-precision histograms
        # (docs/GPU-Performance.md:127-130, gpu_use_dp=false).  Split
        # ROUTING is unaffected (exact f32 compares) — only histogram
        # sums, and through them split choices, can drift.  auto = bf16
        # where the Pallas wave kernels run under single-chip wave
        # growth (promoted round 5: 1.63x at the 10.5M flagship, AUC
        # within 1.0e-4 — tools/BENCH_SUITE.md higgs_bf16); exact
        # growth, data-parallel execution, and every non-pallas engine
        # stay hilo.  Set 'hilo' to force the exact split everywhere.
        "tpu_hist_precision": ("str", "auto"),
        # row-chunk size of the wave engine's fused partition+histogram
        # sweep; smaller chunks shrink the (chunk, F*B) one-hot tile
        # (VMEM-residency vs scan-overhead tradeoff on TPU; engine
        # minimum 256 — smaller values are clamped with a warning)
        "tpu_wave_chunk": ("int", 16384),
        # 'auto' | 'true' | 'false' — 4-bit bin packing (ops/pack.py, the
        # dense_nbits_bin.hpp:37 analog): when every device column holds at
        # most 16 bins (max_bin<=15 plus the reserved zero/missing bin),
        # two columns share a byte in HBM and the wave engine unpacks per
        # chunk.  auto = pack whenever eligible.
        "tpu_bin_pack": ("str", "auto"),
        # device-side sparse bin storage (ops/sparse_store.py, SparseBin
        # analog): per-leaf histograms become one segment_sum over the
        # nonzero entries instead of an O(N*F) dense pass.  Exact engine
        # under the serial and data-parallel learners; default dense.
        "tpu_sparse": ("bool", False),
        # entry-chunk MXU store (ops/sparse_mxu.py): with tpu_sparse=true,
        # replace the segment_sum coordinate store with fixed-size
        # per-column entry chunks whose histograms are small MXU
        # contractions inside a Pallas kernel (the OrderedSparseBin
        # economics, TPU form).  Forces wave growth; serial learner only.
        "tpu_sparse_kernel": ("bool", False),
        # 'auto' | 'gather' | 'pallas' — the train-side score update
        # (score += leaf_value[leaf_id]).  'gather' = XLA small-table
        # gather; 'pallas' = compare-select kernel (ops/predict.py,
        # bit-equal, measured faster at the 10.5M flagship: 1.45 vs
        # 1.30 it/s with EXACTLY equal AUC — tools/BENCH_SUITE.md
        # higgs_su).  auto = pallas (promoted round 5); the dispatch
        # falls back to the gather off-TPU, above 512 leaves, or on
        # f64 scores (tpu_use_dp).
        "tpu_score_update": ("str", "auto"),
        # spectator-row compaction for the transposed wave kernels
        # (tpu_histogram_mode=pallas_ct/pallas_t): late waves touch only the rows
        # whose leaf is still splitting (~35% of row work at the flagship
        # recipe is rows whose leaf is final — measured frontier
        # occupancy, ROADMAP.md r4), so the wave gathers the active rows
        # into a capacity tier (1/2, 1/4, 1/8 of N) and runs the kernel
        # on the compacted slab.  Split structure is exact (spectator
        # rows route nowhere and carry zero histogram weight); float
        # fields can drift by f32 ulps at multi-tile N (tile-boundary
        # reassociation) — pinned vs the full-N pass in
        # tests/test_wave_compact.py.  Off until the on-chip A/B lands.
        "tpu_wave_compact": ("bool", False),
        # 'off' | 'prior' | 'measure' | 'force' — the measured kernel
        # autotuner (ops/autotune.py, docs/Autotuning.md).  off = the
        # heuristic prior only (bit-identical to the legacy inline
        # selection; the CPU-CI default).  prior = adopt a cached
        # winner when one exists, never probe.  measure = on cache miss
        # microbench the 3-5 candidate (kernel, W, precision,
        # compaction) cells for the shape bucket on-device and persist
        # the winner.  force = always re-probe, overwriting the cache.
        "tpu_autotune": ("str", "off"),
        # autotune cache file; empty = autotune_cache.json next to the
        # XLA compile cache (LGBM_TPU_COMPILE_CACHE, utils/common.py)
        "tpu_autotune_cache": ("str", ""),
        # timed waves per probed cell (compile + one warmup wave are
        # always excluded from the timing window)
        "tpu_autotune_waves": ("int", 3),
        # 'auto' | 'on' | 'off' — the fused boosting iteration
        # (ops/fused_iter.py, docs/FusedIteration.md): gradients, the
        # grow program and the score update submitted as ONE jitted
        # device entry per tree instead of the staged three-dispatch
        # chain.  auto = fuse when the booster/objective shape is
        # eligible and either the TPU wave path is live or the
        # autotuner measured the fused cell as the winner (rev-2
        # cells).  on = force when eligible (warns and stays staged
        # when not).  off = always the staged chain.  Fused and staged
        # produce bit-identical models (tests/test_fused_iter.py).
        "tpu_fused_iter": ("str", "auto"),
        # run the Pallas wave kernels through the interpreter on CPU
        # (tests/CI only): exercises the real kernel bodies — tiling,
        # accumulator layout, reduction order — without a TPU, so
        # fused-vs-staged parity is testable end-to-end.  Ignored (with
        # a warning) on TPU.
        "tpu_pallas_interpret": ("bool", False),
        # observability (lightgbm_tpu/obs/): setting any of
        # obs_events_path / obs_trace_iters / obs_memory_every turns the
        # run observer on; all-defaults leaves the NULL observer in place
        # (no fencing, no event objects on the hot path).
        # JSONL event timeline destination (docs/Observability.md);
        # append-mode, one run header + per-iteration records per run.
        "obs_events_path": ("str", ""),
        # 'auto' | 'phase' | 'iter' | 'off' — fencing policy for the
        # phase timers.  'phase' fences every phase boundary with
        # jax.block_until_ready (device-accurate per-phase times; breaks
        # async pipelining).  'iter' fences once per iteration (accurate
        # totals, dispatch-only phases — the bench protocol).  'off'
        # never fences (dispatch cost only).  auto = phase.
        "obs_timing": ("str", "auto"),
        # emit a per-device memory_stats() snapshot every N iterations
        # (0 = off; CPU backend reports device identity only)
        "obs_memory_every": ("int", 0),
        # 'a:b' — open a jax.profiler trace window at iteration a and
        # close it after iteration b-1 (python-range semantics); captures
        # a perfetto trace of exactly the steady-state iterations.
        # Requires obs_trace_dir.
        "obs_trace_iters": ("str", ""),
        # destination directory of the obs_trace_iters profiler window
        "obs_trace_dir": ("str", ""),
        # flush the JSONL writer every N events (crash-tolerant timeline)
        "obs_flush_every": ("int", 16),
        # training health monitors (lightgbm_tpu/obs/health.py):
        # 'off' | 'warn' | 'fatal'.  warn logs + emits a `health` event;
        # fatal additionally flushes the timeline and raises
        # LightGBMError, aborting the run.  Non-default turns the
        # observer on even without obs_events_path (in-memory timeline).
        "obs_health": ("str", "off"),
        # run the health checks every N iterations
        "obs_health_every": ("int", 1),
        # loss-divergence trigger: gradient magnitude above
        # divergence x EMA for 2 consecutive checks (<=0 disables)
        "obs_health_divergence": ("float", 3.0),
        # plateau trigger after N consecutive near-flat checks
        # (0 = off; plateau warns but never escalates to fatal)
        "obs_health_plateau": ("int", 0),
        # memory watermark: warn/fatal when any device's bytes_in_use
        # exceeds this fraction of bytes_limit (backends with byte
        # counters only; <=0 disables)
        "obs_health_mem_frac": ("float", 0.9),
        # write the metrics-registry export at run end: Prometheus
        # textfile format for .prom/.txt suffixes, JSON otherwise
        "obs_metrics_path": ("str", ""),
        # embed a registry snapshot (`metrics` event) in the timeline
        # every N iterations (0 = only the final snapshot at run end)
        "obs_metrics_every": ("int", 0),
        # XLA compile-cache introspection (lightgbm_tpu/obs/compile.py):
        # track per-entry compile counts and the arg shape/dtype/donation
        # signature of every recompile, diffed so the `compile_attr`
        # event names the changed axis, plus cost_analysis() /
        # memory_analysis() estimates.  Turns the observer on.
        "obs_compile": ("bool", False),
        # sample per-shard arrival skew of the distributed learners
        # every N iterations (obs/straggler.py; each sample fences, so
        # keep the cadence coarse).  0 = off.  No-op on single device.
        "obs_straggler_every": ("int", 0),
        # warn (through the obs_health channel) when a straggler
        # sample's skew — (max-median)/total per-shard wait — exceeds
        # this fraction
        "obs_straggler_warn_skew": ("float", 0.5),
        # hang watchdog (obs/watchdog.py): dump a flight record
        # (<events_path>.flight.json — event ring buffer, all thread
        # stacks, device memory, metrics snapshot) when no iteration or
        # host-collective progress lands within this many seconds.
        # 0 = off.  The watchdog only observes; it never kills the run.
        "obs_watchdog_secs": ("float", 0.0),
        # os.fsync the timeline shard on run_end (and flight records
        # always fsync) — survives a host dying mid-close at the cost
        # of one sync per run
        "obs_fsync": ("bool", False),
        # size of the in-memory event ring buffer the flight record
        # snapshots (last N events this rank emitted)
        "obs_flight_events": ("int", 256),
        # split audit trail (obs/model.py): emit a `split_audit` event
        # per tree recording every realized split's feature, bin/real
        # threshold, gain, child counts, and the runner-up feature +
        # gain margin from the split search.  Turns the observer on.
        "obs_split_audit": ("bool", False),
        # emit a top-k sparse `importance` event (cumulative split/gain
        # feature importance) every N iterations (0 = off).  Turns the
        # observer on; read back via Booster.importance_history() /
        # `obs explain` / plotting.plot_importance.
        "obs_importance_every": ("int", 0),
        # how many features each `importance` event keeps (top-k by
        # gain, ties to the smaller feature index)
        "obs_importance_topk": ("int", 20),
        # emit a `data_profile` event at training start (per-feature
        # missing rate, bin-occupancy entropy, constant / near-constant
        # / high-cardinality-categorical flags, label balance) whenever
        # the observer is enabled; degenerate findings route through the
        # obs_health channel (warn logs, fatal aborts naming the
        # feature).  Does NOT enable the observer by itself.
        "obs_data_profile": ("bool", True),
        # cross-run performance ledger (obs/ledger.py): directory the
        # observer ingests finished runs into on clean close (append-only
        # JSONL index + per-run records; crash-safe tmp+replace writes).
        # Empty = no automatic ingestion.  bench.py points this at
        # LGBM_TPU_LEDGER (default /tmp/lgbm_tpu_ledger) so every bench
        # run lands in history; `obs trend --check` and bench_compare
        # --baseline rolling gate against it.  Turns the observer on.
        "obs_ledger_dir": ("str", ""),
        # ledger suite label of this run — the coarse comparability key
        # rolling baselines group by (e.g. 'bench', 'serve',
        # 'suite_tall').  Empty = the run_header context tool name.
        "obs_ledger_suite": ("str", ""),
        # rolling-baseline window: median/MAD statistics cover the last
        # N comparable clean runs of the same (suite, shape, device) cell
        "obs_ledger_window": ("int", 8),
        # roofline attribution (obs/roofline.py): emit a `utilization`
        # rollup event every N iterations — exec-weighted achieved/peak
        # FLOP and HBM-bandwidth fractions of every timed entry against
        # the device-peak registry, dominant bound, headroom seconds.
        # Implies obs_compile (the join needs cost estimates).  0 = off.
        # Turns the observer on.
        "obs_utilization_every": ("int", 0),
        # JSON file of device-peak overrides for the roofline layer
        # ({device_kind: {flops_f32, flops_bf16, hbm_bytes_per_s,
        # ici_bytes_per_s, vmem_bytes}}), merged over the built-in
        # table.  Empty = built-in peaks (unknown kinds fall back to a
        # labelled CPU profile).
        "obs_roofline_peaks": ("str", ""),
        # live telemetry plane (obs/live.py): HTTP port of the in-run
        # scrape server (/metrics /healthz /statusz /events).  -1 = off
        # (the default), 0 = bind an ephemeral port (reported via
        # Booster telemetry and the run log), >0 = that port.  Turns
        # the observer on.
        "obs_http_port": ("int", -1),
        # bind address of the live plane.  Loopback by default — the
        # endpoints expose run params and provenance, so routing them
        # off-host (e.g. 0.0.0.0 on a pod) is a deliberate choice.
        "obs_http_addr": ("str", "127.0.0.1"),
        # drift & online model-quality monitoring (obs/drift.py):
        # evaluate serving traffic against the training-time
        # fingerprint every N submitted rows — per-feature + score
        # PSI/KS, `drift` events, lgbm_drift_psi gauges, obs_health
        # alerts.  0 = off (the default; fingerprints still persist so
        # any later serving process can turn it on).
        "obs_drift_every": ("int", 0),
        # rolling-window size in rows: histograms reset once this many
        # rows accumulated, so stale traffic cannot mask fresh drift
        "obs_drift_window": ("int", 8192),
        # PSI alert threshold (fires at >=, clears at half): 0.1-0.25
        # is the conventional 'moderate shift' band — 0.2 pages on the
        # upper half of it
        "obs_drift_psi": ("float", 0.2),
        # capture the per-feature binned histograms of the training
        # sample and persist them with the model text / binned dataset
        # dir as the serving-time drift reference.  On by default: the
        # cost is one bincount per feature over the binning sample the
        # data-quality profile already scans.
        "obs_drift_fingerprint": ("bool", True),
        # top-k most-divergent features carried in each drift event and
        # exported as lgbm_drift_psi{feature=...} gauges (bounds the
        # metric cardinality on wide models)
        "obs_drift_topk": ("int", 10),
        # minimum joined (prediction, outcome) pairs before online
        # AUC/logloss emit as `online_quality` events
        # (ServingPredictor.record_outcome delayed-label channel)
        "obs_drift_min_labels": ("int", 100),
        # incident engine (obs/incident.py): debounce + group every
        # detector channel's anomaly signals (health, SLO burn,
        # straggler skew, watchdog near-expiry, recompiles, drift,
        # shed storms, operator POSTs) into schema-15 incident events,
        # capturing a host-side evidence bundle at open
        "obs_incident": ("bool", False),
        # quiet seconds after the last grouped signal before the open
        # incident closes; co-occurring signals inside the window join
        # the SAME incident instead of opening new ones
        "obs_incident_window_s": ("float", 5.0),
        # evidence-bundle directory; "" anchors next to the timeline as
        # <obs_events_path>.incidents (no bundles without an events
        # path — incident events still land in the timeline)
        "obs_incident_dir": ("str", ""),
        # arm a one-iteration jax.profiler trace window when an
        # incident opens mid-training (PR-1 trace plumbing; never armed
        # on the serve hot path, which has no iteration to scope to)
        "obs_incident_trace": ("bool", False),
        # continuous host sampling profiler (obs/prof.py): samples per
        # second for the daemon-thread sys._current_frames walker that
        # folds stacks into schema-16 `prof_profile` windows.  0 = off.
        # Runs only when the observer is otherwise enabled — the default
        # does NOT by itself turn the observer on.  29 is deliberately
        # prime-ish so the jittered clock cannot alias with 10/50/100 Hz
        # periodic work.
        "obs_prof_hz": ("int", 29),
        # window length: samples aggregate into one `prof_profile` event
        # per window (top-K folded stacks + per-role/stage/phase totals)
        "obs_prof_window_s": ("float", 5.0),
        # folded stacks kept per window; the dropped tail is counted in
        # the event's `truncated` field, never silently lost
        "obs_prof_topk": ("int", 20),
        # serving tier (lightgbm_tpu/serve/, docs/Serving.md) — the
        # Booster.serve() microbatcher over AOT-compiled predict
        # executables.  Largest coalesced microbatch (and the largest
        # compiled batch bucket); bigger requests run in max_batch
        # chunks through the same executables.
        "serve_max_batch": ("int", 8192),
        # coalescing deadline: a microbatch flushes when it reaches
        # serve_max_batch rows OR the oldest queued request has waited
        # this many milliseconds — the knob trading p99 latency for
        # bucket fill / throughput
        "serve_max_delay_ms": ("float", 2.0),
        # smallest batch bucket: request rows round UP to the nearest
        # power of two between serve_bucket_min and serve_max_batch, so
        # the executable cache holds at most
        # log2(max_batch / bucket_min) + 1 programs per route
        "serve_bucket_min": ("int", 64),
        # donate the encoded input buffers to the predict executable
        # ('auto' | 'true' | 'false'); auto donates on accelerator
        # backends and keeps CPU un-donated (the CPU runtime lacks
        # donation and would warn per call)
        "serve_donate": ("str", "auto"),
        # emit a `serve_batch` timeline event every Nth microbatch when
        # an observer is attached (0 = off; metrics always record)
        "serve_batch_event_every": ("int", 0),
        # overload protection (serve/scheduler.py): bound the microbatch
        # queue at this many requests; arrivals beyond it are shed at
        # admission with ServeOverloadError (0 = unbounded).  Shedding
        # is never silent: lgbm_serve_shed_total counts by route+reason
        "serve_queue_limit": ("int", 0),
        # default per-request latency budget: a request whose projected
        # queue wait (coalescing delay + backlog batches x EWMA execute
        # time) already exceeds it is shed at admission instead of
        # queueing doomed work (0 = no deadline; per-request override
        # via submit(deadline_ms=...)).  Distinct from serve_deadline_ms,
        # which is the historical alias of the serve_max_delay_ms
        # coalescing deadline
        "serve_request_deadline_ms": ("float", 0.0),
        # emit a `serve_request` trace event for every Nth completed
        # request when an observer is attached: the request's latency
        # decomposed into queue / encode / pad / execute / respond
        # spans, with its batch id and bucket (0 = off)
        "serve_request_event_every": ("int", 0),
        # rolling-SLO targets (obs/serve.py SloEngine): p99 latency
        # target in ms and sustained-QPS floor; 0 disables the target.
        # Breaching the p99 budget (1% of requests may exceed the
        # target) faster than 2x on BOTH burn windows fires a
        # `slo_burn_rate` health event through the obs_health channel
        "serve_slo_p99_ms": ("float", 0.0),
        "serve_slo_qps": ("float", 0.0),
        # long rolling window for SLO aggregation (the short burn
        # window is window/6, SRE multi-window convention)
        "serve_slo_window_s": ("float", 60.0),
        # emit a `serve_slo` snapshot event every this many seconds
        # when an observer is attached (0 = off; alert evaluation
        # keeps its own cadence)
        "serve_slo_every_s": ("float", 10.0),
    }

    # keys accepted for config-file compatibility whose behavior differs
    # from the reference in this framework (VERDICT r1 weak #7)
    _BEHAVIOR_DIFFERS = {
        "sparse_threshold": ("bin storage is dense on TPU; sparse inputs "
                             "are binned without densification but stored "
                             "as dense bin columns"),
    }

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 raise_unknown: bool = False):
        params = dict(params or {})
        params = key_alias_transform(params, raise_unknown=raise_unknown)
        self.raw: Dict[str, Any] = params
        for name, (kind, default) in self._FIELDS.items():
            if name in params and params[name] is not None:
                val = params[name]
                if kind == "int":
                    val = _to_int(val)
                elif kind == "float":
                    val = float(val)
                elif kind == "bool":
                    val = _to_bool(val)
                elif kind == "str":
                    val = str(val)
                elif kind == "strvec":
                    if isinstance(val, str):
                        val = [s for s in val.replace(";", ",").split(",") if s]
                    elif not isinstance(val, list):
                        val = list(val)
                    else:
                        val = list(val)
                elif kind == "floatvec":
                    val = _to_double_vec(val)
                elif kind == "intvec":
                    val = _to_int_vec(val)
            else:
                val = default
            setattr(self, name, val)
        # alternate names that land in the same slot
        if "machine_list_filename" in params:
            self.machine_list_file = str(params["machine_list_filename"])
        if "data_filename" in params:
            self.data = str(params["data_filename"])
        if "valid_data_filenames" in params and params["valid_data_filenames"]:
            v = params["valid_data_filenames"]
            self.valid_data = v if isinstance(v, list) else str(v).split(",")
        if "is_provide_training_metric" in params:
            self.is_training_metric = _to_bool(params["is_provide_training_metric"])
        if "subsample_for_bin" in params:
            self.bin_construct_sample_cnt = _to_int(params["subsample_for_bin"])
        if "device" in params:
            self.device_type = str(params["device"])
        if "poission_max_delta_step" in params:  # reference's typo'd key
            self.poisson_max_delta_step = float(params["poission_max_delta_step"])
        # accepted-for-compat keys whose reference behavior differs here:
        # warn so a migrating user is not silently surprised
        for key, why in self._BEHAVIOR_DIFFERS.items():
            if key in params and params[key] not in (None, False, "false", "0"):
                Log.warning("Parameter %s is accepted for compatibility but "
                            "%s", key, why)
        self.check_param_conflict()

    # --- semantics from OverallConfig::CheckParamConflict (src/io/config.cpp)
    def check_param_conflict(self) -> None:
        if self.num_leaves < 2:
            Log.fatal("num_leaves must be >= 2, got %d", self.num_leaves)
        if self.max_bin < 2 or self.max_bin > 65535:
            # bin ids must fit the uint16 stores (io/dataset.py binned
            # matrices and the EFB conflict sample)
            Log.fatal("max_bin must be in [2, 65535], got %d", self.max_bin)
        if self.is_pre_partition and self.num_machines <= 1:
            self.is_pre_partition = False
        if self.max_depth > 0:
            full = 1 << min(self.max_depth, 30)
            if self.num_leaves > full:
                self.num_leaves = full
        obj = self.objective
        if obj in ("multiclass", "multiclassova", "softmax") and self.num_class <= 1:
            Log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        if obj not in ("multiclass", "multiclassova", "softmax") and self.num_class != 1:
            Log.fatal("Number of classes must be 1 for non-multiclass training")
        Log.reset_level(self.verbose)

    def metrics(self) -> List[str]:
        """Resolve metric list; empty metric falls back to the objective's
        default metric as the reference's GetMetricType does."""
        if self.metric:
            out = []
            for m in self.metric:
                m = m.strip()
                if m and m not in out:
                    out.append(m)
            return [m for m in out if m not in ("None", "na", "null", "custom", "")]
        default_map = {
            "regression": "l2", "regression_l2": "l2", "mean_squared_error": "l2",
            "mse": "l2", "regression_l1": "l1", "mean_absolute_error": "l1",
            "mae": "l1", "huber": "huber", "fair": "fair", "poisson": "poisson",
            "binary": "binary_logloss", "multiclass": "multi_logloss",
            "softmax": "multi_logloss", "multiclassova": "multi_logloss",
            "lambdarank": "ndcg",
        }
        if self.objective in default_map:
            return [default_map[self.objective]]
        return []

    def copy_with(self, **overrides) -> "Config":
        new_raw = dict(self.raw)
        new_raw.update(overrides)
        return Config(new_raw)

    def __repr__(self) -> str:
        return "Config(%s)" % (self.raw,)
