"""Generate golden cross-compat artifacts with the REFERENCE CLI.

Provenance: the committed files in this directory were produced by this
script on 2026-07-29, with the reference CLI built unmodified from
/root/reference (cmake Release).  The *.train/*.test TSV files are
synthetic (numpy, fixed seeds — authored here, not copied from anywhere);
the *.model/*.pred files are OUTPUTS of the reference binary on that data.

The parity test (tests/test_model_compat.py) loads each .model with
lightgbm_tpu and checks predict() against the .pred to float precision —
proving our text-model reader/writer is bit-compatible with the
reference's format (gbdt.cpp:817-971).

Usage: python gen_golden.py /path/to/reference-cli-binary
"""
import os
import subprocess
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def write_tsv(path, y, X, qid=None):
    with open(path, "w") as f:
        for i in range(len(y)):
            f.write("\t".join([repr(float(y[i]))] +
                              [repr(float(v)) for v in X[i]]) + "\n")
    if qid is not None:
        # LightGBM .query side-file: rows-per-query counts
        _, counts = np.unique(qid, return_counts=True)
        with open(path + ".query", "w") as f:
            for c in counts:
                f.write("%d\n" % c)


def make(task, seed, n=1200, nf=12):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, nf))
    logit = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3] \
        + 0.3 * rng.normal(size=n)
    if task == "binary":
        y = (logit > 0).astype(float)
        qid = None
    elif task == "regression":
        y = logit
        qid = None
    elif task == "multiclass":
        y = np.digitize(logit, [-1.0, 1.0]).astype(float)
        qid = None
    elif task == "lambdarank":
        y = np.clip(np.digitize(logit, [-1.5, 0, 1.5]), 0, 3).astype(float)
        assert n % 20 == 0, "lambdarank golden data needs n divisible by 20"
        qid = np.repeat(np.arange(n // 20), 20)
    return X, y, qid


CONFIGS = {
    "binary": ("objective=binary metric=binary_logloss", 11),
    "regression": ("objective=regression metric=l2", 22),
    "multiclass": ("objective=multiclass num_class=3 metric=multi_logloss", 33),
    "lambdarank": ("objective=lambdarank metric=ndcg", 44),
}


def main(cli):
    for task, (extra, seed) in CONFIGS.items():
        Xtr, ytr, qtr = make(task, seed=seed)
        Xte, yte, qte = make(task, seed=seed + 1, n=400)
        tr = "%s/%s.train" % (HERE, task)
        te = "%s/%s.test" % (HERE, task)
        write_tsv(tr, ytr, Xtr, qtr)
        write_tsv(te, yte, Xte, qte)
        model = "%s/%s.model" % (HERE, task)
        pred = "%s/%s.pred" % (HERE, task)
        subprocess.run(
            [cli, "task=train", "data=" + tr, "output_model=" + model,
             "num_trees=15", "num_leaves=15", "learning_rate=0.1",
             "min_data_in_leaf=20", "max_bin=63", "verbosity=-1"]
            + extra.split(), check=True)
        subprocess.run(
            [cli, "task=predict", "data=" + te, "input_model=" + model,
             "output_result=" + pred, "verbosity=-1"], check=True)
        print("golden:", task)


if __name__ == "__main__":
    main(sys.argv[1])
