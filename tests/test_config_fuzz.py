"""Config-system robustness: alias canonicalization, string round-trips,
and type coercion over randomized inputs (config.h:360-489 semantics).
"""
import numpy as np
import pytest

from lightgbm_tpu.utils.config import (ALIAS_TABLE, Config,
                                       key_alias_transform,
                                       param_dict_to_str)


def test_every_alias_canonicalizes():
    for alias, canonical in ALIAS_TABLE.items():
        out = key_alias_transform({alias: "7"})
        assert canonical in out, (alias, canonical)
        assert out[canonical] == "7"


def test_canonical_key_wins_over_alias():
    out = key_alias_transform({"num_iterations": 50, "num_trees": 99})
    assert out["num_iterations"] == 50


def test_param_str_roundtrip_fuzz():
    rng = np.random.default_rng(0)
    keys = ["num_leaves", "learning_rate", "max_bin", "bagging_fraction",
            "min_data_in_leaf", "lambda_l2", "verbose"]
    for _ in range(25):
        params = {}
        for k in keys:
            if rng.random() < 0.5:
                continue
            params[k] = (int(rng.integers(1, 100)) if k != "learning_rate"
                         and k != "bagging_fraction" and k != "lambda_l2"
                         else round(float(rng.random()), 4))
        if "num_leaves" in params:
            params["num_leaves"] = max(2, params["num_leaves"])
        if "bagging_fraction" in params:
            params["bagging_fraction"] = max(0.1,
                                             params["bagging_fraction"])
        s = param_dict_to_str(params)
        parsed = {}
        for pair in s.split():
            k, v = pair.split("=", 1)
            parsed[k] = v
        cfg = Config(parsed)
        for k, v in params.items():
            assert float(getattr(cfg, k)) == pytest.approx(float(v)), k


def test_vector_params_parse_both_separators():
    a = Config({"ndcg_eval_at": "1,3,5", "verbose": -1})
    b = Config({"ndcg_eval_at": "1 3 5", "verbose": -1})
    c = Config({"ndcg_eval_at": [1, 3, 5], "verbose": -1})
    assert a.ndcg_eval_at == b.ndcg_eval_at == c.ndcg_eval_at == [1, 3, 5]


def test_unknown_param_raises_on_cli_path():
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        key_alias_transform({"definitely_not_a_param": 1},
                            raise_unknown=True)
