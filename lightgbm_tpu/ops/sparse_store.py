"""Device-side sparse bin storage — histograms from nonzero entries only.

Reference analog: SparseBin/OrderedSparseBin (src/io/sparse_bin.hpp:68,
src/io/ordered_sparse_bin.hpp:26-209), which skip default-bin rows at
histogram-scan time.  The TPU redesign: instead of per-leaf re-sorted
iterators, the store is a flat CSC-ordered coordinate list and the whole
per-leaf histogram is ONE `segment_sum` over nnz entries with segment id
``col * B + bin`` — O(nnz) work and HBM traffic instead of O(N * F).

The trick that makes "nonzero entries only" exact is the same FixHistogram
subtraction the dense path already uses (dataset.cpp:764-783): every
column's fill-bin slot is reconstructed as ``leaf_sums - sum(other bins)``,
so the store simply never materializes fill-bin entries.  The fill bin per
device column is chosen as exactly the slot the downstream view
reconstructs (feature default bin) or never reads (the reserved bin 0 of
multi-feature EFB groups, feature_group.h:34-47).

Partition (the winning feature's full-N bin column) gathers one column's
entry range through a static ``col_cap`` window — fill everywhere else.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp


class SparseDeviceStore(NamedTuple):
    """Flat CSC-ordered nonzero (non-fill) bins, device-resident.

    All leaves are arrays so the store passes through jit/pytree
    boundaries; static sizing (col_cap) travels separately as a static
    argument of the grow program.
    """
    nz_row: jnp.ndarray     # (nnz,) i32 row ids, column-major order
    nz_bin: jnp.ndarray     # (nnz,) i32 bin ids
    nz_seg: jnp.ndarray     # (nnz,) i32 = col * num_bins + bin
    colptr: jnp.ndarray     # (F+1,) i32
    fill: jnp.ndarray       # (F,) i32 per-column fill bin


def _store_arrays(binned: np.ndarray, fill: np.ndarray, num_bins: int):
    """Pure-numpy coordinate arrays for one row block.

    Returns ((nz_row, nz_bin, nz_seg, colptr, fill_i32), col_cap)."""
    n, f = binned.shape
    mask_t = (binned != fill[None, :]).T          # (F, N) column-major walk
    cols, rows = np.nonzero(mask_t)               # sorted by col, then row
    bins = binned.T[mask_t].astype(np.int32)
    counts = np.bincount(cols, minlength=f)
    colptr = np.zeros(f + 1, np.int64)
    np.cumsum(counts, out=colptr[1:])
    col_cap = int(counts.max()) if f else 0
    arrays = (rows.astype(np.int32), bins,
              (cols * num_bins + bins).astype(np.int32),
              colptr.astype(np.int32), fill.astype(np.int32))
    return arrays, col_cap


def build_sparse_store(binned: np.ndarray, fill: np.ndarray,
                       num_bins: int):
    """Host-side build from the (N, F) binned matrix.

    Returns (store, col_cap, device_bytes).  ``fill`` must be the
    per-column bin slot that the histogram view reconstructs (or never
    reads) — entries equal to it are dropped.
    """
    (rows, bins, segs, colptr, fill_i), col_cap = \
        _store_arrays(binned, fill, num_bins)
    store = SparseDeviceStore(
        nz_row=jnp.asarray(rows), nz_bin=jnp.asarray(bins),
        nz_seg=jnp.asarray(segs), colptr=jnp.asarray(colptr),
        fill=jnp.asarray(fill_i),
    )
    device_bytes = 4 * (3 * len(rows) + len(colptr) + len(fill_i))
    return store, col_cap, device_bytes


def sharded_store_parts(binned: np.ndarray, fill: np.ndarray,
                        num_bins: int, n_shards: int):
    """Phase 1 of the sharded build: per-row-block coordinate arrays.

    Returns (parts, nnz_needed, col_cap) — multi-process callers
    allgather (nnz_needed, col_cap) and assemble with the global maxima
    so every process pads its sections identically."""
    n, f = binned.shape
    assert n % n_shards == 0, (n, n_shards)
    block = n // n_shards
    parts = [_store_arrays(binned[s * block:(s + 1) * block], fill,
                           num_bins)
             for s in range(n_shards)]
    nnz_needed = max(max(len(p[0][0]) for p in parts), 1)
    col_cap = max(p[1] for p in parts)
    return parts, nnz_needed, col_cap


def assemble_sharded_store(parts, num_cols: int, num_bins: int,
                           nnz_max: int):
    """Phase 2: pad every per-shard section to ``nnz_max`` entries
    (pad segments point one past the histogram, so segment_sum drops
    them) and flat-concatenate, so a ``P(DATA_AXIS)`` sharding hands
    each device exactly its local store.  Host numpy — the caller
    uploads ONCE."""
    n_shards = len(parts)
    drop_seg = num_cols * num_bins

    def pad_to(arr, value):
        out = np.full(nnz_max, value, arr.dtype)
        out[:len(arr)] = arr
        return out

    store = SparseDeviceStore(
        nz_row=np.concatenate([pad_to(p[0][0], 0) for p in parts]),
        nz_bin=np.concatenate([pad_to(p[0][1], 0) for p in parts]),
        nz_seg=np.concatenate([pad_to(p[0][2], drop_seg) for p in parts]),
        colptr=np.concatenate([p[0][3] for p in parts]),
        fill=np.concatenate([p[0][4] for p in parts]),
    )
    device_bytes = 4 * (3 * n_shards * nnz_max
                        + n_shards * (2 * num_cols + 1))
    return store, device_bytes


def column_fill_bins(num_bin_arr, default_bin_arr, bundle) -> np.ndarray:
    """The per-device-column fill bin (see module docstring).

    No bundle: the feature's default bin (feature_hist_view reconstructs
    it when fix_default is on).  Bundled: multi-feature groups fill with
    the reserved bin 0; single-feature groups carry the feature's own
    bins, so their fill is that feature's default bin.
    """
    if bundle is None:
        return np.asarray(default_bin_arr, np.int64)
    fill = np.zeros(len(bundle.groups), np.int64)
    for gid, feats in enumerate(bundle.groups):
        if len(feats) == 1:
            fill[gid] = int(default_bin_arr[feats[0]])
    return fill


def leaf_histogram_sparse(store: SparseDeviceStore, grad, hess, leaf_id,
                          leaf, row_mult, num_bins: int, num_cols: int):
    """(F, B, 3) histogram of `leaf` from nonzero entries only.

    Fill-bin slots stay ZERO — feature_hist_view (fix_default) or the
    EFB view reconstructs them from the leaf sums.  One segment_sum over
    nnz; rows outside the leaf contribute zero weight.
    """
    m = (leaf_id == leaf).astype(grad.dtype)
    if row_mult is not None:
        m = m * row_mult
    rows = store.nz_row
    w = jnp.stack([jnp.take(grad, rows) * jnp.take(m, rows),
                   jnp.take(hess, rows) * jnp.take(m, rows),
                   jnp.take(m, rows)], axis=-1)           # (nnz, 3)
    seg = jax.ops.segment_sum(w, store.nz_seg,
                              num_segments=num_cols * num_bins)
    return seg.reshape(num_cols, num_bins, 3)


def sparse_split_column(store: SparseDeviceStore, j, n: int, col_cap: int):
    """Full-N int32 bin column j: fill value + the column's entries,
    gathered through a static col_cap window of the flat store."""
    nnz = store.nz_row.shape[0]
    if nnz == 0 or col_cap == 0:        # every value sits at the fill bin
        return jnp.full(n, store.fill[j], jnp.int32)
    start = store.colptr[j]
    end = store.colptr[j + 1]
    idx = start + jnp.arange(max(col_cap, 1), dtype=jnp.int32)
    valid = idx < end
    idxc = jnp.minimum(idx, max(nnz - 1, 0))
    rows = jnp.where(valid, jnp.take(store.nz_row, idxc), n)
    bins = jnp.where(valid, jnp.take(store.nz_bin, idxc), 0)
    col = jnp.full(n, store.fill[j], jnp.int32)
    return col.at[rows].set(bins, mode="drop")
