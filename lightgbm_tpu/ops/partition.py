"""Leaf-membership updates — the TPU replacement for DataPartition.

The reference keeps a leaf-ordered index array and does a stable in-place
partition per split (data_partition.hpp:94-147).  On TPU the natural
structure is a per-row ``leaf_id`` vector updated with a masked where — no
data movement, fully parallel, and identical semantics to
DenseBin::Split (dense_bin.hpp:190-222):

* rows in the default (zero) bin go to the side holding default_bin_for_zero
  (numerical: dbz <= threshold -> left; categorical: dbz == threshold -> left);
* otherwise numerical goes left iff bin <= threshold, categorical iff
  bin == threshold.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.common import kMaxTreeOutput


@jax.jit
def apply_split(binned, leaf_id, leaf, feature, threshold, default_bin,
                default_left, is_cat, right_leaf):
    """Route rows of `leaf` to left (keep id) or right (new id).

    All of feature/threshold/... may be traced scalars so one compiled
    program serves every split.
    """
    col = jnp.take(binned, feature, axis=1).astype(jnp.int32)
    in_leaf = leaf_id == leaf
    go_left_num = col <= threshold
    go_left_cat = col == threshold
    go_left = jnp.where(is_cat, go_left_cat, go_left_num)
    go_left = jnp.where(col == default_bin, default_left, go_left)
    new_id = jnp.where(in_leaf & ~go_left, right_leaf, leaf_id)
    return new_id


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def leaf_outputs_to_scores(leaf_id, leaf_values, num_leaves: int):
    """Gather per-row tree output from leaf assignments (train-set score
    update via the partition, gbdt.cpp:502-515)."""
    return jnp.take(leaf_values, jnp.clip(leaf_id, 0, num_leaves - 1))


def score_update_impl(score, leaf_id, leaf_value, scale):
    """Traceable score += clip(scale * leaf_value)[leaf_id] — the
    partition-side Shrinkage-clamped update (score_updater.hpp:91-99,
    tree.h:110-118).

    THE single source of the gather-form arithmetic: the staged trainer
    reaches it through ops/predict.py's jitted wrapper and the fused
    iteration program (ops/fused_iter.py) inlines it into its one device
    entry — bit-identity between the two paths rests on them tracing the
    exact same ops in the same order, so keep this free of jit wrappers
    and dispatch logic."""
    vals = jnp.clip(leaf_value * scale, -kMaxTreeOutput, kMaxTreeOutput)
    gathered = vals[jnp.clip(leaf_id, 0, leaf_value.shape[0] - 1)]
    return score + gathered.astype(score.dtype)
