"""ctypes bridge to the native data plane (cpp/src/native.cpp).

Mirrors the reference's ctypes loading pattern (python-package basic.py:21 +
libpath.py) — the library is optional: every call site has a pure-Python
fallback, so the package works before `cpp/build.sh` has run.
"""
from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_c_double_p = ctypes.POINTER(ctypes.c_double)


def find_lib_path() -> Optional[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    for cand in (os.path.join(here, "lib", "liblgbm_tpu_native.so"),
                 os.path.join(here, "..", "cpp", "build",
                              "liblgbm_tpu_native.so")):
        if os.path.exists(cand):
            return cand
    return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = find_lib_path()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.LGBMTPU_FindBinNumerical.restype = ctypes.c_int
        lib.LGBMTPU_ValueToBin.restype = ctypes.c_int
        lib.LGBMTPU_ParseFile.restype = ctypes.c_int
        lib.LGBMTPU_PredictRaw.restype = ctypes.c_int
        lib.LGBMTPU_Free.restype = None
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return get_lib() is not None


def _np_ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def find_bin_numerical(values: np.ndarray, total_cnt: int, max_bin: int,
                       min_data_in_bin: int, min_split_data: int):
    """Native FindBin; returns (upper_bounds, is_trivial, min_val, max_val,
    default_bin, sparse_rate) or None when the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    out_bounds = np.empty(max(max_bin, 2), dtype=np.float64)
    num_bin = ctypes.c_int32()
    trivial = ctypes.c_int32()
    vmin = ctypes.c_double()
    vmax = ctypes.c_double()
    default_bin = ctypes.c_int32()
    sparse_rate = ctypes.c_double()
    rc = lib.LGBMTPU_FindBinNumerical(
        _np_ptr(values, ctypes.c_double), ctypes.c_int32(len(values)),
        ctypes.c_int32(total_cnt), ctypes.c_int32(max_bin),
        ctypes.c_int32(min_data_in_bin), ctypes.c_int32(min_split_data),
        _np_ptr(out_bounds, ctypes.c_double), ctypes.byref(num_bin),
        ctypes.byref(trivial), ctypes.byref(vmin), ctypes.byref(vmax),
        ctypes.byref(default_bin), ctypes.byref(sparse_rate))
    if rc != 0:
        return None
    return (out_bounds[:num_bin.value].copy(), bool(trivial.value),
            vmin.value, vmax.value, default_bin.value, sparse_rate.value)


def value_to_bin(upper_bounds: np.ndarray, values: np.ndarray):
    lib = get_lib()
    if lib is None:
        return None
    upper_bounds = np.ascontiguousarray(upper_bounds, dtype=np.float64)
    values = np.ascontiguousarray(values, dtype=np.float64)
    out = np.empty(values.size, dtype=np.uint16)
    rc = lib.LGBMTPU_ValueToBin(
        _np_ptr(upper_bounds, ctypes.c_double),
        ctypes.c_int32(len(upper_bounds)),
        _np_ptr(values, ctypes.c_double), ctypes.c_int64(values.size),
        _np_ptr(out, ctypes.c_uint16))
    if rc != 0:
        return None
    return out.reshape(values.shape).astype(np.int64)


def parse_file(path: str, has_header: bool, label_idx: int):
    """Native file parse -> (features, label) or None."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int32()
    feat_p = _c_double_p()
    lab_p = _c_double_p()
    rc = lib.LGBMTPU_ParseFile(path.encode(), ctypes.c_int32(int(has_header)),
                               ctypes.c_int32(label_idx), ctypes.byref(rows),
                               ctypes.byref(cols), ctypes.byref(feat_p),
                               ctypes.byref(lab_p))
    if rc != 0:
        return None
    n, f = rows.value, cols.value
    feat = np.ctypeslib.as_array(feat_p, shape=(n, f)).copy()
    lab = np.ctypeslib.as_array(lab_p, shape=(n,)).copy()
    lib.LGBMTPU_Free(feat_p)
    lib.LGBMTPU_Free(lab_p)
    return feat, lab


def predict_raw(trees, n_class: int, features: np.ndarray) -> Optional[np.ndarray]:
    """Ensemble prediction through the native traversal.

    trees: list of (models.Tree, class_id).
    """
    lib = get_lib()
    if lib is None or not trees:
        return None
    node_offsets = [0]
    leaf_offsets = [0]
    sf, th, dt, dv, lc, rc_, lv, tc = [], [], [], [], [], [], [], []
    for tree, cls in trees:
        ni = max(tree.num_leaves - 1, 0)
        nl = tree.num_leaves
        sf.append(tree.split_feature[:ni])
        th.append(tree.threshold[:ni])
        dt.append(tree.decision_type[:ni])
        dv.append(tree.default_value[:ni])
        lc.append(tree.left_child[:ni])
        rc_.append(tree.right_child[:ni])
        lv.append(tree.leaf_value[:nl])
        tc.append(cls)
        node_offsets.append(node_offsets[-1] + ni)
        leaf_offsets.append(leaf_offsets[-1] + nl)
    features = np.ascontiguousarray(features, dtype=np.float64)
    n, f = features.shape
    out = np.zeros((n, n_class), dtype=np.float64)
    cat = lambda arrs, dtype: np.ascontiguousarray(
        np.concatenate(arrs) if arrs else np.empty(0), dtype=dtype)
    rc = lib.LGBMTPU_PredictRaw(
        ctypes.c_int32(len(trees)),
        _np_ptr(np.asarray(node_offsets, np.int64), ctypes.c_int64),
        _np_ptr(np.asarray(leaf_offsets, np.int64), ctypes.c_int64),
        _np_ptr(cat(sf, np.int32), ctypes.c_int32),
        _np_ptr(cat(th, np.float64), ctypes.c_double),
        _np_ptr(cat(dt, np.int8), ctypes.c_int8),
        _np_ptr(cat(dv, np.float64), ctypes.c_double),
        _np_ptr(cat(lc, np.int32), ctypes.c_int32),
        _np_ptr(cat(rc_, np.int32), ctypes.c_int32),
        _np_ptr(cat(lv, np.float64), ctypes.c_double),
        _np_ptr(np.asarray(tc, np.int32), ctypes.c_int32),
        ctypes.c_int32(n_class),
        _np_ptr(features, ctypes.c_double), ctypes.c_int64(n),
        ctypes.c_int32(f), _np_ptr(out, ctypes.c_double))
    if rc != 0:
        return None
    return out
