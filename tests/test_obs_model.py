"""Model & data observability (obs/model.py, obs/dataquality.py).

Covers the split audit trail (parity vs the dumped tree structure),
importance evolution (events + Booster.importance_history round-trip),
prediction attribution (pred_contrib sums to the raw score), data-quality
profiling (degeneracy flags, the obs_health=fatal abort), the ``obs
explain`` report, the single-bucket metrics counter, and the
final_eval_metric gate in tools/bench_compare.py.
"""
import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import read_events
from lightgbm_tpu.obs.dataquality import build_findings, label_profile
from lightgbm_tpu.obs.metrics import REGISTRY
from lightgbm_tpu.obs.model import audit_margin_stats, importance_history

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _data(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    y = (X @ w + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _train(params, path, n_rounds=5, X=None, y=None, valid=False):
    if X is None:
        X, y = _data()
    ds = lgb.Dataset(X, label=y)
    base = {"objective": "binary", "num_leaves": 7, "verbose": -1,
            "obs_events_path": str(path)}
    base.update(params)
    kw = {}
    if valid:
        Xv, yv = _data(seed=1)
        kw["valid_sets"] = [lgb.Dataset(Xv, label=yv, reference=ds)]
    return lgb.train(base, ds, num_boost_round=n_rounds, **kw)


# ------------------------------------------------------- split audit trail

def test_split_audit_matches_tree_dump(tmp_path):
    path = tmp_path / "ev.jsonl"
    bst = _train({"obs_split_audit": True}, path, n_rounds=4)
    audits = [e for e in read_events(path) if e["ev"] == "split_audit"]
    assert audits, "no split_audit events"
    assert [e["tree"] for e in audits] == list(range(len(audits)))
    dump = bst.dump_model()
    for e in audits:
        # index the dumped tree's internal nodes by split_index
        nodes = {}

        def walk(node):
            if "split_index" in node:
                nodes[node["split_index"]] = node
                walk(node["left_child"])
                walk(node["right_child"])

        walk(dump["tree_info"][e["tree"]]["tree_structure"])
        assert e["splits"], "audited tree with no splits"
        assert e["num_leaves"] == len(e["splits"]) + 1
        for s in e["splits"]:
            node = nodes[s["node"]]
            assert s["feature"] == node["split_feature"]
            assert s["gain"] == pytest.approx(node["split_gain"], rel=1e-6)
            assert s["count"] == node["internal_count"]
            assert s["left_count"] + s["right_count"] == s["count"]
            assert s["gain"] > 0
            if "second_feature" in s:
                # the runner-up lost: its gain can't beat the winner's
                assert s["second_gain"] <= s["gain"] + 1e-6
                assert s["margin"] == pytest.approx(
                    s["gain"] - s["second_gain"], abs=1e-9)
                assert s["second_feature"] != s["feature"]


def test_audit_margin_stats_aggregates(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_split_audit": True}, path, n_rounds=4)
    events = read_events(path)
    stats = audit_margin_stats(events)
    assert stats
    n_splits = sum(len(e["splits"]) for e in events
                   if e["ev"] == "split_audit")
    assert sum(st["splits"] for st in stats.values()) == n_splits
    for st in stats.values():
        assert st["contested"] <= st["splits"]
        assert st["total_gain"] > 0
        if st["median_margin_rel"] is not None:
            assert 0.0 <= st["median_margin_rel"]


# ---------------------------------------------------- importance evolution

def test_importance_events_and_history_round_trip(tmp_path):
    path = tmp_path / "ev.jsonl"
    bst = _train({"obs_importance_every": 2}, path, n_rounds=5)
    events = read_events(path)
    imps = [e for e in events if e["ev"] == "importance"]
    assert [e["it"] for e in imps] == [0, 2, 4]
    # the final snapshot must agree with the end-of-training importances
    hist = importance_history(events, "split")
    assert [h["it"] for h in hist] == [0, 2, 4]
    dense = bst.feature_importance("split")
    for f, v in hist[-1]["importance"].items():
        assert v == dense[f]
    gains = bst.feature_importance("gain")
    for f, v in importance_history(events, "gain")[-1]["importance"].items():
        assert v == pytest.approx(gains[f], rel=1e-6)
    # Booster.importance_history reads its own telemetry
    assert bst.importance_history("split") == hist
    with pytest.raises(ValueError):
        importance_history(events, "cover")
    # trajectories only grow: split counts are cumulative
    for f in hist[-1]["importance"]:
        series = [h["importance"].get(f, 0.0) for h in hist]
        assert series == sorted(series)


# -------------------------------------------------- prediction attribution

def test_pred_contrib_sums_to_raw(tmp_path):
    X, y = _data()
    bst = _train({}, tmp_path / "ev.jsonl", n_rounds=5, X=X, y=y)
    raw = bst.predict(X, raw_score=True)
    contrib = bst.predict(X, pred_contrib=True)
    assert contrib.shape == (len(X), X.shape[1] + 1)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, atol=1e-5)
    # per-tree attribution sums to the same raw score
    per_tree = bst._gbdt.pred_contrib(X, per="tree")
    assert per_tree.shape[0] == len(X)
    np.testing.assert_allclose(per_tree.sum(axis=1), raw, atol=1e-5)
    with pytest.raises(KeyError):
        bst._gbdt.pred_contrib(X, per="leaf")


def test_pred_contrib_respects_num_iteration(tmp_path):
    X, y = _data()
    bst = _train({}, tmp_path / "ev.jsonl", n_rounds=4, X=X, y=y)
    raw2 = bst.predict(X, raw_score=True, num_iteration=2)
    contrib2 = bst.predict(X, pred_contrib=True, num_iteration=2)
    np.testing.assert_allclose(contrib2.sum(axis=1), raw2, atol=1e-5)
    per_tree2 = bst._gbdt.pred_contrib(X, num_iteration=2, per="tree")
    assert per_tree2.shape[1] == 2


# ----------------------------------------------------- data-quality profile

def test_data_profile_flags_constant_and_imbalance(tmp_path):
    path = tmp_path / "ev.jsonl"
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 5))
    X[:, 3] = 0.0                          # constant (single-bucket)
    y = np.zeros(300)
    y[:2] = 1.0                            # minority fraction 1/150
    _train({}, path, n_rounds=2, X=X, y=y)
    profiles = [e for e in read_events(path) if e["ev"] == "data_profile"]
    assert len(profiles) == 1
    p = profiles[0]
    assert p["n_features"] == 5
    assert 3 in p["constant"]
    assert p["label"]["n_distinct"] == 2
    assert p["label"]["min_class_frac"] == pytest.approx(2 / 300, abs=1e-6)
    flags = {f["flag"]: f["severity"] for f in p["findings"]}
    assert flags.get("constant") == "error"
    assert flags.get("label_imbalance") == "warning"
    # per-feature arrays present for small F
    assert p["missing_rate"][3] == 0.0
    assert p["entropy"][3] is None


def test_constant_nonzero_feature_fatal_abort(tmp_path):
    path = tmp_path / "ev.jsonl"
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 5))
    X[:, 2] = 3.14     # constant NONZERO: still bins into two buckets,
    y = (X[:, 0] > 0).astype(np.float64)   # only one of them occupied
    with pytest.raises(lgb.LightGBMError, match="feature 2"):
        _train({"obs_health": "fatal"}, path, n_rounds=3, X=X, y=y)
    events = [json.loads(ln) for ln in open(path)]
    health = [e for e in events if e.get("ev") == "health"
              and e.get("check") == "data_profile"]
    assert [(h["status"], h["detail"]["feature"], h["detail"]["flag"])
            for h in health] == [("fatal", 2, "constant")]
    # the flight record survives the abort
    assert os.path.exists(str(path) + ".flight.json")
    # warn mode must train through the same data
    bst = _train({"obs_health": "warn"}, tmp_path / "warn.jsonl",
                 n_rounds=3, X=X, y=y)
    assert bst.num_trees() == 3


def test_data_profile_opt_out(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_data_profile": False}, path, n_rounds=2)
    assert not [e for e in read_events(path) if e["ev"] == "data_profile"]


def test_label_profile_and_findings_unit():
    lp = label_profile(np.zeros(10))
    assert lp["n_distinct"] == 1
    findings = build_findings({"n_features": 0}, lp)
    assert [f["flag"] for f in findings] == ["single_class_label"]
    assert findings[0]["severity"] == "error"
    assert label_profile(None) == {"n": 0}
    # a regression-shaped label: distinct count only, no class table
    lp = label_profile(np.linspace(0.0, 1.0, 100))
    assert lp["n_distinct"] == 100 and "classes" not in lp


def test_single_bucket_counter_with_obs_off():
    counter = REGISTRY.counter("dataset_single_bucket_features_total")
    before = counter.value
    X, y = _data(n=200, f=4)
    X[:, 1] = 0.0
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    assert counter.value >= before + 1
    profile = ds._handle._data_profile
    assert profile is None or 1 in profile["constant"]


# ------------------------------------------------------------- obs explain

def test_obs_explain_report(tmp_path, capsys):
    from lightgbm_tpu.obs import query
    path = tmp_path / "ev.jsonl"
    _train({"obs_split_audit": True, "obs_importance_every": 2,
            "metric": "auc"}, path, n_rounds=5, valid=True)
    rc = query.main(["explain", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "data profile (train)" in out
    assert "no data-quality findings" in out
    assert "features by final gain" in out
    assert "split-audit gain margins" in out
    assert "convergence (eval events):" in out
    assert "valid_0 auc" in out
    # --check passes on a clean run, fails on an error-severity finding
    assert query.main(["explain", str(path), "--check"]) == 0
    bad = tmp_path / "bad.jsonl"
    X, y = _data(n=200, f=4)
    X[:, 0] = 0.0
    try:
        _train({"obs_health": "fatal"}, bad, n_rounds=2, X=X, y=y)
    except lgb.LightGBMError:
        pass
    capsys.readouterr()
    assert query.main(["explain", str(bad), "--check"]) == 1
    assert "[error]" in capsys.readouterr().out


def test_obs_explain_cli_subprocess(tmp_path):
    path = tmp_path / "ev.jsonl"
    _train({"obs_split_audit": True, "obs_importance_every": 2}, path,
           n_rounds=3)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-m", "lightgbm_tpu", "obs",
                        "explain", str(path)], capture_output=True,
                       text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "split-audit gain margins" in r.stdout


def test_obs_explain_empty_timeline(tmp_path):
    from lightgbm_tpu.obs import query
    path = tmp_path / "ev.jsonl"
    _train({"obs_data_profile": False}, path, n_rounds=2)
    buf = io.StringIO()
    from lightgbm_tpu.obs.query import last_run, load_timeline
    assert query.render_explain(last_run(load_timeline(str(path))),
                                out=buf) is False
    # schema v8: even a model/data-quiet run records its kernel
    # autotune decision, so the report is never empty for a trained run
    assert "autotune decisions" in buf.getvalue()
    # a timeline with no explainable events at all keeps the fallback
    buf2 = io.StringIO()
    assert query.render_explain([], out=buf2) is False
    assert "no model/data events" in buf2.getvalue()


# ---------------------------------------------------------------- plotting

def test_plot_importance_history_sources(tmp_path):
    pytest.importorskip("matplotlib")
    import matplotlib
    matplotlib.use("Agg")
    from lightgbm_tpu.plotting import (plot_importance,
                                       plot_importance_history)
    path = tmp_path / "ev.jsonl"
    bst = _train({"obs_importance_every": 2}, path, n_rounds=5)
    # timeline path, Booster, and history-result sources all plot
    ax = plot_importance(str(path), importance_type="gain")
    assert ax.get_title() == "Feature importance"
    ax = plot_importance_history(str(path))
    assert len(ax.get_lines()) > 0
    ax = plot_importance_history(bst)
    assert len(ax.get_lines()) > 0
    ax = plot_importance_history(bst.importance_history("gain"))
    assert len(ax.get_lines()) > 0
    with pytest.raises(ValueError):
        plot_importance_history([])


# ------------------------------------------- bench_compare eval-metric gate

def _eval_timeline(path, value):
    with open(path, "w") as f:
        f.write(json.dumps({"ev": "eval", "run": "r", "t": 0.0, "it": 0,
                            "results": [{"dataset": "valid_1",
                                         "metric": "auc",
                                         "value": value}]}) + "\n")


def test_bench_compare_gates_on_eval_metric(tmp_path):
    base, cand = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    _eval_timeline(base, 0.90)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmp_py = os.path.join(REPO, "tools", "bench_compare.py")
    # within tolerance (default 2%): 0.89 vs 0.90 passes
    _eval_timeline(cand, 0.89)
    r = subprocess.run([sys.executable, cmp_py, base, cand],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "final_eval_metric" in r.stdout
    # beyond tolerance: 0.80 vs 0.90 is a quality regression
    _eval_timeline(cand, 0.80)
    r = subprocess.run([sys.executable, cmp_py, base, cand],
                       capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1
    assert "REGRESSED" in r.stdout
