"""Pallas histogram kernel vs the scatter oracle (interpret mode on the CPU
mesh — the reference's OpenCL-on-CPU trick, SURVEY.md §4)."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import leaf_histogram_scatter
from lightgbm_tpu.ops.pallas_hist import HAS_PALLAS, leaf_histogram_pallas

pytestmark = pytest.mark.skipif(not HAS_PALLAS, reason="pallas unavailable")


@pytest.mark.parametrize("n,f,B", [(1000, 5, 16), (3000, 13, 63)])
def test_pallas_matches_scatter(n, f, B):
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.integers(0, B, size=(n, f)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.uniform(0.1, 1, size=n).astype(np.float32))
    leaf_id = jnp.asarray(rng.integers(0, 3, size=n).astype(np.int32))
    rm = jnp.asarray(rng.uniform(0, 2, size=n).astype(np.float32))

    ref = leaf_histogram_scatter(X, g, h, leaf_id, 1, rm, num_bins=B)
    got = leaf_histogram_pallas(X, g, h, leaf_id, 1, rm, num_bins=B)
    assert got.shape == (f, B, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-3)


def test_pallas_no_row_mult():
    rng = np.random.default_rng(1)
    n, f, B = 777, 3, 8     # odd sizes exercise both pad paths
    X = jnp.asarray(rng.integers(0, B, size=(n, f)).astype(np.uint8))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(np.ones(n, np.float32))
    leaf_id = jnp.zeros(n, jnp.int32)
    ref = leaf_histogram_scatter(X, g, h, leaf_id, 0, None, num_bins=B)
    got = leaf_histogram_pallas(X, g, h, leaf_id, 0, None, num_bins=B)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-3)


def test_grow_with_pallas_hist_mode():
    """hist_mode='pallas' grows the same tree as 'scatter'."""
    from lightgbm_tpu.io.dataset import TrainingData
    from lightgbm_tpu.ops.grow import make_grow_fn
    from lightgbm_tpu.ops.learner import build_split_params
    from lightgbm_tpu.ops.split_finder import FeatureMeta
    from lightgbm_tpu.utils.config import Config
    import jax

    rng = np.random.default_rng(2)
    n, f = 600, 4
    Xr = rng.normal(size=(n, f))
    y = (Xr[:, 0] > 0).astype(np.float64)
    cfg = Config({"num_leaves": 7, "min_data_in_leaf": 5, "verbose": -1})
    td = TrainingData.from_matrix(Xr, label=y, config=cfg)
    meta = FeatureMeta(num_bin=jnp.asarray(td.num_bin_arr),
                       default_bin=jnp.asarray(td.default_bin_arr),
                       is_categorical=jnp.asarray(td.is_categorical_arr))
    B = int(td.num_bin_arr.max())
    args = (jnp.asarray(td.binned),
            jnp.asarray((0.5 - y).astype(np.float32)),
            jnp.full(n, 0.25, jnp.float32),
            jnp.ones(n, jnp.float32),
            jnp.ones(f, dtype=bool))
    trees = {}
    for mode in ("scatter", "pallas"):
        grow = make_grow_fn(cfg.num_leaves, B, meta, build_split_params(cfg),
                            cfg.max_depth, hist_mode=mode)
        tree, _ = jax.jit(grow)(*args)
        trees[mode] = tree
    assert int(trees["pallas"].num_leaves) == int(trees["scatter"].num_leaves)
    np.testing.assert_array_equal(
        np.asarray(trees["pallas"].split_feature),
        np.asarray(trees["scatter"].split_feature))
    np.testing.assert_array_equal(
        np.asarray(trees["pallas"].threshold_bin),
        np.asarray(trees["scatter"].threshold_bin))
