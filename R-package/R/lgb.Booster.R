# Booster surface — parity with R-package/R/lgb.Booster.R at the
# reference (predict, save/load/dump, model string, eval results).

#' Predict with a trained booster
#'
#' @param object lgb.Booster
#' @param data matrix / data.frame / file path
#' @param num_iteration number of iterations to use (-1 = all / best)
#' @param rawscore return raw (pre-sigmoid) scores
#' @param predleaf return per-tree leaf indices
#' @export
predict.lgb.Booster <- function(object, data, num_iteration = NULL,
                                rawscore = FALSE, predleaf = FALSE,
                                reshape = FALSE, ...) {
  if (is.data.frame(data)) data <- data.matrix(data)
  if (is.null(num_iteration)) {
    num_iteration <- attr(object, "best_iter")
    if (is.null(num_iteration) || num_iteration < 0L) num_iteration <- -1L
  }
  # reticulate already converts 2-D numpy results (pred_leaf, multiclass
  # probabilities) to R matrices and 1-D results to numeric vectors —
  # including for file-path data, where no local nrow exists
  out <- object$predict(data, num_iteration = as.integer(num_iteration),
                        raw_score = rawscore, pred_leaf = predleaf)
  if (predleaf && is.matrix(out)) storage.mode(out) <- "integer"
  out
}

#' @export
print.lgb.Booster <- function(x, ...) {
  cat(sprintf("<lgb.Booster: %d trees on %d features>\n",
              x$num_trees(), x$num_feature()))
  invisible(x)
}

#' Save the model text file (loadable by the reference too)
#' @export
lgb.save <- function(booster, filename, num_iteration = -1L) {
  if (!lgb.is.Booster(booster)) stop("lgb.save: need an lgb.Booster")
  booster$save_model(filename, num_iteration = as.integer(num_iteration))
  invisible(booster)
}

#' Load a model from a text file or string
#' @export
lgb.load <- function(filename = NULL, model_str = NULL) {
  lgb <- .lgb_py()
  bst <- if (!is.null(filename)) lgb$Booster(model_file = filename)
         else if (!is.null(model_str)) lgb$Booster(model_str = model_str)
         else stop("lgb.load: give filename or model_str")
  .lgb_tag_booster(bst)
}

#' Model as a nested list (parsed JSON dump)
#' @export
lgb.dump <- function(booster, num_iteration = -1L) {
  if (!lgb.is.Booster(booster)) stop("lgb.dump: need an lgb.Booster")
  booster$dump_model(num_iteration = as.integer(num_iteration))
}

#' Model in the reference-compatible text format
#' @export
lgb.model.to.string <- function(booster, num_iteration = -1L) {
  if (!lgb.is.Booster(booster)) stop("lgb.model.to.string: need an lgb.Booster")
  booster$model_to_string(num_iteration = as.integer(num_iteration))
}

#' Metric values recorded during training
#'
#' @param booster a booster returned by lgb.train (carries the record)
#' @param data_name validation set name (e.g. "valid_0")
#' @param eval_name metric name (e.g. "auc")
#' @export
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- attr(booster, "record_evals")
  if (!is.null(rec) && !is.null(rec[[data_name]])
      && !is.null(rec[[data_name]][[eval_name]])) {
    out <- as.numeric(rec[[data_name]][[eval_name]])
    if (!is.null(iters)) out <- out[iters]
    return(out)
  }
  # no training record (e.g. loaded model): fall back to a live eval pass
  out <- c()
  for (tup in booster$eval_valid()) {
    if (identical(tup[[1]], data_name) && identical(tup[[2]], eval_name)) {
      out <- c(out, tup[[3]])
    }
  }
  out
}
