"""Drive the LGBM_* C ABI shared library through ctypes.

The native-bindings smoke the reference runs as tests/c_api_test/test.py:
load the .so, create datasets from raw C buffers, train, evaluate, save /
reload, and predict — all through exported C symbols, never the Python
API.  liblgbm_tpu_capi.so embeds CPython and forwards to the c_api
registry (cpp/src/capi_bridge.cpp); loaded into THIS process it attaches
to the running interpreter via the GIL.
"""
import ctypes
import os

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
LIB = os.path.join(HERE, "..", "lightgbm_tpu", "lib",
                   "liblgbm_tpu_capi.so")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="C ABI library not built")

F64, I32 = 1, 2
N, F = 1500, 10


def _lib():
    lib = ctypes.CDLL(LIB)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.LGBM_GetLastError().decode()


def test_c_abi_train_eval_save_predict(tmp_path):
    lib = _lib()
    rng = np.random.default_rng(4)
    X = np.ascontiguousarray(rng.normal(size=(N, F)))
    y = (X[:, 0] - X[:, 2] > 0).astype(np.float32)

    params = b"objective=binary num_leaves=15 max_bin=63 verbose=-1 metric=auc"
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(N), ctypes.c_int32(F), ctypes.c_int(1), params,
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(N), ctypes.c_int(0)))

    nd = ctypes.c_int()
    nf = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(nf)))
    assert nd.value == N and nf.value == F

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(8):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 8

    # train-set eval through the ABI
    elen = ctypes.c_int()
    evals = (ctypes.c_double * 4)()
    _check(lib, lib.LGBM_BoosterGetEval(bst, ctypes.c_int(0),
                                        ctypes.byref(elen), evals))
    assert elen.value >= 1
    auc = evals[0]
    assert 0.8 < auc <= 1.0

    # predict through raw buffers
    out_len = ctypes.c_int64()
    preds = np.zeros(N, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(N), ctypes.c_int32(F), ctypes.c_int(1),
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.byref(out_len),
        preds.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == N
    assert np.isfinite(preds).all() and 0 < preds.mean() < 1

    # save, reload from file, predictions must match exactly
    model_path = str(tmp_path / "abi.model").encode()
    _check(lib, lib.LGBM_BoosterSaveModel(bst, ctypes.c_int(-1),
                                          model_path))
    nit = ctypes.c_int()
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        model_path, ctypes.byref(nit), ctypes.byref(bst2)))
    assert nit.value == 8
    preds2 = np.zeros(N, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(N), ctypes.c_int32(F), ctypes.c_int(1),
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.byref(out_len),
        preds2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(preds2, preds, rtol=1e-12)

    # model round-trips through the string API too
    slen = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, ctypes.c_int(-1), ctypes.c_int64(0), ctypes.byref(slen),
        None))
    buf = ctypes.create_string_buffer(slen.value)
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, ctypes.c_int(-1), slen, ctypes.byref(slen), buf))
    assert buf.value.decode().startswith("tree\n")

    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_c_abi_full_surface(tmp_path):
    """Round-2 additions: push-rows streaming, subset, field get, feature
    names, custom-objective update, merge/reset-parameter, leaf get/set,
    dump, file predict.  Sampled-column create, CSR push, CSC predict and
    reset-training-data are covered by test_c_abi_streaming_and_csc."""
    lib = _lib()
    rng = np.random.default_rng(6)
    X = np.ascontiguousarray(rng.normal(size=(N, F)))
    y = (X[:, 0] + X[:, 3] > 0).astype(np.float32)
    params = b"objective=binary num_leaves=15 max_bin=63 verbose=-1 metric=auc"

    # reference dataset, then stream rows into an aligned empty dataset
    ds0 = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(N), ctypes.c_int32(F), ctypes.c_int(1), params,
        None, ctypes.byref(ds0)))
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateByReference(ds0, ctypes.c_int64(N),
                                                  ctypes.byref(ds)))
    half = N // 2
    _check(lib, lib.LGBM_DatasetPushRows(
        ds, X[:half].ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(half), ctypes.c_int32(F), ctypes.c_int32(0)))
    tail = np.ascontiguousarray(X[half:])
    _check(lib, lib.LGBM_DatasetPushRows(
        ds, tail.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(N - half), ctypes.c_int32(F), ctypes.c_int32(half)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(N), ctypes.c_int(0)))

    # feature names round-trip
    names = [b"f%d" % i for i in range(F)]
    arr = (ctypes.c_char_p * F)(*names)
    _check(lib, lib.LGBM_DatasetSetFeatureNames(ds, arr, ctypes.c_int(F)))
    bufs = [ctypes.create_string_buffer(255) for _ in range(F)]
    outp = (ctypes.c_char_p * F)(*[ctypes.cast(b, ctypes.c_char_p)
                                   for b in bufs])
    n_names = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetFeatureNames(ds, outp,
                                                ctypes.byref(n_names)))
    assert n_names.value == F and bufs[3].value == b"f3"

    # GetField hands back the label pointer
    flen = ctypes.c_int()
    fptr = ctypes.c_void_p()
    ftype = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetField(ds, b"label", ctypes.byref(flen),
                                         ctypes.byref(fptr),
                                         ctypes.byref(ftype)))
    assert flen.value == N
    got = np.ctypeslib.as_array(
        ctypes.cast(fptr, ctypes.POINTER(ctypes.c_float)), shape=(N,))
    np.testing.assert_allclose(got, y, rtol=1e-6)

    # subset
    idx = np.arange(0, N, 2, dtype=np.int32)
    sub = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetGetSubset(
        ds, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int32(len(idx)), params, ctypes.byref(sub)))
    snd = ctypes.c_int()
    _check(lib, lib.LGBM_DatasetGetNumData(sub, ctypes.byref(snd)))
    assert snd.value == len(idx)

    # booster: custom-objective updates (logistic grad/hess)
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
    nfeat = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetNumFeature(bst, ctypes.byref(nfeat)))
    assert nfeat.value == F
    fin = ctypes.c_int()
    score = np.zeros(N, np.float64)
    for _ in range(4):
        p = 1.0 / (1.0 + np.exp(-score))
        grad = (p - y).astype(np.float32)
        hess = (p * (1 - p)).astype(np.float32)
        _check(lib, lib.LGBM_BoosterUpdateOneIterCustom(
            bst, grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.byref(fin)))
        plen = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterGetNumPredict(bst, ctypes.c_int(0),
                                                  ctypes.byref(plen)))
        assert plen.value == N
        _check(lib, lib.LGBM_BoosterGetPredict(
            bst, ctypes.c_int(0), ctypes.byref(plen),
            score.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))

    # eval/feature name lists
    elen = ctypes.c_int()
    nslots = max(F, 8)
    ebufs = [ctypes.create_string_buffer(255) for _ in range(nslots)]
    eoutp = (ctypes.c_char_p * nslots)(*[ctypes.cast(b, ctypes.c_char_p)
                                         for b in ebufs])
    _check(lib, lib.LGBM_BoosterGetEvalNames(bst, ctypes.byref(elen),
                                             eoutp))
    assert elen.value >= 1 and ebufs[0].value == b"auc"
    _check(lib, lib.LGBM_BoosterGetFeatureNames(bst, ctypes.byref(elen),
                                                eoutp))
    assert elen.value == F

    # leaf get/set + calc-num-predict + dump
    leaf = ctypes.c_double()
    _check(lib, lib.LGBM_BoosterGetLeafValue(bst, ctypes.c_int(0),
                                             ctypes.c_int(0),
                                             ctypes.byref(leaf)))
    _check(lib, lib.LGBM_BoosterSetLeafValue(bst, ctypes.c_int(0),
                                             ctypes.c_int(0),
                                             ctypes.c_double(leaf.value)))
    cnt = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterCalcNumPredict(
        bst, ctypes.c_int(100), ctypes.c_int(0), ctypes.c_int(-1),
        ctypes.byref(cnt)))
    assert cnt.value == 100
    dlen = ctypes.c_int()
    lib.LGBM_BoosterDumpModel(bst, ctypes.c_int(-1), ctypes.c_int(0),
                              ctypes.byref(dlen), None)
    dbuf = ctypes.create_string_buffer(dlen.value)
    _check(lib, lib.LGBM_BoosterDumpModel(bst, ctypes.c_int(-1), dlen,
                                          ctypes.byref(dlen), dbuf))
    assert dbuf.value.decode().lstrip().startswith("{")

    # reset parameter + merge + rollback interplay
    _check(lib, lib.LGBM_BoosterResetParameter(bst, b"learning_rate=0.05"))
    other = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(other)))
    _check(lib, lib.LGBM_BoosterUpdateOneIter(other, ctypes.byref(fin)))
    _check(lib, lib.LGBM_BoosterMerge(bst, other))

    # file predict end-to-end
    data_path = str(tmp_path / "pred.tsv")
    np.savetxt(data_path, np.column_stack([y, X]), delimiter="\t",
               fmt="%.8g")
    result_path = str(tmp_path / "preds.txt")
    _check(lib, lib.LGBM_BoosterPredictForFile(
        bst, data_path.encode(), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", result_path.encode()))
    preds = np.loadtxt(result_path)
    assert preds.shape == (N,) and np.isfinite(preds).all()

    # SetLastError surfaces verbatim
    lib.LGBM_SetLastError(b"custom message")
    assert lib.LGBM_GetLastError() == b"custom message"

    for h in (other, bst):
        _check(lib, lib.LGBM_BoosterFree(h))
    for d in (sub, ds, ds0):
        _check(lib, lib.LGBM_DatasetFree(d))


def test_c_abi_streaming_and_csc():
    """The marshaling-heaviest exports: sampled-column create (double**/
    int**), CSR row pushes, CSC predict, reset-training-data."""
    lib = _lib()
    rng = np.random.default_rng(8)
    n, f = 600, 5
    X = np.ascontiguousarray(rng.normal(size=(n, f)))
    y = (X[:, 0] > 0).astype(np.float32)
    params = b"objective=binary num_leaves=7 max_bin=31 verbose=-1"

    # sampled-column create: every column fully sampled
    col_arrays = [np.ascontiguousarray(X[:, c]) for c in range(f)]
    idx_arrays = [np.arange(n, dtype=np.int32) for _ in range(f)]
    col_ptrs = (ctypes.POINTER(ctypes.c_double) * f)(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
          for a in col_arrays])
    idx_ptrs = (ctypes.POINTER(ctypes.c_int) * f)(
        *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_int))
          for a in idx_arrays])
    per_col = (ctypes.c_int * f)(*([n] * f))
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromSampledColumn(
        col_ptrs, idx_ptrs, ctypes.c_int32(f), per_col, ctypes.c_int32(n),
        ctypes.c_int32(n), params, ctypes.byref(ds)))

    # stream the rows in via CSR pushes (two chunks)
    def csr_of(rows):
        indptr, cols, vals = [0], [], []
        for i in range(rows.shape[0]):
            nz = np.nonzero(rows[i])[0]
            cols.extend(nz.tolist())
            vals.extend(rows[i, nz].tolist())
            indptr.append(len(cols))
        return (np.asarray(indptr, np.int32), np.asarray(cols, np.int32),
                np.asarray(vals, np.float64))

    half = n // 2
    for start, chunk in ((0, X[:half]), (half, X[half:])):
        indptr, cols, vals = csr_of(np.ascontiguousarray(chunk))
        _check(lib, lib.LGBM_DatasetPushRowsByCSR(
            ds, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(I32),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
            ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
            ctypes.c_int64(f), ctypes.c_int64(start)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n), ctypes.c_int(0)))

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(3):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # CSC predict over the same matrix
    colptr, rows_i, vals_c = [0], [], []
    for c in range(f):
        nz = np.nonzero(X[:, c])[0]
        rows_i.extend(nz.tolist())
        vals_c.extend(X[nz, c].tolist())
        colptr.append(len(rows_i))
    colptr = np.asarray(colptr, np.int32)
    rows_i = np.asarray(rows_i, np.int32)
    vals_c = np.asarray(vals_c, np.float64)
    out_len = ctypes.c_int64()
    preds = np.zeros(n, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForCSC(
        bst, colptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(I32),
        rows_i.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals_c.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int64(len(colptr)), ctypes.c_int64(len(vals_c)),
        ctypes.c_int64(n), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_len), preds.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n and np.isfinite(preds).all()

    # dense predict must agree with CSC predict
    dense_preds = np.zeros(n, np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(n), ctypes.c_int32(f), ctypes.c_int(1),
        ctypes.c_int(0), ctypes.c_int(-1), b"", ctypes.byref(out_len),
        dense_preds.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(preds, dense_preds, rtol=1e-9)

    # reset training data to a fresh dataset and keep training
    ds2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int32(n), ctypes.c_int32(f), ctypes.c_int(1), params,
        None, ctypes.byref(ds2)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds2, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n), ctypes.c_int(0)))
    _check(lib, lib.LGBM_BoosterResetTrainingData(bst, ds2))
    _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # missing field is an ERROR (success never yields NULL, as reference)
    flen = ctypes.c_int()
    fptr = ctypes.c_void_p()
    ftype = ctypes.c_int()
    rc = lib.LGBM_DatasetGetField(ds2, b"weight", ctypes.byref(flen),
                                  ctypes.byref(fptr), ctypes.byref(ftype))
    assert rc != 0 and b"not found" in lib.LGBM_GetLastError().lower()

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_c_abi_csr_create_and_predict():
    lib = _lib()
    rng = np.random.default_rng(5)
    dense = rng.normal(size=(800, 12))
    dense[rng.random(dense.shape) > 0.15] = 0.0
    y = (dense[:, 0] + dense[:, 1] > 0).astype(np.float32)
    indptr, cols, vals = [0], [], []
    for i in range(dense.shape[0]):
        nz = np.nonzero(dense[i])[0]
        cols.extend(nz.tolist())
        vals.extend(dense[i, nz].tolist())
        indptr.append(len(cols))
    indptr = np.asarray(indptr, np.int32)
    cols = np.asarray(cols, np.int32)
    vals = np.asarray(vals, np.float64)

    params = b"objective=binary num_leaves=15 max_bin=63 verbose=-1"
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(I32),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
        ctypes.c_int64(12), params, None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(len(y)), ctypes.c_int(0)))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(ds, params, ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(4):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    out_len = ctypes.c_int64()
    preds = np.zeros(len(y), np.float64)
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(I32),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(F64),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(len(vals)),
        ctypes.c_int64(12), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(out_len), preds.ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == len(y)
    assert np.isfinite(preds).all()

    # error path: invalid handle surfaces through LGBM_GetLastError
    bad = ctypes.c_void_p(987654)
    rc = lib.LGBM_BoosterUpdateOneIter(bad, ctypes.byref(fin))
    assert rc != 0
    assert b"handle" in lib.LGBM_GetLastError().lower()
