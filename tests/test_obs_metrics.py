"""Metrics registry, health monitors, and bench_compare gating (PR 2).

Covers the layers on top of the PR-1 run timeline:
  * registry semantics — counter/gauge/histogram, le-inclusive buckets,
    get-or-create identity, type-mismatch errors;
  * Prometheus textfile + JSON export golden output and file routing;
  * health monitors — non-finite gradients injected through a custom
    fobj under obs_health=warn (run completes, warn events in the
    timeline) and obs_health=fatal (run aborts, fatal event + run_end
    status=aborted in the JSONL); EMA divergence, plateau (warn-only),
    memory watermark at the unit level;
  * EventWriter / RunObserver crash-safety (context managers, atexit
    finalization path);
  * tools/bench_compare.py exit codes on synthetic baselines.
"""
import importlib.util
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs import (NULL_OBSERVER, EventWriter, HealthMonitors,
                              MetricsRegistry, REGISTRY, RunObserver,
                              observer_from_config, read_events)
from lightgbm_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                      observe_predict)
from lightgbm_tpu.utils.config import Config

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _data(n=400, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _train(params, n_rounds=5, fobj=None):
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 7, "verbose": -1}
    base.update(params)
    if fobj is not None:
        base.pop("objective", None)
    return lgb.train(base, lgb.Dataset(X, label=y),
                     num_boost_round=n_rounds, fobj=fobj,
                     verbose_eval=False)


class _CollectObs:
    """Minimal observer double for unit-level health tests."""

    def __init__(self):
        self.events = []
        self.flushed = 0

    def event(self, ev, **fields):
        self.events.append(dict(fields, ev=ev))

    def flush(self):
        self.flushed += 1


# --------------------------------------------------------------- registry

def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", "jobs processed")
    c.inc()
    c.inc(2)
    assert c.value == 3
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create: same (name, labels) -> same instrument
    assert reg.counter("jobs_total") is c
    # distinct labels -> distinct series
    c2 = reg.counter("jobs_total", labels={"kind": "a"})
    assert c2 is not c and c2.value == 0
    # type mismatch on an existing name raises, never forks the series
    with pytest.raises(ValueError):
        reg.gauge("jobs_total")


def test_gauge_semantics():
    g = Gauge("temp")
    g.set(5.0)
    g.inc(2)
    g.dec()
    assert g.value == 6.0
    g.max(4.0)            # watermark keeps the larger value
    assert g.value == 6.0
    g.max(9.0)
    assert g.value == 9.0


def test_histogram_buckets_le_inclusive():
    h = Histogram("lat", buckets=(0.5, 1.0))
    h.observe(0.25)
    h.observe(0.5)        # exactly on the bound -> counts in le="0.5"
    h.observe(2.0)        # beyond the last bound -> +Inf only
    assert h.cumulative() == [("0.5", 2), ("1", 2), ("+Inf", 3)]
    assert h.count == 3 and h.sum == pytest.approx(2.75)
    exp = h._export()
    assert exp["type"] == "histogram"
    assert exp["buckets"] == {"0.5": 2, "1": 2, "+Inf": 3}
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(1.0, 1.0))      # not strictly increasing
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(2.0, 1.0))


def test_prometheus_export_golden():
    reg = MetricsRegistry()
    reg.counter("jobs_total", "jobs processed").inc(3)
    reg.gauge("temp", labels={"room": "a"}).set(2.5)
    h = reg.histogram("lat", "request latency", buckets=(0.5, 1.0))
    for v in (0.25, 0.5, 2.0):
        h.observe(v)
    assert reg.to_prometheus() == (
        "# HELP jobs_total jobs processed\n"
        "# TYPE jobs_total counter\n"
        "jobs_total 3\n"
        "# HELP lat request latency\n"
        "# TYPE lat histogram\n"
        'lat_bucket{le="0.5"} 2\n'
        'lat_bucket{le="1"} 2\n'
        'lat_bucket{le="+Inf"} 3\n'
        "lat_sum 2.75\n"
        "lat_count 3\n"
        "# TYPE temp gauge\n"
        'temp{room="a"} 2.5\n')


def test_json_export_and_write_routing(tmp_path):
    reg = MetricsRegistry()
    reg.counter("jobs_total").inc(7)
    doc = json.loads(reg.to_json())
    assert doc == {"metrics": {"jobs_total": {"type": "counter",
                                              "value": 7}}}
    prom = tmp_path / "m.prom"
    reg.write(prom)
    assert "# TYPE jobs_total counter" in prom.read_text()
    js = tmp_path / "m.json"
    reg.write(js)
    assert json.loads(js.read_text())["metrics"]["jobs_total"]["value"] == 7


def test_observe_predict_records_into_global_registry():
    before = REGISTRY.counter("lgbm_predict_rows_total").value
    observe_predict(25, 0.01)
    reg_snap = REGISTRY.snapshot()
    assert REGISTRY.counter("lgbm_predict_rows_total").value == before + 25
    assert reg_snap["lgbm_predict_seconds"]["count"] >= 1
    assert reg_snap["lgbm_predict_batch_rows"]["count"] >= 1


def test_predict_path_is_instrumented():
    bst = _train({})
    X, _ = _data()
    before = REGISTRY.counter("lgbm_predict_rows_total").value
    bst.predict(X[:50])
    assert REGISTRY.counter("lgbm_predict_rows_total").value >= before + 50


# ------------------------------------------------- training integration

def test_training_emits_metric_snapshots_and_export(tmp_path):
    events = tmp_path / "ev.jsonl"
    prom = tmp_path / "metrics.prom"
    before_iters = REGISTRY.counter("lgbm_train_iterations_total").value
    before_trees = REGISTRY.counter("lgbm_trees_built_total").value
    _train({"obs_events_path": str(events), "obs_metrics_every": 2,
            "obs_metrics_path": str(prom), "obs_health": "warn"},
           n_rounds=5)
    evs = read_events(str(events))
    kinds = [e["ev"] for e in evs]
    assert kinds[-1] == "run_end"
    end = evs[-1]
    assert end["status"] == "ok"
    assert end["health"]["mode"] == "warn"
    # clean data: every health verdict is ok
    health = [e for e in evs if e["ev"] == "health"]
    assert health and all(e["status"] == "ok" for e in health)
    # metric snapshots at the cadence plus one final pre-run_end scrape
    snaps = [e for e in evs if e["ev"] == "metrics"]
    assert len(snaps) >= 2
    scrape = snaps[-1]["scrape"]
    assert REGISTRY.counter(
        "lgbm_train_iterations_total").value == before_iters + 5
    assert REGISTRY.counter(
        "lgbm_trees_built_total").value == before_trees + 5
    assert scrape["lgbm_train_iter_seconds"]["count"] >= 5
    text = prom.read_text()
    assert "# TYPE lgbm_train_iterations_total counter" in text
    assert 'lgbm_train_iter_seconds_bucket{le="' in text


def test_nan_gradients_warn_keeps_running(tmp_path):
    events = tmp_path / "ev.jsonl"

    def fobj(preds, dataset):
        n = len(dataset.get_label())
        return np.full(n, np.nan), np.ones(n)

    _train({"obs_events_path": str(events), "obs_health": "warn"},
           n_rounds=2, fobj=fobj)
    evs = read_events(str(events))
    fired = [e for e in evs if e["ev"] == "health"
             and e["check"] == "nonfinite_gradients"]
    assert fired and all(e["status"] == "warn" for e in fired)
    assert evs[-1]["ev"] == "run_end" and evs[-1]["status"] == "ok"
    assert evs[-1]["health"]["counts"]["warn"] >= 1


def test_nan_gradients_fatal_aborts_run(tmp_path):
    """ISSUE acceptance: injected NaN gradients abort under
    obs_health=fatal, with the health event persisted in the JSONL."""
    events = tmp_path / "ev.jsonl"

    def fobj(preds, dataset):
        n = len(dataset.get_label())
        return np.full(n, np.nan), np.ones(n)

    with pytest.raises(lgb.LightGBMError, match="obs_health=fatal"):
        _train({"obs_events_path": str(events), "obs_health": "fatal"},
               n_rounds=5, fobj=fobj)
    evs = read_events(str(events))
    fired = [e for e in evs if e["ev"] == "health"
             and e["check"] == "nonfinite_gradients"]
    assert fired and fired[0]["status"] == "fatal"
    assert evs[-1]["ev"] == "run_end" and evs[-1]["status"] == "aborted"


def test_diverging_gradients_warn(tmp_path):
    events = tmp_path / "ev.jsonl"
    calls = [0]

    def fobj(preds, dataset):
        n = len(dataset.get_label())
        g = np.full(n, 10.0 ** calls[0])
        calls[0] += 1
        return g, np.ones(n)

    _train({"obs_events_path": str(events), "obs_health": "warn"},
           n_rounds=4, fobj=fobj)
    evs = read_events(str(events))
    fired = [e for e in evs if e["ev"] == "health"
             and e["check"] == "loss_divergence"]
    assert fired and all(e["status"] == "warn" for e in fired)


# ------------------------------------------------------ health unit level

def test_divergence_fatal_raises_and_flushes():
    hm = HealthMonitors(mode="fatal", divergence=3.0)
    obs = _CollectObs()
    for it, scale in enumerate((1.0, 10.0)):
        hm.stage_gradients(np.full(8, scale), np.ones(8))
        hm.run_checks(obs, it)
    hm.stage_gradients(np.full(8, 100.0), np.ones(8))
    with pytest.raises(lgb.LightGBMError):
        hm.run_checks(obs, 2)
    assert obs.flushed == 1           # timeline flushed before the raise
    fatal = [e for e in obs.events if e["ev"] == "health"
             and e["check"] == "loss_divergence"]
    assert fatal and fatal[0]["status"] == "fatal"


def test_plateau_is_warn_only_even_under_fatal():
    hm = HealthMonitors(mode="fatal", plateau=2)
    obs = _CollectObs()
    for it in range(4):               # constant gradients: EMA flatlines
        hm.stage_gradients(np.ones(8), np.ones(8))
        hm.run_checks(obs, it)        # must never raise
    fired = [e for e in obs.events if e["ev"] == "health"
             and e["check"] == "plateau"]
    assert fired and all(e["status"] == "warn" for e in fired)


def test_memory_watermark(tmp_path):
    hm = HealthMonitors(mode="warn", mem_frac=0.9)
    obs = _CollectObs()
    rows = [{"id": 0, "bytes_in_use": 95, "bytes_limit": 100},
            {"id": 1, "bytes_in_use": 10, "bytes_limit": 100}]
    hm.check_memory(obs, 3, devices=rows)
    fired = [e for e in obs.events if e["ev"] == "health"
             and e["check"] == "memory_watermark"]
    assert len(fired) == 1 and fired[0]["status"] == "warn"
    assert fired[0]["detail"]["device"] == 0
    assert hm.summary()["mem_peak_frac"] == {"0": 0.95, "1": 0.1}
    # CPU-style identity rows (no byte counters) are a no-op
    hm.check_memory(obs, 4, devices=[{"id": 0}])
    assert len([e for e in obs.events if e["ev"] == "health"]) == 1
    # fatal mode raises
    hm2 = HealthMonitors(mode="fatal", mem_frac=0.9)
    with pytest.raises(lgb.LightGBMError):
        hm2.check_memory(_CollectObs(), 0, devices=rows)


def test_health_cadence_and_mode_validation():
    hm = HealthMonitors(mode="warn", every=3)
    assert [it for it in range(7) if hm.due(it)] == [0, 3, 6]
    with pytest.raises(ValueError):
        HealthMonitors(mode="sideways")


# ------------------------------------------------------- crash safety

def test_event_writer_context_manager(tmp_path):
    path = tmp_path / "w.jsonl"
    with EventWriter(str(path), flush_every=1000) as w:
        w.emit({"ev": "health", "run": "x", "t": 0.0,
                "check": "stats", "status": "ok", "it": 0})
    # closed on exit; the un-flushed tail made it to disk
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["ev"] == "health"


def test_run_observer_context_manager(tmp_path):
    ok_path = tmp_path / "ok.jsonl"
    with RunObserver(events_path=str(ok_path), timing="off"):
        pass
    assert read_events(str(ok_path))[-1]["status"] == "ok"
    bad_path = tmp_path / "bad.jsonl"
    with pytest.raises(RuntimeError):
        with RunObserver(events_path=str(bad_path), timing="off") as obs:
            obs.event("health", check="stats", status="ok", it=0)
            raise RuntimeError("boom")
    evs = read_events(str(bad_path))
    assert evs[-1]["ev"] == "run_end" and evs[-1]["status"] == "aborted"


def test_run_observer_atexit_finalization(tmp_path):
    path = tmp_path / "crash.jsonl"
    obs = RunObserver(events_path=str(path), timing="off")
    obs.event("health", check="stats", status="ok", it=0)
    obs._finalize_at_exit()           # what atexit runs on a crashed run
    evs = read_events(str(path))
    assert evs[-1]["ev"] == "run_end" and evs[-1]["status"] == "aborted"
    obs._finalize_at_exit()           # idempotent: no second run_end
    assert len(read_events(str(path))) == len(evs)


def test_engine_finalizes_aborted_on_callback_crash(tmp_path):
    path = tmp_path / "cb.jsonl"

    def bomb(env):
        raise RuntimeError("callback boom")

    X, y = _data()
    with pytest.raises(RuntimeError):
        lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                   "obs_events_path": str(path)},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  callbacks=[bomb], verbose_eval=False)
    assert read_events(str(path))[-1]["status"] == "aborted"


# -------------------------------------------------------- config wiring

def test_observer_from_config_health_and_metrics():
    assert observer_from_config(Config({})) is NULL_OBSERVER
    obs = observer_from_config(Config({"obs_health": "warn"}))
    assert isinstance(obs, RunObserver)
    assert isinstance(obs.health, HealthMonitors)
    assert obs.health.mode == "warn"
    obs.close()
    obs = observer_from_config(Config({"obs_metrics_every": 3}))
    assert isinstance(obs, RunObserver) and obs.health is None
    obs.close()
    with pytest.raises(lgb.LightGBMError):
        observer_from_config(Config({"obs_health": "bogus"}))
    cfg = Config({"obs_health_mode": "fatal", "obs_health_freq": 2,
                  "obs_metrics_file": "/tmp/m.prom",
                  "obs_metrics_freq": 5})
    assert cfg.obs_health == "fatal" and cfg.obs_health_every == 2
    assert cfg.obs_metrics_path == "/tmp/m.prom"
    assert cfg.obs_metrics_every == 5


# --------------------------------------------------------- bench_compare

def _bench_compare():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(REPO, "tools", "bench_compare.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _timeline(path, iter_s, first_s=1.0, peak=1000):
    evs = [{"ev": "run_header", "run": "r", "t": 0.0},
           {"ev": "iter", "run": "r", "t": 0.0, "time_s": iter_s},
           {"ev": "iter", "run": "r", "t": 0.0, "time_s": iter_s},
           {"ev": "memory", "run": "r", "t": 0.0,
            "devices": [{"id": 0, "bytes_in_use": peak}]},
           {"ev": "run_end", "run": "r", "t": 0.0,
            "entries": {"boost": {"first_s": first_s}}}]
    with open(path, "w") as f:
        for e in evs:
            f.write(json.dumps(e) + "\n")
    return str(path)


def test_bench_compare_identical_passes(tmp_path):
    bc = _bench_compare()
    p = _timeline(tmp_path / "a.jsonl", 0.2)
    assert bc.main([p, p]) == 0
    assert bc.load_metrics(p) == {"iters_per_sec": pytest.approx(5.0),
                                  "compile_s": 1.0,
                                  "peak_mem_bytes": 1000}


def test_bench_compare_regressions(tmp_path):
    bc = _bench_compare()
    base = _timeline(tmp_path / "base.jsonl", 0.2)
    # iters/sec drops 50% -> regression
    slow = _timeline(tmp_path / "slow.jsonl", 0.4)
    assert bc.main([base, slow]) == 1
    # within tolerance passes
    near = _timeline(tmp_path / "near.jsonl", 0.205)
    assert bc.main([base, near]) == 0
    # compile-time regression alone trips too
    compiley = _timeline(tmp_path / "c.jsonl", 0.2, first_s=2.0)
    assert bc.main([base, compiley]) == 1
    # ...unless the tolerance is widened
    assert bc.main([base, compiley, "--tol-compile", "2.0"]) == 0
    # memory regression
    fat = _timeline(tmp_path / "fat.jsonl", 0.2, peak=2000)
    assert bc.main([base, fat]) == 1


def test_bench_compare_lineage_and_child_lines(tmp_path):
    bc = _bench_compare()
    lineage = tmp_path / "BENCH_r01.json"
    lineage.write_text(json.dumps(
        {"round": 1, "parsed": {"metric": "train_iters_per_sec",
                                "value": 1.30, "unit": "iters/sec"}}))
    child = tmp_path / "child.jsonl"
    child.write_text(json.dumps({"metric": "train_iters_per_sec",
                                 "value": 1.0, "unit": "iters/sec"}) + "\n")
    assert bc.main([str(lineage), str(lineage)]) == 0
    assert bc.main([str(lineage), str(child)]) == 1       # 23% drop
    assert bc.main([str(child), str(lineage)]) == 0       # improvement


def test_bench_compare_usage_errors(tmp_path):
    bc = _bench_compare()
    garbage = tmp_path / "garbage.txt"
    garbage.write_text("not json at all\n")
    p = _timeline(tmp_path / "a.jsonl", 0.2)
    assert bc.main([p, str(garbage)]) == 2
    assert bc.main([p, str(tmp_path / "missing.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert bc.main([p, str(empty)]) == 2                  # no overlap


def test_bench_compare_json_verdict(tmp_path, capsys):
    bc = _bench_compare()
    base = _timeline(tmp_path / "base.jsonl", 0.2)
    slow = _timeline(tmp_path / "slow.jsonl", 0.4)
    assert bc.main([base, slow, "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["status"] == "regression"
    bad = [m for m in doc["metrics"] if m["regressed"]]
    assert bad and bad[0]["metric"] == "iters_per_sec"
