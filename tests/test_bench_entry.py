"""End-to-end pin of the driver's bench artifact path.

bench.py is the round artifact the driver runs on real hardware; rounds 1
and 2 both lost it to tunnel failures the script didn't anticipate.  This
test drives the FULL orchestrator (probe -> child subprocess -> one JSON
line on stdout) on the CPU platform with a tiny recipe, so regressions in
the wedge-handling plumbing show up in CI instead of in a red
BENCH_r{N}.json.
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def test_bench_orchestrator_end_to_end():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_ALLOW_CPU": "1",
        "BENCH_ROWS": "20000",
        "BENCH_WARMUP": "1",
        "BENCH_MEASURED": "2",
        "BENCH_DEADLINE_S": "900",
        "BENCH_ATTEMPT_S": "600",
        # a slow CI host must not trip the watchdog mid-run — this test
        # asserts the single-line healthy contract
        "BENCH_FALLBACK_AT_S": "870",
    })
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline",
                        "final_eval_metric", "final_eval_name",
                        "construct_s", "flop_util", "hbm_util"}
    assert rec["value"] > 0
    assert rec["construct_s"] is None or rec["construct_s"] >= 0
    # roofline rollup: present when the timeline carried a utilization
    # event (obs/roofline.py), null otherwise — never out of range
    for k in ("flop_util", "hbm_util"):
        assert rec[k] is None or 0.0 <= rec[k] <= 1.0
    assert rec["unit"] == "iters/sec"
    assert rec["final_eval_name"] == "auc"
    assert 0.0 < rec["final_eval_metric"] <= 1.0
    # an overridden shape must not masquerade as the flagship artifact
    assert "higgs20000x28" in rec["metric"]
    assert rec["vs_baseline"] is None


def test_bench_exits_cleanly_when_deadline_exhausted():
    env = dict(os.environ)
    env["BENCH_DEADLINE_S"] = "5"
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=120,
                       cwd=REPO, env=env)
    assert r.returncode == 2
    assert "deadline exhausted" in r.stderr
    # even the instant-exhaustion path must leave a parseable artifact
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    assert rec["status"] == "no_driver_measurement"


def test_bench_wedge_drill_emits_fallback_artifact():
    """VERDICT r4 Missing #2: a wedged tunnel must still yield one
    parseable JSON line on stdout — status, diagnosis, and the newest
    committed builder-run number — emitted early, not at deadline.

    Drill: CPU backend without BENCH_ALLOW_CPU == persistent backend
    mismatch (the shape of a mid-recovery tunnel), with the watchdog
    armed at 1 s so the fallback beats the fail-fast exit."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_DEADLINE_S": "600",
        "BENCH_FALLBACK_AT_S": "1",
        "BENCH_PROBE_GAP_S": "1",
    })
    env.pop("BENCH_ALLOW_CPU", None)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=300,
                       cwd=REPO, env=env)
    assert r.returncode == 3, (r.returncode, r.stderr[-2000:])
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    rec = json.loads(lines[0])
    # core schema intact so the driver's parser is satisfied...
    assert {"metric", "value", "unit", "vs_baseline"} <= set(rec)
    # ...plus the wedge diagnosis and the provenance pointer
    assert rec["status"] == "no_driver_measurement"
    assert "bench_artifacts" in rec["source"]
    assert rec["value"] > 0    # the committed 9.77x builder number rides


def test_persistent_compilation_cache(tmp_path):
    """enable_compilation_cache points JAX's persistent cache at a durable
    dir (VERDICT r3 Missing #6: bench retries must skip the ~200 s
    flagship compile).  A fresh jit must leave entries on disk."""
    code = (
        "import jax, sys\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from lightgbm_tpu.utils.common import enable_compilation_cache\n"
        "d = enable_compilation_cache(sys.argv[1])\n"
        "assert d == sys.argv[1], d\n"
        "import jax.numpy as jnp\n"
        "jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128)))"
        ".block_until_ready()\n"
    )
    r = subprocess.run([sys.executable, "-c", code, str(tmp_path)],
                       capture_output=True, text=True, timeout=300,
                       cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(list(tmp_path.iterdir())) > 0


def test_compilation_cache_disabled_by_env():
    code = (
        "import os\n"
        "os.environ['LGBM_TPU_COMPILE_CACHE'] = '0'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from lightgbm_tpu.utils.common import enable_compilation_cache\n"
        "assert enable_compilation_cache() is None\n"
    )
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]


def test_compilation_cache_default_off_on_cpu():
    """Without an explicit dir the cache must NOT engage on CPU —
    serializing host-feature-specific CPU executables has segfaulted
    (observed in-process during the r4 suite run); TPU is the target."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from lightgbm_tpu.utils.common import enable_compilation_cache\n"
        "assert enable_compilation_cache() is None\n"
    )
    env = {k: v for k, v in os.environ.items()
           if k != "LGBM_TPU_COMPILE_CACHE"}   # operator opt-in env must
    # not leak in and flip the gate this test pins
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]


def test_package_import_is_backend_clean():
    """honor_jax_platforms() (utils/common.py) is imported THROUGH the
    package by the CPU-pinnable tools (bench.py child, parity child,
    tpu_profile) BEFORE the jax_platforms pin applies — which is only
    safe while `import lightgbm_tpu` touches no JAX backend.  Pin that
    invariant: a module-level jnp/jax.devices() call sneaking into the
    import graph would silently dispatch those tools to the tunneled
    TPU (the failure mode the helper exists to prevent).

    Probed via a public signal (ADVICE r4): with JAX_PLATFORMS set to a
    nonexistent platform, backend initialization raises — so the import
    only succeeds while it touches no backend."""
    code = (
        "import lightgbm_tpu\n"
        "print('clean')\n")
    env = dict(os.environ, JAX_PLATFORMS="nonexistent_platform")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    assert "clean" in r.stdout
