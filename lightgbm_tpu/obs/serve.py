"""Serving-tier observability: rolling SLOs, burn-rate alerts, reports.

Training observability (PRs 1-5) answers "is this run healthy"; this
module answers the serving questions a production operator actually
pages on — "are we inside our p99 target", "how fast are we burning the
error budget", "what is being shed" — the measure-don't-assume
methodology of the serving-benchmark literature (arxiv 1809.04559).

Two halves:

* **SloEngine** (writer side) — a lock-light rolling-window aggregator
  the microbatch scheduler feeds one ``record()`` per completed request
  and one ``record_shed()`` per rejected one.  State is a ring of
  1-second buckets per route kind, each holding a fixed log-spaced
  latency histogram + request/error/shed counts: recording is one lock
  acquisition, one bisect and four int adds — no per-request
  allocation, no sorting, safe on the serve worker's hot path.  Every
  ``serve_slo_every_s`` it evaluates:

  - per-route QPS, p50/p95/p99 (histogram upper bounds, conservative),
    error rate and shed rate over the long window;
  - **multi-window burn rate** against the ``serve_slo_p99_ms`` target:
    the latency SLO is "at most 1% of requests may exceed the target"
    (the 99 in p99), burn = (fraction over target) / 1%, and the alert
    fires only when BOTH the short window (window/6) and the long
    window burn above ``BURN_THRESHOLD`` — the standard SRE recipe that
    pages fast on a real outage but not on one slow request; it clears
    when the short-window burn drops back under threshold;
  - a ``serve_slo`` snapshot event plus, on alert transitions, a
    ``health`` event with ``check="slo_burn_rate"`` routed through the
    same ``obs_health`` warn/fatal channel as the training monitors
    (warn-only: see health._WARN_ONLY — killing a server that is
    missing latency targets only makes the outage total).

* **serve_metrics / render_serve_report** (reader side) — fold a
  recorded timeline's serving events (serve_batch / serve_request /
  serve_slo / serve_summary / serve_bench + slo_burn_rate health
  events) into the report behind ``python -m lightgbm_tpu obs serve``:
  per-route latency table, SLO verdicts, shed/overload summary and
  batch efficiency (real rows / padded slots).  ``--check`` turns the
  report into a CI gate: any shed, any fired burn alert or any failing
  SLO verdict exits nonzero.
"""
from __future__ import annotations

import bisect
import math
import sys
import threading
import time

from .metrics import REGISTRY
from ..utils.log import Log

# log-spaced latency estimation ladder: 50us .. ~26s, 25% resolution.
# Quantiles report a bucket's upper bound, so they over-estimate by at
# most one ratio step — conservative in the direction that never hides
# an SLO violation.
_RATIO = 1.25
LATENCY_LADDER = tuple(5e-5 * (_RATIO ** i) for i in range(60))

# the "99" in p99: the fraction of requests allowed over the target
P99_BUDGET = 0.01
# both burn windows must exceed this multiple of the budget to page
BURN_THRESHOLD = 2.0


def route_kind(route):
    """Route KIND from a route key: tuple -> first element, string ->
    itself.  The cardinality discipline of obs/metrics.py: full route
    tuples embed client-supplied values and stay on sampled events."""
    if isinstance(route, tuple) and route:
        return str(route[0])
    return str(route)


def _kind_from_event(e):
    """Route kind of a recorded serving event: the explicit ``kind``
    field (schema 7) or parsed from the stringified route tuple that
    schema-6 events carry, e.g. ``"('dev', True)"`` -> ``dev``."""
    k = e.get("kind")
    if k:
        return str(k)
    r = str(e.get("route", "")).strip()
    for ch in "(\"'":
        r = r.replace(ch, "")
    return (r.split(",")[0] or "?").strip()


def _pct_sorted(xs, q):
    """Nearest-rank percentile of an already-sorted list."""
    if not xs:
        return 0.0
    i = max(0, min(len(xs) - 1, int(math.ceil(q * len(xs))) - 1))
    return xs[i]


class SloEngine:
    """Rolling-window SLO aggregator + burn-rate alerter for the serve
    tier.  Thread-safe; one instance per ServingPredictor, fed by the
    scheduler worker and submitting threads.

    ``p99_ms``/``qps`` of 0 mean "no target": the engine still
    aggregates and snapshots (the operator's dashboard), it just has
    nothing to verdict or page on.  ``clock`` is injectable so tests
    drive the windows deterministically.
    """

    def __init__(self, observer=None, mode="warn", p99_ms=0.0, qps=0.0,
                 window_s=60.0, every_s=10.0,
                 burn_threshold=BURN_THRESHOLD, clock=time.monotonic):
        from .events import NULL_OBSERVER
        from .health import MODES
        self.observer = observer if observer is not None else NULL_OBSERVER
        mode = str(mode or "warn").strip().lower()
        if mode not in MODES:
            raise ValueError("slo mode %r (expected off/warn/fatal)"
                             % (mode,))
        self.mode = mode
        self.p99_target_s = max(0.0, float(p99_ms or 0.0)) / 1e3
        self.qps_target = max(0.0, float(qps or 0.0))
        self.window_s = max(1.0, float(window_s or 60.0))
        self.short_s = max(1.0, self.window_s / 6.0)
        self.every_s = max(0.0, float(every_s or 0.0))
        # alert evaluation keeps its own cadence when snapshots are off
        self._eval_s = self.every_s or max(1.0, self.short_s / 2.0)
        self.burn_threshold = float(burn_threshold)
        self.clock = clock
        self._lock = threading.Lock()
        # kind -> list of [sec, n, err, shed, lat_sum, counts]; counts
        # has len(LATENCY_LADDER)+1 slots (last = +Inf), buckets sorted
        # by sec, pruned as they age past the long window
        self._routes = {}
        self._last_eval = clock()
        self._last_overall = None      # most recent evaluated window
        self._last_verdicts = {}
        self.alerting = False
        self.alerts_fired = 0
        self.alerts_cleared = 0
        self._m_alerts = REGISTRY.counter(
            "lgbm_serve_slo_alerts_total",
            "burn-rate alerts fired by the serving SLO engine")
        self._m_burn = REGISTRY.gauge(
            "lgbm_serve_slo_burn_rate",
            "long-window p99 error-budget burn rate (1.0 = on budget)")

    # ------------------------------------------------------------ writing
    def _bucket_locked(self, kind, now):
        sec = int(now)
        dq = self._routes.get(kind)
        if dq is None:
            dq = self._routes[kind] = []
        if dq and dq[-1][0] == sec:
            return dq[-1]
        b = [sec, 0, 0, 0, 0.0, [0] * (len(LATENCY_LADDER) + 1)]
        dq.append(b)
        # prune: nothing older than the long window ever aggregates
        cut = now - self.window_s - 2.0
        while dq and dq[0][0] < cut:
            dq.pop(0)
        return b

    def record(self, route, latency_s, error=False):
        """One completed request: latency submit->result; ``error`` for
        futures resolved with an exception (they count against the
        error rate, not the latency quantiles' happy path — but their
        latency is recorded too, slow failures are still slow)."""
        now = self.clock()
        with self._lock:
            b = self._bucket_locked(route_kind(route), now)
            i = bisect.bisect_left(LATENCY_LADDER, float(latency_s))
            b[5][i] += 1
            b[1] += 1
            b[4] += float(latency_s)
            if error:
                b[2] += 1
            due = (now - self._last_eval) >= self._eval_s
            if due:
                self._last_eval = now
        if due:
            self.evaluate(now)

    def record_shed(self, route, reason="queue_full"):
        """One request rejected at admission (overload protection)."""
        now = self.clock()
        with self._lock:
            b = self._bucket_locked(route_kind(route), now)
            b[3] += 1
            due = (now - self._last_eval) >= self._eval_s
            if due:
                self._last_eval = now
        if due:
            self.evaluate(now)

    # ----------------------------------------------------------- reading
    def _aggregate_locked(self, now, horizon, kind=None):
        """(n, err, shed, lat_sum, counts) over buckets newer than
        ``now - horizon`` (1-second bucket granularity)."""
        cut = now - horizon
        n = err = shed = 0
        lat = 0.0
        counts = [0] * (len(LATENCY_LADDER) + 1)
        items = ([(kind, self._routes.get(kind, []))] if kind is not None
                 else list(self._routes.items()))
        for _, dq in items:
            for b in reversed(dq):
                if b[0] < cut:
                    break
                n += b[1]
                err += b[2]
                shed += b[3]
                lat += b[4]
                for i, c in enumerate(b[5]):
                    counts[i] += c
        return n, err, shed, lat, counts

    @staticmethod
    def _pct(counts, n, q):
        """Quantile as a ladder upper bound (conservative)."""
        target = max(1, int(math.ceil(q * n)))
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target:
                if i < len(LATENCY_LADDER):
                    return LATENCY_LADDER[i]
                break
        return LATENCY_LADDER[-1] * _RATIO

    @staticmethod
    def _frac_over(counts, n, target_s):
        """Fraction of requests strictly over ``target_s``.  Counted
        from the first ladder bucket whose entire range exceeds the
        target — never a false positive from the bucket the target
        itself lands in."""
        if n <= 0:
            return 0.0
        j = bisect.bisect_left(LATENCY_LADDER, float(target_s))
        return sum(counts[j + 1:]) / n

    def _stats(self, agg, horizon):
        n, err, shed, lat, counts = agg
        out = {"n": n, "qps": round(n / horizon, 3), "shed": shed}
        if shed:
            out["shed_rate"] = round(shed / float(n + shed), 4)
        if n:
            out["p50_s"] = round(self._pct(counts, n, 0.50), 6)
            out["p95_s"] = round(self._pct(counts, n, 0.95), 6)
            out["p99_s"] = round(self._pct(counts, n, 0.99), 6)
            out["mean_s"] = round(lat / n, 6)
            out["err_rate"] = round(err / n, 4)
        return out

    # -------------------------------------------------------- evaluation
    def evaluate(self, now=None, force_snapshot=False):
        """Aggregate both windows, update the alert state machine, and
        emit the periodic ``serve_slo`` snapshot.  Called from the
        record path on its own cadence and from ``close(force=True)``
        so short-lived servers still leave one snapshot."""
        if now is None:
            now = self.clock()
        with self._lock:
            long_all = self._aggregate_locked(now, self.window_s)
            short_all = self._aggregate_locked(now, self.short_s)
            per_route = {
                k: self._stats(self._aggregate_locked(
                    now, self.window_s, kind=k), self.window_s)
                for k in sorted(self._routes)}
        overall = self._stats(long_all, self.window_s)
        burn_long = burn_short = 0.0
        if self.p99_target_s > 0:
            n_l, _, _, _, c_l = long_all
            n_s, _, _, _, c_s = short_all
            burn_long = self._frac_over(c_l, n_l,
                                        self.p99_target_s) / P99_BUDGET
            burn_short = self._frac_over(c_s, n_s,
                                         self.p99_target_s) / P99_BUDGET
            self._m_burn.set(round(burn_long, 3))
        verdicts = {}
        if self.p99_target_s > 0 and overall.get("n"):
            verdicts["p99"] = ("ok" if overall["p99_s"]
                               <= self.p99_target_s else "fail")
        if self.qps_target > 0:
            verdicts["qps"] = ("ok" if overall["qps"] >= self.qps_target
                               else "fail")
        # host-side snapshot the live /statusz plane reads (obs/live.py)
        self._last_overall = overall
        self._last_verdicts = verdicts
        transition = None
        if self.p99_target_s > 0:
            if (not self.alerting and burn_short >= self.burn_threshold
                    and burn_long >= self.burn_threshold):
                self.alerting = True
                self.alerts_fired += 1
                self._m_alerts.inc()
                transition = "firing"
            elif self.alerting and burn_short < self.burn_threshold:
                self.alerting = False
                self.alerts_cleared += 1
                transition = "cleared"
        obs = self.observer
        if obs.enabled and (force_snapshot or self.every_s > 0):
            rec = {"window_s": self.window_s, "short_s": self.short_s,
                   "routes": per_route, "overall": overall,
                   "alert": "firing" if self.alerting else "clear"}
            targets = {}
            if self.p99_target_s > 0:
                targets["p99_ms"] = self.p99_target_s * 1e3
                rec["burn_short"] = round(burn_short, 3)
                rec["burn_long"] = round(burn_long, 3)
            if self.qps_target > 0:
                targets["qps"] = self.qps_target
            if targets:
                rec["targets"] = targets
            if verdicts:
                rec["verdicts"] = verdicts
            obs.event("serve_slo", **rec)
        if transition is not None:
            self._emit_alert(transition, burn_short, burn_long, overall)
        return overall

    def _emit_alert(self, transition, burn_short, burn_long, overall):
        detail = {
            "burn_short": round(burn_short, 3),
            "burn_long": round(burn_long, 3),
            "threshold": self.burn_threshold,
            "p99_target_ms": round(self.p99_target_s * 1e3, 3),
            "p99_s": overall.get("p99_s"),
            "qps": overall.get("qps"),
            "cleared": transition == "cleared",
        }
        if transition == "firing":
            Log.warning(
                "serve slo: burn-rate alert FIRING — %.1fx/%.1fx of the "
                "p99<=%.1fms error budget (short/long window, "
                "threshold %.1fx)", burn_short, burn_long,
                self.p99_target_s * 1e3, self.burn_threshold)
        else:
            Log.warning("serve slo: burn-rate alert cleared "
                        "(short-window burn %.1fx)", burn_short)
        if self.mode == "off":
            return
        obs = self.observer
        if not obs.enabled:
            return
        from .health import _WARN_ONLY
        status = ("warn" if (self.mode == "warn"
                             or "slo_burn_rate" in _WARN_ONLY)
                  else "fatal")
        if transition == "cleared":
            status = "ok"
        obs.event("health", check="slo_burn_rate", status=status, it=-1,
                  detail=detail)
        obs.flush()

    def summary(self):
        return {"alerting": self.alerting,
                "alerts_fired": self.alerts_fired,
                "alerts_cleared": self.alerts_cleared,
                "targets": {"p99_ms": self.p99_target_s * 1e3,
                            "qps": self.qps_target}}

    def headline(self):
        """Live one-dict SLO digest for /statusz (registered as a
        flight provider by ServingPredictor): alert state + the most
        recent evaluated window's overall stats and verdicts."""
        out = self.summary()
        if self._last_overall is not None:
            out["overall"] = dict(self._last_overall)
        if self._last_verdicts:
            out["verdicts"] = dict(self._last_verdicts)
        return out

    def close(self):
        """Final forced snapshot: a server that lived shorter than one
        snapshot period still leaves its SLO record on the timeline."""
        try:
            self.evaluate(force_snapshot=True)
        except Exception as e:       # forensics must never break close
            Log.warning("serve slo: final snapshot failed: %s", e)


# ======================================================================
# reader side: timeline -> serving report (obs serve / obs summary)
# ======================================================================

def serve_events(events):
    return [e for e in events
            if str(e.get("ev", "")).startswith("serve_")]


def serve_metrics(events):
    """Fold a timeline's serving events into one report dict.  Lifetime
    totals come from the ``serve_summary`` close record when present
    (exact), else from summing the SAMPLED serve_batch events (lower
    bound, flagged ``sampled``)."""
    batches = [e for e in events if e.get("ev") == "serve_batch"]
    reqs = [e for e in events if e.get("ev") == "serve_request"]
    slos = [e for e in events if e.get("ev") == "serve_slo"]
    summaries = [e for e in events if e.get("ev") == "serve_summary"]
    benches = [e for e in events if e.get("ev") == "serve_bench"]
    alerts = [e for e in events if e.get("ev") == "health"
              and e.get("check") == "slo_burn_rate"]
    out = {"present": bool(batches or reqs or slos or summaries
                           or benches)}
    if not out["present"]:
        return out
    if summaries:
        s = summaries[-1]
        out["totals"] = {"batches": s.get("batches", 0),
                         "rows": s.get("rows", 0),
                         "pad_rows": s.get("pad_rows", 0),
                         "max_queue_depth": s.get("max_queue_depth", 0),
                         "shed_total": s.get("shed_total", 0),
                         "shed": dict(s.get("shed") or {}),
                         "sampled": False}
    else:
        out["totals"] = {
            "batches": len(batches),
            "rows": sum(int(e.get("rows", 0)) for e in batches),
            "pad_rows": sum(int(e.get("pad", 0)) for e in batches),
            "max_queue_depth": None,
            "shed_total": sum(int(e.get("shed", 0)) for e in benches),
            "shed": {},
            "sampled": True}
    t = out["totals"]
    slots = t["rows"] + t["pad_rows"]
    t["batch_efficiency"] = round(t["rows"] / slots, 4) if slots else None

    # per-route latency from sampled request traces
    routes = {}
    for e in reqs:
        k = _kind_from_event(e)
        r = routes.setdefault(k, {"n": 0, "lat": [], "rows": 0,
                                  "spans": {}})
        r["n"] += 1
        r["rows"] += int(e.get("rows", 0))
        if e.get("total_s") is not None:
            r["lat"].append(float(e["total_s"]))
        for name, v in (e.get("spans") or {}).items():
            r["spans"][name] = r["spans"].get(name, 0.0) + float(v)
    for k, r in routes.items():
        lat = sorted(r.pop("lat"))
        if lat:
            r["p50_s"] = _pct_sorted(lat, 0.50)
            r["p95_s"] = _pct_sorted(lat, 0.95)
            r["p99_s"] = _pct_sorted(lat, 0.99)
            r["mean_s"] = sum(lat) / len(lat)
        r["spans"] = {name: round(v / max(r["n"], 1), 6)
                      for name, v in sorted(r["spans"].items())}
    out["routes"] = routes

    # per-route microbatch shape from sampled serve_batch events
    broutes = {}
    for e in batches:
        k = _kind_from_event(e)
        b = broutes.setdefault(k, {"batches": 0, "rows": 0, "pad": 0,
                                   "requests": 0, "queue": [],
                                   "exec": []})
        b["batches"] += 1
        b["rows"] += int(e.get("rows", 0))
        b["pad"] += int(e.get("pad", 0))
        b["requests"] += int(e.get("requests", 1))
        b["queue"].append(float(e.get("queue_s", 0.0)))
        b["exec"].append(float(e.get("exec_s", 0.0)))
    for k, b in broutes.items():
        q, x = sorted(b.pop("queue")), sorted(b.pop("exec"))
        b["queue_p50_s"] = _pct_sorted(q, 0.50)
        b["exec_p50_s"] = _pct_sorted(x, 0.50)
        slots = b["rows"] + b["pad"]
        b["efficiency"] = round(b["rows"] / slots, 4) if slots else None
    out["batch_routes"] = broutes

    if slos:
        out["slo"] = slos[-1]
    if benches:
        out["bench"] = benches[-1]
    fired = [a for a in alerts if a.get("status") != "ok"]
    out["alerts"] = {
        "fired": len(fired),
        "cleared": len(alerts) - len(fired),
        "active": bool(alerts) and alerts[-1].get("status") != "ok",
        "last": alerts[-1] if alerts else None}
    return out


def serve_headline(events):
    """The one-line serving digest for ``obs summary`` /
    trace_summary.py: totals + efficiency + shed + last p99."""
    m = serve_metrics(events)
    if not m.get("present"):
        return None
    t = m["totals"]
    head = {"batches": t["batches"], "rows": t["rows"],
            "batch_efficiency": t["batch_efficiency"],
            "shed_total": t["shed_total"], "sampled": t["sampled"],
            "alerts_fired": m["alerts"]["fired"]}
    slo = m.get("slo")
    if slo:
        head["p99_s"] = (slo.get("overall") or {}).get("p99_s")
        head["qps"] = (slo.get("overall") or {}).get("qps")
    bench = m.get("bench")
    if bench:
        head.setdefault("p99_s", bench.get("p99_s"))
        head.setdefault("qps", bench.get("qps"))
    return head


def serve_roofline(events):
    """Roofline rows for the per-bucket AOT predict executables.

    serve/executable.py emits each bucket's program as a
    ``compile_attr`` event named ``serve_predict_b<bucket>[_conv]``
    carrying the shared cost/memory parse (obs/compile.py
    parse_compiled); the sampled ``serve_batch`` events time the same
    buckets' executes.  Joining the two against the device-peak
    registry (obs/roofline.py) gives the serving tier the same
    achieved-vs-peak treatment the training entries get."""
    from .roofline import entry_roofline, peaks_for
    costs = {}
    for e in events:
        if e.get("ev") == "compile_attr" and e.get("cost") \
                and str(e.get("entry", "")).startswith("serve_predict_b"):
            costs[e["entry"]] = e["cost"]
    if not costs:
        return []
    header = next((e for e in events if e.get("ev") == "run_header"), {})
    kind = ""
    for d in header.get("devices") or ():
        if isinstance(d, dict) and d.get("kind"):
            kind = str(d["kind"])
            break
    peaks = peaks_for(kind or str(header.get("backend", "") or ""))
    # executes per bucket from the sampled microbatch events
    execs = {}
    for e in events:
        if e.get("ev") != "serve_batch":
            continue
        b = e.get("bucket")
        execs.setdefault(b, []).append(float(e.get("exec_s", 0.0)))
    rows = []
    for entry, cost in sorted(costs.items()):
        suffix = entry[len("serve_predict_b"):]
        try:
            bucket = int(suffix.split("_")[0])
        except ValueError:
            bucket = None
        xs = execs.get(bucket) or []
        mean = (sum(xs) / len(xs)) if xs else 0.0
        r = entry_roofline(cost, mean, len(xs), peaks)
        r["entry"] = entry
        r["bucket"] = bucket
        r["timed"] = bool(xs)
        r["roof_source"] = peaks.get("source")
        rows.append(r)
    rows.sort(key=lambda r: -r["headroom_s"])
    return rows


def _ms(v):
    return "-" if v is None else "%.2f" % (float(v) * 1e3)


def render_serve_report(events, out=None, check=False):
    """Print the serving report; returns the list of problems (empty =
    healthy).  ``check`` only changes the verdict footer text — the
    caller turns problems into an exit code."""
    out = out or sys.stdout
    w = lambda s="": print(s, file=out)
    m = serve_metrics(events)
    problems = []
    w("== serving report ==")
    if not m.get("present"):
        w("no serving events in this timeline (serve_batch / "
          "serve_request / serve_slo / serve_summary / serve_bench)")
        problems.append("no serving events in timeline")
        return problems
    t = m["totals"]
    src = "sampled serve_batch events (lower bound)" if t["sampled"] \
        else "serve_summary (exact lifetime totals)"
    w("totals [%s]:" % src)
    w("  batches %s   rows %s   pad rows %s   max queue depth %s"
      % (t["batches"], t["rows"], t["pad_rows"],
         "-" if t["max_queue_depth"] is None else t["max_queue_depth"]))
    if t["batch_efficiency"] is not None:
        w("  batch efficiency %.1f%% (rows / padded slots)"
          % (100.0 * t["batch_efficiency"]))

    if m.get("routes"):
        w("")
        w("per-route latency (sampled serve_request traces):")
        w("  %-10s %6s %10s %10s %10s %10s" %
          ("route", "n", "p50_ms", "p95_ms", "p99_ms", "mean_ms"))
        for k in sorted(m["routes"]):
            r = m["routes"][k]
            w("  %-10s %6d %10s %10s %10s %10s"
              % (k, r["n"], _ms(r.get("p50_s")), _ms(r.get("p95_s")),
                 _ms(r.get("p99_s")), _ms(r.get("mean_s"))))
            if r.get("spans"):
                w("  %-10s   spans(ms): %s" % ("", "  ".join(
                    "%s=%s" % (name.replace("_s", ""), _ms(v))
                    for name, v in r["spans"].items())))
    if m.get("batch_routes"):
        w("")
        w("per-route microbatches (sampled serve_batch events):")
        w("  %-10s %8s %9s %8s %6s %9s %12s %11s" %
          ("route", "batches", "rows", "pad", "eff%", "req/batch",
           "queue_p50_ms", "exec_p50_ms"))
        for k in sorted(m["batch_routes"]):
            b = m["batch_routes"][k]
            eff = ("-" if b["efficiency"] is None
                   else "%.1f" % (100.0 * b["efficiency"]))
            w("  %-10s %8d %9d %8d %6s %9.1f %12s %11s"
              % (k, b["batches"], b["rows"], b["pad"], eff,
                 b["requests"] / max(b["batches"], 1),
                 _ms(b["queue_p50_s"]), _ms(b["exec_p50_s"])))

    slo = m.get("slo")
    w("")
    if slo:
        targets = slo.get("targets") or {}
        tgt = "  ".join(filter(None, [
            ("p99<=%.1fms" % targets["p99_ms"]) if "p99_ms" in targets
            else "",
            ("qps>=%g" % targets["qps"]) if "qps" in targets else ""]))
        w("SLO (window %gs%s):" % (slo.get("window_s", 0),
                                   (", targets " + tgt) if tgt else ""))
        overall = slo.get("overall") or {}
        w("  overall: qps %s  p50 %sms  p99 %sms  n %s"
          % (overall.get("qps", "-"), _ms(overall.get("p50_s")),
             _ms(overall.get("p99_s")), overall.get("n", "-")))
        for name, verdict in sorted((slo.get("verdicts") or {}).items()):
            w("  verdict %-4s: %s" % (name, verdict.upper()))
            if verdict != "ok":
                problems.append("SLO verdict %s=FAIL" % name)
        if "burn_long" in slo:
            w("  burn rate: short %sx, long %sx (threshold %gx) — %s"
              % (slo.get("burn_short"), slo.get("burn_long"),
                 BURN_THRESHOLD, slo.get("alert", "clear")))
    else:
        w("SLO: no serve_slo snapshots on this timeline "
          "(set serve_slo_every_s / serve_slo_p99_ms)")

    a = m["alerts"]
    w("")
    w("overload & shedding:")
    shed_bits = ", ".join("%s %d" % (k, v)
                          for k, v in sorted(t["shed"].items()))
    w("  shed: %d request(s)%s" % (t["shed_total"],
                                   (" (%s)" % shed_bits) if shed_bits
                                   else ""))
    w("  burn-rate alerts: %d fired, %d cleared%s"
      % (a["fired"], a["cleared"],
         "  [ACTIVE]" if a["active"] else ""))
    if t["shed_total"]:
        problems.append("%d shed request(s)" % t["shed_total"])
    if a["fired"]:
        problems.append("%d burn-rate alert(s) fired" % a["fired"])

    bench = m.get("bench")
    if bench:
        w("")
        w("bench: qps %s  p50 %sms  p99 %sms%s"
          % (bench.get("qps"), _ms(bench.get("p50_s")),
             _ms(bench.get("p99_s")),
             ("  shed_rate %s" % bench.get("shed_rate")
              if bench.get("shed_rate") is not None else "")))

    rl = serve_roofline(events)
    if rl:
        w("")
        w("executable roofline (achieved vs %s peaks, obs/roofline.py):"
          % (rl[0].get("roof_source", "?")))
        w("  %-26s %7s %10s %6s %6s %-18s %9s" %
          ("entry", "execs", "exec_p50", "MXU%", "HBM%", "bound",
           "headroom"))
        for r in rl:
            w("  %-26s %7d %8.3fms %5.1f%% %5.1f%% %-18s %8.4fs%s"
              % (r["entry"][:26], r["exec_n"], r["exec_mean_s"] * 1e3,
                 100 * r["flop_util"], 100 * r["hbm_util"], r["bound"],
                 r["headroom_s"],
                 "" if r["timed"] else "  (no sampled executes)"))
    w("")
    if problems:
        w("verdict: %s — %s" % ("FAIL" if check else "UNHEALTHY",
                                "; ".join(problems)))
    else:
        w("verdict: %s" % ("PASS" if check else "healthy"))
    return problems
